"""Kill-worker chaos drill (``python -m tpuserve chaos --drill worker_kill``;
PAPERS.md P6 — a resilience property you haven't injected a fault against
is a hope, not a property).

The drill serves a REAL router + N worker processes on an ephemeral port,
drives the closed-loop load generator at one model, then SIGKILLs one
worker mid-load (uncatchable — exactly a native crash / OOM kill) and
measures the properties the process split promises:

- **availability** — n_ok / (n_ok + n_err) over the whole run, kill
  included, must hold the bound (default >= 99%): in-flight requests on
  the victim surface as transport errors the router retries on survivors.
- **respawn_s** — time from the SIGKILL until the victim's slot is healthy
  again; gated against the configured backoff plus a spawn budget.
- **torn / duplicate responses** — a validator task runs one known payload
  in a closed loop throughout and byte-compares every 200 body against a
  pre-kill reference (workers build identical seeded weights, so answers
  are deterministic): any mismatch is a torn or mixed response, and every
  validator request is counted exactly once, so a duplicated answer would
  surface as a protocol error. Both must be zero.
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import time

from tpuserve.config import ServerConfig

log = logging.getLogger("tpuserve.workerproc")


async def _validator(url: str, payload: bytes, ctype: str, ref: bytes,
                     stop: asyncio.Event, out: dict) -> None:
    """Closed-loop correctness probe: every 200 body must equal the
    reference byte-for-byte; non-200s are availability's business."""
    import aiohttp

    async with aiohttp.ClientSession() as session:
        while not stop.is_set():
            try:
                async with session.post(
                        url, data=payload, headers={"Content-Type": ctype},
                        timeout=aiohttp.ClientTimeout(total=30.0)) as r:
                    body = await r.read()
                    if r.status == 200:
                        out["validated"] += 1
                        if body != ref:
                            out["mismatched"] += 1
                            log.error("torn/mixed response: %r != ref",
                                      body[:128])
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — resets count via loadgen
                out["transport_errors"] += 1
            await asyncio.sleep(0.01)


async def _await_postmortem(state, deadline_s: float = 10.0) -> list[dict]:
    """Wait for the supervisor's (executor-thread) postmortem capture to
    land, then return the ledger. The drills gate on this evidence
    (ISSUE 15): an injected SIGKILL that leaves no postmortem naming the
    signal is a forensics regression, not a flaky race."""
    if state.postmortems is None:
        return []
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        records = state.postmortems.dump()
        if any(r.get("signal") == "SIGKILL" for r in records):
            return records
        await asyncio.sleep(0.1)
    return state.postmortems.dump()


async def _worker_compile_totals(urls: dict[int, str]) -> dict[int, float]:
    """Sum runtime_compiles_total across models per worker, straight off
    each worker's own /metrics (the drill shares the router's process, so
    the loopback worker addresses are reachable)."""
    import aiohttp

    out: dict[int, float] = {}
    async with aiohttp.ClientSession() as session:
        for wid, url in urls.items():
            try:
                async with session.get(
                        f"{url}/metrics",
                        timeout=aiohttp.ClientTimeout(total=5.0)) as r:
                    text = await r.text()
            except Exception:  # noqa: BLE001 — dead worker: no snapshot
                continue
            total = 0.0
            for line in text.splitlines():
                if line.startswith("runtime_compiles_total"):
                    try:
                        total += float(line.rsplit(" ", 1)[1])
                    except ValueError:
                        pass
            out[wid] = total
    return out


async def run_host_kill_drill(cfg: ServerConfig, model_name: str | None = None,
                              duration_s: float = 25.0, warmup_s: float = 1.0,
                              concurrency: int = 16,
                              kill_after_s: float | None = None,
                              reabsorb_budget_s: float = 120.0) -> dict:
    """Kill-a-host chaos drill (ISSUE 13; the tentpole drill): serve a
    router over >= 2 host failure domains x >= 2 workers each, SIGKILL one
    ENTIRE host's process group mid-load (agent + every worker — one
    syscall, exactly a machine losing power), and measure:

    - **availability** over the whole run (survivor hosts absorb retries);
    - **reabsorb_s** — SIGKILL until the host slot is respawned with every
      worker healthy again (backoff + agent boot + worker boots);
    - **torn/duplicate audit** — the worker_kill validator, byte-comparing
      every 200 against a pre-kill reference throughout;
    - **compile_deltas** — surviving workers' runtime_compiles_total must
      not move (the kill must not perturb the survivors' variant
      registries).
    """
    from aiohttp import web

    from tpuserve.bench.loadgen import run_load, synthetic_image_npy
    from tpuserve.workerproc.router import RouterState, make_router_app

    cfg.router.enabled = True
    cfg.router.hosts = max(2, cfg.router.hosts)
    cfg.router.workers = max(2, cfg.router.workers)  # per host
    # Every validated response must be a real execution: a cache would
    # happily serve perfect answers from a fleet of corpses.
    cfg.cache.enabled = False
    model = model_name or cfg.models[0].name

    state = RouterState(cfg)
    app = make_router_app(state)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()  # on_startup spawns hosts + workers
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = runner.addresses[0][1]
    url = f"http://127.0.0.1:{port}/v1/models/{model}:predict"
    payload = synthetic_image_npy(edge=cfg.model(model).wire_size)
    ctype = "application/x-npy"

    kill_info: dict = {}
    integrity = {"validated": 0, "mismatched": 0, "transport_errors": 0}
    stop_validator = asyncio.Event()
    loop = asyncio.get_running_loop()

    async def _reference() -> bytes:
        import aiohttp

        async with aiohttp.ClientSession() as s:
            async with s.post(url, data=payload,
                              headers={"Content-Type": ctype}) as r:
                body = await r.read()
                if r.status != 200:
                    raise RuntimeError(
                        f"reference request failed: {r.status} {body[:200]}")
                return body

    async def _killer(survivor_urls: dict[int, str]) -> None:
        await asyncio.sleep(warmup_s + (kill_after_s
                                        if kill_after_s is not None
                                        else duration_s * 0.25))
        victim_ref = state.supervisor.pick()
        if victim_ref is None:
            kill_info["error"] = "no healthy worker whose host to kill"
            return
        hid = victim_ref.host
        h = state.supervisor.hosts[hid]
        if h is None:
            kill_info["error"] = f"host {hid} already down"
            return
        pgid, old_pids = h.pgid, {r.wid: r.pid for r in h.workers.values()}
        # Only NON-victim workers count for the compile-delta audit.
        for wid in list(survivor_urls):
            if wid in old_pids:
                del survivor_urls[wid]
        log.warning("drill: SIGKILL host %d — killpg(%d) takes the agent "
                    "and workers %s at once", hid, pgid, sorted(old_pids))
        t0 = time.monotonic()
        os.killpg(pgid, signal.SIGKILL)
        kill_info.update(killed_host=hid, killed_pgid=pgid,
                         workers_killed=len(old_pids))
        deadline = t0 + reabsorb_budget_s
        while time.monotonic() < deadline:
            nh = state.supervisor.hosts[hid]
            if nh is not None and nh.pgid != pgid and nh.proc.is_alive():
                refs = list(nh.workers.values())
                if len(refs) == cfg.router.workers \
                        and all(r.up and r.healthy for r in refs):
                    kill_info["reabsorb_s"] = round(time.monotonic() - t0, 2)
                    return
            await asyncio.sleep(0.05)
        kill_info["reabsorb_s"] = None  # did not come back in budget

    try:
        ref = await _reference()
        survivor_urls = {w.wid: w.base_url
                         for w in state.supervisor.live_workers()}
        compiles_before = await _worker_compile_totals(dict(survivor_urls))
        validator_task = loop.create_task(
            _validator(url, payload, ctype, ref, stop_validator, integrity))
        load_task = loop.create_task(
            run_load(url, payload, ctype, duration_s, concurrency, warmup_s))
        kill_task = loop.create_task(_killer(survivor_urls))
        result = await load_task
        await kill_task
        stop_validator.set()
        await validator_task
        compiles_after = await _worker_compile_totals(survivor_urls)
        postmortems = await _await_postmortem(state)
        workers = state.supervisor.stats()
    finally:
        await runner.cleanup()  # on_cleanup -> state.stop() -> fleet drain

    out = result.summary()
    total = result.n_ok + result.n_err
    out["availability"] = round(result.n_ok / total, 5) if total else 0.0
    out["drill"] = "host_kill"
    out["postmortems"] = postmortems
    out["kill"] = kill_info
    out["integrity"] = integrity
    out["workers"] = workers
    out["compile_deltas"] = {
        str(wid): compiles_after.get(wid, compiles_before[wid])
        - compiles_before[wid]
        for wid in compiles_before if wid in compiles_after}
    out["router"] = {
        "retries_total": state.handles[model].retries.value,
        "hedges_total": state.handles[model].hedges.value,
        "reabsorb_budget_s": reabsorb_budget_s,
        "respawn_backoff_initial_s": cfg.router.respawn_initial_s,
        "host_breaker_threshold": cfg.router.host_breaker_threshold,
    }
    return out


async def run_worker_kill_drill(cfg: ServerConfig, model_name: str | None = None,
                                duration_s: float = 20.0, warmup_s: float = 1.0,
                                concurrency: int = 16,
                                kill_after_s: float | None = None,
                                respawn_budget_s: float = 120.0) -> dict:
    """Serve a router fleet, SIGKILL one worker mid-load, report the
    availability / respawn / integrity numbers. The caller owns asserting
    the bounds (CLI gates availability; scripts/worker_drill.sh gates the
    rest)."""
    from aiohttp import web

    from tpuserve.bench.loadgen import run_load, synthetic_image_npy
    from tpuserve.workerproc.router import RouterState, make_router_app

    cfg.router.enabled = True
    cfg.router.workers = max(2, cfg.router.workers)
    cfg.router.hosts = 0  # worker-level drill: flat supervisor (PR 8);
    # host-level failure domains have their own drill (host_kill).
    # Every validated response must be a real execution: a cache would
    # happily serve perfect answers from a fleet of corpses.
    cfg.cache.enabled = False
    model = model_name or cfg.models[0].name

    state = RouterState(cfg)
    app = make_router_app(state)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()  # on_startup spawns the fleet
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = runner.addresses[0][1]
    url = f"http://127.0.0.1:{port}/v1/models/{model}:predict"
    payload = synthetic_image_npy(edge=cfg.model(model).wire_size)
    ctype = "application/x-npy"

    kill_info: dict = {}
    integrity = {"validated": 0, "mismatched": 0, "transport_errors": 0}
    stop_validator = asyncio.Event()
    loop = asyncio.get_running_loop()

    async def _reference() -> bytes:
        import aiohttp

        async with aiohttp.ClientSession() as s:
            async with s.post(url, data=payload,
                              headers={"Content-Type": ctype}) as r:
                body = await r.read()
                if r.status != 200:
                    raise RuntimeError(
                        f"reference request failed: {r.status} {body[:200]}")
                return body

    async def _killer() -> None:
        await asyncio.sleep(warmup_s + (kill_after_s
                                        if kill_after_s is not None
                                        else duration_s * 0.25))
        victim = state.supervisor.pick()
        if victim is None:
            kill_info["error"] = "no healthy worker to kill"
            return
        wid, pid = victim.wid, victim.pid
        log.warning("drill: SIGKILL worker %d (pid %d)", wid, pid)
        t0 = time.monotonic()
        os.kill(pid, signal.SIGKILL)
        kill_info.update(killed_worker=wid, killed_pid=pid)
        deadline = t0 + respawn_budget_s
        while time.monotonic() < deadline:
            h = state.supervisor.slots[wid]
            if h is not None and h.pid != pid and h.healthy:
                kill_info["respawn_s"] = round(time.monotonic() - t0, 2)
                return
            await asyncio.sleep(0.05)
        kill_info["respawn_s"] = None  # did not come back in budget

    try:
        ref = await _reference()
        validator_task = loop.create_task(
            _validator(url, payload, ctype, ref, stop_validator, integrity))
        load_task = loop.create_task(
            run_load(url, payload, ctype, duration_s, concurrency, warmup_s))
        kill_task = loop.create_task(_killer())
        result = await load_task
        await kill_task
        stop_validator.set()
        await validator_task
        postmortems = await _await_postmortem(state)
        workers = state.supervisor.stats()
    finally:
        await runner.cleanup()  # on_cleanup -> state.stop() -> fleet drain

    out = result.summary()
    total = result.n_ok + result.n_err
    out["availability"] = round(result.n_ok / total, 5) if total else 0.0
    out["drill"] = "worker_kill"
    out["postmortems"] = postmortems
    out["kill"] = kill_info
    out["integrity"] = integrity
    out["workers"] = workers
    out["router"] = {
        "retries_total": state.handles[model].retries.value,
        "hedges_total": state.handles[model].hedges.value,
        "respawn_budget_s": respawn_budget_s,
        "respawn_backoff_initial_s": cfg.router.respawn_initial_s,
    }
    return out
