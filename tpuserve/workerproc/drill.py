"""Kill-worker chaos drill (``python -m tpuserve chaos --drill worker_kill``;
PAPERS.md P6 — a resilience property you haven't injected a fault against
is a hope, not a property), plus the hostile-tenant autopilot drill
(``--drill autopilot``, ISSUE 16).

The kill drills serve a REAL router + N worker processes on an ephemeral
port, drive the closed-loop load generator at one model, then SIGKILL one
worker (or one whole host's process group) mid-load and measure the
properties the process split promises:

- **availability** — n_ok / (n_ok + n_err) over the whole run, kill
  included, must hold the bound (default >= 99%): in-flight requests on
  the victim surface as transport errors the router retries on survivors.
- **respawn_s** — time from the SIGKILL until the victim's slot is healthy
  again; gated against the configured backoff plus a spawn budget.
- **torn / duplicate responses** — a validator task runs one known payload
  in a closed loop throughout and byte-compares every 200 body against a
  pre-kill reference (workers build identical seeded weights, so answers
  are deterministic): any mismatch is a torn or mixed response, and every
  validator request is counted exactly once, so a duplicated answer would
  surface as a protocol error. Both must be zero.
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import time

from tpuserve.config import ServerConfig

log = logging.getLogger("tpuserve.workerproc")


async def _validator(url: str, payload: bytes, ctype: str, ref: bytes,
                     stop: asyncio.Event, out: dict) -> None:
    """Closed-loop correctness probe: every 200 body must equal the
    reference byte-for-byte; non-200s are availability's business."""
    import aiohttp

    async with aiohttp.ClientSession() as session:
        while not stop.is_set():
            try:
                async with session.post(
                        url, data=payload, headers={"Content-Type": ctype},
                        timeout=aiohttp.ClientTimeout(total=30.0)) as r:
                    body = await r.read()
                    if r.status == 200:
                        out["validated"] += 1
                        if body != ref:
                            out["mismatched"] += 1
                            log.error("torn/mixed response: %r != ref",
                                      body[:128])
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — resets count via loadgen
                out["transport_errors"] += 1
            await asyncio.sleep(0.01)


async def _await_postmortem(state, deadline_s: float = 10.0) -> list[dict]:
    """Wait for the supervisor's (executor-thread) postmortem capture to
    land, then return the ledger. The drills gate on this evidence
    (ISSUE 15): an injected SIGKILL that leaves no postmortem naming the
    signal is a forensics regression, not a flaky race."""
    if state.postmortems is None:
        return []
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        records = state.postmortems.dump()
        if any(r.get("signal") == "SIGKILL" for r in records):
            return records
        await asyncio.sleep(0.1)
    return state.postmortems.dump()


async def _worker_compile_totals(urls: dict[int, str]) -> dict[int, float]:
    """Sum runtime_compiles_total across models per worker, straight off
    each worker's own /metrics (the drill shares the router's process, so
    the loopback worker addresses are reachable)."""
    import aiohttp

    out: dict[int, float] = {}
    async with aiohttp.ClientSession() as session:
        for wid, url in urls.items():
            try:
                async with session.get(
                        f"{url}/metrics",
                        timeout=aiohttp.ClientTimeout(total=5.0)) as r:
                    text = await r.text()
            except Exception:  # noqa: BLE001 — dead worker: no snapshot
                continue
            total = 0.0
            for line in text.splitlines():
                if line.startswith("runtime_compiles_total"):
                    try:
                        total += float(line.rsplit(" ", 1)[1])
                    except ValueError:
                        pass
            out[wid] = total
    return out


async def run_host_kill_drill(cfg: ServerConfig, model_name: str | None = None,
                              duration_s: float = 25.0, warmup_s: float = 1.0,
                              concurrency: int = 16,
                              kill_after_s: float | None = None,
                              reabsorb_budget_s: float = 120.0) -> dict:
    """Kill-a-host chaos drill (ISSUE 13; the tentpole drill): serve a
    router over >= 2 host failure domains x >= 2 workers each, SIGKILL one
    ENTIRE host's process group mid-load (agent + every worker — one
    syscall, exactly a machine losing power), and measure:

    - **availability** over the whole run (survivor hosts absorb retries);
    - **reabsorb_s** — SIGKILL until the host slot is respawned with every
      worker healthy again (backoff + agent boot + worker boots);
    - **torn/duplicate audit** — the worker_kill validator, byte-comparing
      every 200 against a pre-kill reference throughout;
    - **compile_deltas** — surviving workers' runtime_compiles_total must
      not move (the kill must not perturb the survivors' variant
      registries).
    """
    from aiohttp import web

    from tpuserve.bench.loadgen import run_load, synthetic_image_npy
    from tpuserve.workerproc.router import RouterState, make_router_app

    cfg.router.enabled = True
    cfg.router.hosts = max(2, cfg.router.hosts)
    cfg.router.workers = max(2, cfg.router.workers)  # per host
    # Every validated response must be a real execution: a cache would
    # happily serve perfect answers from a fleet of corpses.
    cfg.cache.enabled = False
    model = model_name or cfg.models[0].name

    state = RouterState(cfg)
    app = make_router_app(state)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()  # on_startup spawns hosts + workers
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = runner.addresses[0][1]
    url = f"http://127.0.0.1:{port}/v1/models/{model}:predict"
    payload = synthetic_image_npy(edge=cfg.model(model).wire_size)
    ctype = "application/x-npy"

    kill_info: dict = {}
    integrity = {"validated": 0, "mismatched": 0, "transport_errors": 0}
    stop_validator = asyncio.Event()
    loop = asyncio.get_running_loop()

    async def _reference() -> bytes:
        import aiohttp

        async with aiohttp.ClientSession() as s:
            async with s.post(url, data=payload,
                              headers={"Content-Type": ctype}) as r:
                body = await r.read()
                if r.status != 200:
                    raise RuntimeError(
                        f"reference request failed: {r.status} {body[:200]}")
                return body

    async def _killer(survivor_urls: dict[int, str]) -> None:
        await asyncio.sleep(warmup_s + (kill_after_s
                                        if kill_after_s is not None
                                        else duration_s * 0.25))
        victim_ref = state.supervisor.pick()
        if victim_ref is None:
            kill_info["error"] = "no healthy worker whose host to kill"
            return
        hid = victim_ref.host
        h = state.supervisor.hosts[hid]
        if h is None:
            kill_info["error"] = f"host {hid} already down"
            return
        pgid, old_pids = h.pgid, {r.wid: r.pid for r in h.workers.values()}
        # Only NON-victim workers count for the compile-delta audit.
        for wid in list(survivor_urls):
            if wid in old_pids:
                del survivor_urls[wid]
        log.warning("drill: SIGKILL host %d — killpg(%d) takes the agent "
                    "and workers %s at once", hid, pgid, sorted(old_pids))
        t0 = time.monotonic()
        os.killpg(pgid, signal.SIGKILL)
        kill_info.update(killed_host=hid, killed_pgid=pgid,
                         workers_killed=len(old_pids))
        deadline = t0 + reabsorb_budget_s
        while time.monotonic() < deadline:
            nh = state.supervisor.hosts[hid]
            if nh is not None and nh.pgid != pgid and nh.proc.is_alive():
                refs = list(nh.workers.values())
                if len(refs) == cfg.router.workers \
                        and all(r.up and r.healthy for r in refs):
                    kill_info["reabsorb_s"] = round(time.monotonic() - t0, 2)
                    return
            await asyncio.sleep(0.05)
        kill_info["reabsorb_s"] = None  # did not come back in budget

    try:
        ref = await _reference()
        survivor_urls = {w.wid: w.base_url
                         for w in state.supervisor.live_workers()}
        compiles_before = await _worker_compile_totals(dict(survivor_urls))
        validator_task = loop.create_task(
            _validator(url, payload, ctype, ref, stop_validator, integrity))
        load_task = loop.create_task(
            run_load(url, payload, ctype, duration_s, concurrency, warmup_s))
        kill_task = loop.create_task(_killer(survivor_urls))
        result = await load_task
        await kill_task
        stop_validator.set()
        await validator_task
        compiles_after = await _worker_compile_totals(survivor_urls)
        postmortems = await _await_postmortem(state)
        workers = state.supervisor.stats()
    finally:
        await runner.cleanup()  # on_cleanup -> state.stop() -> fleet drain

    out = result.summary()
    total = result.n_ok + result.n_err
    out["availability"] = round(result.n_ok / total, 5) if total else 0.0
    out["drill"] = "host_kill"
    out["postmortems"] = postmortems
    out["kill"] = kill_info
    out["integrity"] = integrity
    out["workers"] = workers
    out["compile_deltas"] = {
        str(wid): compiles_after.get(wid, compiles_before[wid])
        - compiles_before[wid]
        for wid in compiles_before if wid in compiles_after}
    out["router"] = {
        "retries_total": state.handles[model].retries.value,
        "hedges_total": state.handles[model].hedges.value,
        "reabsorb_budget_s": reabsorb_budget_s,
        "respawn_backoff_initial_s": cfg.router.respawn_initial_s,
        "host_breaker_threshold": cfg.router.host_breaker_threshold,
    }
    return out


async def run_worker_kill_drill(cfg: ServerConfig, model_name: str | None = None,
                                duration_s: float = 20.0, warmup_s: float = 1.0,
                                concurrency: int = 16,
                                kill_after_s: float | None = None,
                                respawn_budget_s: float = 120.0) -> dict:
    """Serve a router fleet, SIGKILL one worker mid-load, report the
    availability / respawn / integrity numbers. The caller owns asserting
    the bounds (CLI gates availability; scripts/worker_drill.sh gates the
    rest)."""
    from aiohttp import web

    from tpuserve.bench.loadgen import run_load, synthetic_image_npy
    from tpuserve.workerproc.router import RouterState, make_router_app

    cfg.router.enabled = True
    cfg.router.workers = max(2, cfg.router.workers)
    cfg.router.hosts = 0  # worker-level drill: flat supervisor (PR 8);
    # host-level failure domains have their own drill (host_kill).
    # Every validated response must be a real execution: a cache would
    # happily serve perfect answers from a fleet of corpses.
    cfg.cache.enabled = False
    model = model_name or cfg.models[0].name

    state = RouterState(cfg)
    app = make_router_app(state)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()  # on_startup spawns the fleet
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = runner.addresses[0][1]
    url = f"http://127.0.0.1:{port}/v1/models/{model}:predict"
    payload = synthetic_image_npy(edge=cfg.model(model).wire_size)
    ctype = "application/x-npy"

    kill_info: dict = {}
    integrity = {"validated": 0, "mismatched": 0, "transport_errors": 0}
    stop_validator = asyncio.Event()
    loop = asyncio.get_running_loop()

    async def _reference() -> bytes:
        import aiohttp

        async with aiohttp.ClientSession() as s:
            async with s.post(url, data=payload,
                              headers={"Content-Type": ctype}) as r:
                body = await r.read()
                if r.status != 200:
                    raise RuntimeError(
                        f"reference request failed: {r.status} {body[:200]}")
                return body

    async def _killer() -> None:
        await asyncio.sleep(warmup_s + (kill_after_s
                                        if kill_after_s is not None
                                        else duration_s * 0.25))
        victim = state.supervisor.pick()
        if victim is None:
            kill_info["error"] = "no healthy worker to kill"
            return
        wid, pid = victim.wid, victim.pid
        log.warning("drill: SIGKILL worker %d (pid %d)", wid, pid)
        t0 = time.monotonic()
        os.kill(pid, signal.SIGKILL)
        kill_info.update(killed_worker=wid, killed_pid=pid)
        deadline = t0 + respawn_budget_s
        while time.monotonic() < deadline:
            h = state.supervisor.slots[wid]
            if h is not None and h.pid != pid and h.healthy:
                kill_info["respawn_s"] = round(time.monotonic() - t0, 2)
                return
            await asyncio.sleep(0.05)
        kill_info["respawn_s"] = None  # did not come back in budget

    try:
        ref = await _reference()
        validator_task = loop.create_task(
            _validator(url, payload, ctype, ref, stop_validator, integrity))
        load_task = loop.create_task(
            run_load(url, payload, ctype, duration_s, concurrency, warmup_s))
        kill_task = loop.create_task(_killer())
        result = await load_task
        await kill_task
        stop_validator.set()
        await validator_task
        postmortems = await _await_postmortem(state)
        workers = state.supervisor.stats()
    finally:
        await runner.cleanup()  # on_cleanup -> state.stop() -> fleet drain

    out = result.summary()
    total = result.n_ok + result.n_err
    out["availability"] = round(result.n_ok / total, 5) if total else 0.0
    out["drill"] = "worker_kill"
    out["postmortems"] = postmortems
    out["kill"] = kill_info
    out["integrity"] = integrity
    out["workers"] = workers
    out["router"] = {
        "retries_total": state.handles[model].retries.value,
        "hedges_total": state.handles[model].hedges.value,
        "respawn_budget_s": respawn_budget_s,
        "respawn_backoff_initial_s": cfg.router.respawn_initial_s,
    }
    return out


async def run_stream_kill_drill(cfg: ServerConfig,
                                model_name: str | None = None,
                                duration_s: float = 20.0,
                                warmup_s: float = 1.0,
                                concurrency: int = 16,
                                kill_after_s: float | None = None,
                                respawn_budget_s: float = 120.0) -> dict:
    """Mid-stream chaos drill (ISSUE 17 tentpole part 4): serve a router
    over >= 2 workers with a generative model, drive MIXED streaming +
    unary load, SIGKILL one worker mid-load, and audit the fail-safe
    stream semantics end-to-end:

    - **zero silent truncations** — every stream that STARTED (the worker
      committed a 200 + first bytes) ends in exactly one terminal event:
      "done", or a well-formed "error" naming the cause. ``torn`` counts
      streams that hit EOF with no terminal; it must be 0 even for the
      streams cut by the SIGKILL (the router appends the terminal).
    - **zero duplicate / reordered tokens** — every stream's token indices
      must be exactly 0..n-1 (a post-latch re-dispatch would replay
      tokens); ``order_violations`` must be 0.
    - **byte audit vs the seeded reference** — one fixed (prompt, seed,
      max_new_tokens) body streams throughout; generation is seeded-
      deterministic and detokenize is append-only, so a "done" stream's
      concatenated text must equal the unary reference EXACTLY
      (``mismatched`` = 0) and an error-terminated stream's text must be
      a strict PREFIX of it (``non_prefix`` = 0 — anything else is
      corruption or replay).
    - **un-started streams retry transparently** — a request the victim
      never answered bytes for is re-dispatched to the survivor by the
      router's pre-latch machinery; availability (gated by the CLI) is
      the UNARY load's, the survivors' view.
    - **zero survivor compiles** — the kill must not perturb the
      survivors' compiled generation programs (compile_deltas all 0).
    """
    import aiohttp
    from aiohttp import web

    from tpuserve.bench.loadgen import (run_load, stream_generate,
                                        synthetic_prompt_pool)
    from tpuserve.obs import percentile
    from tpuserve.workerproc.router import RouterState, make_router_app

    cfg.router.enabled = True
    cfg.router.workers = max(2, cfg.router.workers)
    cfg.router.hosts = 0
    # Streams bypass the cache structurally, but the unary availability
    # load must execute for real too.
    cfg.cache.enabled = False
    model = model_name or cfg.models[0].name

    state = RouterState(cfg)
    app = make_router_app(state)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()  # on_startup spawns the fleet
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = runner.addresses[0][1]
    base = f"http://127.0.0.1:{port}"
    url = f"{base}/v1/models/{model}:generate"
    ctype = "application/json"
    import json as _json

    # The audited stream payload: fixed (prompt, seed, cap) — seeded
    # generation is deterministic across workers (identical seeded
    # weights), so every stream of this body must yield the same tokens.
    ref_body = _json.dumps({"prompt": "the quick brown fox jumps over",
                            "seed": 7, "max_new_tokens": 24,
                            "temperature": 0.7}).encode()
    unary_pool = synthetic_prompt_pool(16, max_new=(2, 24))

    kill_info: dict = {}
    records: list[dict] = []
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()

    async def _reference() -> str:
        async with aiohttp.ClientSession() as s:
            async with s.post(url, data=ref_body,
                              headers={"Content-Type": ctype}) as r:
                body = await r.read()
                if r.status != 200:
                    raise RuntimeError(
                        f"reference request failed: {r.status} {body[:200]}")
                return _json.loads(body)["text"]

    async def _stream_client() -> None:
        async with aiohttp.ClientSession() as session:
            while not stop.is_set():
                rec = await stream_generate(
                    session, url, ref_body, {"Content-Type": ctype})
                records.append(rec)
                await asyncio.sleep(0.01)

    async def _killer(survivor_urls: dict[int, str]) -> None:
        await asyncio.sleep(warmup_s + (kill_after_s
                                        if kill_after_s is not None
                                        else duration_s * 0.25))
        victim = state.supervisor.pick()
        if victim is None:
            kill_info["error"] = "no healthy worker to kill"
            return
        wid, pid = victim.wid, victim.pid
        survivor_urls.pop(wid, None)  # victim is no compile-audit subject
        log.warning("drill: SIGKILL worker %d (pid %d) mid-stream",
                    wid, pid)
        t0 = time.monotonic()
        os.kill(pid, signal.SIGKILL)
        kill_info.update(killed_worker=wid, killed_pid=pid)
        deadline = t0 + respawn_budget_s
        while time.monotonic() < deadline:
            h = state.supervisor.slots[wid]
            if h is not None and h.pid != pid and h.healthy:
                kill_info["respawn_s"] = round(time.monotonic() - t0, 2)
                return
            await asyncio.sleep(0.05)
        kill_info["respawn_s"] = None

    try:
        ref_text = await _reference()
        survivor_urls = {w.wid: w.base_url
                         for w in state.supervisor.live_workers()}
        compiles_before = await _worker_compile_totals(dict(survivor_urls))
        n_streamers = max(2, concurrency // 4)
        stream_tasks = [loop.create_task(_stream_client())
                        for _ in range(n_streamers)]
        load_task = loop.create_task(run_load(
            url, unary_pool, ctype, duration_s,
            max(2, concurrency - n_streamers), warmup_s))
        kill_task = loop.create_task(_killer(survivor_urls))
        result = await load_task
        await kill_task
        stop.set()
        await asyncio.gather(*stream_tasks)
        compiles_after = await _worker_compile_totals(survivor_urls)
        postmortems = await _await_postmortem(state)
        workers = state.supervisor.stats()
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/metrics") as r:
                metrics_text = await r.text() if r.status == 200 else ""
    finally:
        await runner.cleanup()  # on_cleanup -> state.stop() -> fleet drain

    started = [r for r in records if r["status"] == 200]
    done_s = [r for r in started if r["terminal"] == "done"]
    error_s = [r for r in started if r["terminal"] == "error"]
    first_tokens = [r["first_token_ms"] for r in started
                    if r["first_token_ms"] is not None]
    gaps = [(b - a) * 1e3 for r in done_s
            for a, b in zip(r["token_times"], r["token_times"][1:])]
    audit = {
        "streams": len(records),
        "started": len(started),
        "done": len(done_s),
        "error_terminals": len(error_s),
        "error_reasons": {},
        # The three zero-gates:
        "torn": sum(1 for r in started if r["torn"]),
        "order_violations": sum(
            1 for r in started
            if r["indices"] != list(range(len(r["indices"])))),
        "mismatched": sum(1 for r in done_s if r["text"] != ref_text),
        "non_prefix": sum(1 for r in error_s
                          if not ref_text.startswith(r["text"])),
        "junk_events": sum(r["junk"] for r in records),
        # Pre-latch outcomes: the router retried or shed these with a
        # plain status — no stream semantics owed.
        "not_started": len(records) - len(started),
        "first_token_p50_ms": round(percentile(first_tokens, 0.5), 3),
        "first_token_p99_ms": round(percentile(first_tokens, 0.99), 3),
        "inter_token_gap_p99_ms": round(percentile(gaps, 0.99), 3),
    }
    for r in error_s:
        key = str(r["error"])
        audit["error_reasons"][key] = audit["error_reasons"].get(key, 0) + 1
    stream_terminated = {}
    for line in metrics_text.splitlines():
        if line.startswith("router_stream_terminated_total"):
            try:
                k, v = line.rsplit(" ", 1)
                stream_terminated[k] = float(v)
            except ValueError:
                pass

    out = result.summary()
    total = result.n_ok + result.n_err
    out["availability"] = round(result.n_ok / total, 5) if total else 0.0
    out["drill"] = "stream_kill"
    out["postmortems"] = postmortems
    out["kill"] = kill_info
    out["stream_audit"] = audit
    out["workers"] = workers
    out["compile_deltas"] = {
        str(wid): compiles_after.get(wid, compiles_before[wid])
        - compiles_before[wid]
        for wid in compiles_before if wid in compiles_after}
    out["router"] = {
        "retries_total": state.handles[model].retries.value,
        "hedges_total": state.handles[model].hedges.value,
        "streams_total": state.handles[model].streams.value,
        "stream_terminated": stream_terminated,
        "respawn_budget_s": respawn_budget_s,
    }
    return out


async def _tenant_load(url: str, payload: bytes, ctype: str, api_key: str,
                       stop: asyncio.Event, out: dict, clients: int,
                       think_s: float = 0.0) -> None:
    """Closed-loop per-tenant load: ``clients`` concurrent callers, each
    tagging ``X-Api-Key`` and bucketing every response by status + shed
    reason. A hostile tenant is just this with no think time and a tight
    envelope — it deliberately ignores Retry-After."""
    import aiohttp

    headers = {"Content-Type": ctype, "X-Api-Key": api_key}

    async def _one() -> None:
        async with aiohttp.ClientSession() as session:
            while not stop.is_set():
                try:
                    async with session.post(
                            url, data=payload, headers=headers,
                            timeout=aiohttp.ClientTimeout(total=30.0)) as r:
                        if r.status == 200:
                            await r.read()
                            out["n_200"] += 1
                        else:
                            key = f"n_{r.status}" \
                                if r.status in (429, 503) else "n_other"
                            out[key] = out.get(key, 0) + 1
                            try:
                                reason = (await r.json()).get("reason", "")
                            except Exception:  # noqa: BLE001
                                reason = ""
                            if reason:
                                out["reasons"][reason] = \
                                    out["reasons"].get(reason, 0) + 1
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — transport-level failure
                    out["transport_errors"] += 1
                if think_s:
                    await asyncio.sleep(think_s)

    await asyncio.gather(*(_one() for _ in range(clients)))


async def run_autopilot_drill(cfg: ServerConfig, model_name: str | None = None,
                              duration_s: float = 25.0, warmup_s: float = 1.0,
                              concurrency: int = 16) -> dict:
    """Hostile-tenant autopilot drill (ISSUE 16; the closed-loop tentpole):
    serve a router fleet with the autopilot engaged and per-tenant
    containment on, then — unattended — let one tenant turn hostile (a
    quota-busting flood) while any seeded ``[faults]`` latency rule fires
    mid-load, and report the evidence the self-healing loop promises:

    - **containment** — the hostile tenant's overage is 429'd at admission
      (tenant_* shed reasons) while the victim tenant's availability (the
      ``availability`` the CLI gates) stays green;
    - **reaction** — the controller sheds/scales within the run: its
      decision log shows actions, and ``first_action_s`` bounds the
      reaction time from load start;
    - **audit** — every controller decision (rollbacks included) is
      readable from GET /debug/audit as an ``autopilot:*`` verb, fetched
      over HTTP from the live fleet, not from in-process state.

    The caller owns asserting the bounds (the CLI gates availability;
    scripts/autopilot_drill.sh gates the rest)."""
    import aiohttp
    from aiohttp import web

    from tpuserve.bench.loadgen import synthetic_image_npy
    from tpuserve.config import TenantConfig
    from tpuserve.workerproc.router import RouterState, make_router_app

    cfg.router.enabled = True
    cfg.router.hosts = max(2, cfg.router.hosts)
    cfg.router.workers = max(2, cfg.router.workers)  # per host
    if not 1 <= cfg.router.active_workers < cfg.router.workers:
        # Leave one dormant slot per host so scale_up has real headroom.
        cfg.router.active_workers = cfg.router.workers - 1
    # Identical payloads would all coalesce into one cache hit, hiding
    # both the hostile load and the pressure signal the controller reads.
    cfg.cache.enabled = False

    ap = cfg.autopilot
    ap.enabled = True
    # Drill runs tens of seconds, not hours: tighten the controller's
    # clocks so hysteresis/cooldown/follow-up all fit inside the run
    # (never loosen what the config already set tighter).
    ap.interval_s = min(ap.interval_s, 0.25)
    ap.hysteresis_ticks = min(ap.hysteresis_ticks, 2)
    ap.cooldown_s = min(ap.cooldown_s, 3.0)
    ap.follow_up_s = min(ap.follow_up_s, 5.0)

    tn = cfg.tenants
    tn.enabled = True
    have = {t.name for t in tn.tenants}
    if "hostile" not in have:
        # Tight envelope: the flood must hit its quota mid-run.
        tn.tenants.append(TenantConfig(
            name="hostile", api_key="drill-hostile-key", weight=1.0,
            quota_device_s=max(1.0, duration_s * 0.2),
            rate_per_s=float(concurrency)))
    if "victim" not in have:
        tn.tenants.append(TenantConfig(
            name="victim", api_key="drill-victim-key", weight=4.0))
    keys = {t.name: t.api_key for t in tn.tenants}
    model = model_name or cfg.models[0].name

    state = RouterState(cfg)
    app = make_router_app(state)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()  # on_startup spawns hosts + workers + autopilot
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = runner.addresses[0][1]
    base = f"http://127.0.0.1:{port}"
    url = f"{base}/v1/models/{model}:predict"
    payload = synthetic_image_npy(edge=cfg.model(model).wire_size)
    ctype = "application/x-npy"

    def _bucket() -> dict:
        return {"n_200": 0, "n_429": 0, "n_503": 0, "n_other": 0,
                "transport_errors": 0, "reasons": {}}

    hostile = _bucket()
    victim = _bucket()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()

    try:
        # Reference request (as the victim — anonymous is 401 now): the
        # fleet must serve before the clock starts.
        async with aiohttp.ClientSession() as s:
            async with s.post(url, data=payload, headers={
                    "Content-Type": ctype,
                    "X-Api-Key": keys["victim"]}) as r:
                body = await r.read()
                if r.status != 200:
                    raise RuntimeError(
                        f"reference request failed: {r.status} {body[:200]}")
        await asyncio.sleep(warmup_s)
        t_load0 = time.monotonic()
        tasks = [
            loop.create_task(_tenant_load(
                url, payload, ctype, keys["hostile"], stop, hostile,
                clients=max(4, concurrency))),
            loop.create_task(_tenant_load(
                url, payload, ctype, keys["victim"], stop, victim,
                clients=max(2, concurrency // 4), think_s=0.05)),
        ]
        await asyncio.sleep(duration_s)
        stop.set()
        await asyncio.gather(*tasks)

        ap_desc = state.autopilot.describe() if state.autopilot else {}
        decisions = ap_desc.get("decisions", [])
        # Controller reaction time, measured from load start (audit/decision
        # timestamps are wall-clock; so is this conversion).
        wall_load0 = time.time() - (time.monotonic() - t_load0)
        first_action_s = round(decisions[0]["ts"] - wall_load0, 2) \
            if decisions else None
        usage = state.tenants.usage() if state.tenants else {}
        tenant_slo = state.tenant_slo.alerts() \
            if state.tenant_slo is not None else {}
        scale_state = state.supervisor.scale_state() \
            if hasattr(state.supervisor, "scale_state") else []
        # Audit completeness is asserted against the LIVE endpoint: every
        # controller decision must be readable from GET /debug/audit.
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/debug/audit") as r:
                audit_body = await r.json() if r.status == 200 else {}
            async with s.get(f"{base}/debug/autopilot") as r:
                ap_http_status = r.status
            async with s.get(f"{base}/tenants") as r:
                tenants_http_status = r.status
        audit_recs = [rec for rec in audit_body.get("audit", [])
                      if str(rec.get("verb", "")).startswith("autopilot:")]
    finally:
        await runner.cleanup()  # on_cleanup -> state.stop() -> fleet drain

    kinds: dict[str, int] = {}
    for d in decisions:
        kinds[d["kind"]] = kinds.get(d["kind"], 0) + 1
    v_total = (victim["n_200"] + victim["n_429"] + victim["n_503"]
               + victim["n_other"] + victim["transport_errors"])
    return {
        "drill": "autopilot",
        "model": model,
        "duration_s": duration_s,
        # The CLI's --min-availability gates the VICTIM: the hostile
        # tenant's 429s are the contract working, not an outage.
        "availability": round(victim["n_200"] / v_total, 5) if v_total
        else 0.0,
        "tenants": {"hostile": hostile, "victim": victim},
        "autopilot": {
            "ticks": ap_desc.get("ticks", 0),
            "actions_total": ap_desc.get("actions_total", 0),
            "errors_total": ap_desc.get("errors_total", 0),
            "rollbacks_total": ap_desc.get("policy", {}).get(
                "rollbacks_total", 0),
            "action_kinds": kinds,
            "first_action_s": first_action_s,
            "decisions": decisions,
            "http_status": ap_http_status,
        },
        "audit": {
            "autopilot_records": len(audit_recs),
            "decisions_total": len(decisions),
            "complete": len(audit_recs) >= min(
                len(decisions), cfg.events.audit_capacity),
        },
        "tenant_slo": tenant_slo,
        "tenants_endpoint_status": tenants_http_status,
        "usage": usage,
        "scale_state": scale_state,
    }
