"""ctypes binding for the native JPEG->YUV420 decode shim (SURVEY.md §2 C12).

The shim (native/decode/jpegyuv.c) entropy-decodes baseline 4:2:0 JPEGs into
raw Y/Cb/Cr planes — no chroma upsample, no RGB conversion — so the host
ships 1.5 B/px over the wire instead of 3 B/px and the device does the color
math (tpuserve.preproc.device_prepare_images_yuv420). ctypes releases the
GIL for the call, so decode threads scale on multi-core hosts.

``load()`` builds the .so on first use (make, ~1s) and returns None when the
toolchain or libjpeg is absent — callers fall back to the PIL RGB path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

import numpy as np

from tpuserve.utils.locks import new_lock

log = logging.getLogger("tpuserve.native")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                           "native", "decode")
_SO_PATH = os.path.join(_NATIVE_DIR, "libjpegyuv.so")

_lock = new_lock("native.decoder")
_lib = None
_load_failed = False


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=120)
        return True
    except Exception as e:
        log.warning("jpegyuv shim build failed (falling back to PIL): %s", e)
        return False


def load():
    """Return the loaded shim library, or None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_SO_PATH) and not _build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:
            log.warning("jpegyuv shim load failed: %s", e)
            _load_failed = True
            return None
        lib.jpegyuv_decode.restype = ctypes.c_int
        lib.jpegyuv_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
        ]
        lib.jpegyuv_probe.restype = ctypes.c_int
        lib.jpegyuv_probe.argtypes = [
            ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def decode_yuv420(payload: bytes, edge: int):
    """Decode an edge x edge 4:2:0 JPEG to (y, u, v) uint8 planes.

    Returns None when the shim is unavailable or the file isn't an exact-size
    4:2:0 baseline JPEG — the caller falls back to PIL (decode + re-subsample
    or RGB wire).
    """
    lib = load()
    if lib is None:
        return None
    half = edge // 2
    y = np.empty((edge, edge), dtype=np.uint8)
    u = np.empty((half, half), dtype=np.uint8)
    v = np.empty((half, half), dtype=np.uint8)
    rc = lib.jpegyuv_decode(
        payload, len(payload),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        u.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        v.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        edge,
    )
    if rc != 0:
        return None
    return y, u, v
