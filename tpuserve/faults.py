"""Fault injection + recovery machinery (ISSUE 1; docs/ROBUSTNESS.md).

At serving scale the common case is partial failure — a poisoned batch, a
hung deferred worker, a dead group loop — not a clean crash. This module
holds both sides of that story:

- **FaultInjector**: a deterministic, config-driven chaos layer (replacing
  the ad-hoc ``fault_hook`` the batcher used to carry). Rules
  (``[[faults.rule]]`` in TOML, ``FaultRuleConfig``) name a *kind* — a call
  site on the serving path — plus model / probability / count, and draw from
  rule-local seeded RNGs so a chaos run replays exactly. Call sites live in
  the batcher (batch_error, slow_dispatch, kill_group_loop), the runtime
  (device_error, slow_compute), the deferred pool (worker_death), the
  server (decode_corrupt, canary_fail, plus the process-boundary kinds
  worker_slow / worker_hang / worker_crash that degrade, wedge, or
  os._exit the serving process — behind the router split
  (tpuserve.workerproc) they prove hedging/retry/supervision, drilled by
  ``tpuserve chaos --drill worker_kill``), and the reload lifecycle
  (reload_corrupt / reload_nan at the staging gates in
  ModelRuntime.stage_params, reload_regressed at the staged canary in
  tpuserve.lifecycle — drill them with ``tpuserve chaos --drill reload``).

- **CircuitBreaker**: per-model, trips to fast 503 + ``Retry-After`` after N
  consecutive failed dispatches; half-opens via the existing canary path
  (canaries keep riding the batcher while open; the first success closes).
  The fleet isolation drill (``tpuserve chaos --drill fleet``,
  tpuserve.scheduler.drill) poisons one model's dispatches with
  ``device_error`` at 100% under multi-model load and asserts this breaker
  contains the blast radius: the victim trips while every other model
  holds its SLO.

- **Watchdog**: periodic sweep that restarts dead group-accumulation tasks
  and reaps/replenishes dead deferred workers, with restart counters in
  ``/metrics`` (``watchdog_restarts_total{model=...,component=...}``).

- **run_chaos**: the ``python -m tpuserve chaos`` backend — serve a
  fault-injected config on an ephemeral port, drive the load generator at
  it, and report availability + injection counts.

The batch-retry policy itself lives in ``tpuserve.batcher`` (it owns the
dispatch path); graceful drain lives in ``tpuserve.server`` (it owns the
accept path). Both are exercised by tests/test_faults.py.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Callable

from tpuserve.config import FaultRuleConfig, FaultsConfig
from tpuserve.obs import BREAKER_STATES, Metrics
from tpuserve.utils.locks import new_lock

log = logging.getLogger("tpuserve.faults")


class FaultInjected(RuntimeError):
    """An injected chaos fault, not a real serving failure."""


class _ArmedRule:
    """One rule plus its mutable firing state (RNG, remaining budget)."""

    def __init__(self, cfg: FaultRuleConfig, derived_seed: int) -> None:
        self.cfg = cfg
        self.rng = random.Random(cfg.seed if cfg.seed else derived_seed)
        self.remaining = cfg.count  # -1 = unlimited
        self.fired = 0

    def matches(self, kind: str, model: str) -> bool:
        return self.cfg.kind == kind and self.cfg.model in ("*", model)

    def draw(self) -> bool:
        if self.remaining == 0:
            return False
        if self.cfg.probability < 1.0 and self.rng.random() >= self.cfg.probability:
            return False
        if self.remaining > 0:
            self.remaining -= 1
        self.fired += 1
        return True


class FaultInjector:
    """Deterministic config-driven fault injection for the serving path.

    Thread-safe: call sites run on the event loop, in the decode/fetch
    threadpool (runtime.run), and in deferred readers."""

    def __init__(self, cfg: FaultsConfig, metrics: Metrics | None = None) -> None:
        self.cfg = cfg
        self.metrics = metrics
        self._lock = new_lock("faults.FaultInjector")
        # Epoch for rule.after_s gating: rules with after_s > 0 stay cold
        # until the injector has been alive that long, so a drill can arm a
        # fault that reproducibly fires MID-load rather than from boot.
        self._born = time.monotonic()
        # Worker-process id for rule.worker pinning (set by the serving
        # process under the router split); None/-1 rules match any process.
        self.worker_id: int | None = None
        # Derived seeds keep distinct rules decorrelated even when the
        # operator leaves every rule.seed at 0.
        self._rules = [_ArmedRule(r, cfg.seed * 1000003 + i + 1)
                       for i, r in enumerate(cfg.rules)]

    @classmethod
    def single(cls, kind: str, model: str = "*", probability: float = 1.0,
               count: int = -1, delay_ms: float = 0.0, seed: int = 0,
               metrics: Metrics | None = None) -> "FaultInjector":
        """One-rule injector (test/REPL convenience)."""
        rule = FaultRuleConfig(kind=kind, model=model, probability=probability,
                               count=count, delay_ms=delay_ms, seed=seed)
        return cls(FaultsConfig(enabled=True, seed=seed, rules=[rule]), metrics)

    def set_enabled(self, enabled: bool) -> None:
        """Flip injection live (chaos tests stop injecting mid-run)."""
        self.cfg.enabled = enabled

    def fire(self, kind: str, model: str) -> FaultRuleConfig | None:
        """First matching armed rule that draws true, or None."""
        if not self.cfg.enabled:
            return None
        with self._lock:
            alive_s = time.monotonic() - self._born
            for rule in self._rules:
                if rule.cfg.after_s > 0 and alive_s < rule.cfg.after_s:
                    continue
                if rule.cfg.worker >= 0 and rule.cfg.worker != self.worker_id:
                    continue
                if rule.matches(kind, model) and rule.draw():
                    if self.metrics is not None:
                        self.metrics.counter(
                            f"faults_injected_total{{model={model},kind={kind}}}").inc()
                    return rule.cfg
        return None

    def check(self, kind: str, model: str) -> None:
        """Raise FaultInjected when an armed rule fires at this call site."""
        if self.fire(kind, model) is not None:
            raise FaultInjected(f"injected fault: {kind} ({model})")

    def delay_s(self, kind: str, model: str) -> float:
        """Injected sleep for the slow_* kinds; 0.0 when nothing fires."""
        rule = self.fire(kind, model)
        return rule.delay_ms / 1e3 if rule is not None else 0.0

    def snapshot(self) -> list[dict]:
        """Per-rule firing state for /stats and chaos-run reports."""
        with self._lock:
            return [{
                "kind": r.cfg.kind,
                "model": r.cfg.model,
                "probability": r.cfg.probability,
                "fired": r.fired,
                "remaining": r.remaining,
            } for r in self._rules]


class CircuitBreaker:
    """Per-model breaker over consecutive failed dispatches.

    closed --(threshold consecutive failures)--> open
    open   --(canary probe admitted)-----------> half_open
    open/half_open --(any recorded success)----> closed

    While open/half-open the server sheds that model's traffic with a fast
    503 + ``Retry-After`` *before* reading the request body, so a tripped
    model costs microseconds, not a doomed dispatch. Recovery is driven by
    the canary path: ``run_canary`` keeps submitting through the batcher
    regardless of breaker state, and the first successful dispatch closes
    the breaker (within 2 canary intervals of the fault clearing)."""

    def __init__(self, model: str, threshold: int,
                 metrics: Metrics | None = None,
                 retry_after_s: float = 5.0) -> None:
        self.model = model
        self.threshold = threshold
        self.metrics = metrics
        self.retry_after_s = retry_after_s
        self._lock = new_lock("faults.CircuitBreaker")
        self.state = "closed"
        self.consecutive_errors = 0
        self.opened_total = 0
        self.shed_total = 0
        self._set_gauge()

    def allow(self) -> bool:
        """May normal (non-canary) traffic reach this model's batcher?"""
        if self.threshold <= 0:
            return True
        return self.state == "closed"

    def record_success(self) -> None:
        with self._lock:
            self.consecutive_errors = 0
            changed = self.state != "closed"
            self.state = "closed"
        if changed:
            log.info("breaker for %s closed (recovered)", self.model)
            self._set_gauge()

    def record_failure(self) -> None:
        if self.threshold <= 0:
            return
        with self._lock:
            self.consecutive_errors += 1
            was = self.state
            if was == "half_open":
                self.state = "open"  # failed probe: back to shedding
            elif was == "closed" and self.consecutive_errors >= self.threshold:
                self.state = "open"
                self.opened_total += 1
        if was != self.state:
            log.warning("breaker for %s opened after %d consecutive failures",
                        self.model, self.consecutive_errors)
            self._set_gauge()
        elif was == "half_open":
            self._set_gauge()

    def probe(self) -> None:
        """A canary was admitted while tripped: open -> half_open."""
        with self._lock:
            changed = self.state == "open"
            if changed:
                self.state = "half_open"
        if changed:
            self._set_gauge()

    def on_shed(self) -> None:
        """One request answered 503 because the breaker is not closed."""
        with self._lock:
            self.shed_total += 1
        if self.metrics is not None:
            self.metrics.counter(
                f"breaker_shed_total{{model={self.model}}}").inc()

    def _set_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge(
                f"breaker_state{{model={self.model}}}").set(BREAKER_STATES[self.state])

    def describe(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "threshold": self.threshold,
                "consecutive_errors": self.consecutive_errors,
                "opened_total": self.opened_total,
                "shed_total": self.shed_total,
            }


class Watchdog:
    """Periodic sweep restarting dead serving machinery.

    Components register a sweep callable returning how many restarts (or
    reaps of un-retired dead workers) it performed; non-zero sweeps land in
    ``watchdog_restarts_total{model=...,component=...}``. Registered sweeps
    run on the event loop and must be non-blocking."""

    def __init__(self, interval_s: float, metrics: Metrics) -> None:
        self.interval_s = interval_s
        self.metrics = metrics
        self._targets: list[tuple[str, str, Callable[[], int]]] = []
        self._task: asyncio.Task | None = None

    def register(self, model: str, component: str, sweep: Callable[[], int]) -> None:
        self._targets.append((model, component, sweep))

    def start(self) -> None:
        if self.interval_s > 0 and self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                self.sweep()
            except asyncio.CancelledError:
                raise
            except Exception:  # one bad sweep must not end the watchdog
                log.exception("watchdog sweep failed")

    def sweep(self) -> int:
        """Run every registered sweep once; returns total restarts."""
        total = 0
        for model, component, fn in self._targets:
            try:
                n = fn()
            except Exception:
                log.exception("watchdog sweep for %s/%s failed", model, component)
                continue
            if n:
                log.warning("watchdog restarted %d %s for %s", n, component, model)
                self.metrics.counter(
                    f"watchdog_restarts_total{{model={model},component={component}}}").inc(n)
                total += n
        return total


# ---------------------------------------------------------------------------
# Chaos-run harness (python -m tpuserve chaos)
# ---------------------------------------------------------------------------

async def run_chaos(state, model_name: str, duration_s: float = 10.0,
                    warmup_s: float = 1.0, concurrency: int = 16,
                    rate_per_s: float | None = None, verb: str = "predict",
                    edge: int = 256, drill: str | None = None,
                    drill_interval_s: float = 0.5) -> dict:
    """Serve ``state`` on an ephemeral local port, drive the load generator
    at one model, and report availability + per-rule injection counts.

    The server must be built (``state.build()``) but not started; this owns
    its lifecycle. Intended for staging chaos drills: arm ``[faults]`` rules
    in the config and assert the availability number here, not in prod.

    ``drill="reload"`` additionally hammers ``:reload`` every
    ``drill_interval_s`` throughout the run — with ``reload_corrupt`` /
    ``reload_nan`` / ``reload_regressed`` rules armed this proves the
    lifecycle gates hold availability while every reload is failing; the
    summary carries the reload outcomes and final lifecycle state."""
    import aiohttp
    from aiohttp import web

    from tpuserve.bench.loadgen import run_load, run_load_open, synthetic_image_npy
    from tpuserve.server import make_app

    app = make_app(state)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    drill_task = None
    reload_stats = {"attempts": 0, "ok": 0, "rejected": 0, "rolled_back": 0,
                    "errors": 0}

    async def reload_driller(base: str) -> None:
        async with aiohttp.ClientSession() as session:
            while True:
                await asyncio.sleep(drill_interval_s)
                reload_stats["attempts"] += 1
                try:
                    async with session.post(
                            f"{base}/admin/models/{model_name}:reload") as r:
                        body = await r.json()
                        if r.status == 200:
                            reload_stats["ok"] += 1
                        elif body.get("rolled_back"):
                            reload_stats["rolled_back"] += 1
                        else:
                            reload_stats["rejected"] += 1
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — drill races teardown
                    reload_stats["errors"] += 1

    try:
        port = runner.addresses[0][1]
        base = f"http://127.0.0.1:{port}"
        url = f"{base}/v1/models/{model_name}:{verb}"
        payload = synthetic_image_npy(edge=edge)
        if drill == "reload":
            drill_task = asyncio.get_running_loop().create_task(
                reload_driller(base))
        if rate_per_s:
            result = await run_load_open(url, payload, "application/x-npy",
                                         rate_per_s, duration_s, warmup_s)
        else:
            result = await run_load(url, payload, "application/x-npy",
                                    duration_s, concurrency, warmup_s)
    finally:
        if drill_task is not None:
            drill_task.cancel()
            try:
                await drill_task
            except asyncio.CancelledError:
                pass
        # Snapshot lifecycle state BEFORE cleanup tears the server down.
        lifecycle_out = {n: lc.describe()
                         for n, lc in state.lifecycles.items()}
        await runner.cleanup()
    out = result.summary()
    total = result.n_ok + result.n_err
    out["availability"] = round(result.n_ok / total, 5) if total else 0.0
    if state.injector is not None:
        out["faults"] = state.injector.snapshot()
    out["breakers"] = {n: br.describe() for n, br in state.breakers.items()}
    if lifecycle_out:
        out["lifecycle"] = lifecycle_out
    if drill is not None:
        out["reload_drill"] = reload_stats
    return out
