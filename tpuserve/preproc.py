"""Input preprocessing (SURVEY.md §2 C3).

Split deliberately across the host/device boundary (BASELINE.json north-star:
"Image decode/resize preprocessing moves on-device so the host only handles
HTTP and JSON"):

- Host (threadpool): byte decode only — JPEG/PNG -> uint8 RGB (Pillow; an
  optional C++ libjpeg-turbo shim slots in behind the same function, SURVEY.md
  C12), raw tensor parsing, JSON parsing. No resize, no float math.
- Device (inside the jitted forward): resize to model resolution, dtype cast,
  normalize — fused by XLA into the first conv's pipeline, so uint8 images
  cross PCIe (3x smaller than f32) and HBM sees bf16.

Host decode emits a fixed "wire shape" (DECODE_EDGE^2 uint8) so one XLA
executable serves arbitrary client image sizes: Pillow does a cheap
nearest-ish downscale to the wire shape only when the client image is larger;
the precise bilinear resize to the model's input size happens on device.
"""

from __future__ import annotations

import io

import jax
import jax.numpy as jnp
import numpy as np

# Wire shape edge for images: host sends (E, E, 3) uint8; device resizes to
# the model size. 256 covers 224/240/260-class models with margin for crops.
DECODE_EDGE = 256

# ImageNet normalization constants (standard publication values).
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


# -- host side ---------------------------------------------------------------

def decode_image(payload: bytes, content_type: str = "", edge: int = DECODE_EDGE) -> np.ndarray:
    """Bytes -> (edge, edge, 3) uint8 RGB. Runs in the decode threadpool.

    Accepts JPEG/PNG/etc via Pillow, or a raw npy tensor
    (content_type == "application/x-npy") of shape (H, W, 3) uint8.
    """
    if content_type == "application/x-npy":
        arr = np.load(io.BytesIO(payload), allow_pickle=False)
        return decode_image_array(arr, edge)
    from PIL import Image

    with Image.open(io.BytesIO(payload)) as im:
        im = im.convert("RGB")
        if im.size != (edge, edge):
            im = im.resize((edge, edge), Image.BILINEAR)
        return np.asarray(im, dtype=np.uint8)


def decode_npy_items(payload: bytes, edge: int, max_items: int):
    """npy body -> (items, is_batch) with ONE parse: a (N, H, W, 3) tensor is
    a client batch of N, an (H, W, 3) tensor a single item."""
    arr = np.load(io.BytesIO(payload), allow_pickle=False)
    if arr.ndim == 4:
        if arr.shape[0] > max_items:
            raise ValueError(
                f"batch of {arr.shape[0]} exceeds the per-request limit ({max_items})")
        return [decode_image_array(a, edge) for a in arr], True
    return [decode_image_array(arr, edge)], False


def decode_image_array(arr: np.ndarray, edge: int) -> np.ndarray:
    """In-memory (H, W, 3) uint8 -> (edge, edge, 3) uint8 (shared by the
    single-image npy body and each element of a batched (N, H, W, 3) body)."""
    if arr.ndim != 3 or arr.shape[-1] != 3:
        raise ValueError(f"raw tensor must be (H, W, 3), got {arr.shape}")
    if arr.dtype != np.uint8:
        raise ValueError(f"raw tensor must be uint8 (0-255), got {arr.dtype}")
    if arr.shape[:2] != (edge, edge):
        arr = _resize_uint8(arr, edge)
    return arr


def _resize_uint8(img: np.ndarray, edge: int) -> np.ndarray:
    from PIL import Image

    return np.asarray(Image.fromarray(img).resize((edge, edge), Image.BILINEAR), dtype=np.uint8)


# -- device side (call inside jitted forward) --------------------------------

def device_prepare_images(
    batch_u8: jax.Array,
    size: int,
    dtype=jnp.bfloat16,
    mean=IMAGENET_MEAN,
    std=IMAGENET_STD,
) -> jax.Array:
    """(B, E, E, 3) uint8 -> (B, size, size, 3) normalized `dtype`.

    Resize (bilinear) + scale + normalize, all on device; XLA fuses the
    elementwise tail into the consumer conv.
    """
    x = batch_u8.astype(jnp.float32) / 255.0
    if batch_u8.shape[1] != size or batch_u8.shape[2] != size:
        b, _, _, c = batch_u8.shape
        x = jax.image.resize(x, (b, size, size, c), method="bilinear")
    mean_a = jnp.asarray(mean, dtype=jnp.float32)
    std_a = jnp.asarray(std, dtype=jnp.float32)
    x = (x - mean_a) / std_a
    return x.astype(dtype)


def device_prepare_images_yuv420(
    y_u8: jax.Array,
    u_u8: jax.Array,
    v_u8: jax.Array,
    size: int,
    dtype=jnp.bfloat16,
    mean=IMAGENET_MEAN,
    std=IMAGENET_STD,
) -> jax.Array:
    """YUV 4:2:0 planes -> (B, size, size, 3) normalized `dtype`, on device.

    The host ships what the JPEG already stores — full-res luma (B, E, E) and
    2x2-subsampled chroma (B, E/2, E/2) — at 1.5 B/px instead of RGB's 3 B/px
    (half the host->device wire bytes, the serving bottleneck on thin links).
    Chroma upsample (bilinear), BT.601 full-range YCbCr->RGB, resize, and
    normalization all fuse into the model executable; no fidelity is lost
    relative to host-side RGB conversion of the same JPEG.
    """
    b, e, _ = y_u8.shape
    yf = y_u8.astype(jnp.float32)
    uf = jax.image.resize(u_u8.astype(jnp.float32), (b, e, e), method="bilinear")
    vf = jax.image.resize(v_u8.astype(jnp.float32), (b, e, e), method="bilinear")
    # BT.601 full-range (JFIF) inverse transform.
    cb = uf - 128.0
    cr = vf - 128.0
    r = yf + 1.402 * cr
    g = yf - 0.344136 * cb - 0.714136 * cr
    bl = yf + 1.772 * cb
    x = jnp.stack([r, g, bl], axis=-1)
    x = jnp.clip(x, 0.0, 255.0) / 255.0
    if e != size:
        x = jax.image.resize(x, (b, size, size, 3), method="bilinear")
    mean_a = jnp.asarray(mean, dtype=jnp.float32)
    std_a = jnp.asarray(std, dtype=jnp.float32)
    x = (x - mean_a) / std_a
    return x.astype(dtype)


# Native-fallback observability hook (ISSUE 11 satellite): installed by the
# server (ServerState.start) to tick native_decode_fallback_total{model=}
# whenever the libjpeg shim path was attempted but the slow PIL re-subsample
# path served instead — a missing/failed libjpegyuv.so is ~2x slower per
# JPEG and must never be silent. None (tests, offline tools) = no counting.
_native_fallback_hook = None


def set_native_fallback_hook(cb) -> None:
    """Install ``cb(model_name)`` as the native-decode fallback observer
    (thread-safe: decode runs in the threadpool / ingest loops)."""
    global _native_fallback_hook
    _native_fallback_hook = cb


def _note_native_fallback(model: str) -> None:
    cb = _native_fallback_hook
    if cb is not None:
        cb(model)


def decode_image_yuv420(payload: bytes, content_type: str, edge: int,
                        model: str = "") -> tuple:
    """Bytes -> (y, u, v) uint8 planes at the wire edge (threadpool).

    Fast path: the native libjpeg shim decodes exact-size 4:2:0 JPEGs
    straight to planes. Fallback (non-JPEG, size mismatch, no shim): PIL
    decode -> YCbCr -> numpy re-subsample, so the wire contract holds for
    every input the RGB path accepts — but it is ~2x slower, so every
    fallback on a native-eligible request is counted via
    ``native_decode_fallback_total{model=}`` (the ``model`` arg labels it).
    """
    if content_type not in ("application/x-npy",):
        from tpuserve import native

        res = native.decode_yuv420(payload, edge)
        if res is not None:
            return res
        # The native path was attempted and declined (shim missing, build
        # failed, or not an exact-size baseline 4:2:0 JPEG): the 2x-slower
        # PIL path serves this request, visibly.
        _note_native_fallback(model)
    rgb = decode_image(payload, content_type, edge=edge)
    return rgb_to_yuv420(rgb)


def rgb_to_yuv420(rgb: np.ndarray):
    """(E, E, 3) uint8 RGB -> (y, u, v) uint8 planes (host fallback path)."""
    f = rgb.astype(np.float32)
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    y = 0.299 * r + 0.587 * g + 0.114 * b
    cb = 128.0 - 0.168736 * r - 0.331264 * g + 0.5 * b
    cr = 128.0 + 0.5 * r - 0.418688 * g - 0.081312 * b
    # 2x2 mean-pool the chroma planes.
    e = rgb.shape[0]
    cb = cb.reshape(e // 2, 2, e // 2, 2).mean(axis=(1, 3))
    cr = cr.reshape(e // 2, 2, e // 2, 2).mean(axis=(1, 3))
    return (
        np.clip(y + 0.5, 0, 255).astype(np.uint8),
        np.clip(cb + 0.5, 0, 255).astype(np.uint8),
        np.clip(cr + 0.5, 0, 255).astype(np.uint8),
    )
