"""Input preprocessing (SURVEY.md §2 C3).

Split deliberately across the host/device boundary (BASELINE.json north-star:
"Image decode/resize preprocessing moves on-device so the host only handles
HTTP and JSON"):

- Host (threadpool): byte decode only — JPEG/PNG -> uint8 RGB (Pillow; an
  optional C++ libjpeg-turbo shim slots in behind the same function, SURVEY.md
  C12), raw tensor parsing, JSON parsing. No resize, no float math.
- Device (inside the jitted forward): resize to model resolution, dtype cast,
  normalize — fused by XLA into the first conv's pipeline, so uint8 images
  cross PCIe (3x smaller than f32) and HBM sees bf16.

Host decode emits a fixed "wire shape" (DECODE_EDGE^2 uint8) so one XLA
executable serves arbitrary client image sizes: Pillow does a cheap
nearest-ish downscale to the wire shape only when the client image is larger;
the precise bilinear resize to the model's input size happens on device.
"""

from __future__ import annotations

import io

import jax
import jax.numpy as jnp
import numpy as np

# Wire shape edge for images: host sends (E, E, 3) uint8; device resizes to
# the model size. 256 covers 224/240/260-class models with margin for crops.
DECODE_EDGE = 256

# ImageNet normalization constants (standard publication values).
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


# -- host side ---------------------------------------------------------------

def decode_image(payload: bytes, content_type: str = "", edge: int = DECODE_EDGE) -> np.ndarray:
    """Bytes -> (edge, edge, 3) uint8 RGB. Runs in the decode threadpool.

    Accepts JPEG/PNG/etc via Pillow, or a raw npy tensor
    (content_type == "application/x-npy") of shape (H, W, 3) uint8.
    """
    if content_type == "application/x-npy":
        arr = np.load(io.BytesIO(payload), allow_pickle=False)
        if arr.ndim != 3 or arr.shape[-1] != 3:
            raise ValueError(f"raw tensor must be (H, W, 3), got {arr.shape}")
        if arr.dtype != np.uint8:
            raise ValueError(f"raw tensor must be uint8 (0-255), got {arr.dtype}")
        img = arr
        if img.shape[:2] != (edge, edge):
            img = _resize_uint8(img, edge)
        return img
    from PIL import Image

    with Image.open(io.BytesIO(payload)) as im:
        im = im.convert("RGB")
        if im.size != (edge, edge):
            im = im.resize((edge, edge), Image.BILINEAR)
        return np.asarray(im, dtype=np.uint8)


def _resize_uint8(img: np.ndarray, edge: int) -> np.ndarray:
    from PIL import Image

    return np.asarray(Image.fromarray(img).resize((edge, edge), Image.BILINEAR), dtype=np.uint8)


# -- device side (call inside jitted forward) --------------------------------

def device_prepare_images(
    batch_u8: jax.Array,
    size: int,
    dtype=jnp.bfloat16,
    mean=IMAGENET_MEAN,
    std=IMAGENET_STD,
) -> jax.Array:
    """(B, E, E, 3) uint8 -> (B, size, size, 3) normalized `dtype`.

    Resize (bilinear) + scale + normalize, all on device; XLA fuses the
    elementwise tail into the consumer conv.
    """
    x = batch_u8.astype(jnp.float32) / 255.0
    if batch_u8.shape[1] != size or batch_u8.shape[2] != size:
        b, _, _, c = batch_u8.shape
        x = jax.image.resize(x, (b, size, size, c), method="bilinear")
    mean_a = jnp.asarray(mean, dtype=jnp.float32)
    std_a = jnp.asarray(std, dtype=jnp.float32)
    x = (x - mean_a) / std_a
    return x.astype(dtype)
