"""EfficientDet-D0 object detection (SURVEY.md §2 C4, §3f; BASELINE.json
config 4) — the multi-output + NMS-postproc family.

TPU-first shaping decisions (SURVEY.md §7 hard part 4):
- **Everything static.** The classic detection tail (score filter -> sort ->
  NMS -> variable-length result) is dynamic-shape hostile. Here the whole
  tail runs on device with fixed shapes: top-``pre_nms`` candidate selection
  by ``lax.top_k``, a pairwise-IoU matrix, and a ``lax.scan`` greedy
  suppression loop emitting exactly ``max_dets`` slots plus a valid count.
  The HTTP layer slices/filters on the host from that fixed (max_dets, 6)
  array — no device round-trips, no recompiles, ever.
- **Per-class NMS via coordinate offsetting**: candidate boxes are shifted by
  ``class_id * 2.0`` (boxes are normalized to [0,1]) before the IoU matrix,
  so boxes of different classes never overlap — one class-agnostic kernel
  does per-class NMS. One detection per anchor (argmax class), the standard
  "fast" variant.
- Backbone EfficientNet-B0 (MBConv + squeeze-excite, swish), BiFPN with fast
  normalized fusion, separable-conv class/box heads shared across levels with
  per-level BatchNorm — the D0 configuration (64 fpn channels, 3 BiFPN
  repeats, 3 head layers, 9 anchors/cell, levels P3..P7).
- bf16 compute in convs; box decode, scoring, and NMS in f32.

Sizes come from ``cfg.options`` so tests run a tiny variant on CPU:
``det_classes`` (90), ``fpn_channels`` (64), ``fpn_repeats`` (3),
``head_repeats`` (3), ``min_level``/``max_level`` (3/7), ``pre_nms`` (1024),
``max_dets`` (100), ``iou_thresh`` (0.5), ``score_thresh`` (0.05),
``anchor_scale`` (4.0), ``backbone_width``/``backbone_depth`` (1.0/1.0).
"""

from __future__ import annotations

import math
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tpuserve.config import ModelConfig
from tpuserve.models.vision import ImageClassifierServing

# (expand_ratio, channels, repeats, stride, kernel) — EfficientNet-B0 table.
B0_BLOCKS: tuple = (
    (1, 16, 1, 1, 3),
    (6, 24, 2, 2, 3),
    (6, 40, 2, 2, 5),
    (6, 80, 3, 2, 3),
    (6, 112, 3, 1, 5),
    (6, 192, 4, 2, 5),
    (6, 320, 1, 1, 3),
)


def _round_filters(ch: int, width: float) -> int:
    if width == 1.0:
        return ch
    ch *= width
    new = max(8, int(ch + 4) // 8 * 8)
    if new < 0.9 * ch:
        new += 8
    return int(new)


def _round_repeats(r: int, depth: float) -> int:
    return int(math.ceil(r * depth))


class MBConv(nn.Module):
    """Mobile inverted bottleneck with squeeze-excite (EfficientNet block)."""

    expand: int
    out: int
    stride: int
    kernel: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        bn = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=True, momentum=0.99, epsilon=1e-3,
            dtype=self.dtype, name=name)
        inp = x.shape[-1]
        mid = inp * self.expand
        h = x
        if self.expand != 1:
            h = nn.swish(bn("bn_expand")(nn.Conv(
                mid, (1, 1), use_bias=False, dtype=self.dtype, name="expand")(h)))
        h = nn.swish(bn("bn_dw")(nn.Conv(
            mid, (self.kernel, self.kernel), strides=(self.stride, self.stride),
            padding="SAME", feature_group_count=mid, use_bias=False,
            dtype=self.dtype, name="depthwise")(h)))
        # Squeeze-excite at ratio 0.25 of the *input* channels (B0 spec).
        s = jnp.mean(h, axis=(1, 2), keepdims=True)
        se_mid = max(1, inp // 4)
        s = nn.swish(nn.Conv(se_mid, (1, 1), dtype=self.dtype, name="se_reduce")(s))
        s = nn.sigmoid(nn.Conv(mid, (1, 1), dtype=self.dtype, name="se_expand")(s))
        h = h * s
        h = bn("bn_project")(nn.Conv(
            self.out, (1, 1), use_bias=False, dtype=self.dtype, name="project")(h))
        if self.stride == 1 and inp == self.out:
            h = h + x
        return h


class EfficientNetFeatures(nn.Module):
    """EfficientNet backbone returning {level: feature} for levels 3..5
    (strides 8/16/32). Width/depth multipliers give the tiny test variant."""

    width: float = 1.0
    depth: float = 1.0
    blocks: Sequence = B0_BLOCKS
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        bn = nn.BatchNorm(use_running_average=True, momentum=0.99, epsilon=1e-3,
                          dtype=self.dtype, name="bn_stem")
        x = nn.swish(bn(nn.Conv(_round_filters(32, self.width), (3, 3),
                                strides=(2, 2), padding="SAME", use_bias=False,
                                dtype=self.dtype, name="stem")(x)))
        feats = {}
        level, bi = 1, 0  # stem is stride 2 = level 1; first block group keeps it
        for gi, (e, c, r, s, k) in enumerate(self.blocks):
            c = _round_filters(c, self.width)
            r = _round_repeats(r, self.depth)
            if s == 2:
                level += 1
            for j in range(r):
                x = MBConv(e, c, s if j == 0 else 1, k, dtype=self.dtype,
                           name=f"block{bi}")(x)
                bi += 1
            # A level's final feature is the last block at that stride before
            # the next downsampling group.
            nxt = self.blocks[gi + 1][3] if gi + 1 < len(self.blocks) else 2
            if nxt == 2 and level >= 3:
                feats[level] = x
        return feats


class SeparableConv(nn.Module):
    out: int
    dtype: Any = jnp.bfloat16
    bias_init: Any = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(x.shape[-1], (3, 3), padding="SAME",
                    feature_group_count=x.shape[-1], use_bias=False,
                    dtype=self.dtype, name="dw")(x)
        return nn.Conv(self.out, (1, 1), dtype=self.dtype, use_bias=True,
                       bias_init=self.bias_init, name="pw")(h)


def _fuse(nodes: list, name: str, mdl: nn.Module):
    """Fast normalized fusion (EfficientDet eq. 2): relu-weighted mean."""
    w = mdl.param(name, nn.initializers.ones_init(), (len(nodes),), jnp.float32)
    w = nn.relu(w)
    w = w / (jnp.sum(w) + 1e-4)
    return sum(w[i].astype(nodes[i].dtype) * nodes[i] for i in range(len(nodes)))


def _resize_to(x, like):
    if x.shape[1:3] == like.shape[1:3]:
        return x
    if x.shape[1] > like.shape[1]:  # downsample: stride-2 max pool
        return nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
    return jax.image.resize(x, (x.shape[0],) + like.shape[1:3] + (x.shape[-1],),
                            method="nearest")


class BiFPNLayer(nn.Module):
    channels: int
    levels: Sequence[int]
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, feats: dict) -> dict:
        bn = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=True, momentum=0.99, epsilon=1e-3,
            dtype=self.dtype, name=name)
        conv = lambda name: SeparableConv(self.channels, dtype=self.dtype, name=name)  # noqa: E731
        lv = list(self.levels)
        # Top-down pass: td[l] = fuse(in[l], up(td[l+1]))
        td = {lv[-1]: feats[lv[-1]]}
        for l in reversed(lv[:-1]):
            up = _resize_to(td[l + 1], feats[l])
            td[l] = nn.swish(bn(f"bn_td{l}")(conv(f"td{l}")(
                _fuse([feats[l], up], f"w_td{l}", self))))
        # Bottom-up pass: out[l] = fuse(in[l], td[l], down(out[l-1]))
        out = {lv[0]: td[lv[0]]}
        for l in lv[1:]:
            down = _resize_to(out[l - 1], feats[l])
            nodes = [feats[l], down] if l == lv[-1] else [feats[l], td[l], down]
            out[l] = nn.swish(bn(f"bn_out{l}")(conv(f"out{l}")(
                _fuse(nodes, f"w_out{l}", self))))
        return out


class PredictionHead(nn.Module):
    """Class or box net: `repeats` separable convs shared across levels with
    per-level BatchNorm, plus a shared final projection (EfficientDet design)."""

    out_per_anchor: int
    anchors: int
    repeats: int
    levels: Sequence[int]
    final_bias: float = 0.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, feats: dict) -> jax.Array:
        convs = [SeparableConv(feats[self.levels[0]].shape[-1], dtype=self.dtype,
                               name=f"conv{i}") for i in range(self.repeats)]
        final = SeparableConv(
            self.out_per_anchor * self.anchors, dtype=self.dtype,
            bias_init=nn.initializers.constant(self.final_bias), name="final")
        outs = []
        for l in self.levels:
            h = feats[l]
            for i, c in enumerate(convs):
                h = nn.swish(nn.BatchNorm(
                    use_running_average=True, momentum=0.99, epsilon=1e-3,
                    dtype=self.dtype, name=f"bn{i}_l{l}")(c(h)))
            h = final(h)
            b = h.shape[0]
            outs.append(h.reshape(b, -1, self.out_per_anchor))
        return jnp.concatenate(outs, axis=1)  # (B, total_anchors, out)


class EfficientDet(nn.Module):
    num_classes: int
    fpn_channels: int = 64
    fpn_repeats: int = 3
    head_repeats: int = 3
    min_level: int = 3
    max_level: int = 7
    num_anchors: int = 9
    width: float = 1.0
    depth: float = 1.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        levels = list(range(self.min_level, self.max_level + 1))
        feats = EfficientNetFeatures(self.width, self.depth, dtype=self.dtype,
                                     name="backbone")(x)
        bn = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=True, momentum=0.99, epsilon=1e-3,
            dtype=self.dtype, name=name)
        # Lateral 1x1 to fpn_channels; extra levels (P6, P7) from P5.
        p = {}
        for l in [lv for lv in levels if lv in feats]:
            p[l] = bn(f"bn_lat{l}")(nn.Conv(self.fpn_channels, (1, 1),
                                            dtype=self.dtype, name=f"lat{l}")(feats[l]))
        top = max(feats)
        prev = p.get(top, feats[top])
        for l in range(top + 1, self.max_level + 1):
            if l == top + 1:
                prev = bn(f"bn_lat{l}")(nn.Conv(self.fpn_channels, (1, 1),
                                                dtype=self.dtype, name=f"lat{l}")(prev))
            p[l] = nn.max_pool(prev, (3, 3), strides=(2, 2), padding="SAME")
            prev = p[l]
        for i in range(self.fpn_repeats):
            p = BiFPNLayer(self.fpn_channels, levels, dtype=self.dtype,
                           name=f"bifpn{i}")(p)
        cls = PredictionHead(self.num_classes, self.num_anchors,
                             self.head_repeats, levels,
                             final_bias=-math.log((1 - 0.01) / 0.01),
                             dtype=self.dtype, name="class_net")(p)
        box = PredictionHead(4, self.num_anchors, self.head_repeats, levels,
                             dtype=self.dtype, name="box_net")(p)
        return cls.astype(jnp.float32), box.astype(jnp.float32)


# -- anchors & the fixed-shape detection tail --------------------------------

def make_anchors(image_size: int, min_level: int, max_level: int,
                 anchor_scale: float = 4.0) -> np.ndarray:
    """(A, 4) [yc, xc, h, w] in pixels: 3 octave scales x 3 aspect ratios per
    cell per level — the EfficientDet anchor grid."""
    out = []
    for level in range(min_level, max_level + 1):
        stride = 2 ** level
        # SAME-padded stride-2 convs/pools produce ceil-sized feature maps
        # (repeated ceil-halving == ceil(size / stride)), so the grid must
        # match or top_k indices would clamp against a short anchor table.
        n = max(1, -(-image_size // stride))
        yc, xc = np.meshgrid(
            (np.arange(n) + 0.5) * stride, (np.arange(n) + 0.5) * stride,
            indexing="ij")
        cells = np.stack([yc.ravel(), xc.ravel()], axis=-1)  # (n*n, 2)
        sizes = []
        for octave in (0.0, 1.0 / 3.0, 2.0 / 3.0):
            base = anchor_scale * stride * (2.0 ** octave)
            for ratio in (0.5, 1.0, 2.0):
                sizes.append((base / math.sqrt(ratio), base * math.sqrt(ratio)))
        sizes = np.asarray(sizes)  # (9, 2) h, w
        a = np.concatenate([
            np.repeat(cells, len(sizes), axis=0),
            np.tile(sizes, (len(cells), 1)),
        ], axis=-1)
        out.append(a)
    return np.concatenate(out, axis=0).astype(np.float32)


def decode_boxes(reg: jax.Array, anchors: jax.Array, image_size: int) -> jax.Array:
    """(A, 4) regression [ty, tx, th, tw] + anchors -> normalized corners."""
    yc = reg[:, 0] * anchors[:, 2] + anchors[:, 0]
    xc = reg[:, 1] * anchors[:, 3] + anchors[:, 1]
    h = jnp.exp(jnp.clip(reg[:, 2], -8.0, 8.0)) * anchors[:, 2]
    w = jnp.exp(jnp.clip(reg[:, 3], -8.0, 8.0)) * anchors[:, 3]
    boxes = jnp.stack([yc - h / 2, xc - w / 2, yc + h / 2, xc + w / 2], axis=-1)
    return jnp.clip(boxes / image_size, 0.0, 1.0)


def pairwise_iou(boxes: jax.Array) -> jax.Array:
    """(K, 4) corner boxes -> (K, K) IoU, all static shapes."""
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * jnp.maximum(
        boxes[:, 3] - boxes[:, 1], 0)
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def fixed_nms(boxes: jax.Array, scores: jax.Array, classes: jax.Array,
              max_dets: int, iou_thresh: float, score_thresh: float):
    """Greedy NMS with static shapes: `max_dets` scan steps over a K-candidate
    set, suppressing by a precomputed IoU matrix. Per-class via the
    class-offset trick (boxes normalized to [0,1], offset 2.0 * class)."""
    shifted = boxes + (classes.astype(jnp.float32) * 2.0)[:, None]
    iou = pairwise_iou(shifted)  # (K, K)

    def step(alive, _):
        idx = jnp.argmax(alive)
        s = alive[idx]
        valid = s > score_thresh
        suppress = iou[idx] > iou_thresh  # includes idx itself (IoU 1)
        alive = jnp.where(suppress, 0.0, alive)
        alive = alive.at[idx].set(0.0)
        return alive, (idx, jnp.where(valid, s, 0.0), valid)

    _, (idxs, out_scores, valids) = jax.lax.scan(
        step, scores, None, length=max_dets)
    return {
        "boxes": boxes[idxs],                       # (max_dets, 4)
        "scores": out_scores,                       # (max_dets,)
        "classes": jnp.where(valids, classes[idxs], -1),  # (max_dets,)
        "n": jnp.sum(valids.astype(jnp.int32)),
    }


class EfficientDetServing(ImageClassifierServing):
    """Detection serving: shared vision wire/decode plumbing, detect tail."""

    def __init__(self, cfg: ModelConfig) -> None:
        o = cfg.options
        self.det_classes = int(o.get("det_classes", 90))
        self.pre_nms = int(o.get("pre_nms", 1024))
        self.max_dets = int(o.get("max_dets", 100))
        self.iou_thresh = float(o.get("iou_thresh", 0.5))
        self.score_thresh = float(o.get("score_thresh", 0.05))
        self.min_level = int(o.get("min_level", 3))
        self.max_level = int(o.get("max_level", 7))
        super().__init__(cfg)
        self.anchors = jnp.asarray(make_anchors(
            cfg.image_size, self.min_level, self.max_level,
            float(o.get("anchor_scale", 4.0))))

    def make_module(self, cfg: ModelConfig) -> EfficientDet:
        o = cfg.options
        return EfficientDet(
            num_classes=self.det_classes,
            fpn_channels=int(o.get("fpn_channels", 64)),
            fpn_repeats=int(o.get("fpn_repeats", 3)),
            head_repeats=int(o.get("head_repeats", 3)),
            min_level=self.min_level,
            max_level=self.max_level,
            width=float(o.get("backbone_width", 1.0)),
            depth=float(o.get("backbone_depth", 1.0)),
            dtype=jnp.dtype(cfg.dtype),
        )

    def import_tf_variables(self, flat):
        """Keras-applications EfficientNetB0 -> the backbone subtree.

        There is no canonical TF EfficientDet artifact in this environment,
        but the detector's backbone IS EfficientNet-B0, so a classification
        checkpoint transfers it exactly — the standard detection transfer-
        learning setup. BiFPN and heads keep their seeded init (logged); a
        full-detector orbax checkpoint restores everything.

        Source scheme (``tf.keras.applications.EfficientNetB0``): stem
        ``stem_conv``/``stem_bn``; block ``block{stage}{a,b,...}_{expand_conv,
        expand_bn, dwconv, bn, se_reduce, se_expand, project_conv,
        project_bn}`` (stage-1 blocks have no expand: ratio 1). Depthwise
        kernels transpose (H, W, C, 1) -> (H, W, 1, C); SE convs keep biases;
        the classifier-only ``top_conv``/``top_bn``/``predictions`` and the
        input ``normalization`` stats have no detector counterpart and are
        skipped.
        """
        o = self.cfg.options
        if (float(o.get("backbone_width", 1.0)), float(o.get("backbone_depth", 1.0))) != (1.0, 1.0):
            raise ValueError(
                "EfficientNetB0 import requires backbone_width/depth == 1.0")
        f = {k.split(":")[0]: np.asarray(v) for k, v in flat.items()}

        def conv(name):
            return {"kernel": f[f"{name}/kernel"]}

        def bn(name):
            return (
                {"scale": f[f"{name}/gamma"], "bias": f[f"{name}/beta"]},
                {"mean": f[f"{name}/moving_mean"],
                 "var": f[f"{name}/moving_variance"]},
            )

        bp: dict = {"stem": conv("stem_conv")}
        bs: dict = {}
        bp["bn_stem"], bs["bn_stem"] = bn("stem_bn")
        bi = 0
        for stage, (e, _c, r, _s, _k) in enumerate(B0_BLOCKS, start=1):
            for j in range(r):
                pre = f"block{stage}{'abcdefghij'[j]}"
                p: dict = {}
                st: dict = {}
                if e != 1:
                    p["expand"] = conv(f"{pre}_expand_conv")
                    p["bn_expand"], st["bn_expand"] = bn(f"{pre}_expand_bn")
                dw = f[f"{pre}_dwconv/kernel"]  # (H, W, C, 1)
                p["depthwise"] = {"kernel": dw.transpose(0, 1, 3, 2)}
                p["bn_dw"], st["bn_dw"] = bn(f"{pre}_bn")
                p["se_reduce"] = {"kernel": f[f"{pre}_se_reduce/kernel"],
                                  "bias": f[f"{pre}_se_reduce/bias"]}
                p["se_expand"] = {"kernel": f[f"{pre}_se_expand/kernel"],
                                  "bias": f[f"{pre}_se_expand/bias"]}
                p["project"] = conv(f"{pre}_project_conv")
                p["bn_project"], st["bn_project"] = bn(f"{pre}_project_bn")
                bp[f"block{bi}"] = p
                bs[f"block{bi}"] = st
                bi += 1

        full = self.init_params(jax.random.PRNGKey(0))
        want = full["params"]["backbone"]
        if jax.tree_util.tree_structure(bp) != jax.tree_util.tree_structure(want):
            raise ValueError("imported backbone tree does not match the module")
        for got, exp in zip(jax.tree_util.tree_leaves(bp),
                            jax.tree_util.tree_leaves(want)):
            if got.shape != exp.shape:
                raise ValueError(
                    f"backbone shape mismatch: imported {got.shape} vs "
                    f"module {exp.shape}")
        full["params"]["backbone"] = bp
        full["batch_stats"]["backbone"] = bs
        import logging

        logging.getLogger("tpuserve.models").info(
            "%s: EfficientNetB0 backbone imported; BiFPN/heads keep seeded "
            "init (serve a full-detector orbax checkpoint for end-to-end "
            "weights)", self.name)
        return full

    def forward(self, params: Any, batch: Any) -> dict:
        x = self.device_preprocess(batch)
        cls_logits, box_reg = self.module.apply(params, x)  # (B,A,C), (B,A,4)
        probs = jax.nn.sigmoid(cls_logits)
        best = jnp.max(probs, axis=-1)                      # (B, A)
        best_cls = jnp.argmax(probs, axis=-1).astype(jnp.int32)
        k = min(self.pre_nms, best.shape[1])

        def per_image(scores_a, cls_a, reg_a):
            top_s, top_i = jax.lax.top_k(scores_a, k)
            boxes = decode_boxes(reg_a[top_i], self.anchors[top_i],
                                 self.cfg.image_size)
            return fixed_nms(boxes, top_s, cls_a[top_i],
                             self.max_dets, self.iou_thresh, self.score_thresh)

        return jax.vmap(per_image)(best, best_cls, box_reg)

    def host_postprocess(self, outputs: dict, n_valid: int) -> list[dict]:
        res = []
        for r in range(n_valid):
            n = int(outputs["n"][r])
            dets = []
            for j in range(self.max_dets):
                if outputs["classes"][r][j] < 0:
                    continue
                det = {
                    "box": [round(float(c), 5) for c in outputs["boxes"][r][j]],
                    "score": round(float(outputs["scores"][r][j]), 5),
                    "class": int(outputs["classes"][r][j]),
                }
                label = self.label_for(det["class"])
                if label is not None:
                    det["label"] = label
                dets.append(det)
                if len(dets) == n:
                    break
            res.append({"detections": dets, "num_detections": n})
        return res


def create(cfg: ModelConfig) -> EfficientDetServing:
    return EfficientDetServing(cfg)
