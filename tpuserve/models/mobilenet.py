"""MobileNetV3-Large classifier (SURVEY.md §2 C4; BASELINE.json config 2).

The latency-optimized family: BASELINE.json names it "batch=1
latency-optimized", so the intended serving mode is ``parallelism="replica"``
— one single-device executable per chip with independent queues (SURVEY.md
§2.1 DP mode b), small batch buckets, and a short flush deadline. The serving
plumbing (wire formats, fused on-device preproc/top-k) is shared with the
other vision families via tpuserve.models.vision.

Architecture: MobileNetV3-Large (Howard et al. 2019): hard-swish/ReLU
inverted-residual blocks with optional squeeze-excite, 5x5 depthwise convs in
the later stages, 960->1280 head. Depthwise convs map to TPU fine in NHWC;
squeeze-excite's global pool + tiny denses fuse into the surrounding
elementwise work under XLA.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from tpuserve.config import ModelConfig
from tpuserve.models.vision import ImageClassifierServing


def hard_sigmoid(x):
    return nn.relu6(x + 3.0) / 6.0


def hard_swish(x):
    return x * hard_sigmoid(x)


def _divisible(v: float, d: int = 8) -> int:
    out = max(d, int(v + d / 2) // d * d)
    if out < 0.9 * v:
        out += d
    return out


# (kernel, expanded, out, use_se, use_hs, stride) — MobileNetV3-Large table.
V3_LARGE: tuple = (
    (3, 16, 16, False, False, 1),
    (3, 64, 24, False, False, 2),
    (3, 72, 24, False, False, 1),
    (5, 72, 40, True, False, 2),
    (5, 120, 40, True, False, 1),
    (5, 120, 40, True, False, 1),
    (3, 240, 80, False, True, 2),
    (3, 200, 80, False, True, 1),
    (3, 184, 80, False, True, 1),
    (3, 184, 80, False, True, 1),
    (3, 480, 112, True, True, 1),
    (3, 672, 112, True, True, 1),
    (5, 672, 160, True, True, 2),
    (5, 960, 160, True, True, 1),
    (5, 960, 160, True, True, 1),
)


class SqueezeExcite(nn.Module):
    channels: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        s = jnp.mean(x, axis=(1, 2), keepdims=True)
        mid = _divisible(self.channels / 4)
        s = nn.relu(nn.Conv(mid, (1, 1), dtype=self.dtype, name="reduce")(s))
        s = hard_sigmoid(nn.Conv(self.channels, (1, 1), dtype=self.dtype,
                                 name="expand")(s))
        return x * s


class InvertedResidual(nn.Module):
    kernel: int
    expanded: int
    out: int
    use_se: bool
    use_hs: bool
    stride: int
    bn_eps: float = 1e-3
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        act = hard_swish if self.use_hs else nn.relu
        bn = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=True, momentum=0.99, epsilon=self.bn_eps,
            dtype=self.dtype, name=name)
        inp = x.shape[-1]
        h = x
        if self.expanded != inp:
            h = act(bn("bn_expand")(nn.Conv(
                self.expanded, (1, 1), use_bias=False, dtype=self.dtype,
                name="expand")(h)))
        h = act(bn("bn_dw")(nn.Conv(
            self.expanded, (self.kernel, self.kernel),
            strides=(self.stride, self.stride), padding="SAME",
            feature_group_count=self.expanded, use_bias=False,
            dtype=self.dtype, name="depthwise")(h)))
        if self.use_se:
            h = SqueezeExcite(self.expanded, dtype=self.dtype, name="se")(h)
        h = bn("bn_project")(nn.Conv(
            self.out, (1, 1), use_bias=False, dtype=self.dtype,
            name="project")(h))
        if self.stride == 1 and inp == self.out:
            h = h + x
        return h


class MobileNetV3Large(nn.Module):
    num_classes: int = 1000
    blocks: Sequence = V3_LARGE
    bn_eps: float = 1e-3
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        bn = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=True, momentum=0.99, epsilon=self.bn_eps,
            dtype=self.dtype, name=name)
        x = hard_swish(bn("bn_stem")(nn.Conv(
            16, (3, 3), strides=(2, 2), padding="SAME", use_bias=False,
            dtype=self.dtype, name="stem")(x)))
        for i, spec in enumerate(self.blocks):
            x = InvertedResidual(*spec, bn_eps=self.bn_eps, dtype=self.dtype,
                                 name=f"block{i}")(x)
        last = self.blocks[-1][1]  # 960
        x = hard_swish(bn("bn_head")(nn.Conv(
            last, (1, 1), use_bias=False, dtype=self.dtype, name="head_conv")(x)))
        x = jnp.mean(x, axis=(1, 2))
        x = hard_swish(nn.Dense(1280, dtype=self.dtype, name="pre_logits")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="classifier")(x)


class MobileNetV3Serving(ImageClassifierServing):
    def make_module(self, cfg: ModelConfig) -> MobileNetV3Large:
        return MobileNetV3Large(num_classes=cfg.num_classes,
                                dtype=jnp.dtype(cfg.dtype))


def create(cfg: ModelConfig) -> MobileNetV3Serving:
    return MobileNetV3Serving(cfg)
