"""MobileNetV3-Large classifier (SURVEY.md §2 C4; BASELINE.json config 2).

The latency-optimized family: BASELINE.json names it "batch=1
latency-optimized", so the intended serving mode is ``parallelism="replica"``
— one single-device executable per chip with independent queues (SURVEY.md
§2.1 DP mode b), small batch buckets, and a short flush deadline. The serving
plumbing (wire formats, fused on-device preproc/top-k) is shared with the
other vision families via tpuserve.models.vision.

Architecture: MobileNetV3-Large (Howard et al. 2019): hard-swish/ReLU
inverted-residual blocks with optional squeeze-excite, 5x5 depthwise convs in
the later stages, 960->1280 head. Depthwise convs map to TPU fine in NHWC;
squeeze-excite's global pool + tiny denses fuse into the surrounding
elementwise work under XLA.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from tpuserve.config import ModelConfig
from tpuserve.models.vision import ImageClassifierServing


def hard_sigmoid(x):
    return nn.relu6(x + 3.0) / 6.0


def hard_swish(x):
    return x * hard_sigmoid(x)


def _divisible(v: float, d: int = 8) -> int:
    out = max(d, int(v + d / 2) // d * d)
    if out < 0.9 * v:
        out += d
    return out


# (kernel, expanded, out, use_se, use_hs, stride) — MobileNetV3-Large table.
V3_LARGE: tuple = (
    (3, 16, 16, False, False, 1),
    (3, 64, 24, False, False, 2),
    (3, 72, 24, False, False, 1),
    (5, 72, 40, True, False, 2),
    (5, 120, 40, True, False, 1),
    (5, 120, 40, True, False, 1),
    (3, 240, 80, False, True, 2),
    (3, 200, 80, False, True, 1),
    (3, 184, 80, False, True, 1),
    (3, 184, 80, False, True, 1),
    (3, 480, 112, True, True, 1),
    (3, 672, 112, True, True, 1),
    (5, 672, 160, True, True, 2),
    (5, 960, 160, True, True, 1),
    (5, 960, 160, True, True, 1),
)


class SqueezeExcite(nn.Module):
    channels: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        s = jnp.mean(x, axis=(1, 2), keepdims=True)
        mid = _divisible(self.channels / 4)
        s = nn.relu(nn.Conv(mid, (1, 1), dtype=self.dtype, name="reduce")(s))
        s = hard_sigmoid(nn.Conv(self.channels, (1, 1), dtype=self.dtype,
                                 name="expand")(s))
        return x * s


class InvertedResidual(nn.Module):
    kernel: int
    expanded: int
    out: int
    use_se: bool
    use_hs: bool
    stride: int
    bn_eps: float = 1e-3
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        act = hard_swish if self.use_hs else nn.relu
        bn = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=True, momentum=0.99, epsilon=self.bn_eps,
            dtype=self.dtype, name=name)
        inp = x.shape[-1]
        h = x
        if self.expanded != inp:
            h = act(bn("bn_expand")(nn.Conv(
                self.expanded, (1, 1), use_bias=False, dtype=self.dtype,
                name="expand")(h)))
        h = act(bn("bn_dw")(nn.Conv(
            self.expanded, (self.kernel, self.kernel),
            strides=(self.stride, self.stride), padding="SAME",
            feature_group_count=self.expanded, use_bias=False,
            dtype=self.dtype, name="depthwise")(h)))
        if self.use_se:
            h = SqueezeExcite(self.expanded, dtype=self.dtype, name="se")(h)
        h = bn("bn_project")(nn.Conv(
            self.out, (1, 1), use_bias=False, dtype=self.dtype,
            name="project")(h))
        if self.stride == 1 and inp == self.out:
            h = h + x
        return h


class MobileNetV3Large(nn.Module):
    num_classes: int = 1000
    blocks: Sequence = V3_LARGE
    bn_eps: float = 1e-3
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        bn = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=True, momentum=0.99, epsilon=self.bn_eps,
            dtype=self.dtype, name=name)
        x = hard_swish(bn("bn_stem")(nn.Conv(
            16, (3, 3), strides=(2, 2), padding="SAME", use_bias=False,
            dtype=self.dtype, name="stem")(x)))
        for i, spec in enumerate(self.blocks):
            x = InvertedResidual(*spec, bn_eps=self.bn_eps, dtype=self.dtype,
                                 name=f"block{i}")(x)
        last = self.blocks[-1][1]  # 960
        x = hard_swish(bn("bn_head")(nn.Conv(
            last, (1, 1), use_bias=False, dtype=self.dtype, name="head_conv")(x)))
        x = jnp.mean(x, axis=(1, 2))
        x = hard_swish(nn.Dense(1280, dtype=self.dtype, name="pre_logits")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="classifier")(x)


class MobileNetV3Serving(ImageClassifierServing):
    def make_module(self, cfg: ModelConfig) -> MobileNetV3Large:
        return MobileNetV3Large(num_classes=cfg.num_classes,
                                dtype=jnp.dtype(cfg.dtype))

    def import_tf_variables(self, flat):
        """Keras-applications MobileNetV3Large names/layouts -> this pytree.

        Source scheme (``tf.keras.applications.MobileNetV3Large``): stem
        ``conv``/``conv_bn``; block i as ``expanded_conv[_i]_{expand,
        depthwise, project}[_bn]`` (block 0 has no ``_0`` suffix and no
        expand conv) with squeeze-excite at ``..._squeeze_excite_conv``
        (reduce) / ``..._squeeze_excite_conv_1`` (expand), both biased; head
        ``conv_1``/``conv_1_bn`` then the post-pool ``conv_2`` and ``logits``
        1x1 convs. BN eps is 1e-3 on both sides (the module default), and
        Keras' pad-then-VALID stride-2 depthwise equals SAME padding at this
        model's even feature sizes, so no option knobs are needed.

        Layout translations (SURVEY.md §7 hard part 3): depthwise kernels are
        (H, W, C, 1) in Keras vs Flax's (H, W, 1, C) for
        ``feature_group_count=C`` — a transpose of the last two dims; the
        post-pool 1x1 convs ``conv_2``/``logits`` become our Dense layers by
        dropping the spatial 1x1 dims. Plain convs are bias-free on both
        sides (no BN-fold needed, unlike ResNet50's import).
        """
        import numpy as np

        f = {k.split(":")[0]: np.asarray(v) for k, v in flat.items()}

        def conv(name):
            return {"kernel": f[f"{name}/kernel"]}

        def bn(name):
            return (
                {"scale": f[f"{name}/gamma"], "bias": f[f"{name}/beta"]},
                {"mean": f[f"{name}/moving_mean"],
                 "var": f[f"{name}/moving_variance"]},
            )

        def dense_from_1x1(name):
            k = f[f"{name}/kernel"]  # (1, 1, in, out)
            return {"kernel": k.reshape(k.shape[2], k.shape[3]),
                    "bias": f[f"{name}/bias"]}

        params: dict = {}
        stats: dict = {}
        params["stem"] = conv("conv")
        params["bn_stem"], stats["bn_stem"] = bn("conv_bn")
        for i, (_k, _exp, _out, use_se, _hs, _s) in enumerate(self.module.blocks):
            tfp = "expanded_conv" if i == 0 else f"expanded_conv_{i}"
            p: dict = {}
            st: dict = {}
            if f"{tfp}_expand/kernel" in f:
                p["expand"] = conv(f"{tfp}_expand")
                p["bn_expand"], st["bn_expand"] = bn(f"{tfp}_expand_bn")
            dw = f[f"{tfp}_depthwise/kernel"]  # (H, W, C, 1)
            p["depthwise"] = {"kernel": dw.transpose(0, 1, 3, 2)}
            p["bn_dw"], st["bn_dw"] = bn(f"{tfp}_depthwise_bn")
            if use_se:
                p["se"] = {
                    "reduce": {"kernel": f[f"{tfp}_squeeze_excite_conv/kernel"],
                               "bias": f[f"{tfp}_squeeze_excite_conv/bias"]},
                    "expand": {"kernel": f[f"{tfp}_squeeze_excite_conv_1/kernel"],
                               "bias": f[f"{tfp}_squeeze_excite_conv_1/bias"]},
                }
            p["project"] = conv(f"{tfp}_project")
            p["bn_project"], st["bn_project"] = bn(f"{tfp}_project_bn")
            params[f"block{i}"] = p
            stats[f"block{i}"] = st
        params["head_conv"] = conv("conv_1")
        params["bn_head"], stats["bn_head"] = bn("conv_1_bn")
        params["pre_logits"] = dense_from_1x1("conv_2")
        params["classifier"] = dense_from_1x1("logits")
        return {"params": params, "batch_stats": stats}


def create(cfg: ModelConfig) -> MobileNetV3Serving:
    return MobileNetV3Serving(cfg)
