"""Stable Diffusion 1.5 txt2img (SURVEY.md §2 C4, §3e; BASELINE.json
config 5) — the multi-step, large-activation generative family.

TPU-first shaping decisions (SURVEY.md §3e):
- **The entire N-step denoise loop is ONE device-resident executable**: text
  encode (cond + uncond), ``lax.fori_loop`` over DDIM steps with
  classifier-free guidance, VAE decode, and uint8 image quantization all live
  inside a single jitted ``forward``. Exactly two host<->device crossings per
  batch: token ids + seeds in, finished uint8 images out. No per-step Python,
  no per-step dispatch — the main idiomatic divergence from a host-side
  denoise loop.
- Classifier-free guidance runs uncond/cond as one 2B-batch UNet call, so the
  MXU sees one large matmul stream instead of two half-sized ones.
- The DDIM schedule (timesteps, alpha products) is precomputed in numpy at
  build time and baked into the executable as constants — no schedule math on
  device, no dynamic indexing beyond a static-length gather.
- Determinism: requests carry an optional seed; latents come from
  ``jax.random.fold_in(key, seed)`` per item, so identical (prompt, seed)
  requests produce identical images across processes and batch compositions.
- bf16 convs/matmuls, f32 GroupNorm/softmax/scheduler math.

Architecture (SD 1.5 shapes, all overridable via ``cfg.options`` so tests run
a tiny variant on CPU): CLIP ViT-L/14 text tower (12 layers, d=768, causal,
quick-gelu), UNet 860M (320ch, mults 1/2/4/4, 2 res blocks/level, spatial
transformers with one cross-attn block at the three highest resolutions,
8 heads), VAE decoder (128ch base, mults 1/2/4/4, mid self-attention,
latent scale 0.18215). Tokenization reuses the WordPiece machinery from
``tpuserve.text`` with BOS/EOS framing and fixed length 77 — no pretrained
BPE artifacts exist in this container (SURVEY.md §0.1), and with seeded
random weights the tokenizer only needs to be deterministic, not CLIP-BPE
compatible. Real artifacts: ``options["bpe_vocab"]``/``["bpe_merges"]``
load CLIP's byte-level BPE (tpuserve.text.CLIPBPETokenizer);
``options["vocab_file"]`` swaps in a WordPiece vocabulary.
"""

from __future__ import annotations

import io
import json
import math
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from tpuserve import frame as frame_wire
from tpuserve.config import ModelConfig
from tpuserve.genserve.model import GenerativeModel
from tpuserve.text import CLIPBPETokenizer, WordPieceTokenizer, synthetic_vocab

MAX_TOKENS = 77  # CLIP text context length; SD conditions on all 77 states.


def _gn(ch: int, name: str, eps: float = 1e-6) -> nn.GroupNorm:
    """GroupNorm(32) with a group count that divides tiny test channels.

    Epsilons follow the published SD modules exactly (torch-import parity):
    1e-5 in UNet ResBlocks and the UNet output norm, 1e-6 in spatial
    transformers and everywhere in the VAE."""
    return nn.GroupNorm(num_groups=math.gcd(32, ch), epsilon=eps,
                        dtype=jnp.float32, name=name)


def _ln(name: str) -> nn.LayerNorm:
    """LayerNorm with torch's default eps 1e-5 (CLIP/transformer blocks use
    torch nn.LayerNorm; flax's 1e-6 default would drift imported weights)."""
    return nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name=name)


def quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


# -- CLIP text encoder --------------------------------------------------------

class CLIPBlock(nn.Module):
    heads: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, causal_mask):
        d = x.shape[-1]
        h = _ln("ln1")(x).astype(self.dtype)
        h = nn.MultiHeadDotProductAttention(
            num_heads=self.heads, dtype=self.dtype, deterministic=True,
            name="attn")(h, h, h, mask=causal_mask)
        x = x + h
        h = _ln("ln2")(x).astype(self.dtype)
        h = nn.Dense(4 * d, dtype=self.dtype, name="mlp_up")(h)
        h = quick_gelu(h)
        return x + nn.Dense(d, dtype=self.dtype, name="mlp_down")(h)


class CLIPTextEncoder(nn.Module):
    """CLIP ViT-L/14 text tower: pre-LN causal transformer over 77 tokens;
    SD conditions on the full final hidden-state sequence."""

    vocab_size: int
    layers: int = 12
    d_model: int = 768
    heads: int = 12
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, ids):  # (B, 77) int32 -> (B, 77, d)
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                     name="token_embed")(ids)
        pos = self.param("pos_embed", nn.initializers.normal(0.01),
                         (MAX_TOKENS, self.d_model))
        x = x + pos[None, : ids.shape[1], :].astype(self.dtype)
        mask = nn.make_causal_mask(ids)
        for i in range(self.layers):
            x = CLIPBlock(self.heads, dtype=self.dtype, name=f"layer{i}")(x, mask)
        return _ln("ln_final")(x).astype(self.dtype)


# -- UNet ----------------------------------------------------------------------

def timestep_embedding(t: jax.Array, dim: int) -> jax.Array:
    """Sinusoidal embedding, f32: (B,) int -> (B, dim)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(10000.0) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def _flash_unet_attention_fn(q, k, v, bias=None, mask=None, **kw):
    """``flax.linen.MultiHeadDotProductAttention`` attention_fn that routes
    UNet spatial self-attention through the fused Pallas kernel
    (tpuserve.ops.flash_attention) instead of materializing the (N, N)
    score matrix to HBM twice — at 512 px the level-0 self-attention is
    N = 4096 tokens, the single largest HBM-traffic site in the denoise
    step (BASELINE.md "SD 1.5 chip profile").

    SD head dims (40/80/160) are mostly not lane-aligned; the kernel takes
    them zero-padded to the next multiple of 64. Padding is mathematically
    exact: zero lanes add nothing to q.k scores, and padded V columns only
    produce output columns that are sliced off. The kernel scales by
    padded_d**-0.5 internally, so q is pre-scaled by (padded_d/d)**0.5 to
    land on the true d**-0.5. Hooking attention_fn (not replacing the
    module) keeps the param tree identical to the dense path — the torch
    import mappers (sd15_import) are untouched.

    Small token counts (N < 1024: the 16 px and 8 px levels, and all
    77-key cross-attention, which never takes this path) fall back to
    flax's dense attention — at those sizes the score matrix fits cache
    and the kernel's padded lanes would cost more than they save.
    """
    n = q.shape[1]
    if bias is not None or mask is not None or n < 1024:
        return nn.dot_product_attention(
            q, k, v, bias=bias, mask=mask,
            dtype=kw.get("dtype"), deterministic=True)
    from tpuserve.ops.flash_attention import flash_attention

    d = q.shape[-1]
    dp = -(-d // 64) * 64

    def pad_d(x):
        if d == dp:
            return x
        return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, dp - d)])

    qf = pad_d(q) * jnp.asarray((dp / d) ** 0.5, q.dtype)
    out = flash_attention(qf, pad_d(k), pad_d(v))
    return out[..., :d]


class ResBlock(nn.Module):
    out_ch: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, temb):  # x (B,H,W,C), temb (B,T)
        h = nn.swish(_gn(x.shape[-1], "norm1", eps=1e-5)(x)).astype(self.dtype)
        h = nn.Conv(self.out_ch, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv1")(h)
        t = nn.Dense(self.out_ch, dtype=self.dtype, name="temb_proj")(
            nn.swish(temb).astype(self.dtype))
        h = h + t[:, None, None, :]
        h = nn.swish(_gn(self.out_ch, "norm2", eps=1e-5)(h)).astype(self.dtype)
        h = nn.Conv(self.out_ch, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv2")(h)
        if x.shape[-1] != self.out_ch:
            x = nn.Conv(self.out_ch, (1, 1), dtype=self.dtype, name="skip")(x)
        return x + h


class TransformerBlock(nn.Module):
    """LN->self-attn, LN->cross-attn(text), LN->GEGLU feed-forward."""

    heads: int
    dtype: Any = jnp.bfloat16
    # "dense" | "flash": spatial self-attention impl (cross-attention over
    # the 77 text keys always stays dense — see _flash_unet_attention_fn).
    attention_impl: str = "dense"

    @nn.compact
    def __call__(self, x, ctx):  # x (B,N,C), ctx (B,77,Dtxt)
        d = x.shape[-1]

        def attn(name: str, self_attn: bool = False):
            fn = (_flash_unet_attention_fn
                  if self_attn and self.attention_impl == "flash"
                  else nn.dot_product_attention)
            return nn.MultiHeadDotProductAttention(
                num_heads=self.heads, dtype=self.dtype, deterministic=True,
                attention_fn=fn, name=name)

        h = _ln("ln1")(x).astype(self.dtype)
        x = x + attn("self_attn", self_attn=True)(h, h, h)
        h = _ln("ln2")(x).astype(self.dtype)
        x = x + attn("cross_attn")(h, ctx, ctx)
        h = _ln("ln3")(x).astype(self.dtype)
        up = nn.Dense(8 * d, dtype=self.dtype, name="ff_up")(h)
        gate, val = jnp.split(up, 2, axis=-1)
        return x + nn.Dense(d, dtype=self.dtype, name="ff_down")(
            val * nn.gelu(gate, approximate=False))


class SpatialTransformer(nn.Module):
    heads: int
    dtype: Any = jnp.bfloat16
    attention_impl: str = "dense"

    @nn.compact
    def __call__(self, x, ctx):  # (B,H,W,C)
        b, hh, ww, c = x.shape
        h = _gn(c, "norm")(x).astype(self.dtype)
        h = nn.Conv(c, (1, 1), dtype=self.dtype, name="proj_in")(h)
        h = h.reshape(b, hh * ww, c)
        h = TransformerBlock(self.heads, dtype=self.dtype,
                             attention_impl=self.attention_impl,
                             name="block")(h, ctx)
        h = h.reshape(b, hh, ww, c)
        return x + nn.Conv(c, (1, 1), dtype=self.dtype, name="proj_out")(h)


class UNet(nn.Module):
    """SD 1.5 epsilon-predictor: 4ch latent in/out, cross-attended on text."""

    model_ch: int = 320
    mults: Sequence[int] = (1, 2, 4, 4)
    num_res: int = 2
    attn_levels: Sequence[int] = (0, 1, 2)
    heads: int = 8
    dtype: Any = jnp.bfloat16
    attention_impl: str = "dense"  # spatial self-attention: "dense" | "flash"

    @nn.compact
    def __call__(self, x, t, ctx):  # x (B,h,w,4), t (B,), ctx (B,77,D)
        temb = timestep_embedding(t, self.model_ch)
        temb = nn.Dense(4 * self.model_ch, dtype=self.dtype, name="time1")(
            temb.astype(self.dtype))
        temb = nn.Dense(4 * self.model_ch, dtype=self.dtype, name="time2")(
            nn.swish(temb))

        h = nn.Conv(self.model_ch, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv_in")(x)
        skips = [h]
        # Down path.
        for i, m in enumerate(self.mults):
            for j in range(self.num_res):
                h = ResBlock(self.model_ch * m, dtype=self.dtype,
                             name=f"down{i}_res{j}")(h, temb)
                if i in self.attn_levels:
                    h = SpatialTransformer(self.heads, dtype=self.dtype,
                                           attention_impl=self.attention_impl,
                                           name=f"down{i}_attn{j}")(h, ctx)
                skips.append(h)
            if i != len(self.mults) - 1:
                # Explicit (1,1) padding, not SAME: with stride 2, SAME pads
                # (0,1) while SD's Downsample pads symmetrically — same output
                # shape, different window alignment (caught by torch parity).
                h = nn.Conv(h.shape[-1], (3, 3), strides=(2, 2),
                            padding=((1, 1), (1, 1)),
                            dtype=self.dtype, name=f"down{i}_ds")(h)
                skips.append(h)
        # Middle.
        h = ResBlock(h.shape[-1], dtype=self.dtype, name="mid_res1")(h, temb)
        h = SpatialTransformer(self.heads, dtype=self.dtype,
                               attention_impl=self.attention_impl,
                               name="mid_attn")(h, ctx)
        h = ResBlock(h.shape[-1], dtype=self.dtype, name="mid_res2")(h, temb)
        # Up path.
        for i, m in reversed(list(enumerate(self.mults))):
            for j in range(self.num_res + 1):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = ResBlock(self.model_ch * m, dtype=self.dtype,
                             name=f"up{i}_res{j}")(h, temb)
                if i in self.attn_levels:
                    h = SpatialTransformer(self.heads, dtype=self.dtype,
                                           attention_impl=self.attention_impl,
                                           name=f"up{i}_attn{j}")(h, ctx)
            if i != 0:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), method="nearest")
                h = nn.Conv(c, (3, 3), padding="SAME", dtype=self.dtype,
                            name=f"up{i}_us")(h)
        h = nn.swish(_gn(h.shape[-1], "norm_out", eps=1e-5)(h)).astype(self.dtype)
        return nn.Conv(4, (3, 3), padding="SAME", dtype=jnp.float32,
                       name="conv_out")(h)


# -- VAE decoder ---------------------------------------------------------------

class VAEAttn(nn.Module):
    """Single-head full self-attention over spatial positions (VAE mid)."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        b, hh, ww, c = x.shape
        h = _gn(c, "norm")(x).astype(self.dtype)
        q = nn.Dense(c, dtype=self.dtype, name="q")(h).reshape(b, hh * ww, c)
        k = nn.Dense(c, dtype=self.dtype, name="k")(h).reshape(b, hh * ww, c)
        v = nn.Dense(c, dtype=self.dtype, name="v")(h).reshape(b, hh * ww, c)
        s = jnp.einsum("bqc,bkc->bqk", q, k).astype(jnp.float32) * (c ** -0.5)
        a = jax.nn.softmax(s, axis=-1).astype(self.dtype)
        h = jnp.einsum("bqk,bkc->bqc", a, v).reshape(b, hh, ww, c)
        return x + nn.Dense(c, dtype=self.dtype, name="proj")(h)


class VAEResBlock(nn.Module):
    out_ch: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        h = nn.swish(_gn(x.shape[-1], "norm1")(x)).astype(self.dtype)
        h = nn.Conv(self.out_ch, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv1")(h)
        h = nn.swish(_gn(self.out_ch, "norm2")(h)).astype(self.dtype)
        h = nn.Conv(self.out_ch, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv2")(h)
        if x.shape[-1] != self.out_ch:
            x = nn.Conv(self.out_ch, (1, 1), dtype=self.dtype, name="skip")(x)
        return x + h


class VAEDecoder(nn.Module):
    """AutoencoderKL decoder: (B,h,w,4) latents -> (B,8h,8w,3) in [-1,1]."""

    ch: int = 128
    mults: Sequence[int] = (1, 2, 4, 4)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, z):
        z = nn.Conv(z.shape[-1], (1, 1), dtype=self.dtype, name="post_quant")(z)
        top = self.ch * self.mults[-1]
        h = nn.Conv(top, (3, 3), padding="SAME", dtype=self.dtype, name="conv_in")(z)
        h = VAEResBlock(top, dtype=self.dtype, name="mid_res1")(h)
        h = VAEAttn(dtype=self.dtype, name="mid_attn")(h)
        h = VAEResBlock(top, dtype=self.dtype, name="mid_res2")(h)
        for i, m in reversed(list(enumerate(self.mults))):
            for j in range(3):
                h = VAEResBlock(self.ch * m, dtype=self.dtype,
                                name=f"up{i}_res{j}")(h)
            if i != 0:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), method="nearest")
                h = nn.Conv(c, (3, 3), padding="SAME", dtype=self.dtype,
                            name=f"up{i}_us")(h)
        h = nn.swish(_gn(h.shape[-1], "norm_out")(h)).astype(self.dtype)
        return nn.Conv(3, (3, 3), padding="SAME", dtype=jnp.float32,
                       name="conv_out")(h)


# -- DDIM schedule (host-side numpy, baked as executable constants) -----------

def ddim_schedule(steps: int, train_steps: int = 1000,
                  beta_start: float = 0.00085, beta_end: float = 0.012):
    """SD's scaled-linear schedule -> per-step (t, alpha_t, alpha_prev) arrays
    of static length `steps`, ordered from t=high noise down to 0."""
    betas = np.linspace(beta_start ** 0.5, beta_end ** 0.5, train_steps,
                        dtype=np.float64) ** 2
    acum = np.cumprod(1.0 - betas)
    ts = np.linspace(0, train_steps - 1, steps).round().astype(np.int64)[::-1]
    a_t = acum[ts]
    a_prev = np.concatenate([acum[ts[1:]], [1.0]])
    return (ts.astype(np.int32), a_t.astype(np.float32),
            a_prev.astype(np.float32))


# -- serving -------------------------------------------------------------------

class SD15Serving(GenerativeModel):
    """txt2img over HTTP: JSON {"prompt", "negative_prompt"?, "seed"?} in,
    PNG bytes out. The negative prompt rides the classifier-free-guidance
    uncond lane (empty prompt when unset), steering generation away from it.

    Two serving shapes (both deterministic in (prompt, negative, seed)):
    the one-shot ``forward`` bakes the whole N-step denoise loop into one
    executable (the static batcher's locked-batch path), and the
    GenerativeModel decomposition serves the SAME math through the
    iteration-level engine — ``init_state`` text-encodes + seeds latents,
    each ``step`` is one DDIM iteration over the slot block (per-slot step
    counters, so freshly folded-in requests denoise beside half-finished
    ones), and ``extract`` runs the VAE decode only when a slot finishes.
    Fixed ``steps`` per request keeps the large-activation path static."""

    def __init__(self, cfg: ModelConfig) -> None:
        super().__init__(cfg)
        o = cfg.options
        self.dtype = jnp.dtype(cfg.dtype)
        self.steps = int(o.get("steps", 20))
        self.guidance = float(o.get("guidance", 7.5))
        # Streamed responses emit a decoded preview image every N denoise
        # steps (0 disables). Each preview reuses the compiled extract
        # program — previews never add a compile, only extract invocations.
        self.preview_every = int(o.get("preview_every", 0))
        if self.preview_every < 0:
            raise ValueError(
                f"options.preview_every must be >= 0, got {self.preview_every}")
        # The VAE upsamples 2x per level past the first, so the latent edge
        # must be image_size / 2^(levels-1) for the PNG to match image_size
        # (8x for the standard 4-level SD VAE).
        vae_mults = tuple(o.get("vae_mults", (1, 2, 4, 4)))
        self.latent = cfg.image_size // (2 ** (len(vae_mults) - 1))
        vocab_file = o.get("vocab_file")
        if bool(o.get("bpe_vocab")) != bool(o.get("bpe_merges")):
            raise ValueError(
                "bpe_vocab and bpe_merges must be set together "
                "(CLIP BPE needs vocab.json + merges.txt)")
        if o.get("bpe_vocab"):
            # Real SD/CLIP artifacts: byte-level BPE (vocab.json + merges.txt).
            self.tokenizer = CLIPBPETokenizer(o["bpe_vocab"], o["bpe_merges"])
        elif vocab_file:
            self.tokenizer = WordPieceTokenizer.from_vocab_file(vocab_file)
        else:
            self.tokenizer = WordPieceTokenizer(
                synthetic_vocab(int(o.get("vocab_size", 8192))))
        vocab_size = max(self.tokenizer.vocab.values()) + 1
        self.text_encoder = CLIPTextEncoder(
            vocab_size=vocab_size,
            layers=int(o.get("text_layers", 12)),
            d_model=int(o.get("text_d_model", 768)),
            heads=int(o.get("text_heads", 12)),
            dtype=self.dtype)
        unet_attention = str(o.get("unet_attention", "dense"))
        if unet_attention not in ("dense", "flash"):
            raise ValueError("options.unet_attention must be 'dense' or "
                             f"'flash', got {unet_attention!r}")
        self.unet = UNet(
            model_ch=int(o.get("unet_ch", 320)),
            mults=tuple(o.get("unet_mults", (1, 2, 4, 4))),
            num_res=int(o.get("unet_res", 2)),
            attn_levels=tuple(o.get("unet_attn_levels", (0, 1, 2))),
            heads=int(o.get("unet_heads", 8)),
            dtype=self.dtype,
            attention_impl=unet_attention)
        self.vae = VAEDecoder(
            ch=int(o.get("vae_ch", 128)),
            mults=tuple(o.get("vae_mults", (1, 2, 4, 4))),
            dtype=self.dtype)
        self.schedule = ddim_schedule(self.steps)

    # -- params ---------------------------------------------------------------
    def init_params(self, rng: jax.Array) -> Any:
        k1, k2, k3 = jax.random.split(rng, 3)
        ids = jnp.zeros((1, MAX_TOKENS), jnp.int32)
        lat = jnp.zeros((1, self.latent, self.latent, 4), jnp.float32)
        t = jnp.zeros((1,), jnp.int32)
        ctx = jnp.zeros((1, MAX_TOKENS, self.text_encoder.d_model), self.dtype)
        return {
            "text": self.text_encoder.init(k1, ids),
            "unet": self.unet.init(k2, lat, t, ctx),
            "vae": self.vae.init(k3, lat),
        }

    def import_torch_variables(self, flat: dict) -> Any:
        """Published SD 1.5 single-file checkpoint (LDM layout, safetensors
        or .ckpt) -> our param tree; see tpuserve.models.sd15_import. Pair
        with options bpe_vocab/bpe_merges for the real CLIP tokenizer."""
        from tpuserve.models.sd15_import import import_ldm_checkpoint

        return import_ldm_checkpoint(self, flat)

    # -- shapes ---------------------------------------------------------------
    def input_signature(self, bucket: tuple) -> Any:
        (b,) = bucket
        return (
            jax.ShapeDtypeStruct((b, MAX_TOKENS), jnp.int32),
            jax.ShapeDtypeStruct((b, MAX_TOKENS), jnp.int32),
            jax.ShapeDtypeStruct((b,), jnp.int32),
        )

    # -- device side ----------------------------------------------------------
    def forward(self, params: Any, batch: Any) -> dict:
        ids, neg_ids, seeds = batch
        b = ids.shape[0]
        # One 2B text-encoder call covers cond + per-item uncond: negative
        # prompts make the uncond row per-request (empty prompt when unset),
        # and the text tower is a rounding error next to `steps` UNet calls.
        ctx2 = self.text_encoder.apply(
            params["text"], jnp.concatenate([neg_ids, ids], axis=0))  # (2B, 77, D)

        keys = jax.vmap(lambda s: jax.random.fold_in(jax.random.key(0), s))(seeds)
        lat = jax.vmap(lambda k: jax.random.normal(
            k, (self.latent, self.latent, 4), jnp.float32))(keys)

        ts, a_t, a_prev = (jnp.asarray(x) for x in self.schedule)
        g = jnp.float32(self.guidance)

        def body(i, lat):
            t = jnp.broadcast_to(ts[i], (2 * b,))
            x2 = jnp.concatenate([lat, lat], axis=0)
            eps2 = self.unet.apply(params["unet"], x2, t, ctx2)
            eps_u, eps_c = jnp.split(eps2, 2, axis=0)
            eps = eps_u + g * (eps_c - eps_u)
            at, ap = a_t[i], a_prev[i]
            x0 = (lat - jnp.sqrt(1.0 - at) * eps) / jnp.sqrt(at)
            return jnp.sqrt(ap) * x0 + jnp.sqrt(1.0 - ap) * eps

        lat = jax.lax.fori_loop(0, self.steps, body, lat)
        img = self.vae.apply(params["vae"], lat / 0.18215)
        img = jnp.clip((img + 1.0) * 127.5, 0.0, 255.0).astype(jnp.uint8)
        return {"image": img}

    # -- engine decomposition (tpuserve.genserve) -------------------------------
    def state_signature(self, slots: int) -> Any:
        return {
            "lat": jax.ShapeDtypeStruct(
                (slots, self.latent, self.latent, 4), jnp.float32),
            "ctx": jax.ShapeDtypeStruct(
                (slots, 2, MAX_TOKENS, self.text_encoder.d_model), self.dtype),
            "step_i": jax.ShapeDtypeStruct((slots,), jnp.int32),
            "done": jax.ShapeDtypeStruct((slots,), jnp.bool_),
        }

    def gen_item_signature(self) -> Any:
        return (
            jax.ShapeDtypeStruct((MAX_TOKENS,), jnp.int32),
            jax.ShapeDtypeStruct((MAX_TOKENS,), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.int32),
        )

    def init_state(self, params: Any, item: Any) -> Any:
        """Once-per-request work: text-encode cond + uncond, seed the
        latent. Same math as forward's prologue, per slot."""
        ids, neg_ids, seed = item
        ctx2 = self.text_encoder.apply(
            params["text"], jnp.stack([neg_ids, ids]))  # (2, 77, D)
        key = jax.random.fold_in(jax.random.key(0), seed)
        lat = jax.random.normal(
            key, (self.latent, self.latent, 4), jnp.float32)
        return {"lat": lat, "ctx": ctx2.astype(self.dtype),
                "step_i": jnp.int32(0), "done": jnp.bool_(False)}

    def step(self, params: Any, state: Any) -> tuple[Any, dict]:
        """One DDIM iteration over the whole slot block, each slot at its
        OWN schedule index — a request folded in at iteration 400 of the
        block's life denoises from its own t=high-noise next to slots
        about to finish. Finished/free slots freeze via ``done``."""
        lat, ctx, step_i, done = (state["lat"], state["ctx"],
                                  state["step_i"], state["done"])
        b = lat.shape[0]
        ts, a_t, a_prev = (jnp.asarray(x) for x in self.schedule)
        g = jnp.float32(self.guidance)
        idx = jnp.clip(step_i, 0, self.steps - 1)
        t2 = jnp.concatenate([ts[idx], ts[idx]], axis=0)  # (2B,)
        x2 = jnp.concatenate([lat, lat], axis=0)
        ctx2 = jnp.concatenate([ctx[:, 0], ctx[:, 1]], axis=0)  # (2B, 77, D)
        eps2 = self.unet.apply(params["unet"], x2, t2, ctx2)
        eps_u, eps_c = jnp.split(eps2, 2, axis=0)
        eps = eps_u + g * (eps_c - eps_u)
        at = a_t[idx][:, None, None, None]
        ap = a_prev[idx][:, None, None, None]
        x0 = (lat - jnp.sqrt(1.0 - at) * eps) / jnp.sqrt(at)
        new_lat = jnp.sqrt(ap) * x0 + jnp.sqrt(1.0 - ap) * eps
        lat2 = jnp.where(done[:, None, None, None], lat, new_lat)
        step2 = jnp.where(done, step_i, step_i + 1)
        done2 = step2 >= self.steps
        return ({"lat": lat2, "ctx": ctx, "step_i": step2, "done": done2},
                {"done": done2, "step_i": step2})

    def extract(self, params: Any, state: Any, slot: Any) -> Any:
        """The tail work runs ONCE per finished slot: VAE decode + uint8
        quantization of that slot's latent only."""
        lat = jax.lax.dynamic_index_in_dim(state["lat"], slot, 0)  # (1,h,w,4)
        img = self.vae.apply(params["vae"], lat / 0.18215)
        img = jnp.clip((img + 1.0) * 127.5, 0.0, 255.0).astype(jnp.uint8)
        return {"image": img[0]}

    def gen_max_steps(self) -> int:
        return self.steps

    def finalize(self, extracted: Any, item: Any) -> bytes:
        return self._png(np.asarray(extracted["image"]))

    # -- streaming (ISSUE 17) ---------------------------------------------------
    # sd15 streams over the chunked binary frame wire: KIND_EVENT frames
    # carry progress/done/error JSON, single-item KIND_RGB8 frames carry
    # previews and the final image. Everything except the final image and
    # the terminal is droppable — a slow reader loses progress, never art.
    def stream_units(self, step_out: dict, slot: int, stream: dict) -> list:
        s = int(step_out["step_i"][slot])
        sent = int(stream.get("sent", 0))
        if s <= sent:
            return []
        stream["sent"] = s
        return [{"type": "progress", "step": i, "steps": self.steps,
                 "droppable": True} for i in range(sent + 1, s + 1)]

    def stream_wants_preview(self, step_out: dict, slot: int,
                             stream: dict) -> bool:
        if not self.preview_every or bool(step_out["done"][slot]):
            return False
        s = int(step_out["step_i"][slot])
        return s - int(stream.get("previewed", 0)) >= self.preview_every

    def stream_preview_unit(self, extracted: Any, stream: dict) -> dict:
        stream["previewed"] = int(stream.get("sent", 0))
        return {"type": "preview", "image": np.asarray(extracted["image"]),
                "droppable": True}

    def stream_final_units(self, extracted: Any, result: Any) -> list:
        return ([{"type": "image", "image": np.asarray(extracted["image"])}]
                + super().stream_final_units(extracted, result))

    def stream_usage(self, result: Any) -> dict:
        return {"images": 1}

    def stream_content_type(self) -> str:
        return frame_wire.CONTENT_TYPE

    def encode_stream_unit(self, unit: dict) -> bytes:
        if unit["type"] in ("image", "preview"):
            return frame_wire.encode_frame(
                [unit["image"]], frame_wire.KIND_RGB8, self.cfg.image_size)
        data = {k: v for k, v in unit.items() if k != "droppable"}
        return frame_wire.encode_stream_event(
            json.dumps(data).encode("utf-8"))

    def stream_heartbeat(self) -> bytes:
        return frame_wire.encode_stream_event(b'{"type": "hb"}')

    # -- host side --------------------------------------------------------------
    def _tokenize(self, prompt: str) -> np.ndarray:
        """Prompt -> fixed (77,) int32: BOS + pieces + EOS, pad-id padded."""
        ids, _ = self.tokenizer.encode(prompt, MAX_TOKENS)
        return ids

    def host_decode(self, payload: bytes, content_type: str) -> Any:
        if content_type.startswith("application/json"):
            body = json.loads(payload.decode("utf-8"))
            prompt = body.get("prompt")
            if not isinstance(prompt, str):
                raise ValueError('JSON body must contain "prompt": str')
            negative = body.get("negative_prompt", "")
            if not isinstance(negative, str):
                raise ValueError('"negative_prompt" must be a string')
            seed = int(body.get("seed", 0))
        else:
            prompt, negative, seed = payload.decode("utf-8"), "", 0
        # The negative prompt rides the classifier-free-guidance uncond lane
        # (empty prompt when unset), steering generation AWAY from it.
        return self._tokenize(prompt), self._tokenize(negative), np.int32(seed)

    def canary_item(self) -> Any:
        return self.host_decode(b'{"prompt": "canary", "seed": 1}',
                                "application/json")

    @staticmethod
    def _png(arr: np.ndarray) -> bytes:
        from PIL import Image

        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, "PNG")
        return buf.getvalue()

    def host_postprocess(self, outputs: dict, n_valid: int) -> list[bytes]:
        return [self._png(np.asarray(outputs["image"][r]))
                for r in range(n_valid)]

    # -- parallelism ------------------------------------------------------------
    def partition_rules(self) -> list[tuple[str, P]]:
        if self.cfg.tp <= 1:
            return [(".*", P())]
        return [
            # UNet/CLIP attention: shard heads; GEGLU/MLP: shard hidden.
            (r"(self_attn|cross_attn|attn)/(query|key|value)/kernel", P(None, "model", None)),
            (r"(self_attn|cross_attn|attn)/out/kernel", P("model", None, None)),
            (r"(ff_up|mlp_up)/kernel", P(None, "model")),
            (r"(ff_down|mlp_down)/kernel", P("model", None)),
            (r".*", P()),
        ]


def create(cfg: ModelConfig) -> SD15Serving:
    return SD15Serving(cfg)
