"""Autoregressive text generation (ISSUE 9) — the token-by-token family the
iteration-level engine exists for.

A prefix-LM decoder: the prompt is encoded **bidirectionally** in one
prefill pass (which is exactly the per-key-bias shape the seeded Pallas
flash-attention kernel supports — ``options.attention = "flash"`` routes
prefill through ``tpuserve.ops.flash_attention``; generated tokens then
decode strictly left-to-right against the KV cache). Sampling is seeded and
positional (``fold_in(fold_in(key(0), seed), position)``), so identical
(prompt, seed, temperature, max_new_tokens) requests produce identical
token streams across processes, batch compositions, and — the property
tests/test_genserve.py leans on — across the TWO serving paths:

- ``forward`` — the locked-batch twin: prefill + a ``lax.fori_loop`` over
  the FULL ``max_new_tokens`` cap for every lane. This is what the static
  batcher serves ([genserve] off) and what the bench's locked-batch
  baseline measures: a 2-token completion pays the full loop.
- ``init_state`` / ``step`` / ``extract`` — the engine decomposition:
  prefill is the once-per-request insert, each step decodes ONE token for
  every active slot against the per-slot KV cache
  (slots, layers, ctx, heads, head_dim), and a finished slot's token
  buffer is extracted the moment its own ``done`` flag flips.

Both paths share ``_prefill`` and ``_decode_step`` verbatim, so engine ==
locked-batch token parity holds by construction. Tokenization reuses
``tpuserve.text`` WordPiece over the deterministic synthetic vocab (no
artifacts, SURVEY.md §7 hard part 8); [SEP] doubles as EOS.

Sizes come from ``cfg.options`` (layers/d_model/heads/d_ff/vocab_size/
prompt_len/max_new_tokens) with small dev defaults; tests use tiny sizes.
"""

from __future__ import annotations

import json
import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from tpuserve.config import ModelConfig
from tpuserve.genserve.model import GenerativeModel
from tpuserve.parallel.mesh import MODEL_AXIS, SEQ_AXIS, can_shard
from tpuserve.text import WordPieceTokenizer, synthetic_vocab


def _norm(x, scale, bias, eps=1e-5):
    """LayerNorm in f32, cast back to the compute dtype."""
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (out * scale + bias).astype(x.dtype)


class TextGenServing(GenerativeModel):
    """Decoder-only generation over HTTP: JSON {"prompt", "seed"?,
    "max_new_tokens"?, "temperature"?} in, {"text", "tokens", "n_tokens"}
    out. Every sampling parameter rides inside the decoded item, so the
    result cache can never alias two requests differing only in seed."""

    def __init__(self, cfg: ModelConfig) -> None:
        super().__init__(cfg)
        o = cfg.options
        self.dtype = jnp.dtype(cfg.dtype)
        self.layers = int(o.get("layers", 4))
        self.d_model = int(o.get("d_model", 256))
        self.heads = int(o.get("heads", 4))
        self.d_ff = int(o.get("d_ff", 4 * self.d_model))
        # Prompt bucket (host pads every prompt to this) and the generation
        # cap; the KV cache spans their sum.
        self.max_prompt = int(o.get("prompt_len", 32))
        self.max_new = int(o.get("max_new_tokens", 64))
        self.max_ctx = self.max_prompt + self.max_new
        if self.d_model % self.heads:
            raise ValueError(
                f"options.d_model={self.d_model} must divide by "
                f"heads={self.heads}")
        self.head_dim = self.d_model // self.heads
        self.attention = str(o.get("attention", "dense"))
        if self.attention not in ("dense", "flash"):
            raise ValueError("options.attention must be 'dense' or 'flash', "
                             f"got {self.attention!r}")
        # Switch-MoE FFN variant (ISSUE 20): 0 = dense MLP (the default and
        # the historical RNG stream); >= 2 replaces every layer's MLP with
        # top-1 routing over ops.moe.switch_route.
        self.moe_experts = int(o.get("moe_experts", 0))
        if self.moe_experts == 1 or self.moe_experts < 0:
            raise ValueError("options.moe_experts must be 0 (dense MLP) "
                             f"or >= 2 experts, got {self.moe_experts}")
        if self.attention == "flash" and self.max_prompt % 8:
            raise ValueError(
                f"options.attention='flash' needs prompt_len "
                f"({self.max_prompt}) divisible by 8 (TPU tile rows)")
        vocab_file = o.get("vocab_file")
        if vocab_file:
            self.tokenizer = WordPieceTokenizer.from_vocab_file(vocab_file)
        else:
            self.tokenizer = WordPieceTokenizer(
                synthetic_vocab(int(o.get("vocab_size", 8192))))
        self.vocab_size = max(self.tokenizer.vocab.values()) + 1
        self.eos_id = self.tokenizer.sep_id

    # -- params ---------------------------------------------------------------
    def init_params(self, rng: jax.Array) -> Any:
        d, f, v = self.d_model, self.d_ff, self.vocab_size
        hd, h = self.head_dim, self.heads

        def dense(key, shape):
            return (jax.random.normal(key, shape, jnp.float32)
                    * (1.0 / math.sqrt(shape[0]))).astype(jnp.float32)

        # Key budget: dense layers draw 6, MoE layers 7 — dense configs keep
        # the historical RNG stream bit-for-bit.
        per_layer = 7 if self.moe_experts else 6
        keys = iter(jax.random.split(rng, per_layer * self.layers + 4))
        params: dict = {
            "embed": jax.random.normal(next(keys), (v, d), jnp.float32) * 0.02,
            "pos": jax.random.normal(next(keys), (self.max_ctx, d),
                                     jnp.float32) * 0.01,
            "ln_f": {"scale": jnp.ones((d,), jnp.float32),
                     "bias": jnp.zeros((d,), jnp.float32)},
            "head": dense(next(keys), (d, v)),
        }
        for i in range(self.layers):
            lp = {
                "ln1": {"scale": jnp.ones((d,), jnp.float32),
                        "bias": jnp.zeros((d,), jnp.float32)},
                "wq": dense(next(keys), (d, h * hd)),
                "wk": dense(next(keys), (d, h * hd)),
                "wv": dense(next(keys), (d, h * hd)),
                "wo": dense(next(keys), (h * hd, d)),
                "ln2": {"scale": jnp.ones((d,), jnp.float32),
                        "bias": jnp.zeros((d,), jnp.float32)},
            }
            if self.moe_experts:
                e = self.moe_experts
                lp["router"] = dense(next(keys), (d, e))
                lp["moe_up"] = (
                    jax.random.normal(next(keys), (e, d, f), jnp.float32)
                    * (1.0 / math.sqrt(d)))
                lp["moe_down"] = (
                    jax.random.normal(next(keys), (e, f, d), jnp.float32)
                    * (1.0 / math.sqrt(f)))
            else:
                lp["w_up"] = dense(next(keys), (d, f))
                lp["w_down"] = dense(next(keys), (f, d))
            params[f"layer{i}"] = lp
        return params

    # -- parallelism (ISSUE 20: sharded decode) -------------------------------
    def partition_rules(self) -> list[tuple[str, P]]:
        """TP rules for sharded decode: attention QKV and the vocab head
        shard columns (the heads / vocab dim) on "model", the out
        projection shards rows (its contraction dim); MoE expert weights
        shard the leading expert dim. Embeddings, positions, and norms
        replicate — they are small and read by every shard. tp <= 1 keeps
        everything replicated (the historical layout)."""
        if self.cfg.tp <= 1:
            return [(".*", P())]
        return [
            (r"w[qkv]$", P(None, MODEL_AXIS)),
            (r"wo$", P(MODEL_AXIS, None)),
            (r"w_up$", P(None, MODEL_AXIS)),
            (r"w_down$", P(MODEL_AXIS, None)),
            (r"router$", P()),
            (r"moe_(up|down)$", P(MODEL_AXIS, None, None)),
            (r"head$", P(None, MODEL_AXIS)),
            (r".*", P()),
        ]

    def state_partition_specs(self, struct: Any, mesh: Any) -> Any:
        """PartitionSpec tree for the engine's device state block on a
        sharded mesh: the KV heads dim rides "model" next to the QKV
        column shards (each shard decodes its own heads), and the
        pages/context dim rides "seq" when sequence parallelism is on.
        Dims that don't divide the axis fall back to replication
        (``can_shard``), and an all-replicated layout returns None so the
        caller skips spec plumbing entirely. Lane bookkeeping (tokens,
        pos, done, ...) always replicates — every shard must agree on
        done flags for the emission path."""
        specs = {f: P() for f in struct}
        if "kp" in struct:  # tps-ok[TPS503]: host-side structural check
            kv = [None, None, None, None, None]  # (pages, ln, pt, h, hd)
            if can_shard(mesh, MODEL_AXIS, self.heads):
                kv[3] = MODEL_AXIS
            if can_shard(mesh, SEQ_AXIS, int(struct["kp"].shape[0])):
                kv[0] = SEQ_AXIS
            specs["kp"] = specs["vp"] = P(*kv)
        else:
            kv = [None, None, None, None, None]  # (slots, ln, ctx, h, hd)
            if can_shard(mesh, MODEL_AXIS, self.heads):
                kv[3] = MODEL_AXIS
            if can_shard(mesh, SEQ_AXIS, self.max_ctx):
                kv[2] = SEQ_AXIS
            specs["k"] = specs["v"] = P(*kv)
        if all(s == P() for s in specs.values()):
            return None
        return specs

    # -- shapes ---------------------------------------------------------------
    def input_signature(self, bucket: tuple) -> Any:
        (b,) = bucket
        p = self.max_prompt
        return (
            jax.ShapeDtypeStruct((b, p), jnp.int32),   # padded prompt ids
            jax.ShapeDtypeStruct((b,), jnp.int32),     # prompt length
            jax.ShapeDtypeStruct((b,), jnp.int32),     # seed
            jax.ShapeDtypeStruct((b,), jnp.int32),     # max_new_tokens
            jax.ShapeDtypeStruct((b,), jnp.float32),   # temperature
        )

    def gen_item_signature(self) -> Any:
        p = self.max_prompt
        return (
            jax.ShapeDtypeStruct((p,), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.float32),
        )

    def state_signature(self, slots: int) -> Any:
        ln, c, h, hd = self.layers, self.max_ctx, self.heads, self.head_dim
        n = self.max_new
        return {
            "k": jax.ShapeDtypeStruct((slots, ln, c, h, hd), self.dtype),
            "v": jax.ShapeDtypeStruct((slots, ln, c, h, hd), self.dtype),
            "pos": jax.ShapeDtypeStruct((slots,), jnp.int32),
            "tokens": jax.ShapeDtypeStruct((slots, n), jnp.int32),
            "n_new": jax.ShapeDtypeStruct((slots,), jnp.int32),
            "last": jax.ShapeDtypeStruct((slots,), jnp.int32),
            "done": jax.ShapeDtypeStruct((slots,), jnp.bool_),
            "seed": jax.ShapeDtypeStruct((slots,), jnp.int32),
            "max_new": jax.ShapeDtypeStruct((slots,), jnp.int32),
            "temp": jax.ShapeDtypeStruct((slots,), jnp.float32),
        }

    # -- shared device math ---------------------------------------------------
    def _attend_prefill(self, q, k, v, key_bias):
        """(B, P, H, hd) bidirectional attention with an additive per-key
        padding bias (B, P) — flash kernel or the dense twin."""
        if self.attention == "flash":
            from tpuserve.ops.flash_attention import flash_attention

            return flash_attention(q, k, v, key_bias)
        scale = q.shape[-1] ** -0.5
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        s = s + key_bias[:, None, None, :]
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    def _sample(self, logits, seed, position, temp):
        """Per-lane seeded sampling at a cache ``position``: greedy when
        temp == 0, Gumbel-max otherwise — deterministic either way, and
        identical between the locked-batch loop and the engine because the
        fold key is (seed, target cache position)."""
        def one(lg, sd, pos, t):
            key = jax.random.fold_in(jax.random.fold_in(
                jax.random.key(0), sd), pos)
            g = jax.random.gumbel(key, lg.shape, jnp.float32)
            safe_t = jnp.where(t > 0, t, 1.0)
            sampled = jnp.argmax(lg / safe_t + g)
            return jnp.where(t > 0, sampled, jnp.argmax(lg)).astype(jnp.int32)

        return jax.vmap(one)(logits.astype(jnp.float32), seed, position, temp)

    def _logits(self, params, x):
        return (_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
                .astype(jnp.float32) @ params["head"].astype(jnp.float32))

    def _mlp(self, lp, hx, dt):
        """The position-wise FFN delta for a normed hidden block ``hx``
        (..., d) — the dense gelu MLP, or the Switch-MoE twin when
        ``options.moe_experts`` > 0. One seam shared by all four forward
        bodies (prefill / decode / paged-chunk / paged-decode), so the MoE
        variant inherits every serving path at once."""
        if not self.moe_experts:
            return (jax.nn.gelu(hx @ lp["w_up"].astype(dt))
                    @ lp["w_down"].astype(dt))
        return self._moe_ffn(lp, hx, dt)

    def _moe_ffn(self, lp, hx, dt):
        """Top-1 Switch FFN over ``ops.moe.switch_route`` with GROUP SIZE
        ONE: every token routes independently with capacity 1, so no token
        is ever dropped and a lane's FFN output is a function of that lane
        alone. A batch-global capacity would let slot A's routing evict
        slot B's token — fine for training throughput, wrong for serving,
        where results must be independent of batch composition (the
        invariant every engine parity test gates on). Expert weights carry
        a leading (E, ...) dim sharded on "model" under TP — expert
        parallelism via shardings, no hand-written collectives."""
        from tpuserve.ops.moe import switch_route

        lead, d = hx.shape[:-1], hx.shape[-1]
        xt = hx.reshape(-1, d)
        logits = jnp.einsum("td,de->te", xt.astype(jnp.float32),
                            lp["router"].astype(jnp.float32))
        dispatch, combine, _aux = jax.vmap(
            lambda lg: switch_route(lg[None, :], 1))(logits)
        dispatch = dispatch[:, 0, :, 0].astype(dt)   # (T, E) 0/1 routing
        combine = combine[:, 0, :, 0].astype(dt)     # (T, E) gate-weighted
        xe = jnp.einsum("te,td->etd", dispatch, xt)
        up = jax.nn.gelu(
            jnp.einsum("etd,edf->etf", xe, lp["moe_up"].astype(dt)))
        down = jnp.einsum("etf,efd->etd", up, lp["moe_down"].astype(dt))
        out = jnp.einsum("te,etd->td", combine, down)
        return out.reshape(*lead, d).astype(hx.dtype)

    def _prefill(self, params, ids, n, seed, max_new, temp):
        """Batched prompt prefill -> the full decode state pytree (leading
        dim B): per-layer KV for the prompt, plus the FIRST sampled token.
        Shared verbatim by forward (locked batch) and init_state (engine)."""
        b, p = ids.shape
        ln, c, h, hd = self.layers, self.max_ctx, self.heads, self.head_dim
        dt = self.dtype
        x = (jnp.take(params["embed"], ids, axis=0)
             + params["pos"][None, :p, :]).astype(dt)
        key_bias = (jnp.arange(p)[None, :] >= n[:, None]) * jnp.float32(-1e9)
        kc = jnp.zeros((b, ln, c, h, hd), dt)
        vc = jnp.zeros((b, ln, c, h, hd), dt)
        for i in range(ln):
            lp = params[f"layer{i}"]
            hx = _norm(x, lp["ln1"]["scale"], lp["ln1"]["bias"])
            q = (hx @ lp["wq"].astype(dt)).reshape(b, p, h, hd)
            k = (hx @ lp["wk"].astype(dt)).reshape(b, p, h, hd)
            v = (hx @ lp["wv"].astype(dt)).reshape(b, p, h, hd)
            kc = kc.at[:, i, :p].set(k)
            vc = vc.at[:, i, :p].set(v)
            a = self._attend_prefill(q, k, v, key_bias).reshape(b, p, h * hd)
            x = x + a.astype(dt) @ lp["wo"].astype(dt)
            hx = _norm(x, lp["ln2"]["scale"], lp["ln2"]["bias"])
            x = x + self._mlp(lp, hx, dt)
        h_last = jnp.take_along_axis(
            x, jnp.maximum(n - 1, 0)[:, None, None], axis=1)[:, 0, :]
        first = self._sample(self._logits(params, h_last[:, None, :])[:, 0, :],
                             seed, n, temp)
        tokens = jnp.zeros((b, self.max_new), jnp.int32)
        tokens = tokens.at[:, 0].set(first)
        done = (first == self.eos_id) | (max_new <= 1)
        return {
            "k": kc, "v": vc, "pos": n, "tokens": tokens,
            "n_new": jnp.ones((b,), jnp.int32), "last": first, "done": done,
            "seed": seed, "max_new": max_new, "temp": temp,
        }

    def _decode_step(self, params, state):
        """One decode iteration over every lane: process ``last`` at cache
        index ``pos`` (writing its K/V), sample the token for pos+1.
        Finished (and free, zero-initialized) lanes freeze via ``done``."""
        kc, vc = state["k"], state["v"]
        b = kc.shape[0]
        ln, h, hd, c = self.layers, self.heads, self.head_dim, self.max_ctx
        dt = self.dtype
        pos = state["pos"]
        rows = jnp.arange(b)
        x = (jnp.take(params["embed"], state["last"], axis=0)
             + jnp.take(params["pos"], jnp.clip(pos, 0, c - 1), axis=0)
             ).astype(dt)
        mask = (jnp.arange(c)[None, :] > pos[:, None]) * jnp.float32(-1e9)
        for i in range(ln):
            lp = params[f"layer{i}"]
            hx = _norm(x, lp["ln1"]["scale"], lp["ln1"]["bias"])
            q = (hx @ lp["wq"].astype(dt)).reshape(b, h, hd)
            k = (hx @ lp["wk"].astype(dt)).reshape(b, h, hd)
            v = (hx @ lp["wv"].astype(dt)).reshape(b, h, hd)
            kc = kc.at[rows, i, jnp.clip(pos, 0, c - 1)].set(k)
            vc = vc.at[rows, i, jnp.clip(pos, 0, c - 1)].set(v)
            s = (jnp.einsum("bhd,bchd->bhc", q, kc[:, i])
                 .astype(jnp.float32) * (hd ** -0.5)) + mask[:, None, :]
            a = jax.nn.softmax(s, axis=-1).astype(dt)
            o = jnp.einsum("bhc,bchd->bhd", a, vc[:, i]).reshape(b, h * hd)
            x = x + o @ lp["wo"].astype(dt)
            hx = _norm(x, lp["ln2"]["scale"], lp["ln2"]["bias"])
            x = x + self._mlp(lp, hx, dt)
        logits = self._logits(params, x[:, None, :])[:, 0, :]
        sampled = self._sample(logits, state["seed"],
                               jnp.clip(pos + 1, 0, c - 1), state["temp"])
        done = state["done"]
        n_new = state["n_new"]
        write_idx = jnp.clip(n_new, 0, self.max_new - 1)
        tokens = state["tokens"].at[rows, write_idx].set(
            jnp.where(done, state["tokens"][rows, write_idx], sampled))
        n_new2 = jnp.where(done, n_new, n_new + 1)
        done2 = done | (sampled == self.eos_id) | (n_new2 >= state["max_new"])
        new_state = {
            "k": kc, "v": vc,
            "pos": jnp.where(done, pos, jnp.clip(pos + 1, 0, c - 1)),
            "tokens": tokens,
            "n_new": n_new2,
            "last": jnp.where(done, state["last"], sampled),
            "done": done2,
            "seed": state["seed"], "max_new": state["max_new"],
            "temp": state["temp"],
        }
        # The token buffer rides the per-step host fetch (slots x max_new
        # int32 — tens of KB) so the engine's emission channel can stream
        # each token the iteration it lands, without extra device reads.
        return new_state, {"done": done2, "n_new": n_new2, "tokens": tokens}

    # -- one-shot path (locked batch: static batcher + bench baseline) --------
    def forward(self, params: Any, batch: Any) -> dict:
        ids, n, seed, max_new, temp = batch
        state = self._prefill(params, ids, n, seed, max_new, temp)

        def body(_, st):
            st2, _out = self._decode_step(params, st)
            return st2

        # The locked batch runs the FULL cap for every lane — max_new only
        # freezes a lane's outputs, never shortens the loop. That cost gap
        # is precisely what the iteration-level engine removes.
        state = jax.lax.fori_loop(0, self.max_new - 1, body, state)
        return {"tokens": state["tokens"], "n_new": state["n_new"]}

    # -- engine decomposition (tpuserve.genserve) ------------------------------
    def init_state(self, params: Any, item: Any) -> Any:
        ids, n, seed, max_new, temp = item
        state = self._prefill(params, ids[None], n[None], seed[None],
                              max_new[None], temp[None])
        return jax.tree_util.tree_map(lambda x: x[0], state)

    def step(self, params: Any, state: Any) -> tuple[Any, dict]:
        # The state pytree's own shape selects the path (a host-side
        # structural check at trace time): a paged engine allocates the
        # kv_page_signature block, a dense one the state_signature block.
        if "kp" in state:  # tps-ok[TPS503]: pytree structure check at trace time
            return self._paged_decode_step(params, state)
        return self._decode_step(params, state)

    def extract(self, params: Any, state: Any, slot: Any) -> Any:
        idx = jax.lax.dynamic_index_in_dim
        return {
            "tokens": idx(state["tokens"], slot, 0, keepdims=False),
            "n_new": idx(state["n_new"], slot, 0, keepdims=False),
        }

    def gen_max_steps(self) -> int:
        return self.max_new

    # -- paged KV path (ISSUE 18; PagedAttention/vLLM) ------------------------
    # KV lives in one global pool of fixed-size pages --
    # (pages, layers, page_tokens, heads, head_dim) -- addressed through a
    # per-slot block table of TRACED page indices, so the one compiled
    # step serves every page assignment (the zero-recompile obligation
    # slot indices already carry). Global position p of a slot lives at
    # (bt[slot, p // page_tokens], p % page_tokens). Page 0 is the
    # write-sink sentinel: free and frozen lanes scribble there instead
    # of into pages the ledger may have re-handed to another request.

    supports_kv_paging = True

    def kv_pages_per_slot(self, page_tokens: int) -> int:
        return -(-self.max_ctx // int(page_tokens))

    def kv_page_signature(self, slots: int, pages: int,
                          page_tokens: int) -> Any:
        ln, h, hd = self.layers, self.heads, self.head_dim
        pps = self.kv_pages_per_slot(page_tokens)
        return {
            "kp": jax.ShapeDtypeStruct(
                (pages, ln, page_tokens, h, hd), self.dtype),
            "vp": jax.ShapeDtypeStruct(
                (pages, ln, page_tokens, h, hd), self.dtype),
            "bt": jax.ShapeDtypeStruct((slots, pps), jnp.int32),
            "pos": jax.ShapeDtypeStruct((slots,), jnp.int32),
            "tokens": jax.ShapeDtypeStruct((slots, self.max_new), jnp.int32),
            "n_new": jax.ShapeDtypeStruct((slots,), jnp.int32),
            "last": jax.ShapeDtypeStruct((slots,), jnp.int32),
            "done": jax.ShapeDtypeStruct((slots,), jnp.bool_),
            "seed": jax.ShapeDtypeStruct((slots,), jnp.int32),
            "max_new": jax.ShapeDtypeStruct((slots,), jnp.int32),
            "temp": jax.ShapeDtypeStruct((slots,), jnp.float32),
        }

    def pages_needed(self, item: Any, page_tokens: int) -> int:
        _ids, n, _seed, max_new, _temp = item
        return -(-(int(n) + int(max_new)) // int(page_tokens))

    def prompt_tokens(self, item: Any) -> int:
        return int(item[1])

    def kv_prefill_chunk(self, requested: int) -> int:
        if requested <= 0 or requested >= self.max_prompt:
            return self.max_prompt
        return int(requested)

    def _lane_update(self, state, slot, name, value):
        arr = state[name]
        return jax.lax.dynamic_update_index_in_dim(
            arr, jnp.asarray(value).astype(arr.dtype), slot, 0)

    def prefill_chunk(self, params: Any, state: Any, slot: Any, item: Any,
                      start: Any, pages: Any, *, chunk: int) -> Any:
        # Whole-prompt chunk (the prefill_chunk = 0 default) routes through
        # init_state VERBATIM and only changes where K/V is stored, so
        # paged == dense token parity holds by construction.
        if chunk >= self.max_prompt:
            return self._prefill_paged_single(params, state, slot, item,
                                              pages)
        return self._prefill_paged_chunk(params, state, slot, item, start,
                                         pages, chunk)

    def _scatter_pages(self, state, pages, n, positions, per_layer_kv):
        """Write per-position K/V rows into the page pool: position p goes
        to (pages[p // P], p % P); positions >= n (padding) divert to the
        sentinel. ``per_layer_kv(i) -> (k, v)`` each (len(positions), h, hd)."""
        kp, vp = state["kp"], state["vp"]
        P = kp.shape[2]
        pps = state["bt"].shape[1]
        w_pages = jnp.where(
            positions < n,
            jnp.take(pages, jnp.minimum(positions // P, pps - 1), axis=0),
            0)
        offs = positions % P
        for i in range(self.layers):
            k, v = per_layer_kv(i)
            kp = kp.at[w_pages, i, offs].set(k)
            vp = vp.at[w_pages, i, offs].set(v)
        return kp, vp

    def _prefill_paged_single(self, params, state, slot, item, pages):
        _ids, n, _seed, _max_new, _temp = item
        lane = self.init_state(params, item)  # dense prefill, b=1
        p = self.max_prompt
        kp, vp = self._scatter_pages(
            state, pages, n, jnp.arange(p),
            lambda i: (lane["k"][i, :p], lane["v"][i, :p]))
        new = {"kp": kp, "vp": vp,
               "bt": jax.lax.dynamic_update_index_in_dim(
                   state["bt"], pages, slot, 0)}
        for f in ("pos", "tokens", "n_new", "last", "done", "seed",
                  "max_new", "temp"):
            new[f] = self._lane_update(state, slot, f, lane[f])
        return new

    def _prefill_paged_chunk(self, params, state, slot, item, start, pages,
                             chunk: int):
        """One chunk of an incremental prompt prefill: BIDIRECTIONAL within
        the chunk, causal across chunks (earlier chunks' K/V are final by
        the time later chunks attend through them). Multi-chunk encoding is
        therefore NOT bit-identical to the one-pass bidirectional prefill —
        it is a deterministic function of (prompt, seed, chunk width)
        alone, independent of batch composition and of what else the
        engine interleaves (the invariant tests gate on). Non-final chunks
        leave the lane frozen (done=True, pos=0) so interleaved decode
        steps skip it; the final chunk samples the first token and arms
        the lane exactly like init_state does."""
        ids, n, seed, max_new, temp = item
        C = int(chunk)
        ln, h, hd = self.layers, self.heads, self.head_dim
        dt = self.dtype
        kp, vp = state["kp"], state["vp"]
        P = kp.shape[2]
        pps = state["bt"].shape[1]
        c_pad = pps * P
        bt = jax.lax.dynamic_update_index_in_dim(state["bt"], pages, slot, 0)
        cpos = start + jnp.arange(C)
        cids = jnp.take(ids, jnp.minimum(cpos, self.max_prompt - 1), axis=0)
        x = (jnp.take(params["embed"], cids, axis=0)
             + jnp.take(params["pos"],
                        jnp.minimum(cpos, self.max_ctx - 1), axis=0)
             ).astype(dt)
        kv_limit = jnp.minimum(start + C, n)
        w_pages = jnp.where(
            cpos < n,
            jnp.take(pages, jnp.minimum(cpos // P, pps - 1), axis=0), 0)
        offs = cpos % P
        mask = (jnp.arange(c_pad)[None, :] >= kv_limit) * jnp.float32(-1e9)
        for i in range(ln):
            lp = params[f"layer{i}"]
            hx = _norm(x, lp["ln1"]["scale"], lp["ln1"]["bias"])
            q = (hx @ lp["wq"].astype(dt)).reshape(C, h, hd)
            k = (hx @ lp["wk"].astype(dt)).reshape(C, h, hd)
            v = (hx @ lp["wv"].astype(dt)).reshape(C, h, hd)
            kp = kp.at[w_pages, i, offs].set(k)
            vp = vp.at[w_pages, i, offs].set(v)
            # Gather THIS slot's context (earlier chunks + the rows just
            # written) back out of the pool; sentinel rows sit past
            # kv_limit and are masked.
            kall = jnp.take(kp[:, i], pages, axis=0).reshape(c_pad, h, hd)
            vall = jnp.take(vp[:, i], pages, axis=0).reshape(c_pad, h, hd)
            s = (jnp.einsum("qhd,khd->hqk", q, kall).astype(jnp.float32)
                 * (hd ** -0.5)) + mask
            a = jax.nn.softmax(s, axis=-1).astype(dt)
            o = jnp.einsum("hqk,khd->qhd", a, vall).reshape(C, h * hd)
            x = x + o @ lp["wo"].astype(dt)
            hx = _norm(x, lp["ln2"]["scale"], lp["ln2"]["bias"])
            x = x + self._mlp(lp, hx, dt)
        last_off = jnp.clip(n - 1 - start, 0, C - 1)
        h_last = jax.lax.dynamic_index_in_dim(x, last_off, 0, keepdims=False)
        logits = self._logits(params, h_last[None, None, :])[0, 0]
        first = self._sample(logits[None], seed[None], n[None], temp[None])[0]
        is_final = (start + C) >= n
        first_tok = jnp.where(is_final, first, jnp.int32(0))
        new = {"kp": kp, "vp": vp, "bt": bt}
        lane = {
            "pos": jnp.where(is_final, n, jnp.int32(0)),
            "tokens": jnp.zeros((self.max_new,), jnp.int32)
                         .at[0].set(first_tok),
            "n_new": jnp.where(is_final, jnp.int32(1), jnp.int32(0)),
            "last": first_tok,
            "done": jnp.where(is_final,
                              (first == self.eos_id) | (max_new <= 1),
                              jnp.bool_(True)),
            "seed": seed, "max_new": max_new, "temp": temp,
        }
        for f, val in lane.items():
            new[f] = self._lane_update(state, slot, f, val)
        return new

    def _paged_decode_step(self, params, state):
        """The paged twin of _decode_step: identical math and sampling,
        but K/V reads gather through the block table and writes go to
        (page, offset) — frozen/free lanes' writes divert to the sentinel
        so a released slot can never scribble into re-handed pages."""
        kp, vp, bt = state["kp"], state["vp"], state["bt"]
        b, pps = bt.shape
        P = kp.shape[2]
        ln, h, hd, c = self.layers, self.heads, self.head_dim, self.max_ctx
        c_pad = pps * P
        dt = self.dtype
        pos = state["pos"]
        done = state["done"]
        rows = jnp.arange(b)
        x = (jnp.take(params["embed"], state["last"], axis=0)
             + jnp.take(params["pos"], jnp.clip(pos, 0, c - 1), axis=0)
             ).astype(dt)
        mask = (jnp.arange(c_pad)[None, :] > pos[:, None]) * jnp.float32(-1e9)
        cp = jnp.clip(pos, 0, c - 1)
        page_of = jnp.take_along_axis(bt, (cp // P)[:, None], axis=1)[:, 0]
        w_page = jnp.where(done, 0, page_of)
        offs = cp % P
        for i in range(ln):
            lp = params[f"layer{i}"]
            hx = _norm(x, lp["ln1"]["scale"], lp["ln1"]["bias"])
            q = (hx @ lp["wq"].astype(dt)).reshape(b, h, hd)
            k = (hx @ lp["wk"].astype(dt)).reshape(b, h, hd)
            v = (hx @ lp["wv"].astype(dt)).reshape(b, h, hd)
            kp = kp.at[w_page, i, offs].set(k)
            vp = vp.at[w_page, i, offs].set(v)
            kc = jnp.take(kp[:, i], bt, axis=0).reshape(b, c_pad, h, hd)
            vc = jnp.take(vp[:, i], bt, axis=0).reshape(b, c_pad, h, hd)
            s = (jnp.einsum("bhd,bchd->bhc", q, kc)
                 .astype(jnp.float32) * (hd ** -0.5)) + mask[:, None, :]
            a = jax.nn.softmax(s, axis=-1).astype(dt)
            o = jnp.einsum("bhc,bchd->bhd", a, vc).reshape(b, h * hd)
            x = x + o @ lp["wo"].astype(dt)
            hx = _norm(x, lp["ln2"]["scale"], lp["ln2"]["bias"])
            x = x + self._mlp(lp, hx, dt)
        logits = self._logits(params, x[:, None, :])[:, 0, :]
        sampled = self._sample(logits, state["seed"],
                               jnp.clip(pos + 1, 0, c - 1), state["temp"])
        n_new = state["n_new"]
        write_idx = jnp.clip(n_new, 0, self.max_new - 1)
        tokens = state["tokens"].at[rows, write_idx].set(
            jnp.where(done, state["tokens"][rows, write_idx], sampled))
        n_new2 = jnp.where(done, n_new, n_new + 1)
        done2 = done | (sampled == self.eos_id) | (n_new2 >= state["max_new"])
        new_state = {
            "kp": kp, "vp": vp, "bt": bt,
            "pos": jnp.where(done, pos, jnp.clip(pos + 1, 0, c - 1)),
            "tokens": tokens,
            "n_new": n_new2,
            "last": jnp.where(done, state["last"], sampled),
            "done": done2,
            "seed": state["seed"], "max_new": state["max_new"],
            "temp": state["temp"],
        }
        return new_state, {"done": done2, "n_new": n_new2, "tokens": tokens}

    # -- host side ------------------------------------------------------------
    def host_decode(self, payload: bytes, content_type: str) -> Any:
        if content_type.startswith("application/json"):
            body = json.loads(payload.decode("utf-8"))
            prompt = body.get("prompt")
            if not isinstance(prompt, str):
                raise ValueError('JSON body must contain "prompt": str')
            seed = int(body.get("seed", 0))
            max_new = int(body.get("max_new_tokens", self.max_new))
            temp = float(body.get("temperature", 0.0))
        else:
            prompt, seed, max_new, temp = payload.decode("utf-8"), 0, \
                self.max_new, 0.0
        if not 1 <= max_new <= self.max_new:
            raise ValueError(
                f"max_new_tokens must be in [1, {self.max_new}], "
                f"got {max_new}")
        if temp < 0:
            raise ValueError(f"temperature must be >= 0, got {temp}")
        tok = self.tokenizer
        pieces = tok.tokenize(prompt)
        ids = [tok.vocab.get(t, tok.unk_id) for t in pieces][: self.max_prompt]
        ids = ids or [tok.cls_id]  # an empty prompt still needs one position
        arr = np.full((self.max_prompt,), tok.pad_id, np.int32)
        arr[: len(ids)] = ids
        # Every sampling parameter is part of the item ON PURPOSE: the
        # result cache digests the whole tuple, so (prompt, seed=1) and
        # (prompt, seed=2) can never share a key (ISSUE 9 satellite).
        return (arr, np.int32(len(ids)), np.int32(seed), np.int32(max_new),
                np.float32(temp))

    def canary_item(self) -> Any:
        return self.host_decode(
            b'{"prompt": "canary", "seed": 1, "max_new_tokens": 2}',
            "application/json")

    def detokenize(self, token_ids: "list[int]") -> str:
        """WordPiece pieces back to text: '##' continuations merge, EOS and
        pads drop."""
        inv = self.tokenizer.inv
        words: list[str] = []
        for t in token_ids:
            piece = inv.get(int(t), "")
            if not piece or piece in ("[SEP]", "[PAD]", "[CLS]"):
                continue
            if piece.startswith("##") and words:
                words[-1] += piece[2:]
            else:
                words.append(piece)
        return " ".join(words)

    def _result(self, tokens: np.ndarray, n_new: int) -> dict:
        toks = [int(t) for t in np.asarray(tokens)[: int(n_new)]]
        return {"text": self.detokenize(toks), "tokens": toks,
                "n_tokens": len(toks)}

    def finalize(self, extracted: Any, item: Any) -> Any:
        return self._result(extracted["tokens"], int(extracted["n_new"]))

    def result_units(self, result: Any) -> float:
        """Tokens generated — the tokens/s headline unit."""
        return float(result.get("n_tokens", 1))

    # -- streaming (ISSUE 17) -------------------------------------------------
    def stream_units(self, step_out: dict, slot: int, stream: dict) -> list:
        """Token units newly landed for one slot this iteration. The text
        delta is incremental detokenize: detokenize() is append-only under
        WordPiece merges (a new word appends " w", a "##" continuation
        appends its suffix, EOS/PAD add nothing), so the concatenation of
        every unit's "text" equals the unary result's "text" byte-for-byte
        — the stream drill's audit anchor."""
        n = int(step_out["n_new"][slot])
        sent = int(stream.get("sent", 0))
        if n <= sent:
            return []
        toks = [int(t) for t in step_out["tokens"][slot][:n]]
        prev = stream.get("text", "")
        units = []
        for i in range(sent, n):
            text = self.detokenize(toks[: i + 1])
            units.append({"type": "token", "text": text[len(prev):],
                          "token": toks[i], "index": i})
            prev = text
        stream["sent"] = n
        stream["text"] = prev
        return units

    def stream_finish_reason(self, result: Any) -> str:
        toks = result.get("tokens") or []
        return "stop" if toks and toks[-1] == self.eos_id else "length"

    def stream_usage(self, result: Any) -> dict:
        return {"completion_tokens": int(result.get("n_tokens", 0))}

    def host_postprocess(self, outputs: dict, n_valid: int) -> list[dict]:
        return [self._result(outputs["tokens"][r], outputs["n_new"][r])
                for r in range(n_valid)]


def create(cfg: ModelConfig) -> TextGenServing:
    return TextGenServing(cfg)
