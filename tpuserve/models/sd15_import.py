"""LDM-checkpoint -> Flax weight mapping for the SD 1.5 family (SURVEY.md §2
C6; VERDICT r3 missing 1 / next 2).

Published SD 1.5 artifacts ship as single-file torch checkpoints
(``v1-5-pruned-emaonly.safetensors`` / ``.ckpt``) in the original
CompVis/LDM state_dict layout:

- ``cond_stage_model.transformer.text_model.*`` — CLIP ViT-L/14 text tower
  (transformers naming underneath: ``encoder.layers.{i}.self_attn.q_proj``…)
- ``model.diffusion_model.*`` — the UNet (``input_blocks.{k}``,
  ``middle_block``, ``output_blocks.{k}``; each block is a numbered list of
  [ResBlock, SpatialTransformer?, Up/Downsample?])
- ``first_stage_model.*`` — the VAE; serving only needs ``post_quant_conv``
  and ``decoder.*`` (the encoder and any ``model_ema`` copies are ignored).

Layout translations (torch -> flax):

- conv ``(O, I, kh, kw)`` -> ``(kh, kw, I, O)``; linear ``(O, I)`` -> ``(I, O)``
- norm ``weight`` -> ``scale``
- attention q/k/v/out linears -> ``nn.MultiHeadDotProductAttention``'s
  DenseGeneral shapes ``(d_in, heads, head_dim)`` / ``(heads, head_dim, d)``;
  SD's UNet attention has no q/k/v bias, so those flax biases restore as
  zeros (numerically identical).
- GEGLU half-swap: LDM computes ``x, gate = proj(h).chunk(2)`` while our
  ``ff_up`` splits ``gate, val`` — the two output halves of the projection
  swap places on import. (Caught by the randomized-weight parity test;
  an unswapped import still runs but produces garbage images.)
- VAE mid attention q/k/v/proj_out are 1x1 convs in LDM; ours are Dense —
  squeeze the spatial dims and transpose.

Everything is validated against ``jax.eval_shape(model.init_params)`` at the
end: tree structure and every leaf shape must match, so a config/artifact
mismatch (wrong unet_ch, synthetic tokenizer vs the 49408-token CLIP BPE)
fails at import time with guidance instead of at compile time.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


def _conv(f: dict, src: str) -> dict:
    return {"kernel": f[f"{src}.weight"].transpose(2, 3, 1, 0),
            "bias": f[f"{src}.bias"]}


def _lin(f: dict, src: str) -> dict:
    return {"kernel": f[f"{src}.weight"].T, "bias": f[f"{src}.bias"]}


def _norm(f: dict, src: str) -> dict:
    return {"scale": f[f"{src}.weight"], "bias": f[f"{src}.bias"]}


def _mha_from_linears(f: dict, heads: int, q: str, k: str, v: str, o: str,
                      qkv_bias: bool) -> dict:
    """Four torch linears -> one flax MultiHeadDotProductAttention subtree."""
    wq, wk, wv, wo = (f[f"{n}.weight"] for n in (q, k, v, o))
    d_inner, d_q = wq.shape
    head_dim = d_inner // heads

    def in_proj(w, name):
        b = (f[f"{name}.bias"] if qkv_bias
             else np.zeros((d_inner,), w.dtype))
        return {"kernel": w.T.reshape(w.shape[1], heads, head_dim),
                "bias": b.reshape(heads, head_dim)}

    return {
        "query": in_proj(wq, q),
        "key": in_proj(wk, k),
        "value": in_proj(wv, v),
        "out": {"kernel": wo.T.reshape(heads, head_dim, wo.shape[0]),
                "bias": f[f"{o}.bias"]},
    }


def _geglu_up(f: dict, src: str) -> dict:
    """LDM GEGLU proj (x-half first, gate-half second) -> our ff_up
    (gate-half first, val-half second)."""
    w = f[f"{src}.weight"]  # (2*inner, d)
    b = f[f"{src}.bias"]
    inner = w.shape[0] // 2
    return {"kernel": np.concatenate([w[inner:], w[:inner]], axis=0).T,
            "bias": np.concatenate([b[inner:], b[:inner]], axis=0)}


# -- tower mappers ------------------------------------------------------------

def map_clip_text(f: dict, prefix: str, layers: int, heads: int) -> dict:
    """transformers CLIPTextModel naming (the layout inside LDM checkpoints
    under ``cond_stage_model.transformer.``) -> CLIPTextEncoder params."""
    p: dict = {
        "token_embed": {
            "embedding": f[f"{prefix}embeddings.token_embedding.weight"]},
        "pos_embed": f[f"{prefix}embeddings.position_embedding.weight"],
        "ln_final": _norm(f, f"{prefix}final_layer_norm"),
    }
    for i in range(layers):
        lp = f"{prefix}encoder.layers.{i}."
        p[f"layer{i}"] = {
            "ln1": _norm(f, f"{lp}layer_norm1"),
            "attn": _mha_from_linears(
                f, heads, f"{lp}self_attn.q_proj", f"{lp}self_attn.k_proj",
                f"{lp}self_attn.v_proj", f"{lp}self_attn.out_proj",
                qkv_bias=True),
            "ln2": _norm(f, f"{lp}layer_norm2"),
            "mlp_up": _lin(f, f"{lp}mlp.fc1"),
            "mlp_down": _lin(f, f"{lp}mlp.fc2"),
        }
    return p


def _map_unet_resblock(f: dict, src: str, has_skip: bool) -> dict:
    p = {
        "norm1": _norm(f, f"{src}.in_layers.0"),
        "conv1": _conv(f, f"{src}.in_layers.2"),
        "temb_proj": _lin(f, f"{src}.emb_layers.1"),
        "norm2": _norm(f, f"{src}.out_layers.0"),
        "conv2": _conv(f, f"{src}.out_layers.3"),
    }
    if has_skip:
        p["skip"] = _conv(f, f"{src}.skip_connection")
    return p


def _map_spatial_transformer(f: dict, src: str, heads: int) -> dict:
    tb = f"{src}.transformer_blocks.0"
    return {
        "norm": _norm(f, f"{src}.norm"),
        "proj_in": _conv(f, f"{src}.proj_in"),
        "block": {
            "ln1": _norm(f, f"{tb}.norm1"),
            "self_attn": _mha_from_linears(
                f, heads, f"{tb}.attn1.to_q", f"{tb}.attn1.to_k",
                f"{tb}.attn1.to_v", f"{tb}.attn1.to_out.0", qkv_bias=False),
            "ln2": _norm(f, f"{tb}.norm2"),
            "cross_attn": _mha_from_linears(
                f, heads, f"{tb}.attn2.to_q", f"{tb}.attn2.to_k",
                f"{tb}.attn2.to_v", f"{tb}.attn2.to_out.0", qkv_bias=False),
            "ln3": _norm(f, f"{tb}.norm3"),
            "ff_up": _geglu_up(f, f"{tb}.ff.net.0.proj"),
            "ff_down": _lin(f, f"{tb}.ff.net.2"),
        },
        "proj_out": _conv(f, f"{src}.proj_out"),
    }


def map_unet(f: dict, prefix: str, model_ch: int, mults, num_res: int,
             attn_levels, heads: int) -> dict:
    """LDM ``model.diffusion_model.*`` -> our UNet params. The traversal
    mirrors UNet.__call__'s loop structure exactly, so the input_blocks /
    output_blocks numbering is derived, not hard-coded."""
    p: dict = {
        "time1": _lin(f, f"{prefix}time_embed.0"),
        "time2": _lin(f, f"{prefix}time_embed.2"),
        "conv_in": _conv(f, f"{prefix}input_blocks.0.0"),
        "norm_out": _norm(f, f"{prefix}out.0"),
        "conv_out": _conv(f, f"{prefix}out.2"),
    }
    # Down path: channel bookkeeping decides which ResBlocks carry a skip
    # projection (present iff in_ch != out_ch).
    k = 1
    ch = model_ch
    for i, m in enumerate(mults):
        out_ch = model_ch * m
        for j in range(num_res):
            p[f"down{i}_res{j}"] = _map_unet_resblock(
                f, f"{prefix}input_blocks.{k}.0", has_skip=ch != out_ch)
            ch = out_ch
            if i in attn_levels:
                p[f"down{i}_attn{j}"] = _map_spatial_transformer(
                    f, f"{prefix}input_blocks.{k}.1", heads)
            k += 1
        if i != len(mults) - 1:
            p[f"down{i}_ds"] = _conv(f, f"{prefix}input_blocks.{k}.0.op")
            k += 1
    # Middle.
    p["mid_res1"] = _map_unet_resblock(f, f"{prefix}middle_block.0", False)
    p["mid_attn"] = _map_spatial_transformer(f, f"{prefix}middle_block.1", heads)
    p["mid_res2"] = _map_unet_resblock(f, f"{prefix}middle_block.2", False)
    # Up path: every ResBlock consumes a skip concat, so in_ch != out_ch
    # always and the skip projection is always present.
    k = 0
    for i, m in reversed(list(enumerate(mults))):
        for j in range(num_res + 1):
            p[f"up{i}_res{j}"] = _map_unet_resblock(
                f, f"{prefix}output_blocks.{k}.0", has_skip=True)
            idx = 1
            if i in attn_levels:
                p[f"up{i}_attn{j}"] = _map_spatial_transformer(
                    f, f"{prefix}output_blocks.{k}.1", heads)
                idx = 2
            if i != 0 and j == num_res:
                p[f"up{i}_us"] = _conv(f, f"{prefix}output_blocks.{k}.{idx}.conv")
            k += 1
    return p


def _map_vae_resblock(f: dict, src: str, has_skip: bool) -> dict:
    p = {
        "norm1": _norm(f, f"{src}.norm1"),
        "conv1": _conv(f, f"{src}.conv1"),
        "norm2": _norm(f, f"{src}.norm2"),
        "conv2": _conv(f, f"{src}.conv2"),
    }
    if has_skip:
        p["skip"] = _conv(f, f"{src}.nin_shortcut")
    return p


def _vae_attn_dense(f: dict, src: str) -> dict:
    """1x1 conv (C, C, 1, 1) -> Dense kernel (C, C)."""
    w = f[f"{src}.weight"]
    return {"kernel": w.reshape(w.shape[0], w.shape[1]).T,
            "bias": f[f"{src}.bias"]}


def map_vae_decoder(f: dict, prefix: str, ch: int, mults) -> dict:
    """LDM ``first_stage_model.{post_quant_conv,decoder.*}`` -> VAEDecoder
    params. LDM indexes ``decoder.up.{i}`` by resolution level (up.3 runs
    first), matching our ``up{i}_*`` naming directly."""
    d = f"{prefix}decoder."
    top = ch * mults[-1]
    p: dict = {
        "post_quant": _conv(f, f"{prefix}post_quant_conv"),
        "conv_in": _conv(f, f"{d}conv_in"),
        "mid_res1": _map_vae_resblock(f, f"{d}mid.block_1", False),
        "mid_attn": {
            "norm": _norm(f, f"{d}mid.attn_1.norm"),
            "q": _vae_attn_dense(f, f"{d}mid.attn_1.q"),
            "k": _vae_attn_dense(f, f"{d}mid.attn_1.k"),
            "v": _vae_attn_dense(f, f"{d}mid.attn_1.v"),
            "proj": _vae_attn_dense(f, f"{d}mid.attn_1.proj_out"),
        },
        "mid_res2": _map_vae_resblock(f, f"{d}mid.block_2", False),
        "norm_out": _norm(f, f"{d}norm_out"),
        "conv_out": _conv(f, f"{d}conv_out"),
    }
    in_ch = top
    for i, m in reversed(list(enumerate(mults))):
        out_ch = ch * m
        for j in range(3):
            p[f"up{i}_res{j}"] = _map_vae_resblock(
                f, f"{d}up.{i}.block.{j}", has_skip=in_ch != out_ch)
            in_ch = out_ch
        if i != 0:
            p[f"up{i}_us"] = _conv(f, f"{d}up.{i}.upsample.conv")
    return p


# -- entry point --------------------------------------------------------------

LDM_PREFIXES = ("cond_stage_model.transformer.",
                "model.diffusion_model.",
                "first_stage_model.")


def import_ldm_checkpoint(model, flat: dict[str, np.ndarray]) -> Any:
    """Single-file LDM/CompVis SD checkpoint -> SD15Serving param tree."""
    missing = [p for p in LDM_PREFIXES
               if not any(k.startswith(p) for k in flat)]
    if missing:
        raise ValueError(
            "torch checkpoint is not a single-file SD/LDM artifact (no keys "
            f"under {missing}); SD 1.5 import expects the published "
            "v1-5-pruned*.safetensors / .ckpt layout")

    try:
        params = {
            "text": {"params": map_clip_text(
                flat, "cond_stage_model.transformer.text_model.",
                layers=model.text_encoder.layers,
                heads=model.text_encoder.heads)},
            "unet": {"params": map_unet(
                flat, "model.diffusion_model.",
                model_ch=model.unet.model_ch, mults=tuple(model.unet.mults),
                num_res=model.unet.num_res,
                attn_levels=tuple(model.unet.attn_levels),
                heads=model.unet.heads)},
            "vae": {"params": map_vae_decoder(
                flat, "first_stage_model.", ch=model.vae.ch,
                mults=tuple(model.vae.mults))},
        }
    except KeyError as e:
        raise ValueError(
            f"SD checkpoint is missing expected tensor {e}; the model's "
            "unet_ch/unet_mults/text_layers options must describe the same "
            "architecture as the artifact (defaults = SD 1.5)") from e

    want = jax.eval_shape(model.init_params, jax.random.key(0))
    got_l, got_def = jax.tree_util.tree_flatten_with_path(params)
    want_l, want_def = jax.tree_util.tree_flatten_with_path(want)
    if got_def != want_def:
        raise ValueError(
            "imported SD tree structure does not match the module "
            "(config options must describe the artifact's architecture)")
    for (gp, g), (wp, w) in zip(got_l, want_l):
        if tuple(g.shape) != tuple(w.shape):
            name = jax.tree_util.keystr(gp)
            hint = ""
            if "token_embed" in name:
                hint = (" — vocabulary mismatch: real SD weights need the "
                        "real CLIP BPE tokenizer (options bpe_vocab + "
                        "bpe_merges), not the synthetic vocab")
            raise ValueError(
                f"imported SD leaf {name} has shape {tuple(g.shape)}, module "
                f"expects {tuple(w.shape)}{hint}")
    return params
