"""Shared serving plumbing for image-classifier families (C4).

Every vision classifier serves the same way (SURVEY.md §3c): host decodes to
the configured wire format (rgb8 or yuv420 planes), the device executable
fuses resize/normalize in front of the network and softmax+top-k behind it,
and the host formats the tiny (B, k) results. Families subclass and provide
``make_module`` (the flax network) and optionally ``partition_rules``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from tpuserve import frame, preproc
from tpuserve.config import ModelConfig
from tpuserve.models.base import ServingModel


class ImageClassifierServing(ServingModel):
    """ServingModel base for (B, H, W, 3) -> class-probability models."""

    TOP_K = 5

    def __init__(self, cfg: ModelConfig) -> None:
        super().__init__(cfg)
        self.dtype = jnp.dtype(cfg.dtype)
        self.module = self.make_module(cfg)
        self.top_k = min(self.TOP_K, cfg.num_classes)
        # Normalization the network was trained with, as (mean, std) applied
        # after the /255 scale. Default torchvision-style ImageNet stats;
        # override per model in options — e.g. Keras MobileNetV3 weights
        # expect x/127.5 - 1, i.e. mean = std = (0.5, 0.5, 0.5).
        self.norm_mean = tuple(cfg.options.get("preproc_mean", preproc.IMAGENET_MEAN))
        self.norm_std = tuple(cfg.options.get("preproc_std", preproc.IMAGENET_STD))

    def make_module(self, cfg: ModelConfig):
        raise NotImplementedError

    def init_params(self, rng: jax.Array) -> Any:
        dummy = jnp.zeros((1, self.cfg.image_size, self.cfg.image_size, 3), self.dtype)
        return self.module.init(rng, dummy)

    def input_signature(self, bucket: tuple) -> Any:
        (b,) = bucket
        w = self.cfg.wire_size
        if self.cfg.wire_format == "yuv420":
            h = w // 2
            return (
                jax.ShapeDtypeStruct((b, w, w), jnp.uint8),
                jax.ShapeDtypeStruct((b, h, h), jnp.uint8),
                jax.ShapeDtypeStruct((b, h, h), jnp.uint8),
            )
        return jax.ShapeDtypeStruct((b, w, w, 3), jnp.uint8)

    def device_preprocess(self, batch: Any) -> Any:
        """Wire-format dispatch: device-side unpack/resize/normalize
        (jittable), fused by XLA into the first conv. Raw uint8 RGB or
        YUV420 planes in, normalized compute-dtype NHWC out — the fused-
        preproc seam (ServingModel.device_preprocess) shared by every
        vision family (classifiers and detection)."""
        if self.cfg.wire_format == "yuv420":
            y, u, v = batch
            return preproc.device_prepare_images_yuv420(
                y, u, v, self.cfg.image_size, dtype=self.dtype,
                mean=self.norm_mean, std=self.norm_std)
        return preproc.device_prepare_images(batch, self.cfg.image_size, dtype=self.dtype,
                                             mean=self.norm_mean, std=self.norm_std)

    def prepare_batch(self, batch: Any) -> Any:
        """Historical name for ``device_preprocess`` (training utilities and
        parity tests call it); same function."""
        return self.device_preprocess(batch)

    def forward(self, params: Any, batch: Any) -> dict:
        x = self.device_preprocess(batch)
        logits = self.module.apply(params, x)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        top_p, top_i = jax.lax.top_k(probs, self.top_k)
        return {"probs": top_p, "indices": top_i}

    def host_decode(self, payload: bytes, content_type: str) -> Any:
        if self.cfg.wire_format == "yuv420":
            return preproc.decode_image_yuv420(
                payload, content_type, self.cfg.wire_size, model=self.name)
        return preproc.decode_image(payload, content_type, edge=self.cfg.wire_size)

    def host_decode_items(self, payload: bytes, content_type: str) -> tuple[list, bool]:
        """Framed bodies parse zero-copy (the ingest fast path); npy bodies
        parse once: (N, H, W, 3) is a client batch, (H, W, 3) a single
        item; other content types take the single-image path."""
        if content_type == frame.CONTENT_TYPE:
            # Zero-copy frame views at the model's exact wire contract; the
            # one copy happens in assemble_into (tpuserve.frame docstring).
            items = frame.parse_frame(
                payload,
                kind=frame.KIND_BY_WIRE_FORMAT[self.cfg.wire_format],
                edge=self.cfg.wire_size,
                max_items=self.MAX_ITEMS_PER_REQUEST)
            return items, True
        if content_type != "application/x-npy":
            return [self.host_decode(payload, content_type)], False
        items, batched = preproc.decode_npy_items(
            payload, self.cfg.wire_size, self.MAX_ITEMS_PER_REQUEST)
        if self.cfg.wire_format == "yuv420":
            items = [preproc.rgb_to_yuv420(a) for a in items]
        return items, batched

    def canary_item(self) -> Any:
        if self.cfg.wire_format == "yuv420":
            w, h = self.cfg.wire_size, self.cfg.wire_size // 2
            return (np.zeros((w, w), np.uint8), np.full((h, h), 128, np.uint8),
                    np.full((h, h), 128, np.uint8))
        return super().canary_item()

    def host_postprocess(self, outputs: dict, n_valid: int) -> list[dict]:
        return self.format_top_k(outputs, n_valid)
