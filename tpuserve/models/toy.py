"""Toy model family: a tiny MLP classifier used by the test suite.

Fast to compile on CPU, exercises the full ServingModel contract (on-device
preproc, top-k postproc, padding semantics) without real-model compile times.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from tpuserve.config import ModelConfig
from tpuserve.models.base import ServingModel

EDGE = 8  # toy wire shape: (8, 8, 3) uint8


class ToyServing(ServingModel):
    TOP_K = 3

    def __init__(self, cfg: ModelConfig) -> None:
        super().__init__(cfg)
        self.dtype = jnp.dtype(cfg.dtype)
        self.hidden = int(cfg.options.get("hidden", 32))

    def init_params(self, rng: jax.Array) -> Any:
        k1, k2 = jax.random.split(rng)
        d_in = EDGE * EDGE * 3
        return {
            "w1": jax.random.normal(k1, (d_in, self.hidden), jnp.float32) * 0.02,
            "b1": jnp.zeros((self.hidden,), jnp.float32),
            "w2": jax.random.normal(k2, (self.hidden, self.cfg.num_classes), jnp.float32) * 0.02,
            "b2": jnp.zeros((self.cfg.num_classes,), jnp.float32),
        }

    def input_signature(self, bucket: tuple) -> Any:
        (b,) = bucket
        return jax.ShapeDtypeStruct((b, EDGE, EDGE, 3), jnp.uint8)

    def device_preprocess(self, batch: jax.Array) -> jax.Array:
        """Fused-preproc seam: uint8 wire -> flattened [0,1] compute-dtype."""
        return batch.astype(self.dtype).reshape(batch.shape[0], -1) / 255.0

    def forward(self, params: Any, batch: jax.Array) -> dict:
        x = self.device_preprocess(batch)
        h = jnp.tanh(x @ params["w1"].astype(self.dtype) + params["b1"].astype(self.dtype))
        logits = h @ params["w2"].astype(self.dtype) + params["b2"].astype(self.dtype)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        k = min(self.TOP_K, self.cfg.num_classes)
        top_p, top_i = jax.lax.top_k(probs, k)
        return {"probs": top_p, "indices": top_i}

    def host_decode(self, payload: bytes, content_type: str) -> np.ndarray:
        from tpuserve import preproc

        return preproc.decode_image(payload, content_type, edge=EDGE)

    def host_decode_items(self, payload: bytes, content_type: str) -> tuple[list, bool]:
        """Framed (zero-copy) and npy client batches, sharing the vision
        wire contracts (one parse either way)."""
        from tpuserve import frame, preproc

        if content_type == frame.CONTENT_TYPE:
            return frame.parse_frame(
                payload, kind=frame.KIND_RGB8, edge=EDGE,
                max_items=self.MAX_ITEMS_PER_REQUEST), True
        if content_type != "application/x-npy":
            return [self.host_decode(payload, content_type)], False
        return preproc.decode_npy_items(payload, EDGE, self.MAX_ITEMS_PER_REQUEST)

    def host_postprocess(self, outputs: dict, n_valid: int) -> list[dict]:
        return self.format_top_k(outputs, n_valid)

    def canary_item(self) -> np.ndarray:
        return np.zeros((EDGE, EDGE, 3), dtype=np.uint8)


def create(cfg: ModelConfig) -> ToyServing:
    return ToyServing(cfg)
