"""ResNet-50 ImageNet classifier (SURVEY.md §2 C4; BASELINE.json config 1).

TPU-first shaping decisions:
- NHWC layout end-to-end (XLA:TPU's native conv layout; the MXU sees large
  bf16 convs with no transposes).
- On-device fused preprocessing: uint8 (B,256,256,3) crosses PCIe; bilinear
  resize to 224 + normalize happen in front of conv1 inside the executable
  (tpuserve.preproc.device_prepare_images).
- On-device postprocessing: softmax + top-k (lax.top_k) so only (B,5) indices
  and probabilities cross back to the host.
- BatchNorm folded to inference mode (use_running_average=True); batch_stats
  live in the param pytree like any other weights.

Architecture: standard ResNet-v1.5 bottleneck [3,4,6,3] (He et al. 2015,
torchvision convention: stride-2 on the 3x3 of downsampling bottlenecks).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tpuserve import preproc
from tpuserve.config import ModelConfig
from tpuserve.models.base import ServingModel


class Bottleneck(nn.Module):
    features: int
    strides: int = 1
    projection: bool = False
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        bn = partial(nn.BatchNorm, use_running_average=True, momentum=0.9,
                     epsilon=1e-5, dtype=self.dtype)
        residual = x
        y = conv(self.features, (1, 1), name="conv1")(x)
        y = nn.relu(bn(name="bn1")(y))
        y = conv(self.features, (3, 3), strides=(self.strides, self.strides), name="conv2")(y)
        y = nn.relu(bn(name="bn2")(y))
        y = conv(self.features * 4, (1, 1), name="conv3")(y)
        y = bn(name="bn3")(y)
        if self.projection:
            residual = conv(self.features * 4, (1, 1),
                            strides=(self.strides, self.strides), name="proj_conv")(x)
            residual = bn(name="proj_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype, name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=True, momentum=0.9, epsilon=1e-5,
                         dtype=self.dtype, name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, n_blocks in enumerate(self.stage_sizes):
            features = 64 * 2**i
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = Bottleneck(features, strides=strides, projection=(j == 0),
                               dtype=self.dtype, name=f"stage{i + 1}_block{j + 1}")(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


class ResNet50Serving(ServingModel):
    TOP_K = 5

    def __init__(self, cfg: ModelConfig) -> None:
        super().__init__(cfg)
        self.dtype = jnp.dtype(cfg.dtype)
        self.module = ResNet(num_classes=cfg.num_classes, dtype=self.dtype)

    def init_params(self, rng: jax.Array) -> Any:
        dummy = jnp.zeros((1, self.cfg.image_size, self.cfg.image_size, 3), self.dtype)
        return self.module.init(rng, dummy)

    def input_signature(self, bucket: tuple) -> Any:
        (b,) = bucket
        w = self.cfg.wire_size
        if self.cfg.wire_format == "yuv420":
            h = w // 2
            return (
                jax.ShapeDtypeStruct((b, w, w), jnp.uint8),
                jax.ShapeDtypeStruct((b, h, h), jnp.uint8),
                jax.ShapeDtypeStruct((b, h, h), jnp.uint8),
            )
        return jax.ShapeDtypeStruct((b, w, w, 3), jnp.uint8)

    def forward(self, params: Any, batch: Any) -> dict:
        if self.cfg.wire_format == "yuv420":
            y, u, v = batch
            x = preproc.device_prepare_images_yuv420(
                y, u, v, self.cfg.image_size, dtype=self.dtype)
        else:
            x = preproc.device_prepare_images(batch, self.cfg.image_size, dtype=self.dtype)
        logits = self.module.apply(params, x)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        top_p, top_i = jax.lax.top_k(probs, self.TOP_K)
        return {"probs": top_p, "indices": top_i}

    def host_decode(self, payload: bytes, content_type: str) -> Any:
        if self.cfg.wire_format == "yuv420":
            return preproc.decode_image_yuv420(payload, content_type, self.cfg.wire_size)
        return preproc.decode_image(payload, content_type, edge=self.cfg.wire_size)

    def canary_item(self) -> Any:
        if self.cfg.wire_format == "yuv420":
            w, h = self.cfg.wire_size, self.cfg.wire_size // 2
            return (np.zeros((w, w), np.uint8), np.full((h, h), 128, np.uint8),
                    np.full((h, h), 128, np.uint8))
        return super().canary_item()

    def host_postprocess(self, outputs: dict, n_valid: int) -> list[dict]:
        probs = outputs["probs"][:n_valid]
        idx = outputs["indices"][:n_valid]
        return [
            {
                "top_k": [
                    {"class": int(i), "prob": float(p)}
                    for i, p in zip(idx[r], probs[r])
                ]
            }
            for r in range(n_valid)
        ]

    def partition_rules(self):
        """TP rules (off unless cfg.tp > 1): shard wide convs/dense on 'model'."""
        from jax.sharding import PartitionSpec as P

        if self.cfg.tp <= 1:
            return [(".*", P())]
        return [
            (r"head/kernel", P(None, "model")),
            (r"conv\d?/kernel", P(None, None, None, "model")),
            (r".*", P()),
        ]


def create(cfg: ModelConfig) -> ResNet50Serving:
    return ResNet50Serving(cfg)
