"""ResNet-50 ImageNet classifier (SURVEY.md §2 C4; BASELINE.json config 1).

TPU-first shaping decisions:
- NHWC layout end-to-end (XLA:TPU's native conv layout; the MXU sees large
  bf16 convs with no transposes).
- On-device fused preprocessing: uint8 wire crosses the link; bilinear resize
  + normalize happen in front of conv1 inside the executable
  (tpuserve.preproc; serving plumbing in tpuserve.models.vision).
- On-device postprocessing: softmax + top-k (lax.top_k) so only (B,5) indices
  and probabilities cross back to the host.
- BatchNorm folded to inference mode (use_running_average=True); batch_stats
  live in the param pytree like any other weights.

Architecture: standard ResNet bottleneck [3,4,6,3] (He et al. 2015). Two
downsample conventions, selected by ``options.v1_downsample``:
- False (default): v1.5 / torchvision — stride-2 on the 3x3 (conv2).
- True: original v1 / Keras applications — stride-2 on the first 1x1 (conv1),
  for weight-parity with models using that convention.
``options.bn_eps`` matches the source framework (Keras uses 1.001e-5).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from tpuserve import quantize as qz
from tpuserve.config import ModelConfig
from tpuserve.models.vision import ImageClassifierServing


class Bottleneck(nn.Module):
    features: int
    strides: int = 1
    projection: bool = False
    v1_downsample: bool = False
    bn_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # True: the three 1x1 convs run via quantize.Int8Conv1x1 (int8 MXU path
    # when the runtime leaves their kernels quantized — quantize = "int8c";
    # ~45% of block FLOPs). The 3x3 stays a regular conv either way.
    quantize_compute: bool = False

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        if self.quantize_compute:
            conv1x1 = lambda f, strides=(1, 1), name=None: qz.Int8Conv1x1(  # noqa: E731
                f, strides=strides, dtype=self.dtype, name=name)
        else:
            conv1x1 = lambda f, strides=(1, 1), name=None: conv(  # noqa: E731
                f, (1, 1), strides=strides, name=name)
        bn = partial(nn.BatchNorm, use_running_average=True, momentum=0.9,
                     epsilon=self.bn_eps, dtype=self.dtype)
        s = (self.strides, self.strides)
        s1, s2 = (s, (1, 1)) if self.v1_downsample else ((1, 1), s)
        residual = x
        y = conv1x1(self.features, strides=s1, name="conv1")(x)
        y = nn.relu(bn(name="bn1")(y))
        y = conv(self.features, (3, 3), strides=s2, name="conv2")(y)
        y = nn.relu(bn(name="bn2")(y))
        y = conv1x1(self.features * 4, name="conv3")(y)
        y = bn(name="bn3")(y)
        if self.projection:
            residual = conv1x1(self.features * 4, strides=s,
                               name="proj_conv")(x)
            residual = bn(name="proj_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    num_classes: int = 1000
    v1_downsample: bool = False
    bn_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    quantize_compute: bool = False

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype, name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=True, momentum=0.9,
                         epsilon=self.bn_eps, dtype=self.dtype, name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, n_blocks in enumerate(self.stage_sizes):
            features = 64 * 2**i
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = Bottleneck(features, strides=strides, projection=(j == 0),
                               v1_downsample=self.v1_downsample,
                               bn_eps=self.bn_eps, dtype=self.dtype,
                               quantize_compute=self.quantize_compute,
                               name=f"stage{i + 1}_block{j + 1}")(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


class ResNet50Serving(ImageClassifierServing):
    def make_module(self, cfg: ModelConfig) -> ResNet:
        return ResNet(
            num_classes=cfg.num_classes,
            v1_downsample=bool(cfg.options.get("v1_downsample", False)),
            bn_eps=float(cfg.options.get("bn_eps", 1e-5)),
            dtype=jnp.dtype(cfg.dtype),
            # "int8c": bottleneck 1x1 convs on the MXU's int8 path via
            # Int8Conv1x1 (see int8c_native_kernel_paths).
            quantize_compute=cfg.quantize == "int8c",
        )

    def int8c_native_kernel_paths(self):
        """The bottleneck 1x1 convs Int8Conv1x1 consumes natively under
        int8c (~45% of network FLOPs); 3x3/7x7 convs and BN stay on the
        weight-only dequant path.

        MEASURED CAVEAT (BASELINE.md "Int8 COMPUTE", 2026-07-30): on v5e
        at batch 256 this path is 0.78x bf16 — per-pixel activation
        quantization over large spatial activations costs more than the
        int8 MACs save, and the extracted 1x1 forfeits conv+BN+ReLU
        fusion. Prefer quantize="int8" for ResNet on v5e; int8c's win is
        transformer matmul sites (BERT +12%). Kept because the tradeoff
        is chip-dependent and the path is parity-tested."""
        return [r"(conv1|conv3|proj_conv)/kernel$"]

    def import_tf_variables(self, flat):
        """Keras-applications ResNet50 names/layouts -> this Flax pytree.

        Source scheme (``tf.keras.applications.ResNet50``): stem
        ``conv1_conv``/``conv1_bn``; blocks
        ``conv{s}_block{j}_{1,2,3}_conv|bn`` with the projection shortcut at
        ``_0``; head ``predictions``. That architecture puts stride 2 on the
        block's first 1x1 and uses eps 1.001e-5, so serve imported weights
        with ``options={"v1_downsample": true, "bn_eps": 1.001e-5}``.

        Layouts transfer directly (both sides are NHWC with HWIO conv kernels
        and (in, out) dense kernels). The one real translation: Keras convs
        carry biases that immediately feed BatchNorm, while ours are
        bias-free — a conv bias shifts the BN input, so it folds exactly into
        the BN moving mean (``mean' = mean - bias``).
        """
        import numpy as np

        f = {k.split(":")[0]: np.asarray(v) for k, v in flat.items()}

        def unit(conv_tf: str, bn_tf: str):
            """-> (conv params, bn params, bn stats) with the bias fold."""
            mean = f[f"{bn_tf}/moving_mean"].astype(np.float32)
            bias = f.get(f"{conv_tf}/bias")
            if bias is not None:
                mean = mean - bias.astype(np.float32)
            return (
                {"kernel": f[f"{conv_tf}/kernel"]},
                {"scale": f[f"{bn_tf}/gamma"], "bias": f[f"{bn_tf}/beta"]},
                {"mean": mean, "var": f[f"{bn_tf}/moving_variance"]},
            )

        params: dict = {}
        stats: dict = {}
        params["stem_conv"], params["stem_bn"], stats["stem_bn"] = unit(
            "conv1_conv", "conv1_bn")
        for i, n_blocks in enumerate(self.module.stage_sizes):
            s = i + 2  # Keras stages are conv2..conv5
            for j in range(1, n_blocks + 1):
                name = f"stage{i + 1}_block{j}"
                tf_pre = f"conv{s}_block{j}"
                p: dict = {}
                st: dict = {}
                for k in (1, 2, 3):
                    p[f"conv{k}"], p[f"bn{k}"], st[f"bn{k}"] = unit(
                        f"{tf_pre}_{k}_conv", f"{tf_pre}_{k}_bn")
                if f"{tf_pre}_0_conv/kernel" in f:
                    p["proj_conv"], p["proj_bn"], st["proj_bn"] = unit(
                        f"{tf_pre}_0_conv", f"{tf_pre}_0_bn")
                params[name] = p
                stats[name] = st
        params["head"] = {"kernel": f["predictions/kernel"],
                          "bias": f["predictions/bias"]}
        return {"params": params, "batch_stats": stats}

    def partition_rules(self):
        """TP rules (off unless cfg.tp > 1): shard wide convs/dense on 'model'."""
        from jax.sharding import PartitionSpec as P

        if self.cfg.tp <= 1:
            return [(".*", P())]
        return [
            (r"head/kernel", P(None, "model")),
            (r"conv\d?/kernel", P(None, None, None, "model")),
            (r".*", P()),
        ]


def create(cfg: ModelConfig) -> ResNet50Serving:
    return ResNet50Serving(cfg)
