"""ServingModel: the contract between the model zoo and the runtime/batcher.

Design (SURVEY.md §3b/§3c): the runtime AOT-compiles ``forward`` once per
(batch-bucket, input-shape) pair at startup; the batcher assembles padded
host batches, and ``forward`` does everything device-side — resize/normalize
preprocessing fused in front of the network, and postprocessing (top-k, NMS,
image decode to uint8) fused behind it — so exactly two host<->device
crossings happen per batch (H2D inputs, D2H small outputs).

``forward`` must be a pure jittable function of (params, batch) with static
shapes. Dynamic request counts are handled by padding: the batcher passes
``n_valid`` alongside the batch, and host_postprocess slices the first
``n_valid`` rows. Padded lanes must not influence real lanes (tested in
tests/test_runtime.py::test_padding_lanes_do_not_affect_real_lanes).
"""

from __future__ import annotations

import abc
from typing import Any

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from tpuserve.config import ModelConfig

# A host batch: pytree of np.ndarrays with leading batch dim.
HostBatch = Any
# Device outputs: pytree of jax.Arrays with leading batch dim.
Outputs = Any


def _stack_pad(arrs: list[np.ndarray], b: int) -> np.ndarray:
    out = np.stack(arrs, axis=0)
    if out.shape[0] < b:
        pad = np.zeros((b - out.shape[0],) + out.shape[1:], dtype=out.dtype)
        out = np.concatenate([out, pad], axis=0)
    return out


class ServingModel(abc.ABC):
    """One deployable model family instance."""

    def __init__(self, cfg: ModelConfig) -> None:
        self.cfg = cfg
        self.name = cfg.name
        # Result-cache eligibility (server ModelCache + router wire cache).
        # Config-driven so operators can opt a nondeterministic deployment
        # out; families whose sampling params all ride inside the decoded
        # item (textgen, sd15) are safely cacheable by construction.
        self.cacheable = bool(getattr(cfg, "cacheable", True))
        self.class_labels: list[str] | None = None
        if cfg.labels:
            with open(cfg.labels, encoding="utf-8") as f:
                lines = [line.rstrip("\r\n") for line in f]
            while lines and not lines[-1]:  # trailing blank lines
                lines.pop()
            self.class_labels = lines

    # -- parameters ---------------------------------------------------------
    @abc.abstractmethod
    def init_params(self, rng: jax.Array) -> Any:
        """Seeded random params (no-network dev mode, SURVEY.md §7 hard pt 8)."""

    def load_params(self) -> Any:
        """Load real weights if cfg.weights is set, else random init."""
        if self.cfg.weights:
            from tpuserve import savedmodel

            return savedmodel.load_params_for(self)
        return self.init_params(jax.random.key(0))

    def import_tf_variables(self, flat: dict[str, np.ndarray]) -> Any:
        """Translate a flat TF {name: array} dict into this model's pytree.

        Family-specific (name schemes and layouts differ per source repo);
        implement when wiring real TF weights for the family.
        """
        raise NotImplementedError(
            f"{type(self).__name__} has no TF variable mapping; convert the "
            "weights to an orbax checkpoint or implement import_tf_variables"
        )

    def import_torch_variables(self, flat: dict[str, np.ndarray]) -> Any:
        """Translate a flat torch {name: array} state_dict into this model's
        pytree. Family-specific; implement for families whose published
        artifacts ship as torch/safetensors (e.g. SD 1.5)."""
        raise NotImplementedError(
            f"{type(self).__name__} has no torch state_dict mapping; convert "
            "the weights to an orbax checkpoint or implement "
            "import_torch_variables"
        )

    # -- shapes -------------------------------------------------------------
    @abc.abstractmethod
    def input_signature(self, bucket: tuple) -> Any:
        """Pytree of jax.ShapeDtypeStruct for a bucket key.

        Bucket keys are model-specific tuples: ``(batch,)`` for vision,
        ``(batch, seq)`` for text.
        """

    def buckets(self) -> list[tuple]:
        """All bucket keys to AOT-compile at startup."""
        return [(b,) for b in self.cfg.batch_buckets]

    def bucket_for(self, n: int, **kw) -> tuple:
        """Smallest bucket that fits n requests (used by the batcher)."""
        for b in self.cfg.batch_buckets:
            if b >= n:
                return (b,)
        return (self.cfg.batch_buckets[-1],)

    # -- device-side --------------------------------------------------------
    def device_preprocess(self, batch: HostBatch) -> Any:
        """Jittable fused-preprocessing seam: raw wire bytes -> network input.

        The wire contract ships exactly what the host decoded — uint8 RGB or
        YUV420 planes for vision, token ids for text — and EVERY cast,
        /255 scale, normalize, resize, and colorspace conversion happens
        here, inside the compiled program, where XLA fuses it into the
        network's first consumers. ``forward`` implementations must route
        their input through this method (rather than open-coding the math)
        so the fusion is a named, testable, probe-able boundary: the
        roofline attribution compiles ``device_preprocess`` standalone to
        price the fused-preproc share of the executable, and tests assert
        ``forward(params, wire) == net(device_preprocess(wire))``. Identity
        by default for families whose network consumes the wire format
        directly (e.g. token ids)."""
        return batch

    @abc.abstractmethod
    def forward(self, params: Any, batch: HostBatch) -> Outputs:
        """Jittable: on-device preproc (via ``device_preprocess``) + network
        + on-device postproc."""

    def prepare_host_params(self, params: Any) -> Any:
        """Restructure loaded host params for the serving mode before
        sharding (runtime calls this between load and device_put). Default
        identity; the pipeline mode uses it to restack the layer stack into
        stage-major leaves with a leading ("stage",)-shardable dim."""
        return params

    def int8c_native_kernel_paths(self) -> list[str]:
        """Regexes of param paths this model computes in int8 NATIVELY
        (``quantize = "int8c"``): those kernels stay ``{"q8", "q8_scale"}``
        in the compiled forward and run int8 x int8 -> int32 on the MXU
        (tpuserve.quantize.Int8Dense). Empty means the family only supports
        weight-only "int8" — the runtime rejects "int8c" with guidance."""
        return []

    # -- host-side ----------------------------------------------------------
    @abc.abstractmethod
    def host_decode(self, payload: bytes, content_type: str) -> Any:
        """Decode one request body into per-item input arrays (threadpool).

        Runs in the decode threadpool; must touch only its own arguments.
        """

    def host_decode_items(self, payload: bytes, content_type: str) -> tuple[list, bool]:
        """Decode one request body into (items, is_batch) with a single parse.

        Batched client requests amortize HTTP and host-decode overhead and
        let one POST fill a whole device bucket. Families opt in by
        overriding: vision accepts a (N, H, W, 3) uint8 npy tensor, text a
        {"texts": [...]} JSON list; ``is_batch`` requests answer in the
        {"results": [...]} shape even for one item. Default: single-item
        ``host_decode``. Runs in the decode threadpool.
        """
        return [self.host_decode(payload, content_type)], False

    # A single POST may not carry more items than one full device batch era;
    # bounds host memory for the decode stage.
    MAX_ITEMS_PER_REQUEST = 1024

    def canary_item(self) -> Any:
        """A trivial decoded item used by health canaries; default zero image."""
        w = self.cfg.wire_size
        return np.zeros((w, w, 3), dtype=np.uint8)

    def group_key(self, item: Any) -> Any:
        """Batching group for a decoded item (e.g. seq bucket); None = one group."""
        return None

    @abc.abstractmethod
    def host_postprocess(self, outputs: Outputs, n_valid: int) -> list[Any]:
        """Convert device outputs (already np) to n_valid JSON-able results."""

    def format_top_k(self, outputs: dict, n_valid: int) -> list[dict]:
        """Shared classifier response shape: {"top_k": [{class, prob}, ...]},
        plus a "label" per entry when cfg.labels names the classes."""
        probs = outputs["probs"][:n_valid]
        idx = outputs["indices"][:n_valid]
        return [
            {"top_k": [self._class_entry(i, p) for i, p in zip(idx[r], probs[r])]}
            for r in range(n_valid)
        ]

    def _class_entry(self, i, p) -> dict:
        entry = {"class": int(i), "prob": float(p)}
        label = self.label_for(int(i))
        if label is not None:
            entry["label"] = label
        return entry

    def label_for(self, i: int) -> str | None:
        if self.class_labels is not None and 0 <= i < len(self.class_labels):
            return self.class_labels[i]
        return None

    def assemble(self, items: list[Any], bucket: tuple) -> HostBatch:
        """Stack decoded items into one padded host batch for `bucket`.

        Default: items are single np arrays or tuples of np arrays (e.g. YUV
        planes); each component is stacked along axis 0 and zero-padded on the
        batch dim up to bucket[0].
        """
        b = bucket[0]
        if isinstance(items[0], tuple):
            return tuple(
                _stack_pad([it[k] for it in items], b) for k in range(len(items[0]))
            )
        return _stack_pad(items, b)

    def assemble_into(self, items: list[Any], bucket: tuple, out: HostBatch) -> HostBatch:
        """Assemble into a preallocated host-batch buffer (arena recycling).

        ``out`` is a pytree of np arrays shaped like
        ``input_signature(bucket)`` — the same host-batch contract the
        deferred pool's shm slots rely on. Must produce exactly what
        ``assemble`` would, writing in place: real rows copied, padded rows
        zeroed. The batcher only uses this when it can prove equivalence
        (``assemble`` not overridden, or ``assemble_into`` overridden
        alongside it); families that customize ``assemble`` should override
        this too to keep the allocation-free hot path."""
        n = len(items)
        if isinstance(items[0], tuple):
            for k in range(len(items[0])):
                comp = out[k]
                for i, it in enumerate(items):
                    comp[i] = it[k]
                if n < comp.shape[0]:
                    comp[n:] = 0
            return out
        for i, it in enumerate(items):
            out[i] = it
        if n < out.shape[0]:
            out[n:] = 0
        return out

    # -- parallelism --------------------------------------------------------
    def bind_mesh(self, mesh: Any) -> None:
        """Runtime hands the model its serving mesh before params/compile.

        Default no-op. Families whose forward needs mesh-aware ops override —
        e.g. BERT's ring attention closes over the mesh's "seq" axis.
        """

    def partition_rules(self) -> list[tuple[str, P]]:
        """Ordered (regex, PartitionSpec) rules for params; default replicate."""
        return [(".*", P())]

    def batch_spec(self) -> Any:
        """PartitionSpec pytree for the batch input (leading dim = data axis).
        Pipeline mode's ("stage",) mesh has no data axis: batches replicate
        and the model microbatches internally."""
        if self.cfg.parallelism == "pipeline":
            return P()
        return P("data")

    def out_spec(self) -> Any:
        """PartitionSpec pytree for forward outputs (replicated under
        pipeline — the last stage's psum already replicates them)."""
        if self.cfg.parallelism == "pipeline":
            return P()
        return P("data")
