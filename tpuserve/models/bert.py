"""BERT-base text classification (SURVEY.md §2 C4, §3d; BASELINE.json config 3).

TPU-first shaping decisions:
- **Static (batch, seq) buckets**: every (batch_bucket, seq_bucket) pair is
  its own AOT-compiled executable; the batcher groups requests by seq bucket
  (``group_key``) so short texts never pay long-sequence FLOPs. This is the
  build's answer to the reference-era "dynamic seq-len" problem — bucketed
  padding, per BASELINE.json.
- Tokenization on the host threadpool (pure Python WordPiece,
  ``tpuserve.text``); only int32 (ids, mask) arrays cross to the device —
  a few hundred bytes per request.
- Attention masking is additive -1e9 bias from the padding mask, so padded
  lanes cannot perturb real lanes (tested:
  tests/test_bert.py::test_seq_bucket_invariance).
- bf16 compute, f32 softmax/logits; post-LN residual blocks (original BERT),
  gelu FFN, tanh pooler on [CLS], linear classifier.
- TP partition rules shard QKV/out and FFN kernels on "model" when cfg.tp>1.

Sizes come from ``cfg.options`` (layers/d_model/heads/d_ff/vocab_size) with
BERT-base defaults; tests use tiny sizes. ``cfg.options["vocab_file"]`` loads
a standard vocab.txt; otherwise the deterministic synthetic dev vocab is used
(no network, no artifacts — SURVEY.md §7 hard part 8).
"""

from __future__ import annotations

import json
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from tpuserve import quantize as qz
from tpuserve.config import ModelConfig
from tpuserve.models.base import ServingModel
from tpuserve.text import WordPieceTokenizer, synthetic_vocab


class BertBlock(nn.Module):
    heads: int
    d_ff: int
    dtype: Any = jnp.bfloat16
    # "dense" (XLA einsum) | "flash" (Pallas fused kernel) | "ring" /
    # "ulysses" (sequence-parallel over the serving mesh's "seq" axis).
    attention_impl: str = "dense"
    ln_eps: float = 1e-12  # original BERT value; keeps imported weights exact
    mesh: Any = None  # required for "ring" / "ulysses"
    # > 0: replace the dense FFN with a Switch MoE over this many experts
    # (tpuserve.ops.moe); expert dims shard on "model" for EP serving.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    # True: FFN matmuls via quantize.Int8Dense (int8 MXU path when the
    # runtime leaves their kernels quantized — quantize = "int8c").
    quantize_compute: bool = False

    @nn.compact
    def __call__(self, x, mask_bias):
        # Post-LN (original BERT): sublayer -> add -> LayerNorm. Masking is an
        # explicit additive bias inside attention_fn so the semantics stay
        # bucket-invariant (padded keys get -1e9 before the f32 softmax).
        if self.attention_impl == "flash":
            from tpuserve.ops.flash_attention import flash_attention

            # mask_bias is (B, 1, 1, S) additive; flash takes per-key (B, S).
            if self.mesh is not None:
                # Sharded serving: GSPMD cannot auto-partition a Mosaic
                # kernel, so shard_map runs it per device on the local shard
                # (batch on "data", heads on "model" when tp divides them) —
                # the supported composition that used to be a build-time
                # rejection (VERDICT r3 next 3).
                from jax.sharding import PartitionSpec as P

                from tpuserve.utils.compat import shard_map

                head_axis = ("model"
                             if self.heads % self.mesh.shape["model"] == 0
                             else None)
                qkv_spec = P("data", None, head_axis, None)

                def fn(q, k, v, **kw):  # noqa: ANN001
                    f = shard_map(
                        lambda q_, k_, v_, b_: flash_attention(q_, k_, v_, b_),
                        mesh=self.mesh,
                        in_specs=(qkv_spec, qkv_spec, qkv_spec,
                                  P("data", None)),
                        out_specs=qkv_spec,
                        # Pallas interpreter + vma tracking don't compose
                        # (see tpuserve.ops.ring_attention).
                        check_vma=False)
                    return f(q, k, v, mask_bias[:, 0, 0, :])
            else:
                fn = lambda q, k, v, **kw: flash_attention(  # noqa: E731
                    q, k, v, mask_bias[:, 0, 0, :])
        elif self.attention_impl in ("ring", "ulysses"):
            from jax.sharding import PartitionSpec as P

            from tpuserve.ops import ring_attention, ulysses_attention

            if self.mesh is None:
                raise ValueError(
                    f"attention={self.attention_impl!r} needs the serving "
                    "mesh: the runtime calls bind_mesh(mesh); do the same "
                    "before forward")
            # Activations reshard (batch on "data", seq on "seq") at the
            # shard_map boundary; the op then moves K/V (ring: ppermute
            # rotation) or heads (ulysses: all-to-all) over ICI. Heads stay
            # tensor-parallel when tp divides them.
            sp_attn = (ring_attention if self.attention_impl == "ring"
                       else ulysses_attention)
            head_axis = ("model"
                         if self.heads % self.mesh.shape["model"] == 0 else None)
            fn = lambda q, k, v, **kw: sp_attn(  # noqa: E731
                q, k, v, self.mesh, key_padding=mask_bias[:, 0, 0, :],
                spec=P("data", "seq", head_axis, None))
        else:
            fn = lambda q, k, v, **kw: _masked_attention(q, k, v, mask_bias)  # noqa: E731
        if self.quantize_compute:
            # Identical param tree to MHDPA; q/k/v/out projections run
            # int8 on the MXU when the runtime leaves their kernels
            # quantized (quantize = "int8c").
            attn = qz.Int8SelfAttention(
                heads=self.heads, dtype=self.dtype, attention_fn=fn,
                name="attn")
        else:
            attn = nn.MultiHeadDotProductAttention(
                num_heads=self.heads, dtype=self.dtype, deterministic=True,
                attention_fn=fn,
                name="attn")
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=self.ln_eps, dtype=self.dtype, name=name)
        x = ln("ln_attn")(x + attn(x))
        if self.moe_experts:
            from tpuserve.ops.moe import SwitchFFN

            # Recover the (B, S) 0/1 token mask from the additive key bias so
            # padded tokens never claim expert capacity. The serving forward
            # discards the load-balance aux (it only shapes training).
            token_mask = (mask_bias[:, 0, 0, :] == 0.0).astype(jnp.float32)
            h, _aux = SwitchFFN(self.moe_experts, self.d_ff,
                                capacity_factor=self.moe_capacity_factor,
                                dtype=self.dtype, name="moe")(x, token_mask)
        else:
            # Int8Dense == nn.Dense structurally; with quantize="int8c" the
            # runtime leaves these two kernels {"q8","q8_scale"} and the
            # FFN matmuls (2/3 of block FLOPs) run int8 on the MXU.
            dense = (qz.Int8Dense if self.quantize_compute else
                     lambda features, dtype, name: nn.Dense(
                         features, dtype=dtype, name=name))
            h = dense(self.d_ff, dtype=self.dtype, name="mlp_up")(x)
            # Exact (erf) GELU, matching BERT; the tanh approximation drifts
            # ~1e-3 on imported weights.
            h = nn.gelu(h, approximate=False)
            h = dense(x.shape[-1], dtype=self.dtype, name="mlp_down")(h)
        return ln("ln_mlp")(x + h)


def _masked_attention(q, k, v, mask_bias):
    """(B,S,H,D) attention with additive (B,1,1,S) key bias, f32 softmax."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    s = s + mask_bias
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


class BertClassifier(nn.Module):
    vocab_size: int
    layers: int
    d_model: int
    heads: int
    d_ff: int
    max_seq: int
    num_classes: int
    dtype: Any = jnp.bfloat16
    attention_impl: str = "dense"
    ln_eps: float = 1e-12
    mesh: Any = None
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    quantize_compute: bool = False

    @nn.compact
    def __call__(self, ids, mask):
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype, name="embed")(ids)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (self.max_seq, self.d_model))
        x = x + pos[None, : ids.shape[1], :].astype(self.dtype)
        x = nn.LayerNorm(epsilon=self.ln_eps, dtype=self.dtype, name="ln_embed")(x)
        mask_bias = (1.0 - mask.astype(jnp.float32))[:, None, None, :] * -1e9
        for i in range(self.layers):
            x = BertBlock(self.heads, self.d_ff, dtype=self.dtype,
                          attention_impl=self.attention_impl,
                          ln_eps=self.ln_eps, mesh=self.mesh,
                          moe_experts=self.moe_experts,
                          moe_capacity_factor=self.moe_capacity_factor,
                          quantize_compute=self.quantize_compute,
                          name=f"layer{i}")(x, mask_bias)
        cls = x[:, 0, :]
        pooled = jnp.tanh(nn.Dense(self.d_model, dtype=self.dtype, name="pooler")(cls))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="classifier")(pooled)


class BertServing(ServingModel):
    def __init__(self, cfg: ModelConfig) -> None:
        super().__init__(cfg)
        opt = cfg.options
        attention = str(opt.get("attention", "dense"))
        if attention not in ("dense", "flash", "ring", "ulysses"):
            raise ValueError("options.attention must be 'dense', 'flash', "
                             f"'ring', or 'ulysses', got {attention!r}")
        # attention='flash' + parallelism='sharded' is supported: bind_mesh
        # routes the kernel through shard_map (GSPMD can't auto-partition a
        # Mosaic call; per-device local execution is the composition).
        # Pipeline serving (parallelism = "pipeline"): the homogeneous block
        # stack splits into GPipe stages over a ("stage",) mesh, one stage's
        # params per device (tpuserve.parallel.pipeline). v1 composes with
        # dense attention only: flash/ring/ulysses close over meshes or
        # kernels that would nest shard_maps, and MoE routing would need
        # expert state inside the stage scan.
        self.pipeline_capable = True
        self._stage_mesh = None
        if cfg.parallelism == "pipeline":
            if attention != "dense":
                raise ValueError(
                    "parallelism='pipeline' supports options.attention="
                    f"'dense' only, got {attention!r}")
            if int(opt.get("moe_experts", 0)):
                raise ValueError(
                    "parallelism='pipeline' does not compose with "
                    "options.moe_experts")
        if attention in ("ring", "ulysses"):
            if cfg.parallelism == "replica":
                # One shared module can't close over N per-replica meshes;
                # SP over a 1-device replica is pointless anyway.
                raise ValueError(
                    f"options.attention={attention!r} requires parallelism="
                    "'sharded' or 'single' (replica mode has one mesh per "
                    "device)")
            bad = [s for s in cfg.seq_buckets if s % cfg.sp]
            if bad:
                raise ValueError(
                    f"{attention} attention shards the seq dim over "
                    f"sp={cfg.sp}; seq buckets {bad} are not divisible")
        if attention == "ulysses":
            # The all-to-all deals LOCAL heads (after any tp split) across
            # the seq axis; mirror the op's check at build time so a bad
            # config fails with guidance, not at AOT compile.
            heads = int(opt.get("heads", 12))
            local = heads // cfg.tp if heads % cfg.tp == 0 else heads
            if local % cfg.sp:
                raise ValueError(
                    f"ulysses attention deals heads over sp={cfg.sp}; "
                    f"local heads {local} (heads={heads}, tp={cfg.tp}) "
                    "are not divisible")
        moe_experts = int(opt.get("moe_experts", 0))
        if moe_experts and cfg.parallelism == "sharded" and cfg.tp > 1 \
                and moe_experts % cfg.tp:
            raise ValueError(
                f"options.moe_experts={moe_experts} shards the expert dim "
                f"over the model axis (tp={cfg.tp}); it must divide evenly")
        if moe_experts and cfg.weights:
            # import_tf_variables maps dense-FFN checkpoints (mlp_up/down);
            # there is no TF source scheme for the MoE variant's
            # moe/{router, w_up, w_down} params.
            raise ValueError(
                "options.moe_experts cannot be combined with weights=: no "
                "TF import mapping exists for the MoE FFN; serve it with "
                "seeded weights or an orbax checkpoint trained in-framework")
        self.dtype = jnp.dtype(cfg.dtype)
        self.max_seq = max(cfg.seq_buckets)
        vocab_file = opt.get("vocab_file")
        if vocab_file:
            self.tokenizer = WordPieceTokenizer.from_vocab_file(vocab_file)
        else:
            self.tokenizer = WordPieceTokenizer(
                synthetic_vocab(int(opt.get("vocab_size", 8192))))
        self.module = BertClassifier(
            vocab_size=max(self.tokenizer.vocab.values()) + 1,
            layers=int(opt.get("layers", 12)),
            d_model=int(opt.get("d_model", 768)),
            heads=int(opt.get("heads", 12)),
            d_ff=int(opt.get("d_ff", 3072)),
            max_seq=self.max_seq,
            num_classes=cfg.num_classes,
            dtype=self.dtype,
            # "dense" = XLA einsum; "flash" = Pallas fused kernel
            # (tpuserve.ops.flash_attention); "ring"/"ulysses" =
            # sequence-parallel over the serving mesh (tpuserve.ops).
            attention_impl=attention,
            # options.moe_experts=N serves a Switch-MoE FFN variant with the
            # expert dim sharded on "model" (expert parallelism).
            moe_experts=moe_experts,
            moe_capacity_factor=float(opt.get("moe_capacity_factor", 1.25)),
            # "int8c" computes the FFN matmuls int8 x int8 -> int32 on the
            # MXU (quantize.Int8Dense consumes the still-quantized kernels
            # the runtime leaves in place — int8c_native_kernel_paths).
            quantize_compute=cfg.quantize == "int8c",
        )
        self.top_k = min(5, cfg.num_classes)

    def int8c_native_kernel_paths(self) -> list[str]:
        """The kernels the int8c modules consume natively: FFN matmuls
        (Int8Dense, 2/3 of block matmul FLOPs) and the q/k/v/out attention
        projections (Int8SelfAttention, the remaining 1/3). The MoE
        variant has no mlp kernels (SwitchFFN replaces them), so it
        returns [] and the runtime rejects int8c with guidance rather than
        silently degrading to weight-only."""
        if self.module.moe_experts:
            return []
        return [r"mlp_(up|down)/kernel$",
                r"attn/(query|key|value|out)/kernel$"]

    def bind_mesh(self, mesh: Any) -> None:
        """Mesh-aware attention closes over the serving mesh: ring/ulysses
        always; flash only in sharded mode (it shard_maps over the mesh —
        replica/single modes call the kernel directly). Pipeline mode stores
        the ("stage",) mesh for _pipeline_forward and validates the layer
        split here (the stage count is only known once the mesh exists)."""
        if self.cfg.parallelism == "pipeline":
            s = int(mesh.shape["stage"])
            if self.module.layers % s:
                raise ValueError(
                    f"pipeline: layers={self.module.layers} must split "
                    f"evenly over {s} stages; adjust options.layers or pp")
            self._stage_mesh = mesh
            return
        if self.module.attention_impl in ("ring", "ulysses") or (
                self.module.attention_impl == "flash"
                and self.cfg.parallelism == "sharded"):
            self.module = self.module.clone(mesh=mesh)

    def import_tf_variables(self, flat: dict) -> Any:
        """HF transformers TFBert(ForSequenceClassification) -> this pytree.

        Source scheme (``transformers.TFBertForSequenceClassification``
        SavedModel): ``<root>/bert/embeddings/{word_embeddings/weight,
        position_embeddings/embeddings, token_type_embeddings/embeddings,
        LayerNorm}``, per-layer ``bert/encoder/layer_._{i}/{attention/self/
        query|key|value, attention/output/dense, attention/output/LayerNorm,
        intermediate/dense, output/dense, output/LayerNorm}``, then
        ``bert/pooler/dense`` and ``<root>/classifier``.

        Layout translations: HF fuses heads into (d, d) attention kernels;
        Flax MHA wants (d, heads, head_dim) for Q/K/V and (heads, head_dim,
        d) for the out projection — pure reshapes, head-major on both sides.
        The serving path is single-segment (classify one text), so the
        token-type table collapses to its segment-0 row, folded into the
        position embeddings (both are added before the embedding LayerNorm).
        """
        m = self.module
        head_dim = m.d_model // m.heads
        f: dict[str, np.ndarray] = {}
        for k, v in flat.items():
            k = k.split(":")[0]
            k = k.split("/", 1)[1] if "/" in k else k  # drop the root name
            f[k] = np.asarray(v)

        emb = "bert/embeddings"
        words = f[f"{emb}/word_embeddings/weight"]
        if words.shape[0] != m.vocab_size:
            raise ValueError(
                f"imported embedding table has {words.shape[0]} rows but the "
                f"serving tokenizer implies vocab_size {m.vocab_size}; pair "
                "the checkpoint with its matching vocab_file")
        n_cls = f["classifier/kernel"].shape[1]
        if n_cls != self.cfg.num_classes:
            raise ValueError(
                f"imported classifier has {n_cls} classes but cfg.num_classes "
                f"is {self.cfg.num_classes}")
        pos = f[f"{emb}/position_embeddings/embeddings"]
        if pos.shape[0] < self.max_seq:
            raise ValueError(
                f"imported position table covers {pos.shape[0]} positions "
                f"but max seq bucket is {self.max_seq}")
        pos = pos[: self.max_seq]
        tt = f.get(f"{emb}/token_type_embeddings/embeddings")
        if tt is not None:
            pos = pos + tt[0][None, :]

        params: dict = {
            "embed": {"embedding": words},
            "pos_embed": pos,
            "ln_embed": {"scale": f[f"{emb}/LayerNorm/gamma"],
                         "bias": f[f"{emb}/LayerNorm/beta"]},
            "pooler": {"kernel": f["bert/pooler/dense/kernel"],
                       "bias": f["bert/pooler/dense/bias"]},
            "classifier": {"kernel": f["classifier/kernel"],
                           "bias": f["classifier/bias"]},
        }
        for i in range(m.layers):
            lyr = f"bert/encoder/layer_._{i}"

            def qkv(name: str) -> dict:
                return {
                    "kernel": f[f"{lyr}/attention/self/{name}/kernel"].reshape(
                        m.d_model, m.heads, head_dim),
                    "bias": f[f"{lyr}/attention/self/{name}/bias"].reshape(
                        m.heads, head_dim),
                }

            def ln(name: str) -> dict:
                return {"scale": f[f"{lyr}/{name}/gamma"],
                        "bias": f[f"{lyr}/{name}/beta"]}

            def dense(name: str) -> dict:
                return {"kernel": f[f"{lyr}/{name}/kernel"],
                        "bias": f[f"{lyr}/{name}/bias"]}

            params[f"layer{i}"] = {
                "attn": {
                    "query": qkv("query"),
                    "key": qkv("key"),
                    "value": qkv("value"),
                    "out": {
                        "kernel": f[f"{lyr}/attention/output/dense/kernel"]
                        .reshape(m.heads, head_dim, m.d_model),
                        "bias": f[f"{lyr}/attention/output/dense/bias"],
                    },
                },
                "ln_attn": ln("attention/output/LayerNorm"),
                "mlp_up": dense("intermediate/dense"),
                "mlp_down": dense("output/dense"),
                "ln_mlp": ln("output/LayerNorm"),
            }
        return {"params": params}

    # -- params --------------------------------------------------------------
    def init_params(self, rng: jax.Array) -> Any:
        s = min(self.cfg.seq_buckets)
        ids = jnp.zeros((1, s), jnp.int32)
        mask = jnp.ones((1, s), jnp.int32)
        # Init through the dense-attention twin: the attention impl doesn't
        # change the param tree, and init runs on the host CPU (runtime pins
        # it there), where the compiled Pallas kernel can't execute.
        init_module = (self.module.clone(attention_impl="dense")
                       if self.module.attention_impl != "dense" else self.module)
        return init_module.init(rng, ids, mask)

    # -- shapes --------------------------------------------------------------
    def buckets(self) -> list[tuple]:
        return [(b, s) for b in self.cfg.batch_buckets for s in self.cfg.seq_buckets]

    def bucket_for(self, n: int, group=None) -> tuple:
        s = group if group is not None else max(self.cfg.seq_buckets)
        for b in self.cfg.batch_buckets:
            if b >= n:
                return (b, s)
        return (self.cfg.batch_buckets[-1], s)

    def input_signature(self, bucket: tuple) -> Any:
        b, s = bucket
        return (
            jax.ShapeDtypeStruct((b, s), jnp.int32),
            jax.ShapeDtypeStruct((b, s), jnp.int32),
        )

    # -- device side ---------------------------------------------------------
    def forward(self, params: Any, batch: Any) -> dict:
        ids, mask = batch
        if self.cfg.parallelism == "pipeline":
            logits = self._pipeline_logits(params, ids, mask)
        else:
            logits = self.module.apply(params, ids, mask)
        probs = jax.nn.softmax(logits, axis=-1)
        top_p, top_i = jax.lax.top_k(probs, self.top_k)
        return {"probs": top_p, "indices": top_i}

    # -- pipeline serving (parallelism = "pipeline") -------------------------
    def prepare_host_params(self, params: Any) -> Any:
        """Restack the flax tree stage-major for GPipe serving: layer i's
        block params land in stage i // (L/S), slot i %% (L/S), stacked so
        every ``staged/blk{j}`` leaf has a leading (S, ...) dim sharded on
        the "stage" axis — each device materializes 1/S of the trunk, the
        memory point of PP. Embed/pooler/classifier stay replicated under
        ``unstaged``. Inverse mapping keeps checkpoints portable: any
        weights loadable in single mode load identically here."""
        if self.cfg.parallelism != "pipeline":
            return params
        if self._stage_mesh is None:
            raise RuntimeError("bind_mesh must run before prepare_host_params")
        s = int(self._stage_mesh.shape["stage"])
        p = dict(params["params"])
        per = self.module.layers // s
        layers = [p.pop(f"layer{i}") for i in range(self.module.layers)]
        staged = {
            f"blk{j}": jax.tree_util.tree_map(
                lambda *xs: np.stack(xs),
                *[layers[st * per + j] for st in range(s)])
            for j in range(per)
        }
        return {"unstaged": p, "staged": staged}

    def _pp_micro(self, b: int, s: int) -> int:
        """Microbatch count: options.pp_micro, else the largest divisor of
        the bucket batch <= 2*S (enough microbatches to amortize the
        (S-1)-tick pipeline bubble without shrinking the per-tick matmul
        below MXU-filling sizes)."""
        override = int(self.cfg.options.get("pp_micro", 0))
        if override:
            if b % override:
                raise ValueError(
                    f"options.pp_micro={override} must divide every batch "
                    f"bucket; {b} is not divisible")
            return override
        return max(d for d in range(1, b + 1) if b % d == 0 and d <= 2 * s)

    def _pipeline_logits(self, params: Any, ids, mask):
        """BertClassifier.__call__ restructured as embed (replicated) ->
        GPipe trunk (pipeline_forward over the stage mesh) -> head
        (replicated). The padding mask rides the microbatch stream as one
        extra channel so stage_fn stays shape-preserving."""
        from tpuserve.parallel.pipeline import pipeline_forward

        mod = self.module
        mesh = self._stage_mesh
        s_axis = int(mesh.shape["stage"])
        per = mod.layers // s_axis
        dt = mod.dtype
        u = params["unstaged"]
        b, seq = ids.shape

        x = nn.Embed(mod.vocab_size, mod.d_model, dtype=dt).apply(
            {"params": u["embed"]}, ids)
        x = x + u["pos_embed"][None, :seq, :].astype(dt)
        x = nn.LayerNorm(epsilon=mod.ln_eps, dtype=dt).apply(
            {"params": u["ln_embed"]}, x)

        block = BertBlock(mod.heads, mod.d_ff, dtype=dt,
                          attention_impl="dense", ln_eps=mod.ln_eps)

        def stage_fn(sp, x_aug):
            h, maskc = x_aug[..., : mod.d_model], x_aug[..., mod.d_model]
            bias = (1.0 - maskc.astype(jnp.float32))[:, None, None, :] * -1e9
            for j in range(per):
                h = block.apply({"params": sp[f"blk{j}"]}, h, bias)
            return jnp.concatenate([h, maskc[..., None]], axis=-1)

        x_aug = jnp.concatenate([x, mask.astype(dt)[..., None]], axis=-1)
        n_micro = self._pp_micro(b, s_axis)
        xs = x_aug.reshape(n_micro, b // n_micro, seq, mod.d_model + 1)
        ys = pipeline_forward(stage_fn, params["staged"], xs, mesh)
        x = ys.reshape(b, seq, mod.d_model + 1)[..., : mod.d_model]

        cls = x[:, 0, :]
        pooled = jnp.tanh(nn.Dense(mod.d_model, dtype=dt).apply(
            {"params": u["pooler"]}, cls))
        return nn.Dense(mod.num_classes, dtype=jnp.float32).apply(
            {"params": u["classifier"]}, pooled)

    # -- host side -----------------------------------------------------------
    def host_decode(self, payload: bytes, content_type: str) -> np.ndarray:
        """Request body -> unpadded int32 token ids (incl. [CLS]/[SEP])."""
        return self.host_decode_items(payload, content_type)[0][0]

    def host_decode_items(self, payload: bytes, content_type: str) -> tuple[list, bool]:
        """One JSON parse: {"text": str} is single, {"texts": [...]} a batch;
        non-JSON bodies are one plain-text item."""
        if not content_type.startswith("application/json"):
            return [self._encode(payload.decode("utf-8"))], False
        body = json.loads(payload.decode("utf-8"))
        texts = body.get("texts")
        if texts is not None:
            if not isinstance(texts, list) or not all(isinstance(t, str) for t in texts):
                raise ValueError('"texts" must be a list of strings')
            if len(texts) > self.MAX_ITEMS_PER_REQUEST:
                raise ValueError(
                    f"batch of {len(texts)} exceeds the per-request limit "
                    f"({self.MAX_ITEMS_PER_REQUEST})")
            return [self._encode(t) for t in texts], True
        text = body.get("text")
        if not isinstance(text, str):
            raise ValueError('JSON body must contain "text": str')
        return [self._encode(text)], False

    def _encode(self, text: str) -> np.ndarray:
        tok = self.tokenizer
        pieces = tok.tokenize(text)  # once; encode() would re-tokenize
        ids = [tok.cls_id] + [tok.vocab.get(t, tok.unk_id) for t in pieces]
        ids = ids[: self.max_seq - 1] + [tok.sep_id]
        return np.asarray(ids, np.int32)  # unpadded; assemble pads per bucket

    def group_key(self, item: np.ndarray):
        """Seq bucket for an unpadded id array -> batching group."""
        for s in self.cfg.seq_buckets:
            if s >= item.shape[0]:
                return s
        return max(self.cfg.seq_buckets)

    def canary_item(self) -> np.ndarray:
        return self.host_decode(b'{"text": "canary"}', "application/json")

    def assemble(self, items: list[np.ndarray], bucket: tuple) -> Any:
        b, s = bucket
        ids = np.full((b, s), self.tokenizer.pad_id, np.int32)
        mask = np.zeros((b, s), np.int32)
        return self._fill_ids_mask(items, s, ids, mask)

    def assemble_into(self, items: list[np.ndarray], bucket: tuple, out) -> Any:
        ids, mask = out
        ids[:] = self.tokenizer.pad_id
        mask[:] = 0
        return self._fill_ids_mask(items, bucket[1], ids, mask)

    @staticmethod
    def _fill_ids_mask(items, s, ids, mask):
        for i, it in enumerate(items):
            n = min(it.shape[0], s)
            ids[i, :n] = it[:n]
            mask[i, :n] = 1
        return ids, mask

    def host_postprocess(self, outputs: dict, n_valid: int) -> list[dict]:
        return self.format_top_k(outputs, n_valid)

    # -- parallelism ---------------------------------------------------------
    def partition_rules(self):
        if self.cfg.parallelism == "pipeline":
            # Stage-stacked trunk on the ("stage",) axis; embed/head
            # replicated (prepare_host_params produced this layout).
            return [(r"^staged/", P("stage")), (r".*", P())]
        if self.cfg.tp <= 1:
            return [(".*", P())]
        return [
            (r"attn/(query|key|value)/kernel", P(None, "model", None)),
            (r"attn/out/kernel", P("model", None, None)),
            (r"mlp_up/kernel", P(None, "model")),
            (r"mlp_down/kernel", P("model", None)),
            # EP: expert dim of the (E, D, F) MoE weights on "model" (same
            # layout as train.TRAIN_PARTITION_RULES); router replicated.
            (r"moe/w_(up|down)", P("model", None, None)),
            (r".*", P()),
        ]


def create(cfg: ModelConfig) -> BertServing:
    return BertServing(cfg)
