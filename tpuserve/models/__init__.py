"""Model zoo (SURVEY.md §2 C4): the five benchmark families.

Each family implements the ``ServingModel`` interface in ``base.py``:
a jittable on-device ``forward`` (with fused resize/normalize preproc and
on-device postproc like top-k / NMS), host-side request decode, and
regex partition rules for tensor parallelism.

Families (BASELINE.json ``configs``):
- resnet50       — ResNet-50 ImageNet classify
- mobilenetv3    — MobileNetV3-Large, batch=1 latency mode
- bert           — BERT-base text classification, bucketed seq lens
- efficientdet   — EfficientDet-D0 detection with fixed-shape NMS
- sd15           — Stable Diffusion 1.5 txt2img, fori_loop denoise
- textgen        — autoregressive prefix-LM text generation (KV-cache
                   decode via the iteration-level engine, ISSUE 9)
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from tpuserve.config import ModelConfig
    from tpuserve.models.base import ServingModel

_REGISTRY: dict[str, str] = {
    "resnet50": "tpuserve.models.resnet",
    "mobilenetv3": "tpuserve.models.mobilenet",
    "bert": "tpuserve.models.bert",
    "efficientdet": "tpuserve.models.efficientdet",
    "sd15": "tpuserve.models.sd15",
    "textgen": "tpuserve.models.textgen",
    "toy": "tpuserve.models.toy",
}


def build(cfg: "ModelConfig") -> "ServingModel":
    """Instantiate the ServingModel for cfg.family."""
    import importlib

    if cfg.family not in _REGISTRY:
        raise KeyError(f"unknown model family {cfg.family!r}; known: {sorted(_REGISTRY)}")
    try:
        mod = importlib.import_module(_REGISTRY[cfg.family])
    except ModuleNotFoundError as e:
        raise NotImplementedError(
            f"model family {cfg.family!r} is registered but its module "
            f"{_REGISTRY[cfg.family]} is not implemented yet"
        ) from e
    return mod.create(cfg)


def families() -> list[str]:
    return sorted(_REGISTRY)
