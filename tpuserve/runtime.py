"""Runtime: AOT compile & execute model executables on the mesh (SURVEY.md C5).

The reference runs TF SavedModel graphs on TensorFlow-GPU; the TPU-native
equivalent compiles each (model, bucket) pair once, ahead of time, to an XLA
executable resident on the device mesh:

    jax.jit(forward, in_shardings=..., out_shardings=...)
        .lower(params_struct, batch_struct).compile()

Static shapes are the contract: every batch bucket (and seq bucket for text)
is its own executable, compiled at startup — in parallel across buckets — and
cached persistently via the JAX compilation cache so restart != recompile
(SURVEY.md §5 checkpoint/resume).

Execution is asynchronous: ``run`` dispatches and returns device arrays
immediately (XLA async dispatch); ``fetch`` blocks for D2H and is intended to
be called off the event loop (batcher runs it in a threadpool) so batch N+1
dispatches while N computes — the dispatch pipelining from SURVEY.md §7
hard-part 2.

Parallelism modes per model (SURVEY.md §2.1):
- "sharded": one executable over the whole mesh; batch sharded on the data
  axis; params replicated or TP-sharded by the model's partition rules.
- "replica": one single-device executable per device, independent queues —
  lower p50 for batch=1 latency models (MobileNetV3).
- "single": first device only (dev mode).
"""

from __future__ import annotations

import concurrent.futures as cf
import logging
import time
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuserve.analysis import witness
from tpuserve.config import ModelConfig, ParallelConfig, ServerConfig
from tpuserve.models.base import ServingModel
from tpuserve.obs import Metrics
from tpuserve.utils.retrace import allow_transfers, host_fetch
from tpuserve.parallel import make_mesh, match_partition_rules
from tpuserve.parallel.mesh import MeshPlan, plan_for, select_devices
from tpuserve.parallel.partition import specs_to_shardings, struct_shardings
from tpuserve.utils.locks import new_lock

log = logging.getLogger("tpuserve.runtime")

# Sharding-invariant RNG (ISSUE 20). The default ThreeFry lowering draws
# DIFFERENT bits when GSPMD partitions a sample's output across devices: a
# vocab-sharded logits + gumbel draw under tensor parallelism flips sampled
# tokens vs the single-device lowering (observed: same state, same key, a
# 1.12-gap argmax landing on a different token). The partitionable lowering
# computes each element's bits independent of device layout — the property
# the sharded decode's token-identical-to-single-mesh obligation rests on
# (docs/PERFORMANCE.md "Generation on the mesh"). Process-global, set at
# import, so every sampling path (engine, locked batch, bench) shares one
# stream.
jax.config.update("jax_threefry_partitionable", True)


class NaNDetected(ValueError):
    """A candidate weight tree holds NaN/Inf float leaves; the reload gate
    (tpuserve.lifecycle) rejects it and the old version keeps serving."""


def configure_jax(cfg: ServerConfig) -> None:
    """Process-wide JAX settings (call once, before any compilation)."""
    if cfg.compilation_cache_dir:
        jax.config.update("jax_compilation_cache_dir", cfg.compilation_cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    if cfg.debug_nans:
        jax.config.update("jax_debug_nans", True)
        jax.config.update("jax_debug_infs", True)  # NaN alone misses overflow


@dataclass
class Executable:
    """One compiled (bucket, device-set) executable."""

    bucket: tuple
    compiled: Any  # jax.stages.Compiled
    batch_sharding: Any  # pytree of NamedSharding for the batch input
    device_index: int = 0  # replica mode: which replica
    donated: bool = False  # batch input buffers donated to the outputs


@dataclass(frozen=True)
class VariantKey:
    """Identity of one fully-specialized compiled variant (ISSUE 6).

    Clockwork's premise (PAPERS.md P3) is that predictable serving comes
    from precompiled, fully-specialized executables managed bottom-up; the
    registry keys each one by everything the compilation specialized on —
    the static batch/seq bucket, the compute dtype, the quantization mode,
    and the parallelism layout. TF-Serving's servable discipline (P2) adds
    the second half: variants must be cheaply enumerable artifacts, so
    `/v1/models` and `/stats` can list exactly what is resident, and a
    counter (`runtime_compiles_total`) can prove the steady state compiles
    nothing new. Weight versions are deliberately NOT part of the key:
    publish/rollback swap trees under unchanged shapes, so every version
    reuses the same variant set (zero recompiles across reloads)."""

    bucket: tuple
    dtype: str
    quantize: str | None
    parallelism: str

    @property
    def label(self) -> str:
        """Compact metric-label form: "<bucket>/<dtype>/<quantize>/<mode>"."""
        b = "x".join(str(d) for d in self.bucket)
        return f"{b}/{self.dtype}/{self.quantize or 'fp'}/{self.parallelism}"


@dataclass
class Variant:
    """Registry entry: one VariantKey's executables across replicas."""

    key: VariantKey
    executables: list[Executable]
    compile_ms: float = 0.0

    def summary(self) -> dict:
        return {
            "bucket": list(self.key.bucket),
            "dtype": self.key.dtype,
            "quantize": self.key.quantize,
            "parallelism": self.key.parallelism,
            "replicas": len(self.executables),
            "donated": any(e.donated for e in self.executables),
            "compile_ms": round(self.compile_ms, 1),
        }


@dataclass
class GenProgram:
    """One registered generative program (tpuserve.genserve): an AOT-compiled
    jittable of ``(params, *args)`` that is NOT a forward bucket — the
    engine's insert/step/extract executables. Registered in the same
    VariantKey registry as forward buckets (bucket = (tag, width)), counted
    by the same ``runtime_compiles_total``, so the zero-steady-state-
    recompile obligation covers slot churn and reloads in one counter."""

    tag: str
    compiled: list  # jax.stages.Compiled, one per replica mesh
    donated: bool = False
    counter: Any = None  # prebound runtime_variant_batches_total{variant=}


def _leaves_with_shardings(struct: Any, shardings: Any) -> list[tuple]:
    """Pair a ShapeDtypeStruct tree's leaves with their shardings;
    ``shardings`` may be one NamedSharding broadcast over the tree."""
    leaves = jax.tree_util.tree_leaves(struct)
    if isinstance(shardings, NamedSharding):
        return [(l, shardings) for l in leaves]
    sh = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
    return list(zip(leaves, sh))


def _donation_shapes_ok(batch_struct: Any, batch_shardings: Any,
                        out_struct: Any, out_shardings: Any) -> bool:
    """True when EVERY batch input buffer can alias a distinct output buffer
    (same shape, dtype, and sharding spec). Donation is all-or-nothing on
    purpose: partially-usable donation only produces "donated buffers were
    not usable" warnings on every compile with no memory benefit (ADVICE r1,
    which removed unconditional donation) — so the batch argument is donated
    only when XLA can provably consume all of it."""
    def key(leaf, sharding):
        return (tuple(leaf.shape), str(jnp.dtype(leaf.dtype)),
                str(getattr(sharding, "spec", sharding)))

    outs: dict[tuple, int] = {}
    for leaf, sh in _leaves_with_shardings(out_struct, out_shardings):
        k = key(leaf, sh)
        outs[k] = outs.get(k, 0) + 1
    ins = _leaves_with_shardings(batch_struct, batch_shardings)
    if not ins:
        return False
    for leaf, sh in ins:
        k = key(leaf, sh)
        if not outs.get(k):
            return False
        outs[k] -= 1
    return True


class ModelRuntime:
    """Owns params-on-device and the compiled executable set for one model."""

    def __init__(self, model: ServingModel, mesh: Mesh | None = None,
                 metrics: Metrics | None = None,
                 parallel: ParallelConfig | None = None) -> None:
        self.model = model
        self.cfg: ModelConfig = model.cfg
        # A private registry when the caller has none (direct construction in
        # tests/probes): the counters still work, they just aren't scraped.
        self.metrics = metrics if metrics is not None else Metrics()
        # Server-wide multi-chip plan ([parallel] block): bounds the device
        # set and sizes the sharded data axis. The MODE override happens at
        # the config level (ServerState.build rewrites cfg.parallelism
        # before the model is even built, so family-level mode checks see
        # it); by the time a runtime exists, cfg.parallelism is the truth.
        self.pcfg = parallel if parallel is not None else ParallelConfig()
        self.mode = self.cfg.parallelism
        if self.mode not in ("sharded", "replica", "single", "pipeline"):
            raise ValueError(f"unknown parallelism mode {self.mode!r}")
        if self.cfg.quantize not in (None, "int8", "int8c"):
            raise ValueError(f"unknown quantize mode {self.cfg.quantize!r}")
        if (self.cfg.quantize == "int8c"
                and not model.int8c_native_kernel_paths()):
            raise ValueError(
                f"{model.name}: quantize='int8c' (int8 COMPUTE) is not "
                f"supported by family {self.cfg.family!r} — it names no "
                "int8-native kernel sites; use quantize='int8' "
                "(weight-only) instead")

        # Device set the [parallel] plan serves on: every visible device by
        # default, the first n_chips when bounded. `data` alone sizes a
        # sharded mesh to exactly data*tp*sp chips.
        n_chips = self.pcfg.n_chips
        if not n_chips and self.pcfg.data and self.mode == "sharded":
            n_chips = self.pcfg.data * self.cfg.tp * self.cfg.sp
        devs = select_devices(n_chips)
        if self.mode == "replica":
            # One 1-device mesh per device; params replicated per device.
            # Each replica is an independent failure/serving domain: the
            # batcher keeps a depth-k staging-slot pool per entry here.
            self.meshes = [make_mesh(MeshPlan(), devices=[d]) for d in devs]
        elif self.mode == "single":
            self.meshes = [make_mesh(MeshPlan(), devices=[devs[0]])]
        elif self.mode == "pipeline":
            # GPipe stages over a ("stage",) mesh: each device holds 1/S of
            # the layer stack's params (tpuserve.parallel.pipeline). The
            # model pipelines its own depth, so it must opt in.
            if not getattr(model, "pipeline_capable", False):
                raise ValueError(
                    f"{model.name}: parallelism='pipeline' needs a family "
                    f"with a homogeneous block stack; {self.cfg.family!r} "
                    "does not support it (BERT does) — use 'sharded', "
                    "'replica', or 'single'")
            if self.cfg.quantize:
                raise ValueError(
                    "parallelism='pipeline' does not compose with quantize "
                    "modes yet; drop one of the two")
            from tpuserve.parallel.pipeline import make_stage_mesh

            n = self.cfg.pp or len(devs)
            self.meshes = [make_stage_mesh(n)]
        else:
            self.meshes = [mesh if mesh is not None
                           else make_mesh(plan_for(self.pcfg, tp=self.cfg.tp,
                                                   sp=self.cfg.sp),
                                          devices=devs)]
        # Mesh-aware models (e.g. BERT ring attention) rebuild their forward
        # around the serving mesh; must precede param load and compilation.
        model.bind_mesh(self.meshes[0])

        if self.mode == "sharded":
            # Sharded-batch executables need batch % data-axis == 0; normalize
            # buckets up to mesh multiples (batch=1 latency work belongs in
            # replica mode, SURVEY.md §2.1).
            from tpuserve.parallel.mesh import pad_batch_to_mesh

            aligned = sorted({pad_batch_to_mesh(b, self.meshes[0]) for b in self.cfg.batch_buckets})
            if aligned != self.cfg.batch_buckets:
                log.info("%s: batch buckets %s -> %s (data axis %d)",
                         model.name, self.cfg.batch_buckets, aligned,
                         self.meshes[0].shape["data"])
                self.cfg.batch_buckets = aligned

        self.params_per_mesh: list[Any] = []
        # Compiled-variant registry (ISSUE 6): every executable set is keyed
        # by the full specialization (bucket x dtype x quantize x
        # parallelism) and cheap to enumerate; ``executables`` remains the
        # hot-path view of the ACTIVE variant per bucket (same Executable
        # objects — the registry adds identity and accounting, not a copy).
        self.variants: dict[VariantKey, Variant] = {}
        self.executables: dict[tuple, list[Executable]] = {}
        # Generative programs (tpuserve.genserve): tag -> GenProgram. Kept
        # off the forward hot-path view but inside the variant registry.
        self.gen_programs: dict[str, GenProgram] = {}
        # The generation engine's compiled-geometry record (slot width,
        # paged-KV pool shape, prefill chunk): a second engine reusing
        # this runtime's programs must match it exactly — the state block
        # is shape-frozen (genserve.engine.GenEngine.compile).
        self.gen_meta: dict = {}
        # False when this runtime backs an iteration-level engine: the
        # engine's programs replace the forward bucket executables, so
        # compile_all/ensure_compiled must not build (or re-demand) them.
        self.compile_forward = True
        # Per-bucket raw-executable time (ms/batch), measured by
        # probe_raw_ms with inputs already resident — the device-time term
        # of the roofline's compute split (docs/PERFORMANCE.md).
        self.raw_ms_per_batch: dict[tuple, float | None] = {}
        # When True, h2d() blocks until the transfer completes so the "h2d"
        # phase owns the wire and "compute" measures dispatch-to-ready only
        # (roofline attribution; [pipeline] h2d_sync, set by the batcher).
        self.h2d_sync = False
        name = model.name
        # Every .compile() increments this; a steady-state delta of 0 is the
        # proof that serving repeat buckets (and publish/rollback churn)
        # recompiles nothing (scripts/roofline_smoke.sh asserts it).
        self._c_compiles = self.metrics.counter(
            f"runtime_compiles_total{{model={name}}}")
        self._g_variants = self.metrics.gauge(
            f"runtime_variants{{model={name}}}")
        # Batches dispatched per specialized variant, prebound at compile
        # time (one locked inc per batch, not per request).
        self._c_variant_batches: dict[tuple, Any] = {}
        # Per-chip dispatch attribution (Clockwork P3: predictability needs
        # per-device accounting shipped WITH the parallel placement, not
        # after it): one prebound counter per replica, ticked in dispatch().
        # In sharded mode there is one entry covering the whole mesh — the
        # per-chip share is the aggregate divided by the data-axis size,
        # which /stats' parallel block reports alongside.
        self._c_replica_batches = [
            self.metrics.replica_batches_counter(name, i)
            for i in range(len(self.meshes))]
        # Versioned lifecycle (tpuserve.lifecycle): the live tree carries a
        # monotonically numbered version; publish() retains the previous tree
        # as last-known-good so rollback() is a pointer swap, not a reload.
        self.version = 1
        self._version_seq = 1  # never reused, even across rollbacks
        self._prev_params: list[Any] | None = None
        self._prev_version: int | None = None
        self._rr = 0  # round-robin cursor for replica mode
        self._rr_lock = new_lock("runtime.replica_rr")
        self._reload_lock = new_lock("runtime.reload")
        # Deterministic chaos (tpuserve.faults.FaultInjector); None in prod.
        # Kinds "device_error"/"slow_compute" fire inside run() — below the
        # batcher — so retry/breaker behavior is proven against failures the
        # batcher did not itself synthesize.
        self.injector = None

    # -- startup ------------------------------------------------------------
    def load_and_shard_params(self) -> None:
        # Init/load on the host CPU backend, cast on host, then device_put
        # exactly once per mesh. Reasons: (a) a host-side numpy cast
        # (ml_dtypes handles bf16) beats dispatching hundreds of tiny convert
        # ops; (b) on the tunneled dev TPU, reading back accelerator-side
        # buffers flips the relay into a ~30 MB/s synchronous-transfer mode,
        # so param init must never touch the accelerator.
        self.params_per_mesh = self._shard_onto_meshes(
            self.model.prepare_host_params(self._load_host_params()))

    def _load_host_params(self, verify_integrity: bool = True,
                          require_manifest: bool = False) -> Any:
        try:
            cpu = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            cpu = None
        if cpu is not None:
            with jax.default_device(cpu):
                params = self.model.load_params()
        else:
            params = self.model.load_params()
        with allow_transfers():  # deliberate: weights land host-side first
            params = jax.device_get(params)
        # Integrity gate BEFORE the compute-dtype cast: the sidecar manifest
        # digests the checkpoint's raw bytes, so the comparison must see the
        # tree exactly as restored.
        if verify_integrity and self.cfg.weights:
            from tpuserve import savedmodel

            if savedmodel.detect_format(self.cfg.weights) == "orbax":
                savedmodel.verify_manifest_if_present(
                    self.cfg.weights, params, require=require_manifest)
        dtype = jnp.dtype(self.cfg.dtype)
        # Pre-quantized {"q8", "q8_scale"} subtrees stay as saved: scales are
        # deliberately float32 (dequant casts into the compute dtype itself).
        from tpuserve import quantize as qz

        return jax.tree_util.tree_map(
            lambda x: x if qz.is_quantized(x)
            else (x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x),
            params,
            is_leaf=qz.is_quantized,
        )

    def _shard_onto_meshes(self, params: Any) -> list:
        from tpuserve import quantize as qz

        rules = self.model.partition_rules()
        pre_quantized = qz.has_quantized_leaves(params)
        if pre_quantized and self.cfg.quantize not in ("int8", "int8c"):
            raise ValueError(
                f"{self.model.name}: loaded weights are int8-quantized but "
                "quantize is not set; set quantize = \"int8\"")
        if self.cfg.quantize in ("int8", "int8c"):
            # Quantize first (idempotent over pre-quantized checkpoints),
            # then derive specs from the tree's actual quantization state —
            # rule regexes see the original weight paths, scale specs derive
            # from their weight's, and no save-time min_size agreement is
            # needed for sharding.
            params = qz.quantize_tree(params, self.cfg.quantize_min_size)
            specs = qz.specs_for_tree(rules, params)
        else:
            specs = match_partition_rules(rules, params)
        out = []
        for mesh in self.meshes:
            shardings = specs_to_shardings(specs, mesh)
            out.append(jax.tree_util.tree_map(jax.device_put, params, shardings))
        return out

    def _forward_fn(self):
        """The function each bucket compiles: the model's forward, behind a
        dequantization layer when weights are stored int8."""
        if self.cfg.quantize == "int8":
            from tpuserve import quantize as qz

            dtype = jnp.dtype(self.cfg.dtype)
            return lambda p, batch: self.model.forward(
                qz.dequantize_tree(p, dtype), batch)
        if self.cfg.quantize == "int8c":
            # int8 COMPUTE: kernels the model consumes natively (Int8Dense
            # sites) stay {"q8", "q8_scale"} and hit the MXU's int8 path;
            # everything else dequantizes as in weight-only mode.
            from tpuserve import quantize as qz

            dtype = jnp.dtype(self.cfg.dtype)
            keep = self.model.int8c_native_kernel_paths()
            return lambda p, batch: self.model.forward(
                qz.dequantize_tree_except(p, dtype, keep), batch)
        return self.model.forward

    @property
    def parallel_signature(self) -> str:
        """The parallelism dimension of every VariantKey this runtime
        compiles (ISSUE 7): the mode PLUS the device layout it was
        specialized on, so an 8-chip sharded executable and a 1-chip one
        are distinct registry entries (they are different XLA programs)
        while staying one label on a dashboard. "single" stays bare — it
        is the 1-chip degenerate case every prior test/bench name uses."""
        if self.mode == "sharded":
            return f"sharded@d{self.meshes[0].shape['data']}"
        if self.mode == "replica":
            return f"replica@{len(self.meshes)}"
        if self.mode == "pipeline":
            return f"pipeline@{dict(self.meshes[0].shape).get('stage', 1)}"
        return self.mode

    def variant_key(self, bucket: tuple) -> VariantKey:
        """The ACTIVE variant identity for a bucket: what this runtime's
        config specializes its executables on."""
        return VariantKey(bucket=tuple(bucket), dtype=self.cfg.dtype,
                          quantize=self.cfg.quantize,
                          parallelism=self.parallel_signature)

    def compile_all(self, pool: cf.ThreadPoolExecutor | None = None) -> None:
        """AOT-compile every bucket (in parallel when a pool is given)."""
        t0 = time.perf_counter()
        buckets = self.model.buckets()
        if pool is None:
            for b in buckets:
                self._compile_bucket(b)
        else:
            list(pool.map(self._compile_bucket, buckets))
        log.info(
            "%s: compiled %d bucket(s) x %d replica(s) in %.1fs",
            self.model.name, len(buckets), len(self.meshes), time.perf_counter() - t0,
        )

    def ensure_compiled(self, params_per_mesh: "list[Any] | None" = None) -> int:
        """Compile any configured bucket missing from the variant registry;
        returns how many variants were newly compiled.

        The lifecycle calls this at STAGE time (tpuserve.lifecycle), so a
        staged canary — and the first post-publish request — never pays a
        first-compile: by the time a candidate tree runs, every variant it
        can reach is resident. In the common case (shapes unchanged across
        versions, which stage_params enforces) this is a cheap no-op whose
        return value of 0 is itself the steady-state proof.

        ``params_per_mesh`` supplies the tree the compilation derives its
        param shardings/structs from when the LIVE tree is absent — a
        cold-booted model's first warm-up (tpuserve.scheduler) compiles
        against the staged candidate before anything has published. Once
        compiled, warm→cold→warm churn re-uses the variants: the counter
        delta across re-warms of an already-compiled model is 0."""
        new = 0
        if not self.compile_forward:
            # Engine-backed runtime: the generative programs were all
            # registered at engine compile time and shapes never change
            # across versions, so there is nothing to demand here — the
            # 0 return IS the steady-state proof for the gen path.
            return new
        for b in self.model.buckets():
            if self.variant_key(tuple(b)) not in self.variants:
                self._compile_bucket(tuple(b), params_per_mesh)
                new += 1
        return new

    @property
    def compiles_total(self) -> float:
        """Executables compiled over this runtime's lifetime (the
        ``runtime_compiles_total`` counter's value)."""
        return self._c_compiles.value

    def variants_summary(self) -> list[dict]:
        """Cheap enumeration of every resident compiled variant. The sort
        key stringifies bucket elements: forward buckets are int tuples,
        generative programs (tag, width) tuples, and Python refuses to
        order str against int."""
        return [v.summary() for _, v in sorted(
            self.variants.items(),
            key=lambda kv: tuple(str(x) for x in kv[0].bucket))]

    def _compile_bucket(self, bucket: tuple,
                        params_per_mesh: "list[Any] | None" = None) -> None:
        t0 = time.perf_counter()
        exes = []
        ppm = params_per_mesh if params_per_mesh else self.params_per_mesh
        for i, mesh in enumerate(self.meshes):
            params = ppm[i]
            batch_struct = self.model.input_signature(bucket)
            # batch_spec is either one P applied to every leaf, or a pytree of
            # P matching batch_struct's structure.
            spec = self.model.batch_spec()
            if isinstance(spec, P):
                in_batch_sharding = jax.tree_util.tree_map(
                    lambda _s: NamedSharding(mesh, spec), batch_struct
                )
            else:
                in_batch_sharding = jax.tree_util.tree_map(
                    lambda s: NamedSharding(mesh, s), spec,
                    is_leaf=lambda x: isinstance(x, P),
                )
            out_spec = self.model.out_spec()
            if isinstance(out_spec, P):
                out_shardings = NamedSharding(mesh, out_spec)
            else:
                out_shardings = jax.tree_util.tree_map(
                    lambda s: NamedSharding(mesh, s), out_spec,
                    is_leaf=lambda x: isinstance(x, P),
                )
            param_shardings = jax.tree_util.tree_map(lambda x: x.sharding, params)
            params_struct = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding), params
            )
            # Donate the batch input only when every leaf provably aliases an
            # output (shape+dtype+sharding match; _donation_shapes_ok) —
            # typical classifiers (uint8 in, small float out) never qualify
            # and compile warning-free (ADVICE r1). Never on the CPU backend:
            # device_put there may alias host memory (the assembly arena),
            # and a donated alias would let XLA scribble on a recycled
            # arena buffer.
            fwd = self._forward_fn()
            donate = False
            if jax.default_backend() != "cpu":
                out_struct = jax.eval_shape(fwd, params_struct, batch_struct)
                donate = _donation_shapes_ok(
                    batch_struct, in_batch_sharding, out_struct, out_shardings)
            jitted = jax.jit(
                fwd,
                in_shardings=(param_shardings, in_batch_sharding),
                out_shardings=out_shardings,
                donate_argnums=(1,) if donate else (),
            )
            compiled = jitted.lower(params_struct, batch_struct).compile()
            exes.append(Executable(bucket, compiled, in_batch_sharding,
                                   device_index=i, donated=donate))
        key = self.variant_key(bucket)
        self.variants[key] = Variant(
            key, exes, compile_ms=(time.perf_counter() - t0) * 1e3)
        self.executables[bucket] = exes
        # Registered before the counters tick so a scrape can never observe
        # a compile with no variant behind it.
        self._c_compiles.inc(len(exes))
        # Retrace witness: a post-warmup-barrier compile raises here, with
        # the variant already registered and the counter ticked — the
        # ledgers stay consistent while the violation propagates to
        # whoever demanded the compile.
        witness.note_compile(self.model.name, key.label)
        self._g_variants.set(len(self.variants))
        self._c_variant_batches[bucket] = self.metrics.counter(
            f"runtime_variant_batches_total{{model={self.model.name},"
            f"variant={key.label}}}")

    # -- generative programs (tpuserve.genserve) ------------------------------
    def register_program(self, tag: str, fn, arg_structs: tuple,
                         width: int = 0,
                         donate_argnums: tuple = (),
                         arg_specs: "tuple | None" = None,
                         out_specs: Any = None) -> GenProgram:
        """AOT-compile ``fn(params, *args)`` against the live param
        structure and register it in the specialized-variant registry.

        The iteration-level engine's executables (insert / step / extract)
        go through here so they get the same discipline as forward buckets:
        a frozen VariantKey identity (bucket = (tag, width) — enumerable in
        /v1/models and /stats), a ``runtime_compiles_total`` tick per
        compile (the zero-steady-state-recompile proof covers them), and a
        prebound per-variant serving counter ticked by run_program.
        Weight versions stay out of the key exactly as for forward buckets:
        publish/rollback swap trees under unchanged shapes, so every
        version reuses the registered program.

        The zero-recompile obligation covers every index a program
        consumes: slot indices AND — for the paged-KV programs (ISSUE 18)
        — page/block-table indices and the chunk-start cursor are all
        TRACED arguments, never baked into shapes, so slot churn, page
        churn, and chunked-prefill progress all replay the same compiled
        executables (``runtime_compiles_total`` steady-state delta 0).

        Layout composition (ISSUE 20): in "single"/"sharded" modes one
        program is compiled against the one mesh; in "replica" mode the
        SAME program is compiled once per replica mesh (mirroring
        ``_compile_bucket``), so one ``GenEngine`` per replica dispatches
        via ``run_program(..., replica=i)`` with no cross-engine contention
        on compiled state. Pipeline mode does not compose — the engine
        owns whole-model state, stage-stacked params don't.

        ``arg_structs`` leaves are replicated (P()) onto the mesh unless
        ``arg_specs`` (a tuple parallel to ``arg_structs`` of
        PartitionSpec trees or ``None`` per arg) pins them to mesh axes —
        the sharded decode path puts KV heads on "model" and pages on
        "seq". ``out_specs`` (a PartitionSpec pytree-prefix of the output)
        pins output shardings: REQUIRED whenever a sharded output feeds
        back as an input of the same AOT executable (the engine's state
        block), because ``jax.stages.Compiled`` demands exact input
        shardings and would otherwise see GSPMD-chosen layouts drift.
        Params keep their partition-rule shardings. ``donate_argnums``
        indexes into ``args`` (0 = the first arg after params) and is
        honored off-CPU only — on the CPU backend device_put may alias
        host memory (the assembly-arena rule)."""
        if self.mode == "pipeline":
            raise ValueError(
                f"{self.model.name}: generative programs do not compose "
                "with the pipeline layout (the engine owns whole-model "
                "state; stage-stacked params do not)")
        t0 = time.perf_counter()
        donate = ()
        if donate_argnums and jax.default_backend() != "cpu":
            donate = tuple(1 + i for i in donate_argnums)
        if arg_specs is None:
            arg_specs = (None,) * len(arg_structs)
        exes: list[Executable] = []
        compiled_per_mesh: list = []
        arg_shardings: tuple = ()
        for i, mesh in enumerate(self.meshes):
            params = self.params_per_mesh[i]
            param_shardings = jax.tree_util.tree_map(
                lambda x: x.sharding, params)
            params_struct = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                               sharding=x.sharding), params)
            arg_shardings = tuple(
                struct_shardings(mesh, struct, spec)
                for struct, spec in zip(arg_structs, arg_specs))
            jit_kwargs: dict = {}
            if out_specs is not None:
                jit_kwargs["out_shardings"] = specs_to_shardings(
                    out_specs, mesh)
            jitted = jax.jit(fn,
                             in_shardings=(param_shardings, *arg_shardings),
                             donate_argnums=donate, **jit_kwargs)
            compiled = jitted.lower(params_struct, *arg_structs).compile()
            compiled_per_mesh.append(compiled)
            exes.append(Executable((tag, width), compiled,
                                   batch_sharding=arg_shardings,
                                   device_index=i, donated=bool(donate)))
        prog = GenProgram(tag, compiled_per_mesh, donated=bool(donate))
        self.gen_programs[tag] = prog
        key = self.variant_key((tag, width))
        self.variants[key] = Variant(
            key, exes, compile_ms=(time.perf_counter() - t0) * 1e3)
        self._c_compiles.inc(len(exes))
        witness.note_compile(tag, key.label)  # retrace witness (see above)
        self._g_variants.set(len(self.variants))
        prog.counter = self._c_variant_batches[(tag, width)] = \
            self.metrics.counter(
                f"runtime_variant_batches_total{{model={self.model.name},"
                f"variant={key.label}}}")
        return prog

    def run_program(self, tag: str, *args,
                    params_override: "list[Any] | None" = None,
                    replica: int = 0) -> Any:
        """Async-dispatch a registered generative program against the LIVE
        param tree (or a staged candidate via ``params_override`` — the
        lifecycle's staged canary runs a short generation through the real
        compiled programs without the candidate ever serving). The params
        list is snapshotted per call, so every dispatch is version-
        consistent and a mid-flight publish affects only later iterations.
        ``replica`` selects the per-mesh executable + param copy in replica
        mode (each replica engine passes its own index) and ticks that
        replica's dispatch ledger so /stats' parallel block proves every
        chip actually generates."""
        if self.injector is not None:
            delay = self.injector.delay_s("slow_compute", self.model.name)
            if delay > 0:
                time.sleep(delay)  # runs on a stage executor thread
            self.injector.check("device_error", self.model.name)
        prog = self.gen_programs[tag]
        if prog.counter is not None:
            prog.counter.inc()
        self._c_replica_batches[replica].inc()
        params = (params_override if params_override is not None
                  else self.params_per_mesh)
        return prog.compiled[replica](params[replica], *args)

    # -- hot path -----------------------------------------------------------
    @property
    def n_replicas(self) -> int:
        """Independent executable sets (the batcher keeps a depth-k
        staging-slot pool per replica)."""
        return len(self.meshes)

    @property
    def n_chips(self) -> int:
        """Physical devices the serving path occupies: replica meshes are
        disjoint single-device meshes (sum = chip count), a sharded mesh is
        one mesh spanning them all."""
        return sum(m.size for m in self.meshes)

    def replica_batches(self) -> list[float]:
        """Current per-replica dispatch counts (replica_batches_total),
        in replica order — the /stats parallel block and the multichip
        smoke read these to prove every chip actually serves."""
        return [c.value for c in self._c_replica_batches]

    def pick_replica(self, loads: "list[int] | None" = None) -> int:
        """First-choice replica for the next batch.

        With ``loads`` (the batcher passes each replica's staging-slot
        occupancy) this is least-loaded: the emptiest device section gets
        the work, so a slow batch on one chip never starves the other
        seven of their depth-k pipelines. Ties break on a rotating
        round-robin cursor so equal-load replicas still alternate instead
        of replica 0 absorbing every cold start. Without ``loads`` it is
        plain round-robin (prewarm, canaries, direct run() callers)."""
        n = len(self.meshes)
        if n == 1:
            return 0
        with self._rr_lock:
            self._rr = (self._rr + 1) % n
            start = self._rr
        if not loads:
            return start
        return min(range(n), key=lambda i: (loads[i], (i - start) % n))

    def h2d(self, bucket: tuple, host_batch: Any, replica: int = 0) -> Any:
        """Transfer stage: ONE batched device_put of the whole host pytree
        against the bucket's input shardings (a single transfer call, not a
        tree_map of per-leaf puts). Runs on the pipeline's h2d executor.

        With ``h2d_sync`` (the [pipeline] default) the call blocks until the
        transfer completes, so the "h2d" phase owns the wire wait and the
        "compute" phase measures dispatch-to-ready only — without it a
        buffered/async transfer returns instantly and its wall time silently
        lands in "compute" (exactly the r05 465-ms-vs-24-ms ambiguity the
        roofline split exists to name). Throughput is unaffected: the block
        happens on a dedicated h2d stage thread the link serializes anyway."""
        exe = self.executables[bucket][replica]
        dev = jax.device_put(host_batch, exe.batch_sharding)
        if self.h2d_sync:
            jax.block_until_ready(dev)
        return dev

    def dispatch(self, bucket: tuple, dev_batch: Any, replica: int = 0,
                 params_override: list[Any] | None = None) -> Any:
        """Compute stage: async-dispatch the compiled call against an
        already-transferred device batch; returns device outputs immediately
        (XLA async dispatch). Chaos kinds device_error/slow_compute fire
        here — below the batcher — on both the run() and pipelined paths."""
        if self.injector is not None:
            delay = self.injector.delay_s("slow_compute", self.model.name)
            if delay > 0:
                time.sleep(delay)  # runs on a stage executor thread
            self.injector.check("device_error", self.model.name)
        exe = self.executables[bucket][replica]
        c = self._c_variant_batches.get(bucket)
        if c is not None:
            c.inc()
        self._c_replica_batches[replica].inc()
        params = (params_override if params_override is not None
                  else self.params_per_mesh)
        return exe.compiled(params[replica], dev_batch)

    def run(self, bucket: tuple, host_batch: Any, replica: int | None = None,
            params_override: list[Any] | None = None) -> Any:
        """H2D + async dispatch in one call (h2d -> dispatch). Returns the
        device output pytree immediately.

        ``params_override`` (a per-mesh tree list shaped like
        ``params_per_mesh``) runs this batch against a DIFFERENT weight tree
        than the published one — the lifecycle's staged canary executes the
        candidate version through the real compiled executables without it
        ever serving traffic."""
        i = replica if replica is not None else self.pick_replica()
        return self.dispatch(bucket, self.h2d(bucket, host_batch, i), i,
                             params_override=params_override)

    @staticmethod
    def fetch(outputs: Any) -> Any:
        """Block for D2H; call off the event loop. Routes through the
        retrace witness's blessed readback so an armed transfer guard
        (TPUSERVE_RETRACE_WITNESS=1) never trips on deliberate fetches."""
        return host_fetch(outputs)

    def prewarm(self) -> None:
        """Execute every (bucket, replica) once on zeros and block for it.

        Compiling does not load the program onto the device: the first real
        execution pays PJRT program load (~20 s per executable through the
        dev tunnel, BASELINE.md "Link physics"). Paying that at startup keeps
        it off the first real request's latency and out of any measurement
        window.
        """
        t0 = time.perf_counter()
        pending = []
        for bucket, exes in sorted(self.executables.items()):
            struct = self.model.input_signature(bucket)
            host = jax.tree_util.tree_map(
                lambda s: np.zeros(s.shape, s.dtype), struct)
            # Dispatch everything async first so loads on distinct devices
            # overlap; then one D2H fetch per executable. The readback is NOT
            # optional: on the tunneled dev TPU, block_until_ready returns
            # before remote execution finishes (BASELINE.md "Timing caveats"),
            # so only a dependent read proves the program load completed.
            pending.extend(self.run(bucket, host, replica=i)
                           for i in range(len(exes)))
        for out in pending:
            self.fetch(out)
        log.info("%s: prewarmed %d executable(s) in %.1fs",
                 self.model.name, len(pending), time.perf_counter() - t0)

    # -- roofline probes ------------------------------------------------------
    def probe_raw_ms(self, bucket: tuple, iters: int = 8,
                     replica: int = 0) -> float | None:
        """Raw-executable time for one bucket (ms/batch), inputs resident.

        ``iters`` back-to-back async dispatches against an already-
        transferred device batch, closed by ONE dependent D2H read — the
        wire never appears in the window, so this is the device-time
        ceiling the serving "compute" phase is measured against
        (docs/PERFORMANCE.md "Reading the roofline"). Donated variants are
        skipped (None): re-dispatching a donated buffer is a use-after-
        donate, and re-transferring per iteration would put the wire back
        in the window. Call after prewarm (PJRT program load out of the
        way) and before the injector is armed."""
        exes = self.executables.get(bucket)
        if not exes or exes[replica].donated:
            self.raw_ms_per_batch[bucket] = None
            return None
        struct = self.model.input_signature(bucket)
        host = jax.tree_util.tree_map(
            lambda s: np.zeros(s.shape, s.dtype), struct)
        dev = jax.device_put(host, exes[replica].batch_sharding)
        jax.block_until_ready(dev)
        self.fetch(self.dispatch(bucket, dev, replica))  # warm the window
        t0 = time.perf_counter()
        out = None
        for _ in range(max(1, iters)):
            out = self.dispatch(bucket, dev, replica)
        self.fetch(out)  # dependent read: the only honest completion signal
        ms = (time.perf_counter() - t0) / max(1, iters) * 1e3
        self.raw_ms_per_batch[bucket] = round(ms, 3)
        return ms

    def probe_all_raw(self, iters: int = 8) -> dict[tuple, float | None]:
        """probe_raw_ms over every compiled bucket; returns the map (also
        retained on the runtime for /stats roofline attribution)."""
        t0 = time.perf_counter()
        for bucket in sorted(self.executables):
            self.probe_raw_ms(bucket, iters=iters)
        log.info("%s: raw-executable probes %s in %.1fs", self.model.name,
                 {str(b): v for b, v in sorted(self.raw_ms_per_batch.items())},
                 time.perf_counter() - t0)
        return dict(self.raw_ms_per_batch)

    # -- versioned weight lifecycle ------------------------------------------
    #
    # stage_params -> (staged canary, lifecycle.py) -> publish | rollback.
    # Staging builds and validates the candidate tree entirely OFF the
    # serving path; publish is one reference assignment under the reload
    # lock — no window where inference can observe a half-validated tree,
    # and in-flight batches finish on the old params (their dispatch
    # captured the references). The previous tree is retained as
    # last-known-good so rollback is a pointer swap, not a disk load.

    def stage_params(self, verify_integrity: bool = True,
                     nan_scan: bool = True,
                     require_manifest: bool = False) -> list[Any]:
        """Load + validate a candidate weight tree without publishing it.

        Gates, in order (each names the failure precisely so the lifecycle
        can label the rejection): sidecar checksum manifest (IntegrityError),
        NaN/Inf scan of the float leaves (NaNDetected), and shape/dtype/
        structure match against what the executables were compiled for
        (ValueError). Injected ``reload_corrupt`` / ``reload_nan`` faults
        fire at their respective gates so chaos drills prove each rejection
        path keeps the old version serving."""
        name = self.model.name
        if self.injector is not None:
            from tpuserve.faults import FaultInjected
            from tpuserve.savedmodel import IntegrityError

            try:
                self.injector.check("reload_corrupt", name)
            except FaultInjected as e:
                raise IntegrityError(
                    f"checksum mismatch (injected): {e}") from e
        params = self._load_host_params(verify_integrity=verify_integrity,
                                        require_manifest=require_manifest)
        if nan_scan:
            if self.injector is not None:
                from tpuserve.faults import FaultInjected

                try:
                    self.injector.check("reload_nan", name)
                except FaultInjected as e:
                    raise NaNDetected(f"NaN leaves (injected): {e}") from e
            from tpuserve.utils.trees import nonfinite_paths

            bad = nonfinite_paths(params)
            if bad:
                raise NaNDetected(
                    f"candidate weights for {name} hold NaN/Inf in {bad}; "
                    "candidate rejected")
        fresh = self._shard_onto_meshes(self.model.prepare_host_params(params))
        old = self.params_per_mesh
        if old:
            same_struct = (jax.tree_util.tree_structure(old[0])
                           == jax.tree_util.tree_structure(fresh[0]))
            if not same_struct or any(
                a.shape != b.shape or a.dtype != b.dtype
                for a, b in zip(jax.tree_util.tree_leaves(old[0]),
                                jax.tree_util.tree_leaves(fresh[0]))):
                raise ValueError(
                    "reloaded weights do not match the compiled "
                    "shapes/dtypes; old params kept")
        return fresh

    def publish(self, staged: list[Any]) -> dict:
        """Atomically make a staged tree live as version N+1; the previous
        tree is retained as last-known-good for rollback().

        Multi-chip atomicity (ISSUE 7): ``staged`` holds one tree PER MESH
        (stage_params device_puts the candidate to every replica / the
        whole sharded mesh before this is called), and the publication is
        ONE list-reference assignment — so there is no instant at which
        replica 3 serves version N+1 while replica 5 still serves N.
        dispatch() snapshots the list once per batch; in-flight batches
        finish on the version they captured, which is version-consistent
        per batch by construction."""
        with self._reload_lock:
            # A cold-booted/demoted runtime has no live tree: retaining []
            # would make rollback() "restore" an unservable empty state.
            self._prev_params = self.params_per_mesh or None
            self._prev_version = self.version if self.params_per_mesh else None
            self._version_seq += 1
            self.version = self._version_seq
            self.params_per_mesh = staged
            return {"model": self.model.name, "version": self.version,
                    "previous_version": self._prev_version}

    def rollback(self) -> dict:
        """Restore the retained last-known-good tree (version N-1).

        One reference assignment, same publication discipline as publish().
        Version numbers are never reused: a later publish continues the
        monotonic sequence. Raises ValueError when nothing is retained
        (startup state, or already rolled back)."""
        with self._reload_lock:
            if self._prev_params is None:
                raise ValueError(
                    f"no retained previous version for {self.model.name} "
                    "to roll back to")
            rolled_from = self.version
            self.params_per_mesh = self._prev_params
            self.version = self._prev_version
            self._prev_params = None
            self._prev_version = None
            return {"model": self.model.name, "version": self.version,
                    "rolled_back_from": rolled_from}

    def release_params(self) -> None:
        """Demote to cold (tpuserve.scheduler weight paging): drop every
        device-resident param tree — the live one AND the retained
        last-known-good — so the device buffers free once in-flight batches
        (which captured their own references at dispatch) complete. The
        compiled variant registry stays resident: a later re-warm
        (stage_params → publish) serves through the same executables with
        zero recompiles."""
        with self._reload_lock:
            self.params_per_mesh = []
            self._prev_params = None
            self._prev_version = None

    @property
    def params_resident(self) -> bool:
        """True while a live device param tree is resident (False = cold:
        HBM for this model's weights is free)."""
        return bool(self.params_per_mesh)

    def reload_params(self) -> dict:
        """Hot-swap weights from cfg.weights without recompiling.

        Compatibility path (stage + publish in one call, no canary): the
        HTTP reload goes through tpuserve.lifecycle, which canaries the
        staged tree first and owns rollback. A failed stage raises and the
        old params keep serving. Serialized via the reload lock in
        publish(); concurrent stagings are themselves read-only."""
        t0 = time.perf_counter()
        staged = self.stage_params()
        info = self.publish(staged)
        info["reload_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        info["params"] = self.describe()["params"]
        return info

    # -- info ---------------------------------------------------------------
    def describe(self) -> dict:
        from tpuserve.utils.trees import tree_summary

        return {
            "model": self.model.name,
            "family": self.cfg.family,
            "version": self.version,
            "mode": self.mode,
            "dtype": self.cfg.dtype,
            "quantize": self.cfg.quantize,
            # Provenance + behavior knobs operators need to see live: seeded
            # random weights (None) vs a real artifact, and per-family options
            # like BERT's attention impl.
            "weights": self.cfg.weights,
            "labels": self.cfg.labels,
            "options": dict(self.cfg.options),
            "replicas": len(self.meshes),
            "n_chips": self.n_chips,
            "parallel": self.parallel_signature,
            "mesh_shape": dict(self.meshes[0].shape),
            "buckets": [list(b) for b in sorted(self.executables)],
            # Specialized-variant registry: what is compiled-resident, with
            # what it was specialized on (ISSUE 6; enumerable per P2).
            "variants": self.variants_summary(),
            "compiles_total": self.compiles_total,
            "params": tree_summary(self.params_per_mesh[0]) if self.params_per_mesh else {},
        }


def build_runtime(model: ServingModel, mesh: Mesh | None = None,
                  pool: cf.ThreadPoolExecutor | None = None,
                  metrics: Metrics | None = None,
                  parallel: ParallelConfig | None = None,
                  compile_forward: bool = True) -> ModelRuntime:
    """``compile_forward=False`` builds a params-only runtime for an
    iteration-level engine (tpuserve.genserve): the engine registers its
    insert/step/extract programs instead of the forward bucket set, so
    compiling both would double startup compile time for nothing."""
    rt = ModelRuntime(model, mesh, metrics=metrics, parallel=parallel)
    rt.compile_forward = compile_forward
    rt.load_and_shard_params()
    if compile_forward:
        rt.compile_all(pool)
    return rt
