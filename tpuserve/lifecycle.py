"""Versioned model lifecycle: staged, reversible weight transitions (ISSUE 2).

PR 1 made the request path survive partial failure; this module is the state
path's counterpart. TF-Serving's servable lifecycle (PAPERS.md P2) treats
version transitions as the central reliability problem — a new version must
prove itself before serving and must never drop accepted traffic — and the
old ``reload_params``-then-canary flow violated both: unvalidated weights
were published first and a failed canary left them serving.

``ModelLifecycle`` turns `POST /admin/models/{name}:reload` into a gated
pipeline, every step of which keeps the old version serving on failure:

1. **stage** — load the candidate OFF the serving path; verify the sidecar
   checksum manifest (``savedmodel.write_manifest``), scan for NaN/Inf, and
   match shapes/dtypes/structure against the compiled executables
   (``ModelRuntime.stage_params``).
2. **staged canary** — run the model's canary item through the real compiled
   executable *against the staged tree* via the ``params_override`` hook in
   ``ModelRuntime.run``. A regressed candidate never serves one request.
3. **publish** — one reference assignment under the runtime's reload lock;
   the tree becomes numbered version N and version N-1 is retained in
   memory as last-known-good.
4. **post-publish canary + soak** — the canary re-runs on the live serving
   path; failure (or the model's CircuitBreaker tripping within
   ``lifecycle.soak_s``) auto-rolls back to the retained tree.

`POST .../{name}:rollback` exposes the same rollback manually and
`GET .../{name}/versions` the transition history. Behind the router split
(tpuserve.workerproc) each worker process owns one of these lifecycles and
the router fans ``:reload`` out to EVERY live worker atomically: any gate
failure rolls the workers that published back, so the fleet never serves
mixed versions, and a success bumps the router's cache generation so
stale cached answers invalidate fleet-wide. Metrics: ``model_version``
gauge, ``reloads_total`` / ``reload_rejected_total{stage=}`` /
``rollbacks_total{reason=}`` counters (tpuserve.obs). Chaos kinds
``reload_corrupt`` / ``reload_nan`` / ``reload_regressed`` fire at gates 1-2
so ``tpuserve chaos --drill reload`` proves availability holds while every
reload is failing (tests/test_lifecycle.py, scripts/reload_drill.sh).
"""

from __future__ import annotations

import asyncio
import logging
import time
from functools import partial
from typing import Any, Awaitable, Callable

import numpy as np

from tpuserve.analysis import witness
from tpuserve.config import LifecycleConfig
from tpuserve.obs import Metrics
from tpuserve.runtime import NaNDetected
from tpuserve.savedmodel import IntegrityError
from tpuserve.telemetry import events as events_mod
from tpuserve.utils.locks import new_async_lock

log = logging.getLogger("tpuserve.lifecycle")


class ReloadRejected(Exception):
    """A reload did not end with the candidate serving.

    ``stage`` names the gate that failed (``integrity``, ``nan_scan``,
    ``structure``, ``load``, ``staged_canary``, ``post_canary``);
    ``rolled_back`` is True when the candidate HAD published and the
    lifecycle reverted it (post-publish canary failure)."""

    def __init__(self, message: str, stage: str,
                 rolled_back: bool = False) -> None:
        super().__init__(message)
        self.stage = stage
        self.rolled_back = rolled_back


class ModelLifecycle:
    """Per-model version lifecycle manager.

    Owns the reload/rollback state machine for one served model. The server
    constructs one per direct-mode runtime at start() and routes the admin
    endpoints through it; recycle-mode (DeferredPool) models have no
    in-process param tree to stage, so they get no lifecycle (reload 409s,
    as before)."""

    def __init__(self, name: str, runtime: Any, model: Any,
                 cfg: LifecycleConfig, metrics: Metrics,
                 breaker: Any | None = None,
                 canary: Callable[[], Awaitable[bool]] | None = None,
                 canary_status: Callable[[], bool | None] | None = None,
                 injector: Any | None = None,
                 staged_canary_fn: Callable[[list], None] | None = None) -> None:
        self.name = name
        self.runtime = runtime
        self.model = model
        self.cfg = cfg
        self.metrics = metrics
        self.breaker = breaker
        # Coroutine fn re-running the model's live canary (rides the batcher;
        # feeds /healthz and the breaker's half-open path). None in tests
        # that drive the lifecycle without a server.
        self._canary = canary
        # Cheap read of the latest periodic-canary verdict (state.canary_ok);
        # the soak monitor watches it without submitting extra probes.
        self._canary_status = canary_status
        # Replacement staged-canary body (blocking; runs in the executor):
        # engine-served generative models pass GenEngine.staged_canary_sync
        # so the candidate proves itself on a SHORT end-to-end generation
        # through the real compiled insert/step/extract programs, instead
        # of the one-shot forward path they no longer compile.
        self._staged_canary_fn = staged_canary_fn
        self.injector = injector
        self._lock = new_async_lock("lifecycle.ModelLifecycle")
        self._soak_task: asyncio.Task | None = None
        # Version-transition records, newest last: {version, at, status,
        # ...detail}. status: live | superseded | rolled_back | rejected.
        self.history: list[dict] = []
        self._record(version=runtime.version, status="live", source="startup")
        self.metrics.set_model_version(name, runtime.version)

    # -- public API ----------------------------------------------------------

    async def reload(self) -> dict:
        """Staged, reversible reload from cfg.weights. Returns the publish
        info dict on success; raises ReloadRejected with the failing gate
        (and whether a rollback happened) otherwise."""
        async with self._lock:
            self._cancel_soak()
            t0 = time.perf_counter()
            loop = asyncio.get_running_loop()
            # Default executor, NOT the server's decode pool: a slow
            # checkpoint load must not occupy a decode/fetch thread the
            # batcher depends on.
            try:
                staged = await loop.run_in_executor(None, partial(
                    self.runtime.stage_params,
                    verify_integrity=self.cfg.verify_checksum,
                    nan_scan=self.cfg.nan_scan,
                    require_manifest=self.cfg.require_manifest))
            except IntegrityError as e:
                self._reject("integrity", e)
            except NaNDetected as e:
                self._reject("nan_scan", e)
            except ValueError as e:
                self._reject("structure", e)
            except Exception as e:  # noqa: BLE001 — e.g. unreadable ckpt
                self._reject("load", e)

            # Variant completeness gate (ISSUE 6): every configured bucket's
            # specialized executable must be resident BEFORE the staged
            # canary runs, so neither the canary nor the first post-publish
            # request ever pays a first-compile. Steady state (shapes
            # unchanged across versions) this compiles nothing — the
            # runtime_compiles_total delta stays 0 across reload churn.
            if hasattr(self.runtime, "ensure_compiled"):
                try:
                    # The staged tree supplies the param shardings when the
                    # live tree is absent (a cold-booted model's first
                    # warm-up, tpuserve.scheduler); steady state this is
                    # the same no-op it always was.
                    # Sanctioned for the retrace witness: demand-compiling
                    # a cold-booted model's missing variants is the
                    # feature; steady state this window sees 0 compiles.
                    with witness.sanctioned_compiles():
                        n_new = await loop.run_in_executor(
                            None,
                            partial(self.runtime.ensure_compiled, staged))
                    if n_new:
                        log.info("%s: compiled %d missing variant(s) at "
                                 "stage time", self.name, n_new)
                except Exception as e:  # noqa: BLE001 — XLA compile failure
                    self._reject("load", e)

            if self.cfg.staged_canary:
                try:
                    if self.injector is not None:
                        self.injector.check("reload_regressed", self.name)
                    await loop.run_in_executor(
                        None, self._staged_canary_sync, staged)
                except Exception as e:  # noqa: BLE001
                    self._reject("staged_canary", e)

            info = self.runtime.publish(staged)
            self.metrics.counter(
                f"reloads_total{{model={self.name}}}").inc()
            self.metrics.set_model_version(self.name, self.runtime.version)
            if self.history and self.history[-1]["status"] == "live":
                self.history[-1]["status"] = "superseded"
            self._record(version=self.runtime.version, status="live",
                         source=self.model.cfg.weights or "init")
            log.info("%s: published version %d", self.name, self.runtime.version)
            # Structured twin of the log line (ISSUE 15): version fields a
            # postmortem/audit reader can machine-match, where the bridge
            # only carries the rendered message.
            events_mod.emit("info", "lifecycle", "published",
                            model=self.name, version=self.runtime.version)

            canary_ok = True
            if self._canary is not None:
                canary_ok = await self._canary()
            if not canary_ok:
                rb = await self._rollback_locked("post_publish_canary")
                raise ReloadRejected(
                    f"post-publish canary failed for {self.name}; rolled "
                    f"back to version {rb['version']}",
                    stage="post_canary", rolled_back=True)

            if self.cfg.soak_s > 0:
                self._soak_task = asyncio.get_running_loop().create_task(
                    self._soak(self.runtime.version))
            info["reload_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
            info["canary_ok"] = canary_ok
            info["soak_s"] = self.cfg.soak_s
            return info

    async def rollback(self, reason: str = "manual") -> dict:
        """Restore the retained last-known-good version (N-1). Raises
        ValueError when nothing is retained."""
        async with self._lock:
            return await self._rollback_locked(reason)

    def describe(self) -> dict:
        return {
            "model": self.name,
            "live_version": self.runtime.version,
            "previous_version": self.runtime._prev_version,
            "soaking": self._soak_task is not None
                       and not self._soak_task.done(),
            "history": list(self.history),
        }

    def close(self) -> None:
        """Server shutdown: stop the soak monitor."""
        self._cancel_soak()

    # -- internals -----------------------------------------------------------

    def _record(self, **fields) -> None:
        fields.setdefault("at", round(time.time(), 3))
        self.history.append(fields)
        del self.history[: -self.cfg.history_limit]

    def _reject(self, stage: str, err: Exception) -> None:
        self.metrics.counter(
            f"reload_rejected_total{{model={self.name},stage={stage}}}").inc()
        self._record(version=self.runtime.version, status="rejected",
                     stage=stage, error=str(err))
        log.warning("%s: reload rejected at %s gate: %s; version %d keeps "
                    "serving", self.name, stage, err, self.runtime.version)
        events_mod.emit("warning", "lifecycle", "reload_rejected",
                        model=self.name, stage=stage, error=str(err),
                        version=self.runtime.version)
        raise ReloadRejected(
            f"reload rejected at {stage} gate: {err}", stage=stage) from err

    def _staged_canary_sync(self, staged: list[Any]) -> None:
        """Run the model's canary item through the real compiled executables
        against the STAGED tree (params_override): the candidate proves
        itself on device before one request can reach it. Blocking D2H —
        runs in the default executor.

        Multi-chip (ISSUE 7): the canary runs on EVERY replica — staging
        device_puts one candidate copy per mesh, and a copy corrupted on
        replica 5 alone must fail the gate, not serve an eighth of the
        traffic. Dispatches go out async first so the replica loads
        overlap; one fetch per replica then proves each. Sharded mode has
        one mesh, so this degenerates to the single canary it always was."""
        if self._staged_canary_fn is not None:
            self._staged_canary_fn(staged)
            return
        item = self.model.canary_item()
        bucket = self.model.bucket_for(1, group=self.model.group_key(item))
        host_batch = self.model.assemble([item], bucket)
        n = max(1, int(getattr(self.runtime, "n_replicas", 1)))
        pending = [self.runtime.run(bucket, host_batch, replica=i,
                                    params_override=staged)
                   for i in range(n)]
        for i, dev_out in enumerate(pending):
            out = self.runtime.fetch(dev_out)
            bad = [k for k, a in _np_leaves(out)
                   if a.dtype.kind == "f" and not np.isfinite(a).all()]
            if bad:
                raise ValueError("staged canary produced non-finite outputs "
                                 f"in {bad} on replica {i}")
            results = self.model.host_postprocess(out, 1)
            if not results:
                raise ValueError(
                    f"staged canary produced no result on replica {i}")

    async def _rollback_locked(self, reason: str) -> dict:
        self._cancel_soak()
        info = self.runtime.rollback()  # ValueError if nothing retained
        self.metrics.counter(
            f"rollbacks_total{{model={self.name},reason={reason}}}").inc()
        self.metrics.set_model_version(self.name, self.runtime.version)
        for rec in reversed(self.history):
            if rec["version"] == info["rolled_back_from"]\
                    and rec["status"] in ("live", "superseded"):
                rec["status"] = "rolled_back"
                rec["reason"] = reason
                break
        self._record(version=info["version"], status="live",
                     source=f"rollback({reason})")
        log.warning("%s: rolled back version %d -> %d (%s)", self.name,
                    info["rolled_back_from"], info["version"], reason)
        events_mod.emit("warning", "lifecycle", "rolled_back",
                        model=self.name, reason=reason,
                        version=info["version"],
                        rolled_back_from=info["rolled_back_from"])
        # Re-canary so /healthz reflects the restored weights and the
        # breaker's recovery path sees a live probe.
        if self._canary is not None:
            await self._canary()
        return info

    async def _soak(self, version: int) -> None:
        """Post-publish soak monitor: a breaker trip or canary failure
        within the window rolls the just-published version back."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.cfg.soak_s
        try:
            while loop.time() < deadline:
                await asyncio.sleep(self.cfg.soak_poll_s)
                if self.runtime.version != version:
                    return  # superseded or manually rolled back
                reason = None
                if self.breaker is not None and self.breaker.state != "closed":
                    reason = "soak_breaker"
                elif (self._canary_status is not None
                      and self._canary_status() is False):
                    reason = "soak_canary"
                if reason is not None:
                    # Clear our own handle first: _rollback_locked cancels
                    # the registered soak task, which would be this one.
                    self._soak_task = None
                    try:
                        await self.rollback(reason=reason)
                    except ValueError:
                        log.warning("%s: soak wanted rollback but no "
                                    "previous version retained", self.name)
                    return
            log.info("%s: version %d passed its %.1fs soak window",
                     self.name, version, self.cfg.soak_s)
        except asyncio.CancelledError:
            raise

    def _cancel_soak(self) -> None:
        try:
            current = asyncio.current_task()
        except RuntimeError:  # close() outside a running loop
            current = None
        t = self._soak_task
        if t is None or t is current:
            return  # the soak task rolling back clears its own handle
        if not t.done():
            t.cancel()
        self._soak_task = None


def _np_leaves(tree: Any) -> list[tuple[str, np.ndarray]]:
    import jax

    from tpuserve.utils.retrace import allow_transfers

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    with allow_transfers():  # deliberate: canary/guard comparison readback
        return [(jax.tree_util.keystr(p), np.asarray(x)) for p, x in flat]
