from tpuserve.cli import main

raise SystemExit(main())
