import sys

from tpuserve.cli import main

# Guarded: multiprocessing's spawn start method re-imports the parent's
# __main__ in every child (router workers, deferred workers under spawn);
# an unguarded entry would re-run the whole CLI inside each of them.
if __name__ == "__main__":
    sys.exit(main())
