"""Version-compat shims for jax APIs that moved/renamed across releases.

The image this repo targets floats across jax versions; serving code must
not care. Current shims:

- ``shard_map``: ``jax.shard_map`` (new) vs ``jax.experimental.shard_map``
  (jax < 0.4.44), and the ``check_vma`` kwarg (new) vs its former name
  ``check_rep``.
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # jax < 0.4.44 keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map

_HAS_CHECK_VMA = "check_vma" in inspect.signature(_shard_map).parameters


def shard_map(*args, **kwargs):
    """jax.shard_map with check_vma/check_rep renamed to whatever this jax
    understands."""
    if not _HAS_CHECK_VMA and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(*args, **kwargs)


def pcast_varying(x, axes):
    """``jax.lax.pcast(x, axes, to="varying")`` where vma tracking exists;
    identity on jax versions without it (replication-checking era, where
    scan carry types never carried varying-axis annotations)."""
    import jax

    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axes, to="varying")
    pvary = getattr(jax.lax, "pvary", None)
    if pvary is not None:  # intermediate releases: pvary only
        return pvary(x, axes)
    return x
