"""Pytree helpers."""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


def tree_size_bytes(tree: Any) -> int:
    """Total bytes across all array leaves."""
    return sum(
        int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
        for x in jax.tree_util.tree_leaves(tree)
        if hasattr(x, "shape")
    )


def tree_summary(tree: Any) -> dict:
    leaves = jax.tree_util.tree_leaves(tree)
    return {
        "leaves": len(leaves),
        "bytes": tree_size_bytes(tree),
        "params": sum(int(np.prod(x.shape)) for x in leaves if hasattr(x, "shape")),
    }


def nonfinite_paths(tree: Any, limit: int = 8) -> list[str]:
    """Tree paths of float leaves holding any NaN/Inf (first ``limit``).

    The lifecycle reload gate scans candidate weight trees with this: a
    poisoned checkpoint (NaN from a diverged fine-tune, Inf from a bf16
    overflow) must be rejected before it can serve. Runs on host arrays;
    numpy classifies bfloat16 as non-float (kind 'V'), so those leaves are
    widened to float32 for the scan."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    bad: list[str] = []
    for path, leaf in flat:
        a = np.asarray(leaf)
        if a.dtype.kind not in "fV":
            continue
        if a.dtype.kind == "V":  # ml_dtypes bfloat16 et al.
            try:
                a = a.astype(np.float32)
            except (TypeError, ValueError):
                continue  # genuinely structured dtype: nothing to scan
        if not np.isfinite(a).all():
            bad.append(jax.tree_util.keystr(path))
            if len(bad) >= limit:
                break
    return bad
