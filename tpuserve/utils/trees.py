"""Pytree helpers."""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


def tree_size_bytes(tree: Any) -> int:
    """Total bytes across all array leaves."""
    return sum(
        int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
        for x in jax.tree_util.tree_leaves(tree)
        if hasattr(x, "shape")
    )


def tree_summary(tree: Any) -> dict:
    leaves = jax.tree_util.tree_leaves(tree)
    return {
        "leaves": len(leaves),
        "bytes": tree_size_bytes(tree),
        "params": sum(int(np.prod(x.shape)) for x in leaves if hasattr(x, "shape")),
    }
