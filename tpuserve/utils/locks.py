"""Named lock constructors: the witness indirection point (docs/ANALYSIS.md).

Every lock on the serving path is built through these two helpers instead of
bare ``threading.Lock()`` / ``asyncio.Lock()``. In production they return the
raw primitives (zero overhead); with ``TPUSERVE_LOCK_WITNESS=1`` they return
witness wrappers (tpuserve.analysis.witness) that maintain the global
lock-order graph and raise on an inversion or a threading lock held across an
``await``. The ``name`` is the graph node: name the *role* at the creation
site (``"obs.Metrics"``, ``"deferred.spawn"``) so every instance of one role
shares a node and cross-instance inversions are still caught.
"""

from __future__ import annotations

import asyncio
import threading

from tpuserve.analysis import witness


def new_lock(name: str):
    """A threading.Lock, witness-wrapped when TPUSERVE_LOCK_WITNESS=1."""
    if witness.enabled():
        return witness.WitnessLock(name)
    return threading.Lock()


def new_async_lock(name: str):
    """An asyncio.Lock, witness-wrapped when TPUSERVE_LOCK_WITNESS=1."""
    if witness.enabled():
        return witness.WitnessAsyncLock(name)
    return asyncio.Lock()
