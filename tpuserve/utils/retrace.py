"""The jax half of the retrace witness: transfer-guard arming + blessed D2H.

The registry (env check, warmup barrier, ``RetraceViolation``) lives in
``tpuserve.analysis.witness`` so the analysis package stays importable on
bare Python; this module is the part that needs jax. When the server
declares its warmup barrier under ``TPUSERVE_RETRACE_WITNESS=1``,
``arm_transfer_guard`` flips jax's device-to-host transfer guard to
"disallow": any *implicit* D2H readback — a stray ``.item()``, ``float()``
on a live array, ``np.asarray`` outside a blessed site — raises instead of
silently serializing the pipeline. Every deliberate readback on the
serving path routes through ``host_fetch`` (or an ``allow_transfers``
block), which is exactly the sanctioned-pattern contract the static pass
(TPS502) enforces on traced bodies, extended to runtime.

Host-to-device stays on jax's default: compiled calls take numpy batches
implicitly by design (the assembly arena hands host buffers straight to
dispatch), so guarding that direction would only bless every call site and
prove nothing.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from tpuserve.analysis import witness


def arm_transfer_guard() -> bool:
    """Disallow implicit device-to-host transfers for the rest of the
    process; no-op (returns False) when the retrace witness is off."""
    if not witness.retrace_enabled():
        return False
    jax.config.update("jax_transfer_guard_device_to_host", "disallow")
    return True


def allow_transfers():
    """Context manager blessing explicit D2H inside the block — for the
    odd-shaped readbacks (``bool(np.asarray(out["done"]))``-style) that
    don't fit ``host_fetch``'s whole-tree signature."""
    return jax.transfer_guard_device_to_host("allow")


def host_fetch(tree: Any) -> Any:
    """THE blessed device->host readback: materialize every leaf as a
    numpy array under an explicit allow. All deliberate serving-path
    fetches (runtime.fetch, the engine's step/extract syncs, lifecycle
    canaries) funnel through here so the armed guard only ever trips on
    transfers nobody meant to make."""
    with jax.transfer_guard_device_to_host("allow"):
        return jax.tree_util.tree_map(np.asarray, tree)
