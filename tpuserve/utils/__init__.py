"""Small shared utilities."""

from tpuserve.utils.trees import tree_size_bytes, tree_summary  # noqa: F401
