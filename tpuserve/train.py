"""Training step over a (data, model, seq) mesh.

The reference is an inference server, but tpuserve ships a first-class
training path for fine-tuning served models, and it is the surface the
multi-chip dry run validates: one jitted train step whose shardings exercise
DP (batch on "data"), TP (attention/MLP kernels on "model"), and SP
(activation sequence dim on "seq") simultaneously, with XLA inserting the
collectives (psum for grads across data, all-gather/reduce-scatter around TP
matmuls) over ICI.

The model is a compact pre-LN transformer encoder LM trained with masked-token
cross-entropy via optax.adamw. Everything is shape-static and scans-free at
this size; jax.checkpoint on the block stack trades FLOPs for HBM when
layers/seq grow. With ``TrainConfig.seq_attention`` set to "ring" or
"ulysses" the blocks use ``tpuserve.ops.ring_attention`` /
``tpuserve.ops.ulysses`` over the mesh's "seq" axis instead of dense
attention, so the dry run exercises real sequence parallelism.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuserve.parallel import make_mesh, match_partition_rules
from tpuserve.parallel.mesh import MeshPlan
from tpuserve.parallel.partition import specs_to_shardings


@dataclass
class TrainConfig:
    vocab: int = 512
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 128
    max_seq: int = 32
    lr: float = 1e-3
    remat: bool = False
    # Sequence-parallel attention over the mesh "seq" axis: "dense" (no SP),
    # "ring" (K/V ppermute rotation, tpuserve.ops.ring_attention), or
    # "ulysses" (head all-to-all, tpuserve.ops.ulysses).
    seq_attention: str = "dense"
    # Mixture-of-experts FFN: 0 = dense MLP; N > 0 = Switch top-1 routing
    # over N experts (tpuserve.ops.moe), expert dim sharded on "model" (EP).
    moe_experts: int = 0
    moe_capacity: float = 1.25
    moe_aux_weight: float = 0.01


class Block(nn.Module):
    cfg: TrainConfig
    dtype: Any = jnp.float32
    mesh: Any = None  # required when cfg.seq_attention != "dense"

    @nn.compact
    def __call__(self, x, mask=None):
        c = self.cfg
        attention_fn = nn.dot_product_attention
        if c.seq_attention != "dense":
            from tpuserve.ops import ring_attention, ulysses_attention

            if c.seq_attention not in ("ring", "ulysses"):
                raise ValueError(f"unknown seq_attention {c.seq_attention!r}")
            if self.mesh is None:
                raise ValueError(f"TrainConfig.seq_attention={c.seq_attention!r} "
                                 "requires passing mesh= to the module")
            sp_attn = ring_attention if c.seq_attention == "ring" else ulysses_attention
            # Keep heads tensor-parallel when tp divides them; otherwise
            # replicate heads (still seq- and data-parallel). Ulysses further
            # needs the local heads divisible by sp (validated in the op).
            head_axis = "model" if c.n_heads % self.mesh.shape["model"] == 0 else None
            spec = P("data", "seq", head_axis, None)

            def attention_fn(query, key, value, mask=None, **_kw):  # noqa: ANN001
                if mask is not None:
                    raise NotImplementedError(
                        "sequence-parallel train path takes no attention mask; "
                        "pass padding via loss masking instead")
                return sp_attn(query, key, value, self.mesh, spec=spec)

        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        h = nn.MultiHeadDotProductAttention(num_heads=c.n_heads, dtype=self.dtype,
                                            deterministic=True, name="attn",
                                            attention_fn=attention_fn)(h)
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        if c.moe_experts:
            from tpuserve.ops.moe import SwitchFFN

            # mask: pad tokens must not claim expert capacity or drive the
            # balance loss.
            h, aux = SwitchFFN(c.moe_experts, c.d_ff,
                               capacity_factor=c.moe_capacity,
                               dtype=self.dtype, name="moe")(h, mask)
            self.sow("losses", "moe_aux", aux)
        else:
            h = nn.Dense(c.d_ff, dtype=self.dtype, name="up")(h)
            h = nn.gelu(h)
            h = nn.Dense(c.d_model, dtype=self.dtype, name="down")(h)
        return x + h


class TransformerLM(nn.Module):
    cfg: TrainConfig
    dtype: Any = jnp.float32
    mesh: Any = None

    @nn.compact
    def __call__(self, tokens, mask=None):
        c = self.cfg
        x = nn.Embed(c.vocab, c.d_model, dtype=self.dtype, name="embed")(tokens)
        pos = self.param("pos_embed", nn.initializers.normal(0.02), (c.max_seq, c.d_model))
        x = x + pos[None, : tokens.shape[1], :].astype(self.dtype)
        block = Block
        if c.remat:
            block = nn.remat(Block)
        for i in range(c.n_layers):
            x = block(c, dtype=self.dtype, mesh=self.mesh, name=f"block{i}")(x, mask)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        return nn.Dense(c.vocab, dtype=jnp.float32, name="lm_head")(x)


# Tensor-parallel rules: attention QKV/out and MLP kernels split on "model";
# embeddings split on the vocab dim; MoE expert dims split on "model" (EP:
# each device holds E/tp experts, XLA inserts the token all-to-alls);
# everything else replicated.
TRAIN_PARTITION_RULES: list[tuple[str, P]] = [
    (r"embed/embedding", P("model", None)),
    (r"attn/(query|key|value)/kernel", P(None, "model", None)),
    (r"attn/out/kernel", P("model", None, None)),
    (r"moe/w_(up|down)", P("model", None, None)),
    (r"up/kernel", P(None, "model")),
    (r"down/kernel", P("model", None)),
    (r"lm_head/kernel", P(None, "model")),
    (r".*", P()),
]


def make_train_state(mesh: Mesh, cfg: TrainConfig, rng: jax.Array | None = None):
    """Init params + opt state, sharded by the TP rules over `mesh`."""
    model = TransformerLM(cfg, mesh=mesh)
    rng = rng if rng is not None else jax.random.key(0)
    # Init batch must divide the data axis: ring attention shard_maps the
    # activations over ("data", "seq") even at init time.
    tokens = jnp.zeros((mesh.shape["data"], cfg.max_seq), jnp.int32)
    params = model.init(rng, tokens)["params"]

    specs = match_partition_rules(TRAIN_PARTITION_RULES, params)
    shardings = specs_to_shardings(specs, mesh)
    params = jax.tree_util.tree_map(jax.device_put, params, shardings)

    tx = optax.adamw(cfg.lr)
    opt_state = tx.init(params)  # mirrors param shardings via GSPMD on first use
    return model, params, tx, opt_state, shardings


def loss_fn(model, params, tokens, targets, mask):
    logits, mods = model.apply({"params": params}, tokens, mask,
                               mutable=["losses"])
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    loss = (losses * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    # MoE load-balancing aux (zero-leaved when no MoE blocks sowed).
    aux = sum(jnp.sum(v) for v in
              jax.tree_util.tree_leaves(mods.get("losses", {})))
    return loss + model.cfg.moe_aux_weight * aux


def make_train_step(model, tx, mesh: Mesh, param_shardings):
    """Build the jitted train step with dp/tp/sp in/out shardings."""
    batch_sharding = {
        "tokens": NamedSharding(mesh, P("data", "seq")),
        "targets": NamedSharding(mesh, P("data", "seq")),
        "mask": NamedSharding(mesh, P("data", "seq")),
    }

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(partial(loss_fn, model))(
            params, batch["tokens"], batch["targets"], batch["mask"]
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # Donation is a memory optimization only; older jaxlib (no jax.typeof)
    # mis-aliases donated buffers whose inferred opt-state output sharding
    # differs from the input under sp/tp meshes (XlaRuntimeError INTERNAL
    # "aliased input ... to have the same size"), so skip it there.
    donate = (0, 1) if hasattr(jax, "typeof") else ()
    return jax.jit(  # tps-ok[TPS501,TPS505]: setup-time factory, jitted once per run
        step,
        in_shardings=(param_shardings, None, batch_sharding),
        out_shardings=(param_shardings, None, None),
        donate_argnums=donate,
    ), batch_sharding


def save_train_state(path: str, params: Any, opt_state: Any, step: int) -> None:
    """Checkpoint the full train state (params + optimizer + step) with orbax.

    Arrays are saved from wherever they live — on a sharded mesh each host
    writes its own shards (orbax is multi-host-aware), so no host ever
    gathers the full state.
    """
    import os

    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        # force=True: a periodic-checkpoint loop overwrites its stable path.
        ckptr.save(os.path.abspath(path),
                   {"params": params, "opt_state": opt_state, "step": step},
                   force=True)
        ckptr.wait_until_finished()


def restore_train_state(path: str, mesh: Mesh, cfg: TrainConfig):
    """Resume: restore directly into the mesh's shardings (no host staging).

    The abstract restore target comes from ``jax.eval_shape`` — nothing is
    materialized on device before the restore, so peak memory is one train
    state, not two. Each abstract leaf carries its NamedSharding (params from
    the partition rules; optimizer moments inherit the matching param's
    sharding by tree-suffix, scalars replicate), so every device reads
    exactly its own shard from disk. Returns
    ``(model, params, tx, opt_state, shardings, step)`` ready for
    ``make_train_step``.
    """
    import os

    import orbax.checkpoint as ocp

    model = TransformerLM(cfg, mesh=mesh)
    tokens = jnp.zeros((mesh.shape["data"], cfg.max_seq), jnp.int32)
    params_shape = jax.eval_shape(model.init, jax.random.key(0), tokens)["params"]
    specs = match_partition_rules(TRAIN_PARTITION_RULES, params_shape)
    shardings = specs_to_shardings(specs, mesh)
    tx = optax.adamw(cfg.lr)
    opt_shape = jax.eval_shape(tx.init, params_shape)

    flat = jax.tree_util.tree_flatten_with_path(shardings)[0]
    by_suffix = {tuple(str(k) for k in p): s for p, s in flat}
    replicated = NamedSharding(mesh, P())

    def opt_sharding(path, leaf):
        """Adam's mu/nu mirror the param tree: match by path suffix."""
        keys = tuple(str(k) for k in path)
        for i in range(len(keys)):
            s = by_suffix.get(keys[i:])
            if s is not None:
                return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=s)
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=replicated)

    target = {
        "params": jax.tree_util.tree_map(
            lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
            params_shape, shardings),
        "opt_state": jax.tree_util.tree_map_with_path(opt_sharding, opt_shape),
        "step": 0,
    }
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(os.path.abspath(path), target)
    return (model, restored["params"], tx, restored["opt_state"], shardings,
            int(restored["step"]))


def synthetic_batch(cfg: TrainConfig, batch_size: int, seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab, (batch_size, cfg.max_seq)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1)
    mask = np.ones((batch_size, cfg.max_seq), np.float32)
    return {"tokens": tokens, "targets": targets, "mask": mask}


def mesh_plan_for(n_devices: int) -> MeshPlan:
    """Factor n into dp*tp*sp, exercising every axis that fits."""
    tp = 2 if n_devices % 2 == 0 else 1
    sp = 2 if n_devices % 4 == 0 else 1
    return MeshPlan(tp=tp, sp=sp)


def dryrun(devices: list, steps: int = 1) -> float:
    """One (or more) real sharded train step(s) on the given devices.

    When the mesh has a real "seq" axis (sp > 1), attention runs through
    tpuserve.ops.ring_attention so the dry run exercises genuine sequence
    parallelism (K/V ppermute around the ring), alongside DP and TP. When
    the "model" axis is real (tp > 1), the FFN runs as a Switch MoE with
    the expert dim sharded over it — expert parallelism in the same step.
    """
    n = len(devices)
    plan = mesh_plan_for(n)
    mesh = make_mesh(plan, devices=devices)
    cfg = TrainConfig(seq_attention="ring" if plan.sp > 1 else "dense",
                      moe_experts=2 * plan.tp if plan.tp > 1 else 0)
    model, params, tx, opt_state, shardings = make_train_state(mesh, cfg)
    step, _ = make_train_step(model, tx, mesh, shardings)
    batch_size = max(4, 2 * mesh.shape["data"])
    loss = None
    for i in range(steps):
        params, opt_state, loss = step(params, opt_state, synthetic_batch(cfg, batch_size, seed=i))
    return float(loss)
