"""Framed binary multi-item wire format (ISSUE 11; Clipper P1's front door).

``application/x-tpuserve-frame`` is the ingest fast path's wire contract: one
POST carries N exact-wire-size items — uint8 RGB tensors or YUV 4:2:0 planes —
with no per-item npy headers, no base64, no JSON, and no client-side pixel
re-encode on the server. Layout (all integers little-endian)::

    +--------+---------+--------+---------+---------+
    | magic  | version | kind   | count   | edge    |   fixed 16-byte header
    | "TPUF" | u16 = 1 | u16    | u32     | u32     |
    +--------+---------+--------+---------+---------+
    | offset[0] ... offset[count]   (count+1 x u64) |   offset table
    +-----------------------------------------------+
    | item 0 bytes | item 1 bytes | ... | item N-1  |   payload region
    +-----------------------------------------------+

Offsets are relative to the start of the payload region (the byte after the
table), strictly ascending, ``offset[0] == 0``, ``offset[count] == len(payload)``.
Every item is exactly ``item_nbytes(kind, edge)`` long:

- ``KIND_RGB8`` (1): ``(edge, edge, 3)`` uint8, C-order — 3 B/px.
- ``KIND_YUV420`` (2): full-res Y plane ``(edge, edge)`` followed by the two
  2x2-subsampled chroma planes ``(edge/2, edge/2)`` — 1.5 B/px, exactly what
  a baseline JPEG stores and what ``preproc.device_prepare_images_yuv420``
  consumes, so the whole pixel path is copy-count one: request body ->
  (zero-copy ``np.frombuffer`` view) -> assembly-arena bucket buffer.

Parsing is **zero-copy**: ``parse_frame`` returns ``np.frombuffer`` views
over a ``memoryview`` of the request body — no intermediate npy re-parse,
no per-item allocation. The views are read-only and keep the body alive;
the single copy happens when ``ServingModel.assemble_into`` writes them
into the preallocated AssemblyArena bucket buffer (the decode-into seam).

Every malformed-frame condition raises :class:`FrameError` (a ``ValueError``)
with a machine-readable ``frame:``-prefixed message; the HTTP layer maps it
to a 400 with ``frame_errors_total{model=}`` ticking — a bad frame is a
client error, never a 500 (tests/test_frame.py pins each case).
"""

from __future__ import annotations

import struct

import numpy as np

CONTENT_TYPE = "application/x-tpuserve-frame"

MAGIC = b"TPUF"
VERSION = 1
KIND_RGB8 = 1
KIND_YUV420 = 2
# Stream-only (ISSUE 17): a self-delimiting JSON event frame. Never valid in
# a request body (parse_frame rejects the kind); it exists so a
# chunked binary *response* stream can interleave progress/done/error events
# between image frames. ``count`` carries the payload byte length, ``edge``
# is 0, and there is no offset table — header + payload, nothing else.
KIND_EVENT = 3
KIND_NAMES = {KIND_RGB8: "rgb8", KIND_YUV420: "yuv420"}
KIND_BY_WIRE_FORMAT = {"rgb8": KIND_RGB8, "yuv420": KIND_YUV420}

# magic, version, kind, count, edge.
_HEADER = struct.Struct("<4sHHII")
HEADER_SIZE = _HEADER.size  # 16


class FrameError(ValueError):
    """A malformed ``application/x-tpuserve-frame`` body (-> HTTP 400)."""


def item_nbytes(kind: int, edge: int) -> int:
    """Exact payload bytes of ONE item: 3 B/px rgb8, 1.5 B/px yuv420."""
    if kind == KIND_RGB8:
        return 3 * edge * edge
    if kind == KIND_YUV420:
        return edge * edge + 2 * (edge // 2) * (edge // 2)
    raise FrameError(f"frame: unknown item kind {kind}")


def frame_nbytes(kind: int, edge: int, count: int) -> int:
    """Total body bytes of a frame of ``count`` items (header + table +
    payload) — the ingest-link pricing term for the bench roofline."""
    return HEADER_SIZE + 8 * (count + 1) + count * item_nbytes(kind, edge)


def encode_frame(items: list, kind: int, edge: int) -> bytes:
    """Build a frame body from decoded items (client/loadgen/test side).

    ``items`` are ``(edge, edge, 3)`` uint8 arrays for ``KIND_RGB8`` or
    ``(y, u, v)`` uint8 plane tuples for ``KIND_YUV420`` (the
    ``preproc.rgb_to_yuv420`` shape contract)."""
    if not items:
        raise FrameError("frame: cannot encode an empty frame")
    size = item_nbytes(kind, edge)
    chunks: list[bytes] = []
    offsets = [0]
    for it in items:
        if kind == KIND_YUV420:
            raw = b"".join(np.ascontiguousarray(p, dtype=np.uint8).tobytes()
                           for p in it)
        else:
            raw = np.ascontiguousarray(it, dtype=np.uint8).tobytes()
        if len(raw) != size:
            raise FrameError(
                f"frame: item has {len(raw)} bytes, expected {size} "
                f"({KIND_NAMES[kind]}@{edge})")
        chunks.append(raw)
        offsets.append(offsets[-1] + size)
    header = _HEADER.pack(MAGIC, VERSION, kind, len(items), edge)
    table = np.asarray(offsets, dtype="<u8").tobytes()
    return b"".join([header, table, *chunks])


def encode_stream_event(payload: bytes) -> bytes:
    """One self-delimiting ``KIND_EVENT`` frame for a binary response
    stream: 16-byte header (count = payload byte length, edge = 0) followed
    directly by the JSON payload. Pairs with :class:`StreamFrameReader`."""
    return _HEADER.pack(MAGIC, VERSION, KIND_EVENT, len(payload), 0) + payload


class StreamFrameReader:
    """Incremental decoder for a chunked binary response stream (the client
    side of sd15 streaming: drill, loadgen, tests). ``feed`` accepts
    arbitrary transport chunk splits and returns the frames completed so
    far as ``(kind, payload)`` tuples — for ``KIND_EVENT`` the payload is
    the raw JSON bytes; for image kinds it is the COMPLETE frame body
    (header included), ready for :func:`parse_frame`."""

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, chunk: bytes) -> list:
        self._buf += chunk
        frames: list = []
        while len(self._buf) >= HEADER_SIZE:
            magic, version, kind, count, edge = _HEADER.unpack_from(self._buf)
            if magic != MAGIC:
                raise FrameError(f"frame: bad stream magic {bytes(magic)!r}")
            if version != VERSION:
                raise FrameError(
                    f"frame: unsupported stream frame version {version}")
            if kind == KIND_EVENT:
                total = HEADER_SIZE + count
            elif kind in KIND_NAMES:
                total = frame_nbytes(kind, edge, count)
            else:
                raise FrameError(f"frame: unknown stream frame kind {kind}")
            if len(self._buf) < total:
                break
            body = bytes(self._buf[:total])
            del self._buf[:total]
            frames.append((kind, body[HEADER_SIZE:] if kind == KIND_EVENT
                           else body))
        return frames

    @property
    def pending(self) -> int:
        """Buffered bytes of a not-yet-complete frame (a torn stream ends
        with pending > 0 or a missing terminal event — never silently)."""
        return len(self._buf)


def parse_frame(body: bytes, *, kind: int, edge: int, max_items: int) -> list:
    """Parse a frame body into zero-copy per-item views (the server side).

    Returns ``(edge, edge, 3)`` uint8 views for ``KIND_RGB8`` or
    ``(y, u, v)`` plane-view tuples for ``KIND_YUV420`` — every array is an
    ``np.frombuffer`` slice of ``body`` (read-only, keeps the body alive);
    the one copy happens downstream in ``assemble_into``. ``kind`` is what
    the MODEL serves (its ``wire_format``): a client frame of another kind
    is a 400, not a silent server-side convert.

    Raises :class:`FrameError` on every malformed condition: truncated
    header or offset table, bad magic/version/kind, kind mismatch, edge
    mismatch, zero or over-``max_items`` count, non-ascending offsets,
    zero-length or wrong-length items, and a table pointing past the end
    of the body.
    """
    mv = memoryview(body)
    if len(mv) < HEADER_SIZE:
        raise FrameError(
            f"frame: truncated header ({len(mv)} bytes, need {HEADER_SIZE})")
    magic, version, fkind, count, fedge = _HEADER.unpack_from(mv)
    if magic != MAGIC:
        raise FrameError(f"frame: bad magic {bytes(magic)!r}")
    if version != VERSION:
        raise FrameError(
            f"frame: unsupported version {version} (this server speaks "
            f"{VERSION})")
    if fkind not in KIND_NAMES:
        raise FrameError(f"frame: unknown item kind {fkind}")
    if fkind != kind:
        raise FrameError(
            f"frame: item kind {KIND_NAMES[fkind]} does not match the "
            f"model's wire_format {KIND_NAMES[kind]}")
    if count < 1:
        raise FrameError("frame: item count must be >= 1")
    if count > max_items:
        raise FrameError(
            f"frame: {count} items exceeds the per-request limit "
            f"({max_items})")
    if fedge != edge:
        raise FrameError(
            f"frame: edge {fedge} does not match the model's wire_size "
            f"{edge} (clients resize before framing)")
    table_end = HEADER_SIZE + 8 * (count + 1)
    if len(mv) < table_end:
        raise FrameError(
            f"frame: truncated offset table ({len(mv)} bytes, need "
            f"{table_end})")
    offsets = np.frombuffer(mv[HEADER_SIZE:table_end], dtype="<u8")
    payload = mv[table_end:]
    size = item_nbytes(kind, edge)
    if int(offsets[0]) != 0:
        raise FrameError(f"frame: first offset must be 0, got {offsets[0]}")
    if int(offsets[-1]) != len(payload):
        raise FrameError(
            f"frame: offset table ends at {int(offsets[-1])} but the "
            f"payload region is {len(payload)} bytes")
    items: list = []
    half = edge // 2
    y_n, c_n = edge * edge, half * half
    for i in range(count):
        a, b = int(offsets[i]), int(offsets[i + 1])
        if b - a != size:
            raise FrameError(
                f"frame: item {i} spans {b - a} bytes, expected {size} "
                f"({KIND_NAMES[kind]}@{edge}; zero-length and partial "
                "items are rejected)")
        raw = np.frombuffer(payload[a:b], dtype=np.uint8)
        if kind == KIND_RGB8:
            items.append(raw.reshape(edge, edge, 3))
        else:
            items.append((
                raw[:y_n].reshape(edge, edge),
                raw[y_n:y_n + c_n].reshape(half, half),
                raw[y_n + c_n:].reshape(half, half),
            ))
    return items
