"""Typed configuration for tpuserve (SURVEY.md §2 C9).

The reference's configuration story is unknowable (empty mount, SURVEY.md §0);
per SURVEY.md §5 the build uses typed dataclasses, an optional TOML file, and
CLI dot-path overrides — no global mutable flag framework.

Example TOML::

    port = 8000

    [[model]]
    name = "resnet50"
    family = "resnet50"
    batch_buckets = [1, 4, 8, 16, 32]
    deadline_ms = 5.0
    dtype = "bfloat16"
"""

from __future__ import annotations

import dataclasses

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: tomli is the same parser
    import tomli as tomllib
from dataclasses import dataclass, field
from typing import Any


# Fault kinds the chaos injector understands (tpuserve.faults.FaultInjector).
# Each names a call site on the serving path where an armed rule can fire.
FAULT_KINDS = (
    "batch_error",      # raise inside batch dispatch (batcher._execute)
    "slow_dispatch",    # sleep delay_ms inside batch dispatch
    "decode_corrupt",   # fail request decode -> HTTP 400
    "worker_death",     # kill the active deferred worker process
    "canary_fail",      # fail the per-model canary probe
    "device_error",     # raise inside ModelRuntime.run (below the batcher)
    "slow_compute",     # sleep delay_ms inside ModelRuntime.run
    "kill_group_loop",  # crash the group accumulation task (watchdog food)
    "reload_corrupt",   # fail the reload integrity check (checksum mismatch)
    "reload_nan",       # fail the reload NaN/Inf scan (poisoned checkpoint)
    "reload_regressed", # fail the staged canary (regressed weights)
    "worker_crash",     # os._exit the serving process mid-request (native crash)
    "worker_hang",      # wedge the serving process: the request never answers
    "worker_slow",      # sleep delay_ms in the serving process before decode
    "stream_stall",     # stop writing a started stream (consumer wedged):
                        # the reader sees heartbeats dry up / idle timeout
    "stream_disconnect",  # abruptly close a started stream's transport with
                          # NO terminal event (the torn-stream shape clients
                          # must treat as an error)
)


@dataclass
class FaultRuleConfig:
    """One armed chaos rule (TOML ``[[faults.rule]]``; tpuserve.faults)."""

    # Which call site fires (see FAULT_KINDS).
    kind: str = "batch_error"
    # Model name the rule applies to; "*" matches every model.
    model: str = "*"
    # Per-call-site chance of firing, drawn from a rule-local seeded RNG so
    # runs are reproducible.
    probability: float = 1.0
    # Max times the rule fires; -1 = unlimited.
    count: int = -1
    # Sleep for the slow_* kinds (ignored by the others).
    delay_ms: float = 0.0
    # Rule-local RNG seed; 0 derives one from FaultsConfig.seed + rule index.
    seed: int = 0
    # Arm the rule only after the injector has been alive this long (s):
    # a drill's "fault fires MID-load", reproducibly. 0 = armed from boot.
    after_s: float = 0.0
    # Restrict the rule to one worker process id (router split): -1 = any
    # process. Pinning a slow_* rule to one worker makes the fault a
    # single-host/single-slot event, the autopilot drill's blast shape.
    worker: int = -1

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; known: {list(FAULT_KINDS)}")
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError(f"probability must be in [0, 1], got {self.probability}")
        if self.after_s < 0:
            raise ValueError(f"faults.rule.after_s must be >= 0, got {self.after_s}")
        if self.worker < -1:
            raise ValueError(f"faults.rule.worker must be >= -1, got {self.worker}")


@dataclass
class FaultsConfig:
    """Deterministic fault injection for chaos testing (``[faults]`` TOML).

    Off by default; staging configs arm rules to prove the recovery machinery
    (retry, breaker, watchdog, drain) holds the latency SLO while degraded."""

    enabled: bool = False
    # Base seed rule-local RNGs derive from (reproducible chaos runs).
    seed: int = 0
    rules: list[FaultRuleConfig] = field(default_factory=list)


@dataclass
class LifecycleConfig:
    """Versioned model lifecycle (``[lifecycle]`` TOML; tpuserve.lifecycle).

    Every weight reload is a staged, reversible transition: load off the
    serving path -> verify integrity -> canary the *staged* params -> publish
    as a numbered version with the previous tree retained -> auto-rollback on
    post-publish canary failure or a breaker trip within the soak window."""

    # Verify the sidecar checksum manifest (written by save_orbax /
    # import-model) against the loaded tree when one is present.
    verify_checksum: bool = True
    # Reject reloads of orbax checkpoints that carry NO manifest (strict
    # provenance mode). Off by default: TF/torch imports have no manifest.
    require_manifest: bool = False
    # Scan the candidate tree for NaN/Inf float leaves before staging.
    nan_scan: bool = True
    # Run the canary inference against the STAGED params (via the runtime's
    # params-override hook) before publishing; a failure never publishes.
    staged_canary: bool = True
    # Post-publish soak window (s): if the model's circuit breaker trips or
    # the periodic canary fails within this window, the reload auto-rolls
    # back to the retained last-known-good version. 0 disables soaking.
    soak_s: float = 0.0
    # Soak poll cadence (s).
    soak_poll_s: float = 0.25
    # Version-transition records kept per model (/admin .../versions).
    history_limit: int = 16


@dataclass
class PipelineConfig:
    """Pipelined host execution engine (``[pipeline]`` TOML; tpuserve.hostpipe,
    docs/PERFORMANCE.md).

    The direct-mode hot path runs as a staged pipeline — decode/assemble,
    H2D transfer + dispatch, D2H fetch, postprocess — with a dedicated thread
    pool per stage so consecutive batches occupy different stages
    concurrently, preallocated per-bucket assembly arenas instead of
    per-batch np.stack allocation, and a depth-k staging-slot pool per
    replica bounding batches in the device section ([h2d..fetch])."""

    # Thread-pool size per stage (shared across every direct-mode model).
    assemble_workers: int = 2
    h2d_workers: int = 2
    fetch_workers: int = 2
    postproc_workers: int = 2
    # Batches in flight per replica inside [h2d..fetch] ("staging slots");
    # 0 derives it from each model's max_inflight.
    depth: int = 0
    # Extra batches admitted past the device depth so assembly runs ahead of
    # the device (the pipeline's ramp): admission = depth*replicas + this.
    assemble_ahead: int = 2
    # Preallocated assembly buffers per (model, bucket); 0 sizes it to
    # depth + assemble_ahead. Acquires beyond this fall back to one-shot
    # allocations counted in arena_overflow_total{model=}.
    arena_slots: int = 0
    # Block the h2d stage until the transfer completes, so the "h2d" phase
    # owns the wire wait and "compute" measures dispatch-to-ready only
    # (roofline attribution, docs/PERFORMANCE.md "Reading the roofline").
    # The block lands on a dedicated h2d stage thread the link serializes
    # anyway, so throughput is unaffected; false restores buffered puts.
    h2d_sync: bool = True

    def __post_init__(self) -> None:
        for f in ("assemble_workers", "h2d_workers", "fetch_workers",
                  "postproc_workers"):
            if getattr(self, f) < 1:
                raise ValueError(f"pipeline.{f} must be >= 1")
        if self.depth < 0 or self.assemble_ahead < 0 or self.arena_slots < 0:
            raise ValueError(
                "pipeline.depth/assemble_ahead/arena_slots must be >= 0")


@dataclass
class CacheConfig:
    """Content-addressed result cache + single-flight coalescing (``[cache]``
    TOML; tpuserve.cache, docs/PERFORMANCE.md "Result cache & coalescing").

    Key = digest(model, live version, preprocessed item); value = the
    postprocessed result. The live model version is part of every key, so a
    lifecycle publish/rollback (tpuserve.lifecycle) atomically invalidates
    all previous entries without a sweep. Hits and coalesced waiters are
    counted separately from misses so cache traffic can never masquerade as
    model throughput in a bench."""

    enabled: bool = False
    # Max cached results per model (LRU beyond it).
    capacity: int = 4096
    # Entry time-to-live in seconds; 0 disables expiry (version churn is the
    # primary invalidation — TTL exists for non-deterministic models).
    ttl_s: float = 0.0
    # Single-flight: N concurrent identical misses occupy ONE batch slot,
    # the result fanning out to every waiter (Clipper P1's prediction-cache
    # trick, which also de-thunders retry storms).
    coalesce: bool = True
    # JSON results at most this big are pre-serialized at population time so
    # a hit's response body is one memcpy, not a per-request json.dumps.
    max_body_bytes: int = 1048576

    def __post_init__(self) -> None:
        if self.capacity < 1:
            raise ValueError(f"cache.capacity must be >= 1, got {self.capacity}")
        if self.ttl_s < 0 or self.max_body_bytes < 0:
            raise ValueError("cache.ttl_s/max_body_bytes must be >= 0")


@dataclass
class AdaptiveConfig:
    """SLO-aware adaptive batching (``[adaptive]`` TOML; tpuserve.batcher,
    docs/PERFORMANCE.md "Adaptive batching").

    Replaces the fixed max-wait flush with an AIMD-adjusted per-group target
    batch size (Clipper P1) plus a deadline-headroom bound from the per-bucket
    batch-duration EWMA (Clockwork P3): under light load the target decays to
    ``min_target`` and batches flush immediately; under sustained load it
    climbs to the largest bucket and batches fill. ``deadline_ms`` stays as
    the max-wait backstop."""

    enabled: bool = True
    # Floor of the AIMD target batch size.
    min_target: int = 1
    # Starting target per group; 0 = the model's largest batch bucket (the
    # pre-adaptive behavior, so cold groups favor throughput).
    initial_target: int = 0
    # Additive increase applied when a batch fills to target with more work
    # still queued (arrivals outpace the target: grow it).
    increase: float = 1.0
    # Multiplicative decrease applied on a timer-driven partial flush
    # (arrivals can't fill the target: shrink it toward min_target).
    decrease: float = 0.5
    # Smoothing factor for the per-bucket batch-duration EWMA.
    ewma_alpha: float = 0.2
    # Safety margin (ms) subtracted with the EWMA from the earliest request
    # deadline when computing the flush headroom bound.
    slack_ms: float = 2.0

    def __post_init__(self) -> None:
        if self.min_target < 1 or self.initial_target < 0:
            raise ValueError(
                "adaptive.min_target must be >= 1 and initial_target >= 0")
        if self.increase <= 0 or not 0.0 < self.decrease <= 1.0:
            raise ValueError(
                "adaptive.increase must be > 0 and decrease in (0, 1]")
        if not 0.0 < self.ewma_alpha <= 1.0 or self.slack_ms < 0:
            raise ValueError(
                "adaptive.ewma_alpha must be in (0, 1] and slack_ms >= 0")


@dataclass
class GenserveConfig:
    """Iteration-level generation engine (``[genserve]`` TOML;
    tpuserve.genserve, docs/PERFORMANCE.md "The generation engine").

    The static-bucket batcher locks a batch for its whole run — correct for
    one-shot classifiers, wrong for multi-step generative work. With this
    block enabled, models whose family implements the generative contract
    (``tpuserve.genserve.GenerativeModel``: textgen, sd15) serve through an
    iteration-level engine instead (Orca, PAPERS.md P4): the active batch
    re-forms every model iteration, finished sequences retire immediately,
    queued requests fold into free slots mid-flight, and past-deadline
    sequences evict with the fast-504 contract. Non-generative models keep
    the batcher regardless."""

    enabled: bool = False
    # Generative slot capacity per model (the compiled step batch width);
    # 0 = the model's largest batch bucket.
    slots: int = 0
    # Max queued requests folded into free slots per iteration; 0 = fill
    # every free slot (bounding it smooths per-iteration insert cost).
    admit_per_step: int = 0
    # Streaming (ISSUE 17, docs/ROBUSTNESS.md "Streaming failure
    # semantics"): per-request emission queue depth between the step loop
    # and the HTTP writer. A full queue applies the model's stream_policy
    # (drop droppable progress units, or block the slot).
    stream_queue: int = 64
    # SSE heartbeat comments (": hb") across idle emission gaps, so a
    # proxy/client can distinguish "still generating" from a dead stream;
    # 0 disables heartbeats.
    stream_heartbeat_s: float = 5.0
    # Graceful-drain stream budget: on SIGTERM, in-flight STREAMS get this
    # long to finish before the engine terminates stragglers with the
    # well-formed error event (reason "drain" — never a silent
    # truncation); 0 = streams only get the shared drain_timeout_s.
    stream_drain_s: float = 5.0
    # Paged KV cache (ISSUE 18, docs/PERFORMANCE.md "Paged KV & chunked
    # prefill"; PagedAttention/vLLM): families that implement the paged
    # contract (textgen) allocate KV as fixed-size pages behind a
    # device-resident block table instead of one dense worst-case-ctx slab
    # per slot. Pages are reserved at fold-in (prompt + decode budget) and
    # returned on retire/evict/disconnect; exhaustion sheds 503 with a
    # Retry-After (reason kv_pressure). Default off: dense path stays
    # byte-compatible, and families without paged programs (sd15) keep the
    # dense slab regardless.
    kv_paging: bool = False
    # Tokens per KV page. Smaller pages track real context tighter (less
    # internal fragmentation); larger pages mean fewer gather indices.
    kv_page_tokens: int = 16
    # Total device pages in the pool, INCLUDING the write-sink sentinel
    # (page 0, never allocated). 0 = auto: slots * pages-per-max-ctx + 1,
    # i.e. the same worst-case KV bytes as the dense slab — set it lower
    # to hold memory fixed while raising [genserve] slots, which is the
    # whole point of paging.
    kv_pages: int = 0
    # Chunked prefill (Orca-style iteration-level scheduling applied to
    # the prompt): a paged prompt folds in this many tokens per engine
    # iteration, interleaved with decode steps, so a max-length prompt
    # never stalls in-flight decoders. 0 = whole prompt in one chunk
    # (exactly the dense prefill math). Only meaningful with kv_paging.
    prefill_chunk: int = 0

    def __post_init__(self) -> None:
        if self.slots < 0 or self.admit_per_step < 0:
            raise ValueError(
                "genserve.slots/admit_per_step must be >= 0")
        if self.stream_queue < 1:
            raise ValueError(
                f"genserve.stream_queue must be >= 1, got {self.stream_queue}")
        if self.stream_heartbeat_s < 0 or self.stream_drain_s < 0:
            raise ValueError(
                "genserve.stream_heartbeat_s/stream_drain_s must be >= 0")
        if self.kv_page_tokens < 1:
            raise ValueError(
                f"genserve.kv_page_tokens must be >= 1, got "
                f"{self.kv_page_tokens}")
        if self.kv_pages < 0 or self.prefill_chunk < 0:
            raise ValueError(
                "genserve.kv_pages/prefill_chunk must be >= 0")
        if self.kv_pages == 1:
            raise ValueError(
                "genserve.kv_pages must be 0 (auto) or >= 2 (the pool "
                "includes the sentinel page)")


@dataclass
class TraceConfig:
    """Request-scoped distributed tracing (``[trace]`` TOML; tpuserve.obs,
    docs/OBSERVABILITY.md).

    Every HTTP request gets a 128-bit trace context at ingest (adopted
    from ``X-Trace-Id`` when the router tier already stamped one) and the
    id comes back as an ``X-Trace-Id`` response header on EVERY response,
    errors included — that part is unconditional, the contract clients and
    the router rely on. This block sizes what gets RETAINED: the flight
    recorder's slowest-N-per-model reservoir, the errored-request FIFO,
    and whether /metrics histograms render per-bucket trace-id
    exemplars."""

    # Slowest-N complete span trees retained per model for /debug/slow;
    # 0 disables the slow reservoir (errors still record).
    slow_n: int = 16
    # Record every errored/shed request (HTTP status >= 400) even when
    # fast — a shed 503 or fast 504 is exactly what gets reported.
    always_record_errors: bool = True
    # Errored-request span trees retained (FIFO beyond it).
    error_capacity: int = 256
    # Render per-bucket trace-id exemplars on /metrics histogram bucket
    # lines (OpenMetrics exemplar syntax), so a dashboard p99 bucket names
    # a recorded trace to click through to.
    exemplars: bool = True

    def __post_init__(self) -> None:
        if self.slow_n < 0 or self.error_capacity < 0:
            raise ValueError(
                "trace.slow_n/error_capacity must be >= 0")


@dataclass
class EventsConfig:
    """Structured event plane (``[events]`` TOML; tpuserve.telemetry.events,
    docs/OBSERVABILITY.md "The third pillar").

    On by default: every process owns a bounded ring of structured event
    records (ts_us / level / subsystem / event / model / trace correlation
    ids / free-form fields) fed by explicit emissions AND a stdlib
    ``logging.Handler`` bridge over the existing ``tpuserve.*`` loggers, so
    call sites flow in without rewriting. Queryable at ``GET /debug/events``
    on the server, every worker, and the router. The same block sizes the
    crash-forensics black box (per-worker stderr capture files + periodic
    postmortem snapshots, folded into ``GET /debug/postmortems`` on reap)
    and the admin audit trail (``GET /debug/audit``)."""

    enabled: bool = True
    # Event records retained in the per-process ring (newest kept).
    capacity: int = 4096
    # Optional JSONL file sink: every event appended as one JSON line
    # ("" disables). The ring is the query surface; the file survives the
    # process.
    jsonl_path: str = ""
    # Minimum stdlib-logging level bridged into the event ring
    # (DEBUG/INFO/WARNING/ERROR). Explicit emissions ignore this.
    bridge_level: str = "INFO"
    # Black-box directory for per-slot stderr capture files and postmortem
    # snapshots; "" derives a per-deployment default under the system temp
    # dir (stable across respawns — the supervisor process resolves it
    # once).
    dir: str = ""
    # Per-worker postmortem-snapshot cadence (s): last-N events, flight-
    # recorder summaries, and key counters checkpointed to the slot's
    # snapshot file (one snapshot is also written at startup). 0 disables.
    snapshot_interval_s: float = 2.0
    # Bytes of a dead process's stderr capture folded into its postmortem
    # record.
    stderr_tail_bytes: int = 4096
    # Admin audit records retained (FIFO beyond it).
    audit_capacity: int = 256
    # Postmortem records retained (FIFO beyond it).
    postmortem_capacity: int = 64
    # Derived per worker slot by the supervisor (stderr capture file /
    # snapshot file under `dir`); set explicitly only in tests.
    stderr_path: str = ""
    snapshot_path: str = ""

    def __post_init__(self) -> None:
        if self.capacity < 1 or self.audit_capacity < 1 \
                or self.postmortem_capacity < 1:
            raise ValueError(
                "events.capacity/audit_capacity/postmortem_capacity "
                "must be >= 1")
        if self.snapshot_interval_s < 0 or self.stderr_tail_bytes < 0:
            raise ValueError(
                "events.snapshot_interval_s/stderr_tail_bytes must be >= 0")
        if self.bridge_level.upper() not in ("DEBUG", "INFO", "WARNING",
                                             "ERROR"):
            raise ValueError(
                f"events.bridge_level must be DEBUG/INFO/WARNING/ERROR, "
                f"got {self.bridge_level!r}")


@dataclass
class TelemetryConfig:
    """Fleet telemetry plane (``[telemetry]`` TOML; tpuserve.telemetry,
    docs/OBSERVABILITY.md "The telemetry plane").

    On by default: a background sampler thread snapshots every counter/
    gauge/histogram into bounded per-metric rings at ``sample_interval_s``,
    from which ``GET /stats/history`` serves time-resolved counter rates
    and histogram-delta quantiles, the SLO engine evaluates multi-window
    burn rates (``[model.slo]`` blocks → ``/alerts``), and the sampler
    derives ``device_utilization{model=,replica=}`` from the device-seconds
    ledger. The router tier additionally scrapes every live worker and peer
    router into ``GET /metrics/fleet`` / ``/stats/fleet``."""

    enabled: bool = True
    # Sampler cadence (s): every tick snapshots the whole metric registry
    # into the rings and re-evaluates burn rates + utilization.
    sample_interval_s: float = 1.0
    # History retained per metric (s); ring capacity = history_s /
    # sample_interval_s, hard-capped at 4096 samples per metric.
    history_s: float = 600.0
    # Burn-rate evaluation windows (s), ascending (Google-SRE multi-window
    # style): an alert FIRES when the burn rate exceeds the model's
    # `burn_alert` threshold over BOTH the first two windows, is PENDING on
    # the first alone, and all windows are exported as
    # slo_burn_rate{model=,window=} gauges.
    burn_windows_s: list[float] = field(
        default_factory=lambda: [60.0, 300.0, 1800.0])
    # Sliding window (s) for deriving device_utilization{model=,replica=}
    # from the device_seconds_total counters.
    utilization_window_s: float = 10.0
    # Per-source budget for the router's fleet scrape (/metrics/fleet):
    # a worker/peer slower than this is stale-marked, never a 5xx.
    fleet_timeout_ms: float = 2000.0
    # Upper bound on POST /debug/profile?duration_ms= (one capture at a
    # time; the jax.profiler device trace merges with the span ring).
    profile_max_ms: float = 10000.0

    def __post_init__(self) -> None:
        if self.sample_interval_s <= 0 or self.history_s <= 0:
            raise ValueError(
                "telemetry.sample_interval_s/history_s must be > 0")
        if len(self.burn_windows_s) < 2 \
                or any(w <= 0 for w in self.burn_windows_s) \
                or sorted(self.burn_windows_s) != list(self.burn_windows_s):
            raise ValueError(
                "telemetry.burn_windows_s must be >= 2 ascending positive "
                f"windows, got {self.burn_windows_s}")
        if self.utilization_window_s <= 0 or self.fleet_timeout_ms <= 0 \
                or self.profile_max_ms <= 0:
            raise ValueError(
                "telemetry.utilization_window_s/fleet_timeout_ms/"
                "profile_max_ms must be > 0")


@dataclass
class SloConfig:
    """Per-model service-level objective (``[model.slo]`` TOML;
    tpuserve.telemetry.slo, docs/OBSERVABILITY.md "The telemetry plane").

    A request is "good" when it answers within ``latency_ms``;
    ``availability`` is the target good fraction, so the error budget is
    ``1 - availability`` and the burn rate over a window is
    (bad fraction) / budget — burn 1.0 spends the budget exactly at the
    sustainable pace, burn N spends it N× too fast. Evaluated per
    ``[telemetry] burn_windows_s`` window by the sampler; `latency_ms = 0`
    (the default) disables the SLO for the model."""

    # Latency objective (ms): requests at or under it are "good".
    # 0 disables SLO evaluation for this model.
    latency_ms: float = 0.0
    # Target good fraction; error budget = 1 - availability.
    availability: float = 0.999
    # Burn-rate threshold: FIRING when exceeded over both the short and
    # mid [telemetry] windows, PENDING on the short alone.
    burn_alert: float = 10.0
    # First-token (first-unit) objective for STREAMED generation (ISSUE
    # 17): a stream is "good" when its first emitted unit landed within
    # this many ms (fed by gen_first_unit_ms{model=}). Evaluated by the
    # same burn-rate machinery as latency_ms, surfaced on /alerts as
    # "<model>:first_unit" and in the autopilot's shed-on-burn seam.
    # 0 (default) disables the first-token SLO.
    first_unit_ms: float = 0.0

    def __post_init__(self) -> None:
        if self.latency_ms < 0:
            raise ValueError(
                f"slo.latency_ms must be >= 0, got {self.latency_ms}")
        if self.first_unit_ms < 0:
            raise ValueError(
                f"slo.first_unit_ms must be >= 0, got {self.first_unit_ms}")
        if not 0.0 < self.availability < 1.0:
            raise ValueError(
                f"slo.availability must be in (0, 1), got {self.availability}")
        if self.burn_alert <= 0:
            raise ValueError(
                f"slo.burn_alert must be > 0, got {self.burn_alert}")


@dataclass
class ParallelConfig:
    """Multi-chip serving plan (``[parallel]`` TOML; docs/PERFORMANCE.md
    "Serving on the mesh").

    Server-wide selection of how the serving path uses the device mesh.
    Per-model ``parallelism`` remains the fine-grained knob; this block
    exists so one line flips a whole deployment between the two multi-chip
    modes (AlpaServe, PAPERS.md P5: placement is a throughput/latency
    lever, not a memory trick):

    - ``mode = "replica"`` — N independent single-device runtime replicas,
      params replicated per chip, the batcher keeping every replica's
      depth-k staging slots full via least-loaded dispatch.
    - ``mode = "sharded"`` — ONE executable over the whole mesh, the batch
      sharded on the data axis (``parallel.mesh.batch_sharding``).
    - ``mode = "single"`` — first device only (dev mode).
    - ``mode = ""`` (default) — every model keeps its own ``parallelism``.

    A non-empty mode overrides EVERY configured model (including
    ``pipeline`` models — the override is deliberate and total, so a
    drill can flatten a fleet to one layout with one override flag)."""

    # "" = respect per-model `parallelism`; "replica" / "sharded" /
    # "single" override every model's mode at build time.
    mode: str = ""
    # Devices the serving path uses; 0 = every visible device. Lets one
    # host carve chips between serving and background work, and makes
    # CPU-CI runs (8 forced host devices) byte-for-byte reproducible.
    n_chips: int = 0
    # Sharded mode: data-axis size; 0 derives it from the device count and
    # the model's tp/sp axes. Setting `data` with n_chips = 0 sizes the
    # mesh to exactly data * tp * sp devices.
    data: int = 0

    def __post_init__(self) -> None:
        if self.mode not in ("", "replica", "sharded", "single"):
            raise ValueError(
                f"parallel.mode must be one of '', 'replica', 'sharded', "
                f"'single'; got {self.mode!r} (pipeline is per-model only)")
        if self.n_chips < 0 or self.data < 0:
            raise ValueError("parallel.n_chips/data must be >= 0")


@dataclass
class SchedulerConfig:
    """Fleet-level SLO scheduler (``[scheduler]`` TOML; tpuserve.scheduler,
    docs/ROBUSTNESS.md "Fleet isolation & SLO admission").

    Off by default — every model keeps its independent batcher with no
    cross-model arbitration. When enabled, a central scheduler sits between
    admission and the per-model batchers/engines (Clockwork, PAPERS.md P3):
    requests whose stamped deadline provably cannot be met are shed at
    admission with a fast 504 (``deadline_unmeetable``) instead of dying in
    the queue; ``X-Priority: interactive|batch`` requests arbitrate device
    time through a per-model device-seconds ledger (low-priority work sheds
    first under overload, and no model's interactive traffic is starved
    below ``min_share``); and models declared ``cold_start`` boot without
    device params, warming through the lifecycle stage→publish path on
    first request (or ``:warm``) and demoting back to cold after
    ``idle_demote_s`` so more models than fit in HBM serve honestly."""

    enabled: bool = False
    # Sliding window (s) for the per-model device-seconds ledger that
    # backs the priority-share arbitration.
    window_s: float = 10.0
    # The fleet counts as saturated (low-priority sheds, share floors
    # enforce) when the aggregate predicted queue-clear time across warm
    # models exceeds this many seconds.
    overload_clear_s: float = 1.0
    # Interactive floor: under saturation, a model with queued work whose
    # windowed device-time share is below this is "starved", and models
    # consuming more than their allowance (1 - min_share * others) shed
    # until the starved model catches up. 0 disables the floor.
    min_share: float = 0.05
    # Grace (ms) a request gets beyond the predicted completion before the
    # deadline_unmeetable shed fires — raise it to shed less eagerly when
    # duration EWMAs are noisy.
    headroom_ms: float = 0.0
    # > 0: a warm cold_start model idle this long demotes back to cold,
    # freeing its device params (HBM) until the next request re-warms it.
    idle_demote_s: float = 0.0
    # Retry-After hint (s) on warming-window 503s before the first warm-up
    # has been measured (after that, the measured warm duration is used).
    warm_retry_after_s: float = 5.0
    # Idle-demotion sweep cadence (s).
    sweep_interval_s: float = 0.5
    # Chip budget for warm models (ISSUE 20): the scheduler places models
    # by their parallelism DEGREE (chips a warm runtime occupies — every
    # replica mesh, or tp x sp x data for a sharded one). Warming a cold
    # model whose degree would push the warm fleet past this budget first
    # demotes idle cold_start models to make room, and sheds 503
    # ``chip_budget`` when room cannot be made. 0 = unlimited (the
    # pre-budget behavior).
    chip_budget: int = 0

    def __post_init__(self) -> None:
        if self.window_s <= 0 or self.sweep_interval_s <= 0:
            raise ValueError(
                "scheduler.window_s/sweep_interval_s must be > 0")
        if self.chip_budget < 0:
            raise ValueError(
                f"scheduler.chip_budget must be >= 0, got {self.chip_budget}")
        if not 0.0 <= self.min_share < 0.5:
            raise ValueError(
                f"scheduler.min_share must be in [0, 0.5), got {self.min_share}")
        if self.overload_clear_s < 0 or self.headroom_ms < 0 \
                or self.idle_demote_s < 0 or self.warm_retry_after_s < 0:
            raise ValueError(
                "scheduler.overload_clear_s/headroom_ms/idle_demote_s/"
                "warm_retry_after_s must be >= 0")


@dataclass
class TenantConfig:
    """One tenant (``[[tenants.tenant]]`` TOML; tpuserve.scheduler.tenants).

    A tenant is an API key plus its containment envelope: a fairness
    weight, a windowed device-seconds quota, and a request-rate limit.
    Overage is rejected at admission with 429 + Retry-After — one hostile
    tenant's flood must cost itself capacity, never its neighbors'."""

    name: str = ""
    # The key clients present as ``X-Api-Key``. Must be unique and
    # non-empty.
    api_key: str = ""
    # Fairness weight: the tenant's relative share of device time under
    # saturation, and its share of the result-cache capacity partition.
    weight: float = 1.0
    # Device-seconds the tenant may consume per [tenants] window_s window;
    # 0 = unlimited. Enforced from the windowed ledger at admission
    # (tenant_quota_exceeded 429s with a drain-based Retry-After).
    quota_device_s: float = 0.0
    # Request-rate limit (token bucket, requests/s); 0 = unlimited.
    rate_per_s: float = 0.0
    # Token-bucket burst; 0 derives max(1, 2 * rate_per_s).
    burst: float = 0.0

    def __post_init__(self) -> None:
        if not self.name:
            raise ValueError("tenants.tenant.name must be non-empty")
        if not self.api_key:
            raise ValueError(
                f"tenants.tenant {self.name!r}: api_key must be non-empty")
        if self.weight <= 0:
            raise ValueError(
                f"tenants.tenant {self.name!r}: weight must be > 0, "
                f"got {self.weight}")
        if self.quota_device_s < 0 or self.rate_per_s < 0 or self.burst < 0:
            raise ValueError(
                f"tenants.tenant {self.name!r}: quota_device_s/rate_per_s/"
                "burst must be >= 0")


@dataclass
class TenantsConfig:
    """Multi-tenant front door (``[tenants]`` TOML;
    tpuserve.scheduler.tenants, docs/OPERATIONS.md).

    Off by default. When enabled, every predict request must present a
    configured ``X-Api-Key`` (401 otherwise, unless ``allow_anonymous``),
    and admission enforces per-tenant rate, windowed device-seconds quota,
    and — under fleet saturation — weighted fair share, all from one
    sliding-window weighted device-seconds ledger (the PR 10 per-model
    ledger grown one dimension). The result cache partitions its capacity
    by tenant weight so one tenant's churn cannot evict another's hits,
    and each tenant gets its own SLO burn gauges over
    ``tenant_latency_ms{tenant=}``."""

    enabled: bool = False
    # Sliding window (s) for the per-tenant device-seconds ledger.
    window_s: float = 60.0
    # Admit requests with no/unknown API key as the tenant named here
    # ("" = reject them with 401). The anonymous tenant gets weight 1 and
    # no quota/rate unless a [[tenants.tenant]] entry names it explicitly.
    allow_anonymous: str = ""
    # Multiplier of slack over a tenant's weighted fair share before
    # share-based shedding fires under saturation (tenant_share_exceeded);
    # 0 disables fair-share shedding (rate + quota still enforce).
    share_slack: float = 1.25
    # Per-tenant SLO over tenant_latency_ms{tenant=}: latency objective
    # (ms; 0 disables per-tenant burn evaluation), availability target,
    # and burn-alert threshold — same semantics as [model.slo].
    slo_latency_ms: float = 0.0
    slo_availability: float = 0.999
    slo_burn_alert: float = 10.0
    tenants: list[TenantConfig] = field(default_factory=list)

    def __post_init__(self) -> None:
        if self.window_s <= 0:
            raise ValueError(
                f"tenants.window_s must be > 0, got {self.window_s}")
        if self.share_slack < 0:
            raise ValueError(
                f"tenants.share_slack must be >= 0, got {self.share_slack}")
        if self.slo_latency_ms < 0:
            raise ValueError(
                f"tenants.slo_latency_ms must be >= 0, got {self.slo_latency_ms}")
        if not 0.0 < self.slo_availability < 1.0:
            raise ValueError(
                f"tenants.slo_availability must be in (0, 1), "
                f"got {self.slo_availability}")
        if self.slo_burn_alert <= 0:
            raise ValueError(
                f"tenants.slo_burn_alert must be > 0, got {self.slo_burn_alert}")
        names = [t.name for t in self.tenants]
        if len(set(names)) != len(names):
            raise ValueError(f"tenants.tenant names must be unique: {names}")
        keys = [t.api_key for t in self.tenants]
        if len(set(keys)) != len(keys):
            raise ValueError("tenants.tenant api_keys must be unique")


@dataclass
class AutopilotConfig:
    """Self-healing fleet controller (``[autopilot]`` TOML;
    tpuserve.scheduler.autopilot, docs/OPERATIONS.md "Self-operating
    fleet").

    Off by default. When enabled on the primary router, a background
    reconcile loop reads SLO burn state, fleet queue pressure, and
    predicted clear time every ``interval_s`` and acts through the same
    audited verbs an operator would use: scale worker slots per host
    domain up/down, engage/clear shed-on-burn per model, and (with
    ``paging``) warm/demote models under a cross-model budget. Every
    decision is damped by hysteresis (``hysteresis_ticks`` consecutive
    ticks over threshold), a per-(action, target) cooldown, and a bounded
    action budget per window; every action opens a follow-up watch and is
    rolled back when the objective got WORSE. Every decision — rollbacks
    included — lands in the audit trail with its triggering signal
    values."""

    enabled: bool = False
    # Reconcile tick cadence (s).
    interval_s: float = 0.5
    # Consecutive ticks a trigger condition must hold before acting.
    hysteresis_ticks: int = 3
    # Per-(action kind, target) cooldown (s): the same knob is not touched
    # twice within it (rollbacks are exempt — undo must never wait).
    cooldown_s: float = 10.0
    # Action budget: at most this many non-rollback actions per window_s.
    max_actions_per_window: int = 8
    window_s: float = 60.0
    # Follow-up watch: this long after an action the objective is
    # re-measured; if it got worse by more than rollback_tolerance the
    # action is inverted (audited as outcome "rollback"). 0 disables.
    follow_up_s: float = 15.0
    rollback_tolerance: float = 0.5
    # Queue-pressure thresholds (mean in-flight relays per active healthy
    # worker slot): above high -> scale a domain up; below low with no
    # model burning -> scale down. high must exceed low.
    pressure_high: float = 2.0
    pressure_low: float = 0.25
    # Predicted queue-clear time (s) that also triggers scale-up when the
    # signal is available; 0 disables the clear-time trigger.
    clear_high_s: float = 0.0
    # Never scale a domain below this many active slots.
    min_slots: int = 1
    # Allow shed-on-burn actions: a model FIRING its burn alert gets its
    # batch-class traffic shed at the front door until the alert clears.
    burn_shed: bool = True
    # Allow scale actions against host domains.
    scale: bool = True
    # Allow warm/demote paging actions (fan out :warm / :demote to the
    # workers). Off by default: paging needs [scheduler] cold_start models.
    paging: bool = False
    # Cross-model device-memory budget for paging: max concurrently warm
    # models; 0 = unlimited (demote only on idle sweep).
    max_warm: int = 0
    # Decision records retained for GET /debug/autopilot.
    history: int = 256

    def __post_init__(self) -> None:
        if self.interval_s <= 0 or self.window_s <= 0:
            raise ValueError(
                "autopilot.interval_s/window_s must be > 0")
        if self.hysteresis_ticks < 1 or self.max_actions_per_window < 1 \
                or self.min_slots < 1 or self.history < 1:
            raise ValueError(
                "autopilot.hysteresis_ticks/max_actions_per_window/"
                "min_slots/history must be >= 1")
        if self.cooldown_s < 0 or self.follow_up_s < 0 \
                or self.rollback_tolerance < 0 or self.clear_high_s < 0 \
                or self.max_warm < 0:
            raise ValueError(
                "autopilot.cooldown_s/follow_up_s/rollback_tolerance/"
                "clear_high_s/max_warm must be >= 0")
        if not 0.0 <= self.pressure_low < self.pressure_high:
            raise ValueError(
                f"autopilot.pressure_low must be in [0, pressure_high), got "
                f"low={self.pressure_low} high={self.pressure_high}")


@dataclass
class RouterConfig:
    """Router/worker process split (``[router]`` TOML; tpuserve.workerproc,
    docs/ROBUSTNESS.md "Process failure domains").

    Off by default — the single-process server is unchanged. When enabled,
    ``tpuserve serve`` starts a **router** process owning HTTP/JSON, the
    result cache + single-flight coalescing, admission/deadline stamping,
    and per-model circuit breakers, plus ``workers`` isolated worker
    processes each owning batching + the TPU runtime (Clipper's layered
    architecture, PAPERS.md P1). A supervisor health-checks workers, reaps
    dead ones, and respawns them with exponential backoff; the router
    re-dispatches idempotent work to a surviving worker on transport
    failure (never past the request's absolute deadline) and hedges slow
    attempts — one misbehaving or crashed worker costs capacity, never
    availability."""

    enabled: bool = False
    # Worker processes to supervise (each builds every configured model).
    # With hosts > 0 this is the worker count PER HOST.
    workers: int = 2
    # Host failure domains (ISSUE 13, docs/ROBUSTNESS.md "Host failure
    # domains"). 0 = no host layer: workers are direct children of the
    # router (the PR-8 flat supervisor). N >= 1 groups the workers into N
    # named hosts — locally each host is a supervisor subprocess in its own
    # process group owning `workers` worker processes, so one SIGKILL of
    # the group takes out the entire failure domain exactly like a machine
    # dying. The router routes around a dead host (host breaker + health
    # probes), respawns it with the same exponential backoff as workers,
    # and never places a hedge on its primary's host.
    hosts: int = 0
    # Router processes sharing the serving port via SO_REUSEPORT. Router 0
    # (the primary) owns the host/worker supervisor and supervises the
    # N - 1 peer routers; every router shards the result cache by
    # consistent hash, forwarding hits and single-flight leadership to the
    # key's owning router over loopback HTTP and degrading to local-only
    # (counted, never erroring) when the owner is unreachable.
    routers: int = 1
    # Consecutive relay transport failures (connection refused/reset)
    # against one host's workers before the whole host is routed around
    # without waiting for health probes; 0 disables the host breaker.
    host_breaker_threshold: int = 3
    # How long a tripped host breaker sheds picks before half-opening
    # (the next pick is the recovery probe; success closes it).
    host_breaker_cooldown_s: float = 1.0
    # Peer routers poll the primary for topology (worker addresses, ring
    # membership, cache generations) this often.
    peer_sync_interval_s: float = 0.5
    # Primary's peer-listener bind port (the loopback control plane the
    # peer routers sync from and forward cache hops to); 0 = ephemeral.
    peer_port: int = 0
    # Transport-failure re-dispatches per request (connection refused/reset,
    # a worker dying mid-request). Definitive worker answers (any HTTP
    # status from a live worker except 503-not-admitted) are NEVER retried:
    # a 500 means the work already executed and failed — re-running it
    # would double-execute. Retries always honor the admission deadline.
    retry_max: int = 2
    # > 0: an attempt silent for this long gets a duplicate dispatched to a
    # different worker; first definitive answer wins, the loser is
    # cancelled (tail-latency hedging; covers a wedged-but-alive worker).
    hedge_ms: float = 0.0
    # TCP connect budget per attempt.
    connect_timeout_ms: float = 500.0
    # Supervisor HTTP health-probe cadence and per-probe budget.
    health_interval_s: float = 0.5
    health_timeout_ms: float = 1000.0
    # Consecutive failed probes before a live process is routed around.
    unhealthy_after: int = 3
    # Exponential respawn backoff for dead workers:
    # min(max_s, initial_s * multiplier^consecutive_failures).
    respawn_initial_s: float = 0.5
    respawn_max_s: float = 30.0
    respawn_multiplier: float = 2.0
    # Worker boot budget (spawn -> ready handshake), seconds. Generous:
    # a cold worker AOT-compiles every bucket.
    spawn_timeout_s: float = 900.0
    # Initial ACTIVE worker slots per host domain (autopilot scaling seam):
    # slots beyond this boot scaled-down and cost nothing until the
    # controller (or an operator via /admin/hosts/{hid}:scale) activates
    # them. 0 = all `workers` slots active (the pre-autopilot behavior).
    active_workers: int = 0
    # Streaming relay (ISSUE 17): per-stream idle timeout — a STARTED
    # stream whose worker goes silent (no chunk) this long is terminated
    # with the well-formed error event (reason "idle_timeout"), distinct
    # from the absolute request deadline. 0 disables the idle timeout
    # (only the deadline bounds the stream).
    stream_idle_timeout_ms: float = 30000.0
    # Router-side graceful-drain stream budget: on SIGTERM, in-flight
    # streams get this long to finish before the router terminates them
    # with the error event (reason "drain"); 0 = only drain_timeout_s.
    stream_drain_s: float = 5.0

    def __post_init__(self) -> None:
        if self.workers < 1:
            raise ValueError(f"router.workers must be >= 1, got {self.workers}")
        if self.active_workers < 0 or self.active_workers > self.workers:
            raise ValueError(
                f"router.active_workers must be in [0, workers], got "
                f"{self.active_workers}")
        if self.retry_max < 0 or self.hedge_ms < 0:
            raise ValueError("router.retry_max/hedge_ms must be >= 0")
        if self.respawn_initial_s < 0 or self.respawn_max_s <= 0 \
                or self.respawn_multiplier < 1.0:
            raise ValueError(
                "router.respawn_initial_s must be >= 0, respawn_max_s > 0, "
                "respawn_multiplier >= 1")
        if self.health_interval_s <= 0 or self.unhealthy_after < 1:
            raise ValueError(
                "router.health_interval_s must be > 0 and unhealthy_after >= 1")
        if self.hosts < 0:
            raise ValueError(f"router.hosts must be >= 0, got {self.hosts}")
        if self.routers < 1:
            raise ValueError(
                f"router.routers must be >= 1, got {self.routers}")
        if self.host_breaker_threshold < 0 \
                or self.host_breaker_cooldown_s <= 0:
            raise ValueError(
                "router.host_breaker_threshold must be >= 0 and "
                "host_breaker_cooldown_s > 0")
        if self.peer_sync_interval_s <= 0 or self.peer_port < 0:
            raise ValueError(
                "router.peer_sync_interval_s must be > 0 and "
                "peer_port >= 0")
        if self.stream_idle_timeout_ms < 0 or self.stream_drain_s < 0:
            raise ValueError(
                "router.stream_idle_timeout_ms/stream_drain_s must be >= 0")


@dataclass
class WorkerConfig:
    """Worker-process side of the router split (``[worker]`` TOML;
    tpuserve.workerproc.worker). Workers are full single-process servers
    bound to loopback; the router relays to them."""

    # Bind address for worker HTTP listeners (loopback: workers are an
    # internal tier, never exposed).
    host: str = "127.0.0.1"
    # Worker i listens on port_base + i; 0 = ephemeral ports (recommended —
    # the supervisor learns them from the ready handshake).
    port_base: int = 0
    # Per-worker SIGTERM drain budget; 0 = inherit the server's
    # drain_timeout_s.
    drain_timeout_s: float = 0.0

    def __post_init__(self) -> None:
        if self.port_base < 0 or self.drain_timeout_s < 0:
            raise ValueError(
                "worker.port_base/drain_timeout_s must be >= 0")


@dataclass
class ModelConfig:
    """Per-model serving configuration."""

    name: str
    # Which implementation in tpuserve.models to build.
    family: str = "resnet50"
    # Optional path to weights: a TF SavedModel dir, a frozen GraphDef .pb,
    # or an orbax checkpoint dir. None => seeded random init (no-network dev).
    weights: str | None = None
    # Optional path to a class-label file (one name per line, in class-index
    # order, e.g. ImageNet synset names). classify/detect responses then
    # carry a human-readable "label" next to each class index.
    labels: str | None = None
    # Static batch-size buckets, ascending. Each (bucket, input-shape) pair is
    # AOT-compiled to its own XLA executable at startup.
    batch_buckets: list[int] = field(default_factory=lambda: [1, 4, 8, 16, 32])
    # Sequence-length buckets for text models (BERT, SD text encoder).
    seq_buckets: list[int] = field(default_factory=lambda: [64, 128, 256, 512])
    # Batcher flush deadline: a request waits at most this long for the batch
    # to fill before a partial (padded) batch is dispatched.
    deadline_ms: float = 5.0
    # Max requests queued before the server sheds load with 429s.
    max_queue: int = 4096
    # Per-request end-to-end deadline -> 504 when exceeded.
    request_timeout_ms: float = 2000.0
    # Compute dtype for params/activations on device.
    dtype: str = "bfloat16"
    # Quantization: "int8" stores large weights as int8 + per-channel scales
    # and dequantizes inside the compiled forward (halves HBM weight
    # streaming and upload bytes); "int8c" additionally COMPUTES the
    # model's opted-in matmul sites int8 x int8 -> int32 on the MXU with
    # dynamic per-token activation scales (families that name native sites
    # only — see tpuserve.quantize). None = full compute-dtype weights.
    quantize: str | None = None
    # Float leaves smaller than this stay unquantized (biases, norms).
    quantize_min_size: int = 4096
    # Image input edge (H == W) for vision models.
    image_size: int = 224
    # Host->device wire shape edge for images: host decodes to (wire, wire, 3)
    # uint8; the device resizes to image_size. Smaller wire = fewer PCIe (or
    # dev-tunnel) bytes; 256 leaves headroom for crop-style augmentation.
    wire_size: int = 256
    # Wire encoding for images crossing host->device:
    # - "rgb8":   (wire, wire, 3) uint8 — 3 B/px.
    # - "yuv420": raw JPEG planes (full-res Y + 2x2-subsampled Cb/Cr) —
    #   1.5 B/px, half the transfer bytes with no extra fidelity loss (a JPEG
    #   stores exactly these planes); color conversion happens on device
    #   (preproc.device_prepare_images_yuv420). Requires wire_size % 16 == 0.
    wire_format: str = "rgb8"
    # Parallelism mode: "sharded" (one executable, batch sharded over the
    # mesh), "replica" (one executable per device, independent queues),
    # "single" (first device only), or "pipeline" (layer stack split into
    # `pp` GPipe stages over a ("stage",) mesh — families whose depth is a
    # homogeneous block stack, e.g. BERT; for models too deep/large for one
    # device's memory). SURVEY.md §2.1.
    parallelism: str = "sharded"
    # Tensor-parallel axis size carved out of the mesh (1 = TP off).
    tp: int = 1
    # Pipeline stage count for parallelism = "pipeline" (0 = all devices).
    pp: int = 0
    # Sequence-parallel axis size (1 = SP off). With BERT's
    # options.attention = "ring", activations shard their seq dim over this
    # axis and attention rotates K/V around the ICI ring — long-context
    # serving beyond one chip's attention memory.
    sp: int = 1
    # Model-specific knobs (e.g. SD: num_steps, guidance_scale; detect: score
    # threshold). Kept open-ended on purpose.
    options: dict[str, Any] = field(default_factory=dict)
    # Number of classes / detection size etc. where the family needs it.
    num_classes: int = 1000
    # Device-section pipeline depth per replica (>=1): how many of this
    # model's batches occupy [h2d..fetch] staging slots at once. The
    # server-wide [pipeline] block's `depth` overrides it when nonzero; in
    # recycle mode it bounds batches between assembly and shm enqueue.
    max_inflight: int = 2
    # Execution mode (SURVEY.md C5; tpuserve/deferred.py):
    # - "direct":  per-batch dispatch + readback in-process (real TPU / CPU).
    # - "recycle": deferred-readback worker pool — results are read back in
    #   bulk once per epoch by single-use worker processes. For links where
    #   per-batch device->host reads destroy throughput (see BASELINE.md
    #   "Link physics").
    session_mode: str = "direct"
    # recycle mode: worker processes to pre-warm at startup.
    relay_workers: int = 2
    # recycle mode: epoch budget — a worker retires after this many image
    # rows, or relay_epoch_ms after its first batch, whichever first. Bounds
    # result latency.
    relay_epoch_images: int = 4096
    relay_epoch_ms: float = 2000.0
    # recycle mode: per-worker shared-memory batch slots (in-flight batches).
    relay_slots: int = 4
    # Default priority class for requests that carry no X-Priority header
    # ("interactive" or "batch"). Only consulted when the fleet scheduler
    # ([scheduler] enabled) arbitrates: under overload, batch-class work
    # sheds first (docs/ROBUSTNESS.md "Fleet isolation & SLO admission").
    priority: str = "interactive"
    # Fleet scheduler weight paging: True boots this model COLD — compiled
    # variants and device params are not built/resident until the first
    # request (or POST .../{name}:warm) stages them through the lifecycle
    # path, and [scheduler] idle_demote_s can demote them back, freeing
    # HBM. Requires [scheduler] enabled and session_mode = "direct".
    cold_start: bool = False
    # Result-cache eligibility: False keeps this model out of every result
    # cache (server-side ModelCache AND the router tier's wire-level cache).
    # Generative families keep every sampling parameter (seed, temperature,
    # max_new_tokens, steps) inside the decoded item, so two requests
    # differing only in seed can never alias a cache key — set this False
    # only for models that are genuinely nondeterministic in their input
    # (e.g. unseeded sampling).
    cacheable: bool = True
    # Streaming slow-consumer policy (ISSUE 17): what the engine does when
    # a stream's bounded emission queue is full because the client reads
    # slowly. "drop" discards DROPPABLE units (progress/preview events —
    # counted in gen_stream_dropped_total; tokens and terminals are never
    # dropped) and blocks only on non-droppable ones; "block" always
    # blocks the step loop (exact delivery, at the cost of backpressuring
    # the whole slot block).
    stream_policy: str = "drop"
    # Service-level objective ([model.slo] sub-table): latency objective +
    # availability target the telemetry plane's burn-rate engine evaluates
    # (docs/OBSERVABILITY.md "The telemetry plane"). Defaults to disabled
    # (latency_ms = 0).
    slo: SloConfig = field(default_factory=SloConfig)
    # -- robustness (docs/ROBUSTNESS.md) ------------------------------------
    # One-shot batch retry: a failed dispatch re-assembles and re-runs the
    # batch once before failing its futures (absorbs transient device/worker
    # faults without the client seeing a 500).
    batch_retry: bool = True
    # When the whole-batch retry also fails, recursively bisect so a single
    # poison item fails only its own future while the other lanes succeed.
    retry_split: bool = True
    # Circuit breaker: consecutive failed dispatches before the model trips
    # to fast 503 + Retry-After (0 disables). Half-opens via the canary path:
    # canary inferences keep riding the batcher while open, and the first
    # success closes the breaker.
    breaker_threshold: int = 5
    # Retry-After hint (s) on breaker 503s when no periodic canary is
    # configured; with canary_interval_s > 0 the hint is the canary interval.
    breaker_retry_after_s: float = 5.0

    def __post_init__(self) -> None:
        if self.tp < 1 or self.sp < 1:
            raise ValueError(
                f"tp and sp must be >= 1, got tp={self.tp} sp={self.sp}")
        if self.priority not in ("interactive", "batch"):
            raise ValueError(
                f"priority must be 'interactive' or 'batch', "
                f"got {self.priority!r}")
        if self.stream_policy not in ("drop", "block"):
            raise ValueError(
                f"stream_policy must be 'drop' or 'block', "
                f"got {self.stream_policy!r}")
        if self.cold_start and self.session_mode != "direct":
            raise ValueError(
                "cold_start requires session_mode = 'direct' (recycle-mode "
                "workers own their params out of process)")


@dataclass
class DistributedConfig:
    """Multi-host (multi-process) JAX runtime initialization (SURVEY.md §5
    "Distributed communication backend").

    On TPU pods each host runs one tpuserve process; setting
    ``coordinator_address`` to process 0's ``host:port`` makes startup call
    ``jax.distributed.initialize`` BEFORE any device use, after which
    ``jax.devices()`` is the global device set and the serving mesh spans
    hosts — data-parallel over DCN, tensor/sequence axes within each host's
    ICI domain (see ``tpuserve.parallel.mesh``). Leave empty for single-host.
    """

    # "host:port" of the process-0 coordinator; "" disables distributed init.
    coordinator_address: str = ""
    # Total process (host) count; -1 = take from the TPU/cluster environment.
    num_processes: int = -1
    # This process's rank; -1 = take from the TPU/cluster environment.
    process_id: int = -1


@dataclass
class ServerConfig:
    """Top-level server configuration."""

    host: str = "0.0.0.0"
    port: int = 8000
    # Multi-host runtime init; defaults to single-host (disabled).
    distributed: DistributedConfig = field(default_factory=DistributedConfig)
    # Multi-chip serving plan: replica-per-chip vs sharded-batch over the
    # local mesh (docs/PERFORMANCE.md "Serving on the mesh").
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    # Iteration-level generation engine for generative families
    # (docs/PERFORMANCE.md "The generation engine"). Off by default: the
    # static-bucket batcher serves everything, including generative models
    # as locked batches.
    genserve: GenserveConfig = field(default_factory=GenserveConfig)
    # Fleet-level SLO scheduler: predictive admission, priority classes,
    # warm/cold weight paging (docs/ROBUSTNESS.md "Fleet isolation & SLO
    # admission"). Off by default.
    scheduler: SchedulerConfig = field(default_factory=SchedulerConfig)
    # Router/worker process split: multi-process failure domains with
    # supervision + hedged retry (docs/ROBUSTNESS.md). Off by default.
    router: RouterConfig = field(default_factory=RouterConfig)
    # Worker-process knobs for the router split (loopback bind, drain).
    worker: WorkerConfig = field(default_factory=WorkerConfig)
    models: list[ModelConfig] = field(default_factory=list)
    # Parallel ingest (docs/PERFORMANCE.md "The ingest fast path"): total
    # HTTP accept loops on the serving port. 1 = the classic single event
    # loop. N > 1 adds N-1 dedicated ingest event-loop THREADS, each with
    # its own SO_REUSEPORT listener on the same port, so the kernel spreads
    # connections and body read / frame parse / JSON encode stop
    # serializing on one loop — the loop that owns the batchers only runs
    # admission + dispatch (handlers hop to it via a loop-safe entry).
    # Per-loop balance is visible as ingest_requests_total{loop=}.
    ingest_loops: int = 1
    # Host-side decode threadpool size.
    decode_threads: int = 8
    # Decode request bodies inline on the event loop instead of hopping to
    # the threadpool. On a single-core host the executor hop only adds
    # latency; leave False when real CPU parallelism exists.
    decode_inline: bool = False
    # jax.profiler.start_server port; 0 disables.
    profiler_port: int = 0
    # Directory for the persistent XLA compilation cache ("" disables).
    compilation_cache_dir: str = ""
    # Validate-on-startup canary (tiny inference per model) on/off.
    startup_canary: bool = True
    # > 0: re-run the per-model canary every this many seconds so /healthz
    # reflects live serving health, not the startup snapshot. Canary
    # inferences ride the normal serving path and appear in /metrics like
    # any synthetic probe; a shed canary (queue full) keeps the last status.
    canary_interval_s: float = 0.0
    # Debug mode (SURVEY.md §5): raise on NaN/Inf produced by any jitted
    # computation (sets jax_debug_nans + jax_debug_infs). Expensive —
    # re-checks every output; dev only.
    debug_nans: bool = False
    # Run every compiled executable once at startup so first requests don't
    # pay PJRT program load (runtime.ModelRuntime.prewarm).
    prewarm_executables: bool = True
    # > 0: after prewarm, time each bucket's raw executable with this many
    # back-to-back dispatches (inputs resident, one dependent read) so the
    # /stats "roofline" block can split the serving compute phase into
    # device-time vs host-wait (docs/PERFORMANCE.md "Reading the roofline").
    # 0 disables the startup probe (the bench runs its own in a subprocess).
    roofline_probe_iters: int = 0
    # Observability: max request-trace events kept for /debug/trace.
    trace_capacity: int = 65536
    # Request-scoped distributed tracing: flight-recorder reservoir sizes
    # and metric exemplars (docs/OBSERVABILITY.md).
    trace: TraceConfig = field(default_factory=TraceConfig)
    # Fleet telemetry plane: time-series history sampler, SLO burn-rate
    # engine, device-utilization derivation, fleet scrape + deep profiling
    # (docs/OBSERVABILITY.md "The telemetry plane"). On by default.
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    # Structured event plane + crash-forensics black box + admin audit
    # trail (docs/OBSERVABILITY.md "The third pillar"). On by default.
    events: EventsConfig = field(default_factory=EventsConfig)
    # Multi-tenant front door: per-tenant API keys, weighted device-seconds
    # ledger, quota/rate/fair-share admission, partitioned result cache,
    # per-tenant SLO burn (docs/OPERATIONS.md). Off by default.
    tenants: TenantsConfig = field(default_factory=TenantsConfig)
    # Self-healing fleet controller: reconcile loop acting through audited
    # admin verbs with hysteresis/cooldown/budget/rollback
    # (docs/OPERATIONS.md "Self-operating fleet"). Off by default.
    autopilot: AutopilotConfig = field(default_factory=AutopilotConfig)
    # Emit one JSON object per log line (machine-ingestible) instead of the
    # human-readable default.
    log_json: bool = False
    # Pipelined host execution engine knobs (stage pools, depth, arenas).
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    # Content-addressed result cache + single-flight coalescing (off by
    # default: only correct for models deterministic in their input).
    cache: CacheConfig = field(default_factory=CacheConfig)
    # SLO-aware adaptive batching (AIMD target batch size per group).
    adaptive: AdaptiveConfig = field(default_factory=AdaptiveConfig)
    # Deterministic fault injection (chaos testing); disabled by default.
    faults: FaultsConfig = field(default_factory=FaultsConfig)
    # Versioned reload lifecycle (integrity checks, staged canary, rollback).
    lifecycle: LifecycleConfig = field(default_factory=LifecycleConfig)
    # Watchdog sweep interval: restart dead group-accumulation tasks and reap
    # dead deferred workers every this many seconds (0 disables).
    watchdog_interval_s: float = 1.0
    # Graceful-drain budget on SIGTERM: new requests 503 immediately while
    # every accepted request gets this long to finish before hard stop.
    drain_timeout_s: float = 30.0
    # Retry-After hint (seconds) on 429 shed and drain 503 responses.
    shed_retry_after_s: float = 1.0

    def __post_init__(self) -> None:
        if self.ingest_loops < 1:
            raise ValueError(
                f"ingest_loops must be >= 1, got {self.ingest_loops}")

    def model(self, name: str) -> ModelConfig:
        for m in self.models:
            if m.name == name:
                return m
        raise KeyError(f"no model named {name!r} configured")


def _build(cls: type, data: dict[str, Any]) -> Any:
    """Construct dataclass ``cls`` from a dict, erroring on unknown keys."""
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(data) - names
    if unknown:
        raise ValueError(f"unknown {cls.__name__} keys: {sorted(unknown)}")
    return cls(**data)


def load_config(path: str | None = None, overrides: list[str] | None = None) -> ServerConfig:
    """Load a ServerConfig from a TOML file plus ``key.path=value`` overrides.

    Overrides use dot paths, e.g. ``port=9000`` or
    ``model.resnet50.deadline_ms=2.5`` (the second path element selects the
    model by name). Values are parsed as TOML scalars/arrays.
    """
    raw: dict[str, Any] = {}
    if path:
        with open(path, "rb") as f:
            raw = tomllib.load(f)

    model_dicts = raw.pop("model", [])
    dist_dict = raw.pop("distributed", None)
    trace_dict = raw.pop("trace", None)
    telemetry_dict = raw.pop("telemetry", None)
    events_dict = raw.pop("events", None)
    parallel_dict = raw.pop("parallel", None)
    genserve_dict = raw.pop("genserve", None)
    scheduler_dict = raw.pop("scheduler", None)
    router_dict = raw.pop("router", None)
    worker_dict = raw.pop("worker", None)
    faults_dict = raw.pop("faults", None)
    tenants_dict = raw.pop("tenants", None)
    autopilot_dict = raw.pop("autopilot", None)
    lifecycle_dict = raw.pop("lifecycle", None)
    pipeline_dict = raw.pop("pipeline", None)
    cache_dict = raw.pop("cache", None)
    adaptive_dict = raw.pop("adaptive", None)
    cfg: ServerConfig = _build(ServerConfig, raw)
    models = []
    for m in model_dicts:
        # [model.slo] is a nested sub-table of its [[model]] entry.
        slo_dict = m.pop("slo", None)
        mc = _build(ModelConfig, m)
        if slo_dict is not None:
            mc.slo = _build(SloConfig, slo_dict)
        models.append(mc)
    cfg.models = models
    if dist_dict is not None:
        cfg.distributed = _build(DistributedConfig, dist_dict)
    if trace_dict is not None:
        cfg.trace = _build(TraceConfig, trace_dict)
    if telemetry_dict is not None:
        cfg.telemetry = _build(TelemetryConfig, telemetry_dict)
    if events_dict is not None:
        cfg.events = _build(EventsConfig, events_dict)
    if parallel_dict is not None:
        cfg.parallel = _build(ParallelConfig, parallel_dict)
    if genserve_dict is not None:
        cfg.genserve = _build(GenserveConfig, genserve_dict)
    if scheduler_dict is not None:
        cfg.scheduler = _build(SchedulerConfig, scheduler_dict)
    if router_dict is not None:
        cfg.router = _build(RouterConfig, router_dict)
    if worker_dict is not None:
        cfg.worker = _build(WorkerConfig, worker_dict)
    if lifecycle_dict is not None:
        cfg.lifecycle = _build(LifecycleConfig, lifecycle_dict)
    if pipeline_dict is not None:
        cfg.pipeline = _build(PipelineConfig, pipeline_dict)
    if cache_dict is not None:
        cfg.cache = _build(CacheConfig, cache_dict)
    if adaptive_dict is not None:
        cfg.adaptive = _build(AdaptiveConfig, adaptive_dict)
    if faults_dict is not None:
        rule_dicts = faults_dict.pop("rule", [])
        cfg.faults = _build(FaultsConfig, faults_dict)
        cfg.faults.rules = [_build(FaultRuleConfig, r) for r in rule_dicts]
    if tenants_dict is not None:
        # [[tenants.tenant]] entries are nested sub-tables of [tenants].
        tenant_dicts = tenants_dict.pop("tenant", [])
        cfg.tenants = _build(TenantsConfig, tenants_dict)
        cfg.tenants.tenants = [_build(TenantConfig, t) for t in tenant_dicts]
        cfg.tenants.__post_init__()  # re-check uniqueness with the list set
    if autopilot_dict is not None:
        cfg.autopilot = _build(AutopilotConfig, autopilot_dict)

    for ov in overrides or []:
        _apply_override(cfg, ov)
    return cfg


def _parse_toml_value(text: str) -> Any:
    try:
        return tomllib.loads(f"v = {text}")["v"]
    except tomllib.TOMLDecodeError:
        return text  # bare string


def _apply_override(cfg: ServerConfig, override: str) -> None:
    if "=" not in override:
        raise ValueError(f"override must look like key.path=value, got {override!r}")
    key, _, text = override.partition("=")
    value = _parse_toml_value(text.strip())
    parts = key.strip().split(".")

    target: Any = cfg
    if parts[0] == "model":
        if len(parts) < 3:
            raise ValueError(f"model override needs model.<name>.<field>: {override!r}")
        target = cfg.model(parts[1])
        parts = parts[2:]
    for p in parts[:-1]:
        target = target[p] if isinstance(target, dict) else getattr(target, p)
    leaf = parts[-1]
    if isinstance(target, dict):  # e.g. model.<name>.options.<key>
        target[leaf] = value
        return
    if dataclasses.is_dataclass(target) and leaf not in {f.name for f in dataclasses.fields(target)}:
        raise ValueError(f"unknown config field {leaf!r} in {type(target).__name__}")
    setattr(target, leaf, value)


def default_config() -> ServerConfig:
    """The out-of-the-box config: ResNet-50 with random weights."""
    return ServerConfig(models=[ModelConfig(name="resnet50", family="resnet50")])
