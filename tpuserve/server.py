"""HTTP serving layer (SURVEY.md §2 C1, §3c).

The reference's web layer is a Flask/WSGI predict handler (BASELINE.json);
threads + blocking handlers don't suit a batching TPU server, so this layer is
a single asyncio event loop (aiohttp) where handlers only:

1. read the body,
2. decode it in the shared threadpool (``model.host_decode``),
3. submit to the batcher and await the per-request Future,
4. JSON-encode the result.

All device work happens behind the batcher. Endpoints:

- ``POST /v1/models/{name}:predict`` (aliases ``:classify``, ``:detect``,
  ``:generate``) — body is an image (``image/jpeg``, ``image/png``,
  ``application/x-npy``) or JSON (``{"text": ...}``, ``{"prompt": ...}``).
- ``GET  /healthz``     — liveness + per-model canary status.
- ``GET  /metrics``     — Prometheus text format.
- ``GET  /stats``       — JSON latency/throughput summary.
- ``GET  /debug/trace`` — Chrome trace JSON: the span ring (``?limit=``,
  ``?since_us=``) or one recorded request's tree (``?trace_id=``).
- ``GET  /debug/slow``  — flight recorder: slowest-N span trees per model
  plus every errored/shed request (docs/OBSERVABILITY.md).
- ``GET  /v1/models``   — model inventory (buckets, mesh, dtype).
- ``GET  /``            — minimal HTML upload page for manual poking.
- ``POST /admin/models/{name}:reload``   — staged, canary-gated weight swap
  (tpuserve.lifecycle); ``:rollback`` restores the retained previous
  version; ``GET /admin/models/{name}/versions`` lists the history.

Error mapping: decode failure -> 400, unknown model -> 404, queue full -> 429,
request deadline exceeded -> 504, batch failure (after retry) -> 500, breaker
open / draining -> 503. Shed responses (429/503) carry ``Retry-After``.

Every predict response — success OR error — carries an ``X-Trace-Id``
header (ISSUE 12): the request's 128-bit trace id, minted at ingest or
adopted from the router tier, joining the response to its recorded span
tree in the flight recorder. Error JSON bodies repeat it as ``trace_id``.
"""

from __future__ import annotations

import asyncio
import concurrent.futures as cf
import contextlib
import functools
import json
import logging
import math
import os
import signal
import socket
import threading
import time

from aiohttp import web

import jax

from tpuserve import frame as frame_wire
from tpuserve import models as modelzoo
from tpuserve import preproc
from tpuserve.analysis import witness
from tpuserve.batcher import (DeadlineExceeded, ModelBatcher, QueueFull,
                              clamp_retry_after_s)
from tpuserve.bench.roofline import compute_split, phase_p50
from tpuserve.cache import ModelCache
from tpuserve.config import ServerConfig, SloConfig
from tpuserve.faults import CircuitBreaker, FaultInjector, Watchdog
from tpuserve.genserve import GenEngine, GenEngineGroup, KVPressure
from tpuserve.hostpipe import StageExecutors
from tpuserve.lifecycle import ModelLifecycle, ReloadRejected
from tpuserve.obs import (PRIORITIES, FlightRecorder, Metrics, TraceContext,
                          exposition_content_type, spans_to_chrome)
from tpuserve.runtime import ModelRuntime, build_runtime, configure_jax
from tpuserve.scheduler import FleetScheduler
from tpuserve.scheduler.tenants import TenantLedger
from tpuserve.telemetry import (AuditLog, BlackBoxWriter, EventLog,
                                MetricSampler, PostmortemLog, ProfileCapture,
                                SloEngine, TimeSeriesStore,
                                UtilizationDeriver)
from tpuserve.telemetry import events as events_mod
from tpuserve.telemetry.profile import CaptureBusy

log = logging.getLogger("tpuserve.server")

_VERBS = ("predict", "classify", "detect", "generate")

# Typed aiohttp app keys (string keys are deprecated).
STATE_KEY: "web.AppKey[ServerState]" = web.AppKey("tpuserve_state", object)
# Per-app ingest handles: which accept loop this app serves (ISSUE 11).
INGEST_KEY: "web.AppKey[IngestHandles]" = web.AppKey("tpuserve_ingest", object)

# Client batches at least this big JSON-encode off the event loop (the
# encode for a full bucket of top-k results is hundreds of microseconds —
# enough to stall every other in-flight response at high request rates).
# Smaller responses stay inline: the executor hop costs more than it saves.
_JSON_OFFLOAD_MIN_ITEMS = 32

# Injected worker_hang wedge duration: long enough that the request never
# answers within any sane deadline (the router's hedging/504 owns it), short
# enough that a forgotten armed rule can't pin a connection forever.
_WORKER_HANG_S = 3600.0


def _dumps_utf8(obj) -> bytes:
    return json.dumps(obj).encode("utf-8")


class ModelHandles:
    """Per-model hot-path state hoisted out of handle_predict (ISSUE 5):
    prebound metric objects and config, built once at start(). The handler
    previously paid an f-string format plus a locked registry lookup per
    counter per request, and a linear config scan per request."""

    __slots__ = ("mcfg", "requests", "bad_requests", "timeouts", "total_hist",
                 "body_read_hist", "parse_hist", "frame_errors",
                 "native_fallback")

    def __init__(self, name: str, mcfg, metrics: Metrics) -> None:
        self.mcfg = mcfg
        self.requests = metrics.counter(f"requests_total{{model={name}}}")
        self.bad_requests = metrics.counter(
            f"bad_requests_total{{model={name}}}")
        self.timeouts = metrics.counter(f"timeouts_total{{model={name}}}")
        self.total_hist = metrics.histogram(
            f"latency_ms{{model={name},phase=total}}")
        # Ingest-phase attribution (ISSUE 11, docs/PERFORMANCE.md "The
        # ingest fast path"): body_read = socket-to-memory time for the
        # request body (the HTTP ingress wire), parse = host decode /
        # zero-copy frame parse. Request-scoped twins of the batcher's
        # batch-scoped phases, same latency_ms{phase=} family.
        self.body_read_hist = metrics.histogram(
            f"latency_ms{{model={name},phase=body_read}}")
        self.parse_hist = metrics.histogram(
            f"latency_ms{{model={name},phase=parse}}")
        # Malformed application/x-tpuserve-frame bodies (every one also
        # counts in bad_requests_total; this isolates wire-format trouble).
        self.frame_errors = metrics.counter(
            f"frame_errors_total{{model={name}}}")
        # yuv420 decode served by the 2x-slower PIL fallback although the
        # native shim path was attempted (missing/failed libjpegyuv.so or
        # a non-4:2:0 input); fed by the preproc hook installed at start().
        self.native_fallback = metrics.counter(
            f"native_decode_fallback_total{{model={name}}}")


class IngestHandles:
    """Per-accept-loop prebound ingest counters (ISSUE 11): loop 0 is the
    main serving loop, 1..N-1 the dedicated SO_REUSEPORT ingest threads.
    Balance across loops proves no single accept loop chokes the mesh."""

    __slots__ = ("index", "requests", "bytes")

    def __init__(self, index: int, metrics: Metrics) -> None:
        self.index = index
        self.requests = metrics.ingest_requests_counter(index)
        self.bytes = metrics.ingest_bytes_counter(index)


class ServerState:
    """Everything a running server owns."""

    def __init__(self, cfg: ServerConfig) -> None:
        self.cfg = cfg
        self.metrics = Metrics(cfg.trace_capacity,
                               exemplars=cfg.trace.exemplars)
        # Tail-latency flight recorder (ISSUE 12, docs/OBSERVABILITY.md):
        # complete span trees for the slowest-N requests per model plus
        # every errored/shed request, served at /debug/slow and
        # /debug/trace?trace_id=. Thread-safe — every ingest accept loop
        # finishes its own requests into it.
        self.recorder = FlightRecorder(
            slow_n=cfg.trace.slow_n,
            error_capacity=cfg.trace.error_capacity,
            always_record_errors=cfg.trace.always_record_errors,
            metrics=self.metrics)
        self.pool = cf.ThreadPoolExecutor(max_workers=cfg.decode_threads, thread_name_prefix="tpuserve")
        # Pipelined host execution engine (tpuserve.hostpipe): one dedicated
        # thread pool per stage, shared across every model's batcher so work
        # is scheduled at stage granularity (docs/PERFORMANCE.md).
        self.stages = StageExecutors(cfg.pipeline, self.metrics)
        self.models: dict[str, object] = {}
        self.runtimes: dict[str, ModelRuntime] = {}
        # Per-model dispatch engine: ModelBatcher (one-shot locked batches)
        # or GenEngine (iteration-level continuous batching) — both expose
        # the same submit/start/stop/drain/revive surface, so every caller
        # below (canaries, drain, watchdog, handle_predict) is agnostic.
        self.batchers: "dict[str, ModelBatcher | GenEngine]" = {}
        # The GenEngine subset of batchers (feeds the /stats genserve block;
        # built in build() so program compilation happens at startup).
        self.engines: dict[str, GenEngine] = {}
        self.breakers: dict[str, CircuitBreaker] = {}
        # Versioned reload lifecycle (tpuserve.lifecycle); direct-mode
        # runtimes only — recycle-mode workers own their params.
        self.lifecycles: dict[str, ModelLifecycle] = {}
        # Demand-shaping layer (tpuserve.cache): per-model result cache +
        # single-flight coalescing; empty unless [cache] enabled.
        self.caches: dict[str, ModelCache] = {}
        # Prebound per-model hot-path handles (metrics + config), built at
        # start() so handle_predict does zero registry lookups per request.
        self.handles: dict[str, ModelHandles] = {}
        # Fleet-level SLO scheduler (tpuserve.scheduler): cross-model
        # admission, priority arbitration, warm/cold weight paging. None
        # unless [scheduler] enabled — the per-model batchers then stay
        # fully independent, exactly as before.
        self.scheduler = (FleetScheduler(cfg.scheduler, self.metrics)
                          if cfg.scheduler.enabled else None)
        # Tenant containment (ISSUE 16): X-Api-Key resolution + the
        # weighted device-seconds ledger, enforced at admission in
        # _predict_traced. The fleet scheduler's saturation signal gates
        # fair-share shedding; without a scheduler only rate + quota run.
        self.tenants = (TenantLedger(cfg.tenants, self.metrics)
                        if cfg.tenants.enabled else None)
        if self.tenants is not None and self.scheduler is not None:
            self.tenants.saturated_fn = self.scheduler.saturated
        self.canary_ok: dict[str, bool] = {}
        # Telemetry plane (ISSUE 14, docs/OBSERVABILITY.md "The telemetry
        # plane"): bounded time-series history over every metric, the SLO
        # burn-rate engine over [model.slo] objectives, device-utilization
        # derivation, and on-demand deep profiling. All None when
        # [telemetry] enabled = false.
        self.store: TimeSeriesStore | None = None
        self.sampler: MetricSampler | None = None
        self.slo: SloEngine | None = None
        self.tenant_slo: SloEngine | None = None
        self.util: UtilizationDeriver | None = None
        self.profiler: ProfileCapture | None = None
        if cfg.telemetry.enabled:
            tcfg = cfg.telemetry
            self.store = TimeSeriesStore(
                self.metrics,
                capacity=int(tcfg.history_s / tcfg.sample_interval_s))
            self.slo = SloEngine(self.metrics, self.store,
                                 tcfg.burn_windows_s)
            self.util = UtilizationDeriver(self.metrics, self.store,
                                           tcfg.utilization_window_s)
            hooks = [self.slo.tick, self.util.tick]
            if self.tenants is not None and cfg.tenants.slo_latency_ms > 0:
                # Per-tenant SLO burn (ISSUE 16 satellite): the same
                # burn-rate machinery over tenant_latency_ms{tenant=},
                # one shared objective from [tenants].
                self.tenant_slo = SloEngine(
                    self.metrics, self.store, tcfg.burn_windows_s,
                    metric_fmt="tenant_latency_ms{{tenant={name}}}",
                    label="tenant")
                tenant_slo_cfg = SloConfig(
                    latency_ms=cfg.tenants.slo_latency_ms,
                    availability=cfg.tenants.slo_availability,
                    burn_alert=cfg.tenants.slo_burn_alert)
                for tname in self.tenants.names():
                    self.tenant_slo.register(tname, tenant_slo_cfg)
                hooks.append(self.tenant_slo.tick)
            self.sampler = MetricSampler(
                self.store, tcfg.sample_interval_s, hooks=hooks)
            self.profiler = ProfileCapture(self.metrics)
        # Structured event plane (ISSUE 15, docs/OBSERVABILITY.md "The
        # third pillar"): bounded event ring + logging bridge, admin audit
        # trail, and the postmortem ledger (populated behind the router
        # tier by the supervisors; a worker's own log records its view of
        # the world for the black-box snapshot). All None when [events]
        # enabled = false.
        self.events: EventLog | None = None
        self.audit: AuditLog | None = None
        self.postmortems: PostmortemLog | None = None
        self.blackbox: BlackBoxWriter | None = None
        if cfg.events.enabled:
            ecfg = cfg.events
            self.events = EventLog(self.metrics, ecfg.capacity,
                                   jsonl_path=ecfg.jsonl_path)
            self.audit = AuditLog(self.metrics, ecfg.audit_capacity,
                                  events=self.events)
            self.postmortems = PostmortemLog(
                self.metrics, ecfg.postmortem_capacity,
                tail_bytes=ecfg.stderr_tail_bytes, events=self.events)
            events_mod.install_bridge(self.events, ecfg.bridge_level)
            events_mod.set_active(self.events)
        # The event loop that owns the batchers/engines/cache/scheduler
        # (set in start()). Handlers running on a parallel ingest loop
        # (cfg.ingest_loops > 1) hop their submission onto it; on the main
        # loop the hop is a no-op (_on_main).
        self.main_loop: asyncio.AbstractEventLoop | None = None
        # Per-accept-loop ingest counters, keyed by loop index (built
        # lazily by make_app; the /stats "ingest" block reads them).
        self.ingest: dict[int, IngestHandles] = {}
        self._canary_task: asyncio.Task | None = None
        # Next periodic-canary fire time (time.monotonic clock): the live
        # basis for breaker-503 Retry-After hints (the canary IS the
        # recovery probe, so "retry after the next canary" is exact).
        self._next_canary_at: float | None = None
        # Worker-process id when this server runs behind the router tier
        # (tpuserve.workerproc); None in single-process mode.
        self.worker_id: int | None = None
        # Chaos layer (docs/ROBUSTNESS.md): None unless [faults] is armed.
        self.injector = (FaultInjector(cfg.faults, self.metrics)
                         if cfg.faults.enabled else None)
        self.watchdog = Watchdog(cfg.watchdog_interval_s, self.metrics)
        # Graceful drain: True once shutdown began — new requests shed with
        # 503 + Retry-After while accepted ones finish.
        self.draining = False
        # Bound (host, port) pairs once serve_async is listening.
        self.serving_addresses: list = []

    def build(self) -> None:
        # Retrace witness (docs/ANALYSIS.md): every build starts a fresh
        # warmup window; the barrier is declared once startup compilation
        # below is done, after which an unsanctioned compile raises.
        witness.reset_retrace()
        configure_jax(self.cfg)
        if self.cfg.profiler_port:
            jax.profiler.start_server(self.cfg.profiler_port)
        # [parallel] mode override (docs/PERFORMANCE.md "Serving on the
        # mesh"): applied at the CONFIG level, before the model is built,
        # so family-level mode validation (e.g. BERT ring attention
        # rejecting replica) and the model's own batch_spec see the real
        # serving mode. Recycle-mode models keep their own parallelism —
        # their runtimes live in worker processes with one device each.
        if self.cfg.parallel.mode:
            for mcfg in self.cfg.models:
                if mcfg.session_mode != "recycle" \
                        and mcfg.parallelism != self.cfg.parallel.mode:
                    log.info("model %s: [parallel] mode overrides "
                             "parallelism %r -> %r", mcfg.name,
                             mcfg.parallelism, self.cfg.parallel.mode)
                    mcfg.parallelism = self.cfg.parallel.mode
        compile_pool = cf.ThreadPoolExecutor(max_workers=4, thread_name_prefix="compile")
        try:
            for mcfg in self.cfg.models:
                t0 = time.perf_counter()
                model = modelzoo.build(mcfg)
                if mcfg.cold_start and self.scheduler is None:
                    log.warning("model %s: cold_start ignored — [scheduler] "
                                "is not enabled", mcfg.name)
                if mcfg.cold_start and self.scheduler is not None:
                    if self.cfg.genserve.enabled \
                            and getattr(model, "generative", False):
                        raise ValueError(
                            f"model {mcfg.name}: cold_start does not compose "
                            "with the generation engine yet (its programs "
                            "compile against the live param structure)")
                    # Cold boot (weight paging, docs/ROBUSTNESS.md "Fleet
                    # isolation & SLO admission"): meshes are planned but NO
                    # params are loaded and NO variants compiled — zero HBM
                    # resident. The first request (or :warm) stages weights
                    # through the lifecycle path; the scheduler sheds with
                    # 503 + Retry-After until the publish lands.
                    rt = ModelRuntime(model, metrics=self.metrics,
                                      parallel=self.cfg.parallel)
                    rt.injector = self.injector
                elif mcfg.session_mode == "recycle":
                    # Deferred-readback worker pool (tpuserve.deferred): this
                    # process never touches the accelerator; forked workers
                    # own one PJRT session each.
                    from tpuserve.deferred import DeferredPool

                    rt = DeferredPool(mcfg, self.cfg.compilation_cache_dir,
                                      model, injector=self.injector)
                    rt.prewarm()
                elif self.cfg.genserve.enabled \
                        and getattr(model, "generative", False):
                    # Iteration-level engine (docs/PERFORMANCE.md "The
                    # generation engine"): the engine's insert/step/extract
                    # programs replace the forward bucket set — compiling
                    # both would double startup compile time for nothing.
                    rt = build_runtime(model, metrics=self.metrics,
                                       parallel=self.cfg.parallel,
                                       compile_forward=False)
                    if getattr(rt, "n_replicas", 1) > 1:
                        # Replica-per-chip engines (docs/PERFORMANCE.md
                        # "Generation on the mesh"): one engine per replica
                        # mesh, least-loaded placement, the engine surface
                        # aggregated — everything downstream (watchdog,
                        # lifecycle, scheduler, /stats) wires unchanged.
                        eng = GenEngineGroup(model, rt, self.metrics,
                                             self.cfg.genserve,
                                             stages=self.stages,
                                             pipeline_cfg=self.cfg.pipeline)
                    else:
                        eng = GenEngine(model, rt, self.metrics,
                                        self.cfg.genserve, stages=self.stages,
                                        pipeline_cfg=self.cfg.pipeline)
                    eng.compile()  # registers + prewarms the programs
                    self.engines[mcfg.name] = eng
                    # Armed after compile/prewarm, like the batcher path.
                    eng.injector = self.injector
                    rt.injector = self.injector
                else:
                    rt = build_runtime(model, pool=compile_pool,
                                       metrics=self.metrics,
                                       parallel=self.cfg.parallel)
                    if self.cfg.prewarm_executables:
                        rt.prewarm()
                    if self.cfg.roofline_probe_iters > 0:
                        # Raw-executable ceilings per bucket (inputs
                        # resident, dependent read): the device-time term
                        # of /stats' roofline compute split. After prewarm
                        # (program load out of the window), before the
                        # injector arms (probes are not chaos targets).
                        rt.probe_all_raw(int(self.cfg.roofline_probe_iters))
                    # Armed after prewarm: chaos targets the serving path,
                    # not startup.
                    rt.injector = self.injector
                self.models[mcfg.name] = model
                self.runtimes[mcfg.name] = rt
                log.info("model %s ready in %.1fs: %s", mcfg.name, time.perf_counter() - t0, rt.describe())
        finally:
            compile_pool.shutdown()
        # Startup compilation done: from here on the steady-state
        # compile-delta-0 invariant is LIVE. Under
        # TPUSERVE_RETRACE_WITNESS=1 any further unsanctioned compile
        # raises RetraceViolation naming its (tag, variant), and implicit
        # device->host transfers are disallowed (utils.retrace).
        witness.declare_warmup_complete()
        if witness.retrace_enabled():
            from tpuserve.utils.retrace import arm_transfer_guard

            arm_transfer_guard()
            log.info("retrace witness armed (TPUSERVE_RETRACE_WITNESS)")

    def ingest_handles(self, index: int) -> IngestHandles:
        """Prebound ingest counters for accept loop ``index`` (idempotent)."""
        h = self.ingest.get(index)
        if h is None:
            h = self.ingest[index] = IngestHandles(index, self.metrics)
        return h

    async def start(self) -> None:
        self.main_loop = asyncio.get_running_loop()
        # Debug-mode race detection (docs/ANALYSIS.md): with
        # TPUSERVE_LOCK_WITNESS=1 every task created on this loop checks at
        # each suspension that no witnessed threading lock is held across an
        # await, and every lock built via utils.locks feeds the global
        # lock-order graph. The chaos drill runs with this armed in CI.
        if witness.maybe_install():
            log.info("lock witness installed (TPUSERVE_LOCK_WITNESS)")
        for name, model in self.models.items():
            rt = self.runtimes[name]
            if hasattr(rt, "enqueue"):  # DeferredPool: bind to the loop
                await rt.start()
            br = CircuitBreaker(name, model.cfg.breaker_threshold,
                                self.metrics,
                                retry_after_s=model.cfg.breaker_retry_after_s)
            self.breakers[name] = br
            eng = self.engines.get(name)
            if eng is not None:
                # Iteration-level engine: same front-door surface as the
                # batcher, so everything below (canary, cache, watchdog,
                # lifecycle, drain) composes unchanged.
                eng.breaker = br
                await eng.start()
                b: "ModelBatcher | GenEngine" = eng
            else:
                b = ModelBatcher(model, rt, self.metrics, self.pool,
                                 breaker=br, injector=self.injector,
                                 stages=self.stages,
                                 pipeline_cfg=self.cfg.pipeline,
                                 adaptive_cfg=self.cfg.adaptive)
                await b.start()
            self.batchers[name] = b
            self.handles[name] = ModelHandles(name, model.cfg, self.metrics)
            if self.cfg.cache.enabled and getattr(model, "cacheable", True):
                # Keys carry the LIVE runtime version, so a lifecycle
                # publish/rollback atomically invalidates older entries;
                # recycle-mode pools have no in-process version and pin 0.
                # Models with cacheable = false never get a cache: their
                # results are not a pure function of the decoded item.
                self.caches[name] = ModelCache(
                    name, self.cfg.cache, self.metrics,
                    version_fn=functools.partial(getattr, rt, "version", 0))
            self.watchdog.register(name, "group_loop", b.revive_group_loops)
            if hasattr(rt, "watchdog_sweep"):
                self.watchdog.register(name, "worker", rt.watchdog_sweep)
            if hasattr(rt, "stage_params"):
                # functools.partial, not a lambda: late binding would hand
                # every lifecycle the last loop iteration's name. Engine
                # models swap in the engine's staged canary: a SHORT
                # generation end-to-end through the real compiled programs
                # against the candidate tree.
                self.lifecycles[name] = ModelLifecycle(
                    name, rt, model, self.cfg.lifecycle, self.metrics,
                    breaker=br,
                    canary=functools.partial(self.run_canary, name),
                    canary_status=functools.partial(self.canary_ok.get, name),
                    injector=self.injector,
                    staged_canary_fn=eng.staged_canary_sync
                    if eng is not None else None)
            if self.scheduler is not None:
                # Fleet registration: the scheduler reads each batcher's
                # demand (pending, raw clear estimate, duration EWMAs) and
                # feeds its device-seconds ledger from dispatch timings;
                # cold models warm through the lifecycle's staged path so
                # no request is ever answered by unvalidated weights.
                lc = self.lifecycles.get(name)
                self.scheduler.register(
                    name, batcher=b, mcfg=model.cfg, runtime=rt,
                    warm_fn=lc.reload if lc is not None else None,
                    cold=bool(model.cfg.cold_start))
        if self.tenants is not None:
            # Tenant-partitioned cache capacity (ISSUE 16): each tenant's
            # weighted share bounds how many entries its misses may pin,
            # so one tenant's flood churns its OWN share first. Hits stay
            # content-addressed across tenants.
            weights = self.tenants.weights()
            for c in self.caches.values():
                c.set_tenant_weights(weights)
        # Native-decode fallback observability (ISSUE 11 satellite): the
        # preproc yuv420 decoder reports every PIL fallback on a
        # native-eligible request; route it to the prebound per-model
        # counter (Counter.inc is locked — decode threads and ingest loops
        # may call this concurrently).
        preproc.set_native_fallback_hook(self._note_native_fallback)
        if self.slo is not None:
            # SLO registration: models whose [model.slo] names a latency
            # objective get burn-rate gauges + an /alerts row; the rest
            # are simply not evaluated.
            for mcfg in self.cfg.models:
                self.slo.register(mcfg.name, mcfg.slo)
                # First-token objective (ISSUE 17): a separate subject
                # over the engine's gen_first_unit_ms histogram, so the
                # autopilot's shed-on-burn seam sees streaming health —
                # a model can meet its total-latency SLO while its
                # time-to-first-token burns.
                if mcfg.slo is not None and mcfg.slo.first_unit_ms > 0:
                    self.slo.register(
                        f"{mcfg.name}:first_unit",
                        SloConfig(latency_ms=mcfg.slo.first_unit_ms,
                                  availability=mcfg.slo.availability,
                                  burn_alert=mcfg.slo.burn_alert),
                        metric=f"gen_first_unit_ms{{model={mcfg.name}}}")
        if self.scheduler is not None:
            # Shed-on-burn seam (ISSUE 14): the scheduler can read each
            # model's live alert state (FleetScheduler.slo) — future PRs
            # shed batch-class work while a model burns budget instead of
            # waiting for fleet saturation.
            self.scheduler.slo = self.slo
        if self.sampler is not None:
            self.sampler.start()
        if self.scheduler is not None:
            await self.scheduler.start()
        if self.cfg.startup_canary:
            await self.run_canaries()
        if self.cfg.canary_interval_s > 0:
            self._canary_task = asyncio.create_task(self._canary_loop())
        if self.events is not None and self.cfg.events.snapshot_path \
                and self.cfg.events.snapshot_interval_s > 0:
            # Black box (ISSUE 15): checkpoint a postmortem snapshot to the
            # per-slot file (once immediately, then on the interval) so a
            # SIGKILL at any point after boot leaves last-N events, flight
            # summaries, and key counters for the supervisor's reap.
            self.blackbox = BlackBoxWriter(
                self.cfg.events.snapshot_path,
                self.cfg.events.snapshot_interval_s,
                self._blackbox_snapshot)
            self.blackbox.start()
        self.watchdog.start()

    # Counter families worth carrying in the black-box snapshot: the
    # serving volume and failure tallies a postmortem reader checks first.
    _BLACKBOX_COUNTERS = frozenset((
        "requests_total", "bad_requests_total", "timeouts_total",
        "deadline_exceeded_total", "batches_total",
        "watchdog_restarts_total", "events_logged_total"))

    def _blackbox_snapshot(self) -> dict:
        """One postmortem checkpoint (tpuserve.telemetry.events
        BlackBoxWriter `collect`): the last-N event records, compact
        flight-recorder summaries (trace ids, not span trees — the
        snapshot must stay small), and the key counters. Runs on the
        black-box thread; everything it reads is locked."""
        counters = {
            name: v for name, v in self.metrics.counter_values().items()
            if name.split("{", 1)[0] in self._BLACKBOX_COUNTERS}
        slow = []
        dumped = self.recorder.dump()
        for model, recs in sorted(dumped.get("slow", {}).items()):
            slow.extend({"model": model, "trace_id": r["trace_id"],
                         "status": r["status"],
                         "duration_ms": r["duration_ms"]}
                        for r in recs[:4])
        errors = [{"model": r["model"], "trace_id": r["trace_id"],
                   "status": r["status"], "duration_ms": r["duration_ms"]}
                  for r in dumped.get("errors", [])[:8]]
        return {
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
            "worker_id": self.worker_id,
            "events": self.events.tail(50) if self.events is not None else [],
            "flight": {"slow": slow, "errors": errors},
            "counters": counters,
        }

    def _note_native_fallback(self, model: str) -> None:
        h = self.handles.get(model)
        if h is not None:
            h.native_fallback.inc()
        else:  # decode racing startup/teardown: count unlabeled-but-visible
            self.metrics.counter(
                f"native_decode_fallback_total{{model={model}}}").inc()

    async def _canary_loop(self) -> None:
        """Re-run the per-model canary on an interval so /healthz reflects
        live serving health (degrades on failure, recovers on success).
        Canary inferences ride the normal serving path, so they are visible
        in /metrics like any synthetic probe; the per-cycle timeout is
        bounded by the interval so one hung model can't stretch staleness
        to the startup canary's 60 s budget — but never drops below a
        model's own request_timeout_ms (ADVICE r3: a 2 s floor made slow
        models like sd15, ~1.6 s+ device time per image, flap /healthz
        under ordinary load when canary_interval_s was small)."""
        timeouts = self.canary_timeouts()
        while True:
            self._next_canary_at = time.monotonic() + self.cfg.canary_interval_s
            await asyncio.sleep(self.cfg.canary_interval_s)
            try:
                await self.run_canaries(timeouts=timeouts)
            except asyncio.CancelledError:
                raise
            except Exception:  # one bad cycle must not end re-canarying
                log.exception("periodic canary cycle failed")

    def canary_timeouts(self) -> dict[str, float]:
        """Per-model periodic-canary timeout: bounded by the interval but
        floored at the model's own request_timeout_ms (ADVICE r3)."""
        base = min(60.0, max(2.0, 2.0 * self.cfg.canary_interval_s))
        return {
            name: max(base, m.cfg.request_timeout_ms / 1e3)
            for name, m in self.models.items()
        }

    async def run_canary(self, name: str, timeout_s: float = 60.0) -> bool:
        """Tiny end-to-end inference for one model; feeds /healthz and
        half-opens/closes the circuit breaker (canaries ride the batcher
        regardless of breaker state — they ARE the recovery probe)."""
        if self.scheduler is not None and not self.scheduler.is_warm(name):
            # Cold/warming model (weight paging): there are no live params
            # to probe, and the staged canary inside the warm-up path owns
            # candidate validation. Never-measured reads as healthy.
            return self.canary_ok.get(name, True)
        model = self.models[name]
        br = self.breakers.get(name)
        try:
            if self.injector is not None:
                self.injector.check("canary_fail", name)
            if br is not None:
                br.probe()
            item = model.canary_item()
            fut = self.batchers[name].submit(item, group=model.group_key(item))
            await asyncio.wait_for(fut, timeout=timeout_s)
            self.canary_ok[name] = True
        except QueueFull:
            # A full queue is load shedding doing its job, not ill health;
            # flipping /healthz to 503 here would pull the busiest instance
            # from rotation and cascade the overload. Keep the last status.
            log.info("canary for %s skipped: queue full (shedding)", name)
        except Exception:
            log.exception("canary failed for %s", name)
            self.canary_ok[name] = False
        # .get: a shed canary with no prior status (startup_canary=False)
        # must not KeyError — treat never-measured as healthy.
        return self.canary_ok.get(name, True)

    async def run_canaries(self, timeout_s: float = 60.0,
                           timeouts: dict[str, float] | None = None) -> None:
        # Concurrent: one hung model must not stall (or stale) the others.
        await asyncio.gather(
            *(self.run_canary(name, timeout_s=(timeouts or {}).get(name, timeout_s))
              for name in self.models))

    # -- graceful drain ------------------------------------------------------
    def begin_drain(self) -> None:
        """Stop admitting requests: predict answers 503 + Retry-After and
        /healthz flips so load balancers pull this replica."""
        self.draining = True

    async def drain(self) -> bool:
        """SIGTERM path: refuse new work, then wait (<= drain_timeout_s) for
        every accepted request to finish — a rolling restart drops zero
        accepted requests. Returns False if the budget expired first.

        The revival machinery stops FIRST: the watchdog must not revive a
        group loop (or background-respawn a deferred worker) that this
        drain is intentionally quiescing, and the periodic canary must not
        inject new probe work after admission closed. The old ordering left
        both running until state.stop() — a stop/revive race window where a
        post-drain sweep could recreate machinery stop() was about to tear
        down (and, for deferred pools, fork a multi-second replacement
        worker nobody would ever use)."""
        t_drain = time.perf_counter()
        await self.watchdog.stop()
        await self._stop_canary_loop()
        if self.scheduler is not None:
            # Same discipline: the idle-demotion sweep (and any in-flight
            # warm-up) must not mutate model state under the drain.
            await self.scheduler.stop()
        self.begin_drain()
        if self.sampler is not None:
            # The telemetry sampler joins during the drain too (no orphan
            # thread ticking a dying registry) — but AFTER the draining
            # flag: it only READS metrics, so it is not revival machinery,
            # and admission must close before anything that can suspend.
            # stop() is idempotent for the non-drain teardown path.
            await asyncio.get_running_loop().run_in_executor(
                None, self.sampler.stop)
        # Early-retire deferred epochs so pending futures resolve in
        # readback time instead of at the epoch deadline.
        for rt in self.runtimes.values():
            if hasattr(rt, "retire_active"):
                rt.retire_active()
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.cfg.drain_timeout_s
        ok = True
        for b in self.batchers.values():
            ok &= await b.drain(deadline)
        if self.blackbox is not None:
            # Final checkpoint, then stop: the last snapshot records the
            # drained state (counters at rest) for whoever reads the slot.
            await loop.run_in_executor(None, self.blackbox.stop)
        if self.audit is not None:
            # Drain is an admin action like any other: the audit trail is
            # how an operator later tells a rolling restart from a crash.
            self.audit.record(
                "drain", "server", "ok" if ok else "budget_expired",
                duration_ms=(time.perf_counter() - t_drain) * 1e3,
                drain_timeout_s=self.cfg.drain_timeout_s)
        return ok

    def roofline(self, latency_summary: dict) -> dict:
        """The /stats ``roofline`` block (docs/PERFORMANCE.md "Reading the
        roofline"): per model the resident specialized variants, lifetime
        compile count, per-bucket raw-executable ms (when
        ``roofline_probe_iters`` armed the startup probe), and the serving
        compute phase split into device-time vs host-wait."""
        out: dict = {}
        for name, rt in self.runtimes.items():
            if not hasattr(rt, "variants"):
                continue  # deferred pools own their executables out-of-process
            row: dict = {
                "variants": rt.variants_summary(),
                "compiles_total": rt.compiles_total,
                "raw_ms_per_batch": {
                    str(list(b)): v
                    for b, v in sorted(rt.raw_ms_per_batch.items())},
            }
            if self.util is not None:
                # Chip-occupancy context (ISSUE 14): the roofline's ceiling
                # percentages read differently at 0.2 vs 0.9 utilization —
                # carry the live busy fractions beside the raw-ms terms.
                u = self.util.stats().get(name)
                if u:
                    row["utilization"] = u
            raw_vals = [v for v in rt.raw_ms_per_batch.values() if v]
            if raw_vals:
                # The largest probed bucket prices the split: it is what a
                # saturated loop overwhelmingly serves, and using the
                # biggest raw time makes host_wait a LOWER bound.
                split = compute_split(
                    phase_p50(latency_summary, name, "compute"),
                    max(raw_vals))
                if split is not None:
                    row["compute_split"] = split
            out[name] = row
        return out

    def parallel_stats(self) -> dict:
        """The /stats ``parallel`` block (docs/PERFORMANCE.md "Serving on
        the mesh"): per model the live serving layout and per-chip dispatch
        attribution — replica mode lists one count per chip; sharded mode
        has one mesh-wide count, reported with its per-chip share."""
        out: dict = {}
        for name, rt in self.runtimes.items():
            if not hasattr(rt, "parallel_signature"):
                continue  # deferred pools own their devices out-of-process
            batches = rt.replica_batches()
            out[name] = {
                "mode": rt.mode,
                "signature": rt.parallel_signature,
                "n_chips": rt.n_chips,
                "replicas": rt.n_replicas,
                "replica_batches_total": batches,
                "batches_per_chip": round(sum(batches) / rt.n_chips, 2)
                if rt.n_chips else 0.0,
            }
        return out

    def shed_retry_after(self) -> int:
        """Retry-After seconds for drain 503 responses (hint to hit another
        replica — this one is going away, so there is no live state to
        derive a better number from)."""
        return max(1, math.ceil(self.cfg.shed_retry_after_s))

    def queue_retry_after(self, name: str) -> int:
        """Retry-After seconds for queue-full 429s, derived from live state:
        the batcher's estimated queue-clear time at the observed serving
        rate (per-bucket duration EWMAs), clamped to [1, 30] s by
        ``batcher.clamp_retry_after_s`` (the estimate itself stays raw for
        the fleet scheduler's admission math). Falls back
        to the configured constant before any batch has completed."""
        b = self.batchers.get(name)
        hint = clamp_retry_after_s(b.estimate_clear_s()
                                   if b is not None else None)
        return hint if hint is not None else self.shed_retry_after()

    def kv_retry_after(self, name: str, exc: Exception) -> int:
        """Retry-After seconds for paged-KV pressure 503s (ISSUE 18): the
        engine's page-clear estimate carried on the KVPressure itself,
        clamped like every shed hint; falls back to the queue-clear hint
        before the engine has duration evidence."""
        hint = clamp_retry_after_s(getattr(exc, "retry_after_s", None))
        return hint if hint is not None else self.queue_retry_after(name)

    def breaker_retry_after(self, name: str) -> int:
        """Retry-After seconds for breaker 503s, derived from live state:
        the time until the NEXT periodic canary — the probe that half-opens
        and closes the breaker — when canaries drive recovery (the interval
        itself before the loop has armed a fire time), else the model's
        configured hint."""
        if self.cfg.canary_interval_s > 0:
            if self._next_canary_at is not None:
                eta = self._next_canary_at - time.monotonic()
                if eta > 0:
                    return max(1, math.ceil(eta))
                return 1  # probe due now: retry immediately after it lands
            return max(1, math.ceil(self.cfg.canary_interval_s))
        br = self.breakers.get(name)
        return max(1, math.ceil(br.retry_after_s if br else 1.0))

    async def _stop_canary_loop(self) -> None:
        """Cancel the periodic canary task (idempotent; drain + stop)."""
        if self._canary_task is not None:
            self._canary_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._canary_task
            self._canary_task = None

    async def stop(self) -> None:
        await self.watchdog.stop()
        if self.blackbox is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, self.blackbox.stop)
        if self.sampler is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, self.sampler.stop)
        if self.scheduler is not None:
            await self.scheduler.stop()
        for lc in self.lifecycles.values():
            lc.close()  # stop soak monitors
        await self._stop_canary_loop()
        # Deferred pools first retire their active workers (fast) so batcher
        # dispatch tasks awaiting epoch readback resolve in readback time,
        # not at the epoch deadline; then drain batchers, then stop pools.
        for rt in self.runtimes.values():
            if hasattr(rt, "retire_active"):
                rt.retire_active()
        for b in self.batchers.values():
            await b.stop()
        for rt in self.runtimes.values():
            if hasattr(rt, "enqueue"):
                await rt.stop()
        self.stages.shutdown()
        self.pool.shutdown(wait=False, cancel_futures=True)
        if self.events is not None:
            self.events.close()  # flush/close the JSONL sink fd


# -- handlers ----------------------------------------------------------------

class NotServing(RuntimeError):
    """Batcher refused the submit (stopped / racing shutdown) -> 503."""


async def _on_main(state: ServerState, factory):
    """Run ``factory()`` (a coroutine factory) on the main serving loop.

    On the main loop this is a plain await — the single-loop hot path pays
    nothing. On a parallel ingest loop (cfg.ingest_loops > 1) the coroutine
    is scheduled onto the main loop, which owns every batcher/cache/
    scheduler structure (all deliberately lock-free and loop-only), and the
    result/exception crosses back through a concurrent future. Cancelling
    the ingest-side await (client disconnect) cancels the main-loop task —
    asyncio.wrap_future propagates cancellation both ways."""
    loop = asyncio.get_running_loop()
    if state.main_loop is None or loop is state.main_loop:
        return await factory()
    cfut = asyncio.run_coroutine_threadsafe(factory(), state.main_loop)
    return await asyncio.wrap_future(cfut)


def _main_loop_handler(handler):
    """Route an admin/stats handler onto the main serving loop when the
    request landed on a parallel ingest loop. These handlers touch
    loop-only state (lifecycles, scheduler, batcher stats) and read only
    ``request.match_info`` — synchronous data, safe to carry across the
    loop boundary; the Response is built unprepared and returned."""

    @functools.wraps(handler)
    async def wrapped(request: web.Request) -> web.StreamResponse:
        state: ServerState = request.app[STATE_KEY]
        loop = asyncio.get_running_loop()
        if state.main_loop is None or loop is state.main_loop:
            return await handler(request)
        cfut = asyncio.run_coroutine_threadsafe(handler(request),
                                                state.main_loop)
        return await asyncio.wrap_future(cfut)

    return wrapped


async def _submit_and_gather(state: ServerState, name: str, model,
                             items: list, deadline_at: float,
                             priority: str | None,
                             timeout_ms: float | None,
                             ctx: "TraceContext | None" = None,
                             tenant: str | None = None,
                             ) -> tuple[list, "object | None"]:
    """Cache/single-flight lookup + batcher submission + deadline-bounded
    gather for one decoded request — everything that must run on the main
    serving loop. Returns (results, hit_entry). Raises QueueFull (-> 429),
    NotServing (-> 503), DeadlineExceeded (-> fast 504),
    asyncio.TimeoutError (-> backstop 504), or the batch failure (-> 500);
    the HTTP handler owns the status mapping on whichever loop it runs."""
    cache = state.caches.get(name)
    batcher = state.batchers[name]
    results: list = [None] * len(items)
    futs: list[asyncio.Future] = []
    slots: list[int] = []
    hit_entry = None
    try:
        for i, item in enumerate(items):
            if cache is not None:
                key = cache.key_for(item)
                entry = cache.get(key)
                if entry is not None:
                    results[i] = entry.value
                    hit_entry = entry
                    if ctx is not None:
                        now = time.time()
                        ctx.span("cache_hit", now, now, tid=name)
                    continue
                fut = cache.submit_through(
                    key, lambda it=item: batcher.submit(
                        it, group=model.group_key(it),
                        deadline_at=deadline_at, priority=priority,
                        ctx=ctx), ctx=ctx, tenant=tenant)
            else:
                fut = batcher.submit(item, group=model.group_key(item),
                                     deadline_at=deadline_at,
                                     priority=priority, ctx=ctx)
            futs.append(fut)
            slots.append(i)
    except QueueFull:
        for f in futs:
            f.cancel()
        raise
    except RuntimeError as e:
        # Batcher stopped/not started: requests racing shutdown get a clean
        # retryable status instead of an unhandled 500.
        for f in futs:
            f.cancel()
        raise NotServing(str(e)) from e

    if futs:
        try:
            remaining = max(0.0, deadline_at - time.perf_counter())
            # With an explicit client deadline the batcher enforces it
            # precisely at flush time (fast 504 + deadline_exceeded_total);
            # the timer here then runs slightly late as a pure backstop so
            # the two never race.
            grace = 0.25 if timeout_ms is not None else 0.0
            done = await asyncio.wait_for(asyncio.gather(*futs),
                                          timeout=remaining + grace)
        except BaseException:
            # TimeoutError, DeadlineExceeded, batch failure, cancellation:
            # nothing may leave dangling single-item futures behind.
            for f in futs:
                f.cancel()
            raise
        for i, res in zip(slots, done):
            results[i] = res
    return results, hit_entry


async def handle_predict(request: web.Request) -> web.Response:
    """Predict entry: mints (or adopts, behind the router) the request's
    trace context, delegates to the traced handler, then stamps
    ``X-Trace-Id`` on the response — EVERY response, success or error —
    records the root span, and offers the finished trace to the flight
    recorder (ISSUE 12, docs/OBSERVABILITY.md)."""
    state: ServerState = request.app[STATE_KEY]
    name = request.match_info["name"]
    # Behind the router tier the worker's spans land on their own process
    # lane (pid = worker id + 1; the router is lane 0), which is what makes
    # the cross-process hop visible as a gap in a stitched Chrome trace.
    ctx = TraceContext.from_headers(
        request.headers,
        pid=state.worker_id + 1 if state.worker_id is not None else 0)
    wall0 = time.time()
    t0 = time.perf_counter()
    resp = await _predict_traced(request, state, name, ctx)
    dur_s = time.perf_counter() - t0
    ctx.root_span("request", wall0, wall0 + dur_s, tid=name,
                  status=resp.status)
    if "X-Trace-Id" not in resp.headers:
        resp.headers["X-Trace-Id"] = ctx.trace_id
    # Streamed responses score by max(first-unit, largest gap) — set by
    # _predict_stream — so a slow STREAM is catchable while a long healthy
    # generation isn't misfiled as slow (ISSUE 17 satellite).
    score_ms = getattr(resp, "tpuserve_stream_score_ms", None)
    kinds = state.recorder.finish(
        ctx, name, resp.status,
        score_ms if score_ms is not None else dur_s * 1e3)
    if state.events is not None:
        # Trace-correlated flight data (ISSUE 15): errored/shed and
        # retained-slow requests leave an event carrying the trace id, so
        # /debug/trace?trace_id= interleaves what the process was saying.
        if resp.status >= 400:
            state.events.emit(
                "error" if resp.status >= 500 else "warning", "http",
                "request_error", model=name, trace_id=ctx.trace_id,
                status=resp.status, duration_ms=round(dur_s * 1e3, 3))
        elif "slow" in kinds:
            state.events.emit(
                "info", "http", "slow_request", model=name,
                trace_id=ctx.trace_id, status=resp.status,
                duration_ms=round(dur_s * 1e3, 3))
    return resp


async def _predict_traced(request: web.Request, state: ServerState,
                          name: str, ctx: TraceContext) -> web.Response:
    model = state.models.get(name)
    if model is None:
        return _err(404, f"unknown model {name!r}", trace=ctx)
    # Query validation (shared validator, ISSUE 15 idiom): predict knows
    # exactly two parameters; junk keys or a junk stream= value are a 400
    # before any body work.
    try:
        events_mod.reject_unknown_query(request.query,
                                        {"timeout_ms", "stream"})
        want_stream = _requested_stream(request)
    except ValueError as e:
        return _err(400, str(e), trace=ctx)
    # Shed checks run BEFORE the body read: a draining replica or tripped
    # model answers in microseconds, with a Retry-After hint, instead of
    # paying decode + a doomed dispatch.
    if state.draining:
        return _err(503, "server draining; retry against another replica",
                    retry_after=state.shed_retry_after(), trace=ctx)
    # Tenant containment (ISSUE 16): identity, rate, quota, and fair
    # share are judged pre-body — a flooding tenant is refused in
    # microseconds and never reaches decode or the batcher. Behind the
    # router tier the ROUTER admits (it fronts clients); the worker's
    # [tenants] block is normally disabled there.
    tenant: str | None = None
    if state.tenants is not None:
        tenant = state.tenants.resolve(request.headers.get("X-Api-Key"))
        if tenant is None:
            t_shed = state.tenants.shed_unknown()
            return _err(t_shed.status, t_shed.message, reason=t_shed.reason,
                        trace=ctx)
        t_shed = state.tenants.admit(tenant)
        if t_shed is not None:
            return _err(t_shed.status, t_shed.message,
                        retry_after=t_shed.retry_after,
                        reason=t_shed.reason, trace=ctx)
    breaker = state.breakers.get(name)
    if breaker is not None and not breaker.allow():
        breaker.on_shed()
        return _err(503, f"circuit open for model {name!r}; recovery probe "
                         "in progress",
                    retry_after=state.breaker_retry_after(name), trace=ctx)
    # Fleet scheduler admission, part 1 (pre-body; tpuserve.scheduler):
    # warm/cold state and priority arbitration need only headers, so a
    # cold model or shed batch-class request answers in microseconds. The
    # deadline check runs after the deadline is stamped, below. Scheduler
    # state is main-loop-only; on a parallel ingest loop the check hops
    # (_on_main) — microseconds of coroutine scheduling, still pre-body.
    raw_priority = request.headers.get("X-Priority")
    priority: str | None = None
    if state.scheduler is not None:
        async def _precheck():
            p = state.scheduler.resolve_priority(name, raw_priority)
            shed = state.scheduler.check_admission(name, p)
            if shed is None:
                state.scheduler.touch(name)
            return p, shed

        try:
            priority, shed = await _on_main(state, _precheck)
        except ValueError as e:
            return _err(400, str(e), trace=ctx)
        if shed is not None:
            return _err(shed.status, shed.message,
                        retry_after=shed.retry_after, reason=shed.reason,
                        trace=ctx)
    elif raw_priority:
        # No scheduler = no arbitration, but the class still labels the
        # queue-wait split (header -> batcher); junk degrades to the
        # model default rather than 400ing an unscheduled server.
        value = raw_priority.strip().lower()
        priority = value if value in PRIORITIES else None
    h = state.handles[name]
    mcfg = h.mcfg
    h.requests.inc()
    t_start = time.perf_counter()

    if state.injector is not None:
        # Process-boundary chaos (docs/ROBUSTNESS.md "Process failure
        # domains"): simulate a degraded (worker_slow), wedged (worker_hang
        # — the request simply never answers), or natively-crashed
        # (worker_crash — the whole process exits, taking every in-flight
        # request with it) serving process. Behind the router tier these
        # prove hedging, retry, and supervision; in single-process mode
        # they demonstrate exactly the blast radius the split removes.
        delay = state.injector.delay_s("worker_slow", name)
        if delay > 0:
            await asyncio.sleep(delay)
        if state.injector.fire("worker_hang", name) is not None:
            await asyncio.sleep(_WORKER_HANG_S)
            return _err(503, "wedged worker unwedged; retry")
        if state.injector.fire("worker_crash", name) is not None:
            log.error("chaos: worker_crash fired for %s — exiting process",
                      name)
            os._exit(17)

    # Ingest phase 1 (ISSUE 11): the body read is the HTTP ingress wire —
    # on a framed multi-item POST this is megabytes off the socket, and
    # with ingest_loops > 1 it runs on whichever accept loop the kernel's
    # SO_REUSEPORT spread picked, not serialized on the batcher's loop.
    ing: IngestHandles = request.app[INGEST_KEY]
    t_read = time.perf_counter()
    w_read = time.time()
    body = await request.read()
    read_s = time.perf_counter() - t_read
    h.body_read_hist.observe(read_s * 1e3, trace_id=ctx.trace_id)
    ctx.span("body_read", w_read, w_read + read_s, tid=name,
             loop=ing.index, bytes=len(body))
    ing.requests.inc()
    ing.bytes.inc(len(body))
    ctype = request.content_type or ""

    # Per-request deadline (docs/ROBUSTNESS.md): the client's timeout_ms
    # (JSON body key, ?timeout_ms= query, or X-Timeout-Ms header) overrides
    # the model's request_timeout_ms. The absolute deadline is stamped at
    # admission and travels with each queued item, so the batcher can fail
    # already-dead work in microseconds instead of dispatching it.
    try:
        timeout_ms = _requested_timeout_ms(request, body, ctype)
    except ValueError as e:
        return _err(400, str(e), trace=ctx)
    timeout_s = (timeout_ms if timeout_ms is not None
                 else mcfg.request_timeout_ms) / 1e3
    deadline_at = t_start + timeout_s

    # Fleet scheduler admission, part 2 (Clockwork P3): a deadline that
    # provably cannot be met — predicted queue-clear + service time exceed
    # the remaining budget — sheds with a fast 504 BEFORE decode or
    # enqueue, instead of dying at the back of the queue.
    if state.scheduler is not None:
        async def _deadline_check():
            return state.scheduler.check_deadline(name, deadline_at)

        shed = await _on_main(state, _deadline_check)
        if shed is not None:
            return _err(shed.status, shed.message,
                        retry_after=shed.retry_after, reason=shed.reason,
                        trace=ctx)

    try:
        if state.injector is not None:
            state.injector.check("decode_corrupt", name)
        # Ingest phase 2: (items, is_batch) with one parse; a 1-element
        # client batch still answers in the {"results": [...]} shape.
        # Framed bodies parse as zero-copy views (tpuserve.frame) — the
        # "parse" phase for them is offset-table validation, not pixel work.
        t_parse = time.perf_counter()
        w_parse = time.time()
        if state.cfg.decode_inline:
            items, batched = model.host_decode_items(body, ctype)
        else:
            loop = asyncio.get_running_loop()
            items, batched = await loop.run_in_executor(
                state.pool, model.host_decode_items, body, ctype)
        if not items:
            raise ValueError("empty batch")
        parse_s = time.perf_counter() - t_parse
        h.parse_hist.observe(parse_s * 1e3, trace_id=ctx.trace_id)
        ctx.span("parse", w_parse, w_parse + parse_s, tid=name,
                 items=len(items))
    except frame_wire.FrameError as e:
        # Malformed frame: machine-readable 400 (message is "frame: ..."),
        # never a 500 — and counted apart from generic decode failures.
        h.frame_errors.inc()
        h.bad_requests.inc()
        return _err(400, str(e), trace=ctx)
    except Exception as e:
        h.bad_requests.inc()
        return _err(400, f"could not decode request: {e}", trace=ctx)

    if want_stream:
        # Streaming dispatch (ISSUE 17): straight to the generation
        # engine's emission channel — no result cache, no single-flight
        # (a stream must never coalesce onto a buffered leader or be
        # answered from a cached body; it force-misses by construction).
        eng = state.engines.get(name)
        if eng is None:
            h.bad_requests.inc()
            return _err(400, f"model {name!r} does not support streaming "
                             "(stream=true needs a [genserve]-served "
                             "generative model)", trace=ctx)
        if len(items) != 1:
            h.bad_requests.inc()
            return _err(400, "stream=true requires a single-item request",
                        trace=ctx)
        return await _predict_stream(request, state, name, model, h, eng,
                                     items[0], deadline_at, timeout_s,
                                     priority, tenant, ctx, t_start)

    # Demand-shaping layer (tpuserve.cache): per item, answer from the
    # content-addressed result cache, join an identical in-flight miss
    # (single-flight: one batch slot, the result fanned out), or lead a
    # fresh batcher submission. Hit/miss/coalesced are counted disjointly
    # so cache traffic never masquerades as model throughput. Everything
    # below the decode runs on the MAIN loop (_submit_and_gather): cache,
    # single-flight, batcher, and scheduler state are loop-only by design,
    # so a parallel ingest loop makes exactly ONE hop per request here.
    w_dispatch = time.time()
    t_dispatch = time.perf_counter()
    try:
        results, hit_entry = await _on_main(
            state, lambda: _submit_and_gather(
                state, name, model, items, deadline_at, priority,
                timeout_ms, ctx, tenant))
    except KVPressure as e:
        # Paged-KV admission shed (ISSUE 18): the fast-shed contract of
        # queue-full, but 503 with reason "kv_pressure" so clients (and
        # the router) can tell memory pressure from queue pressure.
        return _err(503, str(e), retry_after=state.kv_retry_after(name, e),
                    reason="kv_pressure", trace=ctx)
    except QueueFull:
        return _err(429, "queue full, retry later",
                    retry_after=state.queue_retry_after(name), trace=ctx)
    except NotServing as e:
        return _err(503, f"server not accepting requests: {e}", trace=ctx)
    except DeadlineExceeded as e:
        # The batcher rejected the queued work before dispatch: same 504
        # as the timer path, but fast, in deadline_exceeded_total.
        return _err(504, f"deadline_exceeded: {e}", trace=ctx)
    except asyncio.TimeoutError:
        h.timeouts.inc()
        return _err(504,
                    f"request deadline ({timeout_s * 1e3:.0f} ms) exceeded",
                    trace=ctx)
    except Exception as e:
        return _err(500, f"inference failed: {e}", trace=ctx)
    finally:
        # The ingest-loop→main-loop hop plus everything the main loop ran
        # (cache, single-flight, batcher/engine): its children are the
        # queue/phase spans the batcher recorded; a gap between "parse"
        # and "queue" inside this span IS the cross-loop hop.
        ctx.span("dispatch", w_dispatch,
                 w_dispatch + (time.perf_counter() - t_dispatch), tid=name)

    total_ms = (time.perf_counter() - t_start) * 1e3
    h.total_hist.observe(total_ms, trace_id=ctx.trace_id)
    if state.tenants is not None and tenant is not None:
        # Charge the tenant's sliding-window ledger with the wall time
        # the request occupied the server (the device-time proxy quota
        # and fair share enforce) and feed its latency series (the
        # per-tenant SLO burn input).
        state.tenants.record(tenant, total_ms / 1e3, latency_ms=total_ms)
    if batched:
        payload = {"results": results}
        if len(results) >= _JSON_OFFLOAD_MIN_ITEMS and not state.cfg.decode_inline:
            # Large batched responses encode off the loop (egress fast
            # path); single-core hosts (decode_inline) stay inline — the
            # executor hop costs more than the encode there.
            raw = await asyncio.get_running_loop().run_in_executor(
                state.pool, _dumps_utf8, payload)
            return web.Response(body=raw, content_type="application/json")
        return web.json_response(payload)
    result = results[0]
    if isinstance(result, bytes):  # e.g. SD PNG output
        return web.Response(body=result, content_type="image/png")
    if hit_entry is not None and hit_entry.body is not None:
        # Cache-hit egress fast path: the response bytes were serialized
        # once at population time; a hit is one memcpy, zero json.dumps.
        return web.Response(body=hit_entry.body,
                            content_type="application/json")
    return web.json_response(result)


def _requested_stream(request: web.Request) -> bool:
    """The ``?stream=`` query flag; ValueError (-> 400) on junk values —
    a typo'd flag must fail loudly, not silently serve unary."""
    raw = request.query.get("stream")
    if raw is None:
        return False
    val = raw.strip().lower()
    if val in ("true", "1"):
        return True
    if val in ("false", "0"):
        return False
    raise ValueError(
        f'stream must be "true", "1", "false" or "0", got {raw!r}')


def _stream_error_status(reason: str) -> int:
    """Pre-first-unit terminal -> plain HTTP status (the fast-504 half of
    the deadline contract: no bytes were written, so no stream semantics
    are owed and the router's hedge/retry stays legal)."""
    return {"deadline_exceeded": 504, "shutdown": 503, "drain": 503}.get(
        reason, 500)


async def _predict_stream(request: web.Request, state: ServerState,
                          name: str, model, h: ModelHandles, eng,
                          item, deadline_at: float, timeout_s: float,
                          priority: str | None, tenant: str | None,
                          ctx: TraceContext,
                          t_start: float) -> web.StreamResponse:
    """One streamed generation end-to-end (ISSUE 17 tentpole layer 2).

    The engine's GenStream queue is the single channel: units flush per
    engine iteration, heartbeats cover idle gaps, and exactly one terminal
    ("done" with finish reason + usage, or "error" naming the cause)
    closes every started stream. The deadline contract splits here: until
    the first unit no bytes are written and failures stay plain statuses
    (fast 504 — and the router's first-byte latch sees no body, keeping
    hedges legal); after it, failures become in-stream error events. A
    client disconnect cancels the engine future, freeing the slot for
    fold-in (gen_client_disconnects_total ticks engine-side)."""

    async def _submit():
        try:
            return eng.submit_stream(item, deadline_at=deadline_at,
                                     priority=priority, ctx=ctx)
        except QueueFull:
            raise
        except RuntimeError as e:
            raise NotServing(str(e)) from e

    try:
        fut, stream = await _on_main(state, _submit)
    except KVPressure as e:
        # Shed before any stream byte: plain 503 + reason, no SSE involved.
        return _err(503, str(e), retry_after=state.kv_retry_after(name, e),
                    reason="kv_pressure", trace=ctx)
    except QueueFull:
        return _err(429, "queue full, retry later",
                    retry_after=state.queue_retry_after(name), trace=ctx)
    except NotServing as e:
        return _err(503, f"server not accepting requests: {e}", trace=ctx)

    hb_s = eng.gcfg.stream_heartbeat_s
    hb = model.stream_heartbeat()
    encode = model.encode_stream_unit
    resp: web.StreamResponse | None = None
    terminal: dict | None = None
    n_units = 0
    last_write: float | None = None
    first_unit_ms: float | None = None
    max_gap_ms = 0.0
    max_gap_end = 0.0
    try:
        while terminal is None:
            if resp is None:
                # Admission -> first unit: bounded by the request deadline
                # plus the same 0.25 s backstop grace the unary path uses
                # (the engine's fast-504 eviction normally answers first).
                budget = max(0.0, deadline_at - time.perf_counter()) + 0.25
                try:
                    unit = await _on_main(state, lambda: asyncio.wait_for(
                        stream.get(), budget))
                except asyncio.TimeoutError:
                    h.timeouts.inc()
                    return _err(
                        504,
                        f"request deadline ({timeout_s * 1e3:.0f} ms) "
                        "exceeded", trace=ctx)
                if unit["type"] == "error":
                    status = _stream_error_status(unit.get("error", ""))
                    if status == 504:
                        h.timeouts.inc()
                    return _err(status,
                                f"{unit.get('error', 'error')}: "
                                f"{unit.get('message', '')}", trace=ctx)
                resp = web.StreamResponse(status=200)
                resp.content_type = model.stream_content_type()
                resp.headers["X-Tpuserve-Stream"] = "1"
                resp.headers["X-Trace-Id"] = ctx.trace_id
                await resp.prepare(request)
            else:
                try:
                    unit = await _on_main(state, lambda: asyncio.wait_for(
                        stream.get(), hb_s if hb_s > 0 else None))
                except asyncio.TimeoutError:
                    if hb:
                        await resp.write(hb)
                    continue
            now = time.perf_counter()
            if first_unit_ms is None:
                first_unit_ms = (now - t_start) * 1e3
            elif last_write is not None:
                gap = (now - last_write) * 1e3
                if gap > max_gap_ms:
                    max_gap_ms, max_gap_end = gap, time.time()
            last_write = now
            if unit["type"] in ("done", "error"):
                terminal = unit
            try:
                await resp.write(encode(unit))
            except (ConnectionResetError, ConnectionError):
                # Client went away mid-write: the finally below cancels
                # the engine future (slot frees for fold-in;
                # gen_client_disconnects_total ticks engine-side).
                return resp
            n_units += 1
            if state.injector is not None and terminal is None:
                # Chaos on a STARTED stream (docs/ROBUSTNESS.md):
                # stream_stall wedges the writer (the reader sees
                # heartbeats dry up — the router's idle timeout owns it);
                # stream_disconnect tears the transport with NO terminal
                # event (the torn-stream shape clients must error on).
                if state.injector.fire("stream_stall", name) is not None:
                    await asyncio.sleep(_WORKER_HANG_S)
                    return resp
                if state.injector.fire("stream_disconnect",
                                       name) is not None:
                    if request.transport is not None:
                        request.transport.close()
                    return resp
    finally:
        if terminal is None:
            # Abandoned mid-stream (client disconnect, handler
            # cancellation, injected tear): cancel the engine future so
            # the slot frees for fold-in, and close the stream so a
            # blocked producer wakes. Scheduled, not awaited — this
            # finally may itself be running a cancellation.
            def _abandon():
                fut.cancel()
                stream.close()

            (state.main_loop
             or asyncio.get_running_loop()).call_soon_threadsafe(_abandon)

    # Stream health spans + recorder score (ISSUE 17 satellite): a
    # stream's slowness is first-unit latency and the largest inter-unit
    # gap — total wall time would score every long generation "slow".
    wall_end = time.time()
    if max_gap_ms > 0:
        ctx.span("stream_gap", max_gap_end - max_gap_ms / 1e3, max_gap_end,
                 tid=name, gap_ms=round(max_gap_ms, 3))
    ctx.span("stream_terminal", wall_end, wall_end, tid=name,
             type=terminal["type"],
             finish_reason=(terminal.get("finish_reason")
                            if terminal["type"] == "done"
                            else terminal.get("error")),
             units=n_units)
    resp.tpuserve_stream_score_ms = max(first_unit_ms or 0.0, max_gap_ms)
    if state.tenants is not None and tenant is not None:
        # Charge wall occupancy; the tenant latency series gets the
        # client-perceived responsiveness (first unit), not stream length.
        total_ms = (time.perf_counter() - t_start) * 1e3
        state.tenants.record(tenant, total_ms / 1e3,
                             latency_ms=first_unit_ms or total_ms)
    await resp.write_eof()
    return resp


async def handle_models(request: web.Request) -> web.Response:
    state: ServerState = request.app[STATE_KEY]
    return web.json_response({n: rt.describe() for n, rt in state.runtimes.items()})


async def handle_healthz(request: web.Request) -> web.Response:
    state: ServerState = request.app[STATE_KEY]
    if state.draining:
        return web.json_response(
            {"status": "draining", "models": state.canary_ok}, status=503)
    ok = all(state.canary_ok.values()) if state.canary_ok else True
    return web.json_response(
        {"status": "ok" if ok else "degraded", "models": state.canary_ok},
        status=200 if ok else 503,
    )


async def handle_metrics(request: web.Request) -> web.Response:
    """GET /metrics — Prometheus/OpenMetrics exposition. The body always
    ends with the OpenMetrics ``# EOF`` terminator; the Content-Type is
    negotiated from the Accept header (ISSUE 14 satellite)."""
    state: ServerState = request.app[STATE_KEY]
    ctype = exposition_content_type(request.headers.get("Accept"))
    return web.Response(
        body=state.metrics.render_prometheus().encode("utf-8"),
        headers={"Content-Type": ctype})


async def handle_history(request: web.Request) -> web.Response:
    """GET /stats/history?metric=&window_s= — time-resolved metric history
    from the telemetry rings: raw samples plus derived counter rates and
    histogram window-delta quantiles (docs/OBSERVABILITY.md "The telemetry
    plane"). Without ``metric=``, lists the recorded series names.
    ``metric=`` may be a full labeled name or a bare base name (every
    matching series is returned)."""
    state: ServerState = request.app[STATE_KEY]
    if state.store is None:
        return _err(409, "[telemetry] is disabled; no history is recorded")
    metric = request.query.get("metric")
    if not metric:
        return web.json_response({"metrics": state.store.metric_names(),
                                  **state.store.stats()})
    try:
        window_s = (float(request.query["window_s"])
                    if "window_s" in request.query else None)
        if window_s is not None and window_s <= 0:
            raise ValueError(window_s)
    except (TypeError, ValueError):
        return _err(400, "window_s must be a positive number")
    names = state.store.match(metric)
    if not names:
        return _err(404, f"no recorded series matches {metric!r} "
                         "(GET /stats/history lists the inventory)")
    series = [state.store.history(n, window_s) for n in names]
    return web.json_response(
        {"series": [s for s in series if s is not None]})


async def handle_alerts(request: web.Request) -> web.Response:
    """GET /alerts — the SLO engine's burn-rate alert states: per model
    ok/pending/firing with live burn per window. Models without a
    [model.slo] latency objective are absent; with [telemetry] disabled
    the endpoint says so instead of guessing."""
    state: ServerState = request.app[STATE_KEY]
    if state.slo is None:
        return _err(409, "[telemetry] is disabled; no SLO evaluation runs")
    return web.json_response(state.slo.alerts())


async def handle_profile(request: web.Request) -> web.Response:
    """POST /debug/profile?duration_ms= — arm a jax.profiler device trace
    for the window and answer ONE merged Chrome trace: device lanes (pids
    >= 1000) beside the span ring's serving-path events from the same
    window. 409 while a capture is already armed; device-trace
    unavailability degrades (the span half still answers), never 5xx."""
    state: ServerState = request.app[STATE_KEY]
    if state.profiler is None:
        return _err(409, "[telemetry] is disabled; profiling is not armed")
    try:
        duration_ms = float(request.query.get("duration_ms", "500"))
    except (TypeError, ValueError):
        return _err(400, "duration_ms must be a number")
    if not (1.0 <= duration_ms <= state.cfg.telemetry.profile_max_ms):
        return _err(400, f"duration_ms must be in [1, "
                         f"{state.cfg.telemetry.profile_max_ms:g}], "
                         f"got {duration_ms:g}")
    t0 = time.perf_counter()
    try:
        merged = await state.profiler.capture(duration_ms)
    except CaptureBusy:
        if state.audit is not None:
            state.audit.record("profile", "server", "busy",
                               requested_ms=duration_ms)
        return _err(409, "a profile capture is already armed "
                         "(jax.profiler is one-at-a-time)")
    if state.audit is not None:
        state.audit.record(
            "profile", "server", "ok",
            duration_ms=(time.perf_counter() - t0) * 1e3,
            requested_ms=duration_ms)
    return web.json_response(merged)


async def handle_stats(request: web.Request) -> web.Response:
    from tpuserve.parallel import process_info

    state: ServerState = request.app[STATE_KEY]
    out = state.metrics.summary()
    # Topology block (ISSUE 13 satellite): the multi-machine seam's
    # process coordinates (tpuserve.parallel.distributed.process_info —
    # rank/host facts once jax.distributed runs under a coordinator) plus
    # this process's place in the router tier when it serves as a worker.
    # This is what a future multi-machine `[router] hosts` maps onto.
    out["topology"] = {
        **process_info(),
        "worker_id": state.worker_id,
        "distributed": bool(state.cfg.distributed.coordinator_address),
    }
    # Shed/breaker state for operators (docs/ROBUSTNESS.md): what is tripped,
    # what is draining, and what chaos is armed.
    out["robustness"] = {
        "draining": state.draining,
        "breakers": {n: br.describe() for n, br in state.breakers.items()},
    }
    if state.injector is not None:
        out["robustness"]["faults"] = state.injector.snapshot()
    # Flight-recorder occupancy (docs/OBSERVABILITY.md): how many slow/
    # errored span trees are retained per model (the trees themselves live
    # at /debug/slow and /debug/trace?trace_id=).
    out["trace"] = state.recorder.stats()
    # Structured event plane (docs/OBSERVABILITY.md "The third pillar"):
    # ring occupancy + per-level/subsystem tallies, audit/postmortem
    # ledger sizes. The records themselves live at /debug/events,
    # /debug/audit, /debug/postmortems.
    if state.events is not None:
        out["events"] = {
            **state.events.stats(),
            "audit": state.audit.stats(),
            "postmortems": state.postmortems.stats(),
        }
    # Telemetry plane (docs/OBSERVABILITY.md "The telemetry plane"):
    # sampler heartbeat + ring occupancy, per-chip device utilization, and
    # profiling state. History itself lives at /stats/history, alerts at
    # /alerts.
    if state.store is not None:
        out["telemetry"] = {
            **state.store.stats(),
            "sample_interval_s": state.cfg.telemetry.sample_interval_s,
            "profile": state.profiler.stats()
            if state.profiler is not None else None,
        }
    if state.util is not None:
        util = state.util.stats()
        if util:
            out["utilization"] = util
    if state.slo is not None:
        alerts = state.slo.alerts()
        if alerts["models"]:
            out["slo"] = alerts
    if witness.enabled():
        # Observed lock-order graph + any violations (docs/ANALYSIS.md).
        out["robustness"]["lock_witness"] = witness.snapshot()
    if witness.retrace_enabled():
        # Warmup barrier + post-barrier compile ledger (docs/ANALYSIS.md).
        out["robustness"]["retrace_witness"] = witness.retrace_snapshot()
    # Versioned lifecycle state: what version is live per model, what is
    # retained for rollback, and the recent transition history.
    if state.lifecycles:
        out["lifecycle"] = {n: lc.describe()
                            for n, lc in state.lifecycles.items()}
    # Ingest fast path (ISSUE 11, docs/PERFORMANCE.md "The ingest fast
    # path"): per-accept-loop request/byte balance, malformed-frame counts,
    # and the native-decode fallback tallies (a nonzero fallback row under
    # JPEG load means the 2x-slower PIL path is serving — fix the shim).
    out["ingest"] = {
        "loops": {str(i): {"requests": ih.requests.value,
                           "bytes": ih.bytes.value}
                  for i, ih in sorted(state.ingest.items())},
        "frame_errors_total": {n: hd.frame_errors.value
                               for n, hd in state.handles.items()},
        "native_decode_fallback_total": {
            n: hd.native_fallback.value for n, hd in state.handles.items()},
    }
    # Host-pipeline state (docs/PERFORMANCE.md "Reading the metrics"):
    # per-stage executor sizes/queue depth and, per model, the in-flight
    # occupancy, staging-slot usage, and assembly-arena recycling stats.
    out["pipeline"] = {
        "stages": state.stages.stats(),
        "models": {n: b.pipeline_stats() for n, b in state.batchers.items()},
    }
    # Multi-chip serving layout + per-chip dispatch attribution
    # (docs/PERFORMANCE.md "Serving on the mesh").
    parallel = state.parallel_stats()
    if parallel:
        out["parallel"] = parallel
    # Iteration-level generation engines (docs/PERFORMANCE.md "The
    # generation engine"): slot occupancy, fold-in/early-exit/eviction
    # counts, step timing — per engine-served model.
    if state.engines:
        out["genserve"] = {n: e.pipeline_stats()
                           for n, e in state.engines.items()}
    # Fleet scheduler (docs/ROBUSTNESS.md "Fleet isolation & SLO
    # admission"): saturation, per-model paging state, device-time shares,
    # live completion predictions, and shed accounting.
    if state.scheduler is not None:
        out["scheduler"] = state.scheduler.stats()
    # Tenant containment (ISSUE 16): per-tenant envelopes + live window
    # usage; the full view (with SLO burn) is at /tenants.
    if state.tenants is not None:
        out["tenants"] = state.tenants.usage()
    # Demand-shaping layer: per-model result-cache occupancy and the
    # hit/miss/coalesced/stale accounting (docs/PERFORMANCE.md).
    if state.caches:
        out["cache"] = {n: c.stats() for n, c in state.caches.items()}
    # Compute fast path (docs/PERFORMANCE.md "Reading the roofline"):
    # resident specialized variants, lifetime compile count, per-bucket
    # raw-executable ceilings, and the compute device/host-wait split.
    roofline = state.roofline(out["latency"])
    if roofline:
        out["roofline"] = roofline
    return web.json_response(out)


async def handle_trace(request: web.Request) -> web.Response:
    """GET /debug/trace — Chrome trace JSON.

    ``?trace_id=`` pulls ONE recorded request's complete span tree from the
    flight recorder (``&format=record`` returns the raw record instead —
    the router tier stitches worker records into one cross-process trace),
    with matching structured events interleaved (``events`` key on the
    record; instant ``ph: "i"`` marks in the Chrome output — ISSUE 15).
    Without it, the span ring is dumped, bounded by ``?limit=`` (default
    5000 — an unbounded 65536-event dump built a multi-hundred-MB body on
    the event loop of a loaded server) and ``?since_us=`` (epoch µs)."""
    state: ServerState = request.app[STATE_KEY]
    trace_id = request.query.get("trace_id")
    if trace_id:
        rec = state.recorder.get(trace_id)
        if rec is None:
            return _err(404, f"trace {trace_id!r} is not in the flight "
                             "recorder (evicted or never retained)")
        events = (state.events.query(trace_id=trace_id, limit=200)
                  if state.events is not None else [])
        if request.query.get("format") == "record":
            rec = dict(rec)
            rec["events"] = events
            return web.json_response(rec)
        return web.Response(text=spans_to_chrome(rec["spans"],
                                                 events=events),
                            content_type="application/json")
    try:
        limit = int(request.query.get("limit", "5000"))
        since_us = (float(request.query["since_us"])
                    if "since_us" in request.query else None)
    except ValueError as e:
        return _err(400, f"limit/since_us must be numbers: {e}")
    if limit < 0:
        return _err(400, f"limit must be >= 0, got {limit}")
    return web.Response(
        text=state.metrics.tracer.chrome_trace(limit=limit,
                                               since_us=since_us),
        content_type="application/json")


async def handle_slow(request: web.Request) -> web.Response:
    """GET /debug/slow — the flight recorder's reservoirs: slowest-N span
    trees per model (slowest first) plus the errored-request FIFO (newest
    first). ``?model=`` filters to one model."""
    state: ServerState = request.app[STATE_KEY]
    return web.json_response(state.recorder.dump(
        model=request.query.get("model")))


async def handle_events(request: web.Request) -> web.Response:
    """GET /debug/events?since_us=&level=&subsystem=&trace_id=&limit= —
    the structured event ring (docs/OBSERVABILITY.md "The third pillar"),
    oldest-first within the newest ``limit`` matches. Junk query params
    400 (the /debug/trace hardening discipline)."""
    state: ServerState = request.app[STATE_KEY]
    if state.events is None:
        return _err(409, "[events] is disabled; no events are recorded")
    try:
        q = events_mod.parse_events_query(request.query)
    except ValueError as e:
        return _err(400, str(e))
    return web.json_response({"events": state.events.query(**q),
                              **state.events.stats()})


async def handle_postmortems(request: web.Request) -> web.Response:
    """GET /debug/postmortems — the crash-forensics ledger: one record per
    reaped process death (exit code/signal, stderr tail, last black-box
    snapshot), newest first. Populated by the supervisors behind the
    router tier; a leaf worker answers its (empty) own ledger so the
    endpoint shape is uniform across tiers."""
    state: ServerState = request.app[STATE_KEY]
    if state.postmortems is None:
        return _err(409, "[events] is disabled; no postmortems are kept")
    return web.json_response({"postmortems": state.postmortems.dump(),
                              **state.postmortems.stats()})


async def handle_audit(request: web.Request) -> web.Response:
    """GET /debug/audit — the admin audit trail: every :reload /
    :rollback / :warm / /debug/profile / drain with outcome, duration, and
    verb-specific fields, newest first."""
    state: ServerState = request.app[STATE_KEY]
    if state.audit is None:
        return _err(409, "[events] is disabled; no audit trail is kept")
    return web.json_response({"audit": state.audit.dump(),
                              **state.audit.stats()})


_INDEX_HTML = """<!doctype html><title>tpuserve</title>
<h1>tpuserve</h1>
<p>POST an image to <code>/v1/models/&lt;name&gt;:classify</code>.
See <a href="/v1/models">models</a>, <a href="/metrics">metrics</a>,
<a href="/stats">stats</a>, <a href="/healthz">health</a>.</p>
<form method=post enctype=multipart/form-data onsubmit="
  event.preventDefault();
  const f=document.getElementById('f').files[0];
  const m=document.getElementById('m').value;
  fetch('/v1/models/'+m+':predict',{method:'POST',body:f,
    headers:{'Content-Type':f.type}})
   .then(r=>r.json()).then(j=>document.getElementById('out').textContent=
     JSON.stringify(j,null,2));
">
<input type=text id=m value=resnet50> <input type=file id=f>
<button>predict</button></form><pre id=out></pre>
"""


async def handle_reload(request: web.Request) -> web.Response:
    """POST /admin/models/{name}:reload — staged, reversible weight swap.

    Lifecycle-backed (tpuserve.lifecycle): the candidate is integrity-checked
    and canaried against its STAGED params before publishing as a numbered
    version; same shapes slot into the compiled executables with zero
    recompilation. Any gate failure 409s with the failing ``stage`` and the
    old version keeps serving — including a post-publish canary failure,
    which auto-rolls back (500 + ``rolled_back: true``) instead of leaving
    bad weights live."""
    state: ServerState = request.app[STATE_KEY]
    name = request.match_info["name"]
    if name not in state.runtimes:
        return _err(404, f"unknown model {name!r}")
    lc = state.lifecycles.get(name)
    if lc is None:
        return _err(409, "weight reload is not supported in recycle mode")
    t0 = time.perf_counter()

    def _audit(outcome: str, **fields) -> None:
        if state.audit is not None:
            state.audit.record(
                "reload", name, outcome,
                duration_ms=(time.perf_counter() - t0) * 1e3, **fields)

    try:
        info = await lc.reload()
    except ReloadRejected as e:
        body = {"error": str(e), "stage": e.stage,
                "rolled_back": e.rolled_back,
                "version": state.runtimes[name].version}
        _audit("rolled_back" if e.rolled_back else "rejected",
               stage=e.stage, version=state.runtimes[name].version,
               error=str(e))
        # Pre-publish rejection = client/artifact conflict (409); a
        # post-publish rollback means the server briefly published bad
        # weights and recovered (500 so operators page on it).
        return web.json_response(body, status=500 if e.rolled_back else 409)
    except Exception as e:  # noqa: BLE001
        _audit("error", error=str(e))
        return _err(500, f"reload failed: {e}")
    _audit("ok", version=info.get("version"))
    return web.json_response(info)


async def handle_rollback(request: web.Request) -> web.Response:
    """POST /admin/models/{name}:rollback — restore version N-1 (the
    retained last-known-good tree). 409 when nothing is retained."""
    state: ServerState = request.app[STATE_KEY]
    name = request.match_info["name"]
    if name not in state.runtimes:
        return _err(404, f"unknown model {name!r}")
    lc = state.lifecycles.get(name)
    if lc is None:
        return _err(409, "versioned lifecycle is not supported in recycle mode")
    t0 = time.perf_counter()
    try:
        info = await lc.rollback(reason="manual")
    except ValueError as e:
        if state.audit is not None:
            state.audit.record(
                "rollback", name, "rejected",
                duration_ms=(time.perf_counter() - t0) * 1e3, error=str(e))
        return _err(409, str(e))
    if state.audit is not None:
        state.audit.record(
            "rollback", name, "ok",
            duration_ms=(time.perf_counter() - t0) * 1e3,
            version=info.get("version"),
            rolled_back_from=info.get("rolled_back_from"))
    return web.json_response(info)


async def handle_versions(request: web.Request) -> web.Response:
    """GET /admin/models/{name}/versions — live version, retained previous
    version, soak state, and the transition history."""
    state: ServerState = request.app[STATE_KEY]
    name = request.match_info["name"]
    if name not in state.runtimes:
        return _err(404, f"unknown model {name!r}")
    lc = state.lifecycles.get(name)
    if lc is None:
        return _err(409, "versioned lifecycle is not supported in recycle mode")
    return web.json_response(lc.describe())


async def handle_warm(request: web.Request) -> web.Response:
    """POST /admin/models/{name}:warm — stage a cold model's weights to
    live through the lifecycle path (integrity gates, variant compile,
    staged canary, atomic publish) and return once it serves. Idempotent
    on a warm model; joins any warm-up already in flight. 409 when the
    fleet scheduler is not enabled."""
    state: ServerState = request.app[STATE_KEY]
    name = request.match_info["name"]
    if name not in state.runtimes:
        return _err(404, f"unknown model {name!r}")
    if state.scheduler is None:
        return _err(409, "the fleet scheduler ([scheduler] enabled) owns "
                         "warm/cold states; it is not enabled")
    t0 = time.perf_counter()

    def _audit(outcome: str, **fields) -> None:
        if state.audit is not None:
            state.audit.record(
                "warm", name, outcome,
                duration_ms=(time.perf_counter() - t0) * 1e3, **fields)

    try:
        info = await state.scheduler.warm(name)
    except ValueError as e:
        _audit("rejected", error=str(e))
        return _err(409, str(e))
    except Exception as e:  # noqa: BLE001 — a failed warm keeps it cold
        _audit("error", error=str(e))
        return _err(500, f"warm-up failed (model stays cold): {e}")
    _audit("ok", state=info.get("state"))
    return web.json_response(info)


async def handle_demote(request: web.Request) -> web.Response:
    """POST /admin/models/{name}:demote — release a warm cold_start
    model's device params back to cold (the autopilot's warm-budget
    actuator, and an operator's manual page-out). Idempotent: demoting a
    cold (or non-cold_start) model answers 200 with demoted = false.
    409 when the fleet scheduler is not enabled."""
    state: ServerState = request.app[STATE_KEY]
    name = request.match_info["name"]
    if name not in state.runtimes:
        return _err(404, f"unknown model {name!r}")
    if state.scheduler is None:
        return _err(409, "the fleet scheduler ([scheduler] enabled) owns "
                         "warm/cold states; it is not enabled")
    t0 = time.perf_counter()
    try:
        demoted = state.scheduler.demote(name)
    except Exception as e:  # noqa: BLE001 — a failed demote keeps it warm
        if state.audit is not None:
            state.audit.record(
                "demote", name, "error",
                duration_ms=(time.perf_counter() - t0) * 1e3, error=str(e))
        return _err(500, f"demote failed (model stays warm): {e}")
    if state.audit is not None:
        state.audit.record(
            "demote", name, "ok",
            duration_ms=(time.perf_counter() - t0) * 1e3, demoted=demoted)
    return web.json_response({"model": name, "demoted": demoted})


async def handle_tenants(request: web.Request) -> web.Response:
    """GET /tenants — per-tenant containment envelopes + live window
    usage (ISSUE 16). ``?tenant=`` narrows to one tenant's row; any other
    query param is a 400 (the shared validator)."""
    state: ServerState = request.app[STATE_KEY]
    try:
        events_mod.reject_unknown_query(request.query, {"tenant"})
    except ValueError as e:
        return _err(400, str(e))
    if state.tenants is None:
        return _err(409, "[tenants] is disabled; no tenant ledger is kept")
    body = state.tenants.usage()
    if state.tenant_slo is not None:
        body["slo"] = state.tenant_slo.alerts()
    want = request.query.get("tenant")
    if want is not None:
        if want not in body["tenants"]:
            return _err(404, f"unknown tenant {want!r}")
        body["tenants"] = {want: body["tenants"][want]}
    return web.json_response(body)


async def handle_index(request: web.Request) -> web.Response:
    return web.Response(text=_INDEX_HTML, content_type="text/html")


def _err(status: int, message: str,
         retry_after: int | None = None,
         reason: str | None = None,
         trace: "TraceContext | str | None" = None) -> web.Response:
    headers: dict[str, str] = {}
    if retry_after:
        headers["Retry-After"] = str(retry_after)
    body = {"error": message}
    if reason is not None:
        # Machine-readable shed reason (obs.SCHED_SHED_REASONS): the
        # router tier relays it so its own breaker 503s can carry the
        # fleet's live shed cause.
        body["reason"] = reason
    if trace is not None:
        # Trace identity on the ERROR path (ISSUE 12 satellite): the id
        # rides both the X-Trace-Id header and the JSON body, so a user
        # report quoting a shed/504 body joins directly against the
        # flight recorder (/debug/trace?trace_id=...).
        tid = trace if isinstance(trace, str) else trace.trace_id
        body["trace_id"] = tid
        headers["X-Trace-Id"] = tid
    return web.json_response(body, status=status, headers=headers or None)


def _requested_timeout_ms(request: web.Request, body: bytes,
                          ctype: str) -> float | None:
    """Client-supplied per-request deadline: ``timeout_ms`` as a top-level
    JSON body key, a ``?timeout_ms=`` query parameter, or an
    ``X-Timeout-Ms`` header (binary bodies can't carry a JSON key). None
    when absent; ValueError (-> 400) when present but not a positive
    number. The substring guard keeps the extra JSON parse off every
    text/prompt request that doesn't use the feature."""
    raw = request.query.get("timeout_ms") or request.headers.get("X-Timeout-Ms")
    if raw is None and ctype == "application/json" and b"timeout_ms" in body:
        try:
            parsed = json.loads(body)
        except ValueError:
            return None  # model decode owns malformed-body errors
        if isinstance(parsed, dict):
            raw = parsed.get("timeout_ms")
    if raw is None:
        return None
    try:
        val = float(raw)
    except (TypeError, ValueError):
        raise ValueError(f"timeout_ms must be a number, got {raw!r}") from None
    if not math.isfinite(val) or val <= 0:
        raise ValueError(f"timeout_ms must be a positive number, got {val}")
    return val


# -- app wiring --------------------------------------------------------------

def make_app(state: ServerState, loop_index: int = 0,
             primary: bool = True) -> web.Application:
    """Build the aiohttp app for one accept loop.

    ``loop_index`` labels the per-loop ingest counters (0 = main loop).
    ``primary=False`` (a parallel ingest loop, ISSUE 11) skips the
    startup/cleanup hooks — the main app owns the ServerState lifecycle;
    ingest apps only share it. Admin and /stats handlers are wrapped so a
    request landing on an ingest loop executes on the main loop, where
    lifecycles/scheduler/batcher state lives."""
    app = web.Application(client_max_size=64 * 1024 * 1024)
    app[STATE_KEY] = state
    app[INGEST_KEY] = state.ingest_handles(loop_index)
    for verb in _VERBS:
        app.router.add_post(f"/v1/models/{{name}}:{verb}", handle_predict)
    app.router.add_get("/v1/models", handle_models)
    app.router.add_post("/admin/models/{name}:reload",
                        _main_loop_handler(handle_reload))
    app.router.add_post("/admin/models/{name}:rollback",
                        _main_loop_handler(handle_rollback))
    app.router.add_post("/admin/models/{name}:warm",
                        _main_loop_handler(handle_warm))
    app.router.add_post("/admin/models/{name}:demote",
                        _main_loop_handler(handle_demote))
    app.router.add_get("/admin/models/{name}/versions",
                       _main_loop_handler(handle_versions))
    app.router.add_get("/healthz", handle_healthz)
    app.router.add_get("/metrics", handle_metrics)
    app.router.add_get("/stats", _main_loop_handler(handle_stats))
    # Telemetry plane (ISSUE 14): history + alerts read the sampler's own
    # locked structures (safe from any loop); profiling arms process-global
    # jax.profiler state and is cheapest kept off the ingest loops.
    app.router.add_get("/stats/history", handle_history)
    app.router.add_get("/alerts", handle_alerts)
    app.router.add_post("/debug/profile", _main_loop_handler(handle_profile))
    app.router.add_get("/debug/trace", handle_trace)
    app.router.add_get("/debug/slow", handle_slow)
    # Event plane (ISSUE 15): all three read locked structures — safe from
    # any accept loop, like /debug/slow.
    app.router.add_get("/debug/events", handle_events)
    app.router.add_get("/debug/postmortems", handle_postmortems)
    app.router.add_get("/debug/audit", handle_audit)
    # Tenant containment (ISSUE 16): the ledger is locked — safe from any
    # accept loop.
    app.router.add_get("/tenants", handle_tenants)
    app.router.add_get("/", handle_index)

    if primary:
        async def on_startup(app: web.Application) -> None:
            await state.start()

        async def on_cleanup(app: web.Application) -> None:
            await state.stop()

        app.on_startup.append(on_startup)
        app.on_cleanup.append(on_cleanup)
    return app


# -- parallel ingest loops (ISSUE 11) -----------------------------------------

class IngestLoop(threading.Thread):
    """One dedicated ingest accept loop: its own thread, its own asyncio
    event loop, its own SO_REUSEPORT listener on the serving port.

    The kernel spreads incoming connections across every listener on the
    port, so HTTP parse, body reads, request decode (frame parse /
    decode_inline), and JSON response encode for this loop's connections
    never serialize on the main loop; handlers hop their submission onto
    the main loop via ``_on_main`` (one hop per request). The thread is a
    daemon: a wedged cleanup can delay exit but never hang the process."""

    def __init__(self, state: ServerState, index: int, host: str,
                 port: int) -> None:
        super().__init__(name=f"tpuserve-ingest-{index}", daemon=True)
        self.state = state
        self.index = index
        self.host = host
        self.port = port
        self.error: BaseException | None = None
        self._ready = threading.Event()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._stop_ev: asyncio.Event | None = None

    def run(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        try:
            loop.run_until_complete(self._serve())
        except BaseException as e:  # noqa: BLE001 — surfaced via wait_ready
            self.error = e
            log.exception("ingest loop %d failed", self.index)
        finally:
            self._ready.set()
            loop.close()

    async def _serve(self) -> None:
        # The witness instruments this loop too: a threading lock held
        # across an await on an ingest loop is just as much a bug here.
        witness.maybe_install()
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            sock.bind((self.host, self.port))
        except OSError:
            sock.close()
            raise
        app = make_app(self.state, loop_index=self.index, primary=False)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.SockSite(runner, sock)
        await site.start()
        self._stop_ev = asyncio.Event()
        self._ready.set()
        try:
            await self._stop_ev.wait()
        finally:
            await runner.cleanup()

    def wait_ready(self, timeout: float = 30.0) -> None:
        """Block (call from an executor) until the listener is up; re-raise
        a bind/startup failure in the caller."""
        self._ready.wait(timeout)
        if self.error is not None:
            raise self.error

    def request_stop(self) -> None:
        """Thread-safe: ask the loop to tear its listener down and exit."""
        loop, ev = self._loop, self._stop_ev
        if loop is not None and ev is not None:
            loop.call_soon_threadsafe(ev.set)


def start_ingest_loops(state: ServerState, host: str,
                       port: int) -> list[IngestLoop]:
    """Spawn the N-1 extra accept loops for ``cfg.ingest_loops = N``.

    Returns the (possibly empty) thread list; the caller must
    ``await stop_ingest_loops`` on shutdown. Degrades to zero extra loops
    with a warning where SO_REUSEPORT is unavailable — correctness never
    depends on the parallel listeners, only ingest throughput does."""
    n = max(1, state.cfg.ingest_loops)
    if n <= 1:
        return []
    if not hasattr(socket, "SO_REUSEPORT"):
        log.warning("ingest_loops = %d requested but SO_REUSEPORT is not "
                    "available on this platform; serving on one loop", n)
        return []
    threads = [IngestLoop(state, i, host, port) for i in range(1, n)]
    for t in threads:
        t.start()
    return threads


async def stop_ingest_loops(threads: list[IngestLoop]) -> None:
    """Stop + join ingest loops without blocking the calling loop."""
    loop = asyncio.get_running_loop()
    for t in threads:
        t.request_stop()
    for t in threads:
        await loop.run_in_executor(None, functools.partial(t.join, 10.0))


class JsonLogFormatter(logging.Formatter):
    """One JSON object per line: ts/level/logger/msg (+ exc when present)."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        if record.stack_info:
            out["stack"] = self.formatStack(record.stack_info)
        return json.dumps(out, ensure_ascii=False)


def configure_logging(cfg: ServerConfig) -> None:
    if cfg.log_json:
        handler = logging.StreamHandler()
        handler.setFormatter(JsonLogFormatter())
        logging.basicConfig(level=logging.INFO, handlers=[handler])
    else:
        logging.basicConfig(
            level=logging.INFO,
            format="%(asctime)s %(name)s %(levelname)s %(message)s")


async def serve_async(state: ServerState,
                      ready: asyncio.Event | None = None,
                      stop: asyncio.Event | None = None) -> None:
    """Serve until SIGTERM/SIGINT, then drain gracefully.

    Rolling restarts drop zero accepted requests: on signal the server (1)
    stops admitting — predict answers 503 + Retry-After and /healthz flips
    to "draining" so the load balancer pulls the replica; (2) flushes every
    accepted request within ``drain_timeout_s``; (3) only then tears the
    batchers/pools down (runner cleanup -> state.stop()).

    With ``cfg.ingest_loops = N > 1`` the main loop's listener binds with
    SO_REUSEPORT and N-1 dedicated ingest loops (IngestLoop threads) bind
    sibling listeners on the same port: the kernel spreads connections, so
    one asyncio accept/read loop is no longer the ingest choke point
    (docs/PERFORMANCE.md "The ingest fast path").

    ``ready`` (tests) is set once every listener is up and signal handlers
    are installed; the bound addresses land in ``state.serving_addresses``.
    ``stop`` (tests) substitutes for the signal-driven shutdown event."""
    cfg = state.cfg
    app = make_app(state)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    reuse = cfg.ingest_loops > 1 and hasattr(socket, "SO_REUSEPORT")
    site = web.TCPSite(runner, cfg.host, cfg.port, reuse_port=reuse or None)
    await site.start()
    state.serving_addresses = list(runner.addresses)
    # Parallel ingest loops bind the ACTUAL port (cfg.port may be 0 =
    # ephemeral; every SO_REUSEPORT sibling must name the bound one).
    port = cfg.port or state.serving_addresses[0][1]
    ingest_threads = start_ingest_loops(state, cfg.host, port)
    loop = asyncio.get_running_loop()
    for t in ingest_threads:
        await loop.run_in_executor(None, t.wait_ready)
    log.info("serving on %s (%d accept loop(s))", state.serving_addresses,
             1 + len(ingest_threads))

    if stop is None:
        stop = asyncio.Event()
    installed: list[signal.Signals] = []
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
            installed.append(sig)
        except (NotImplementedError, RuntimeError):
            pass  # non-main thread / platform without signal support
    if ready is not None:
        ready.set()
    try:
        await stop.wait()
        log.info("shutdown signal: draining (budget %.0fs)", cfg.drain_timeout_s)
        drained = await state.drain()
        if not drained:
            log.warning("drain budget expired with requests still in flight")
    finally:
        for sig in installed:
            loop.remove_signal_handler(sig)
        # Ingest listeners go first: no accept loop may outlive the state
        # teardown below (their handlers hop onto this loop's structures).
        await stop_ingest_loops(ingest_threads)
        await runner.cleanup()  # on_cleanup -> state.stop()


def serve(cfg: ServerConfig) -> None:
    """Blocking entry point: build models, compile, serve."""
    configure_logging(cfg)
    # Multi-host: must happen before ServerState.build() touches a device —
    # backend init freezes the process's view of the topology.
    from tpuserve.parallel import init_distributed

    init_distributed(cfg.distributed)
    state = ServerState(cfg)
    state.build()
    asyncio.run(serve_async(state))
