"""Parallelism layer (SURVEY.md §2 C7, §2.1).

TPU-native parallelism is expressed through ``jax.sharding``: a ``Mesh`` over
the device grid, ``NamedSharding``/``PartitionSpec`` annotations on inputs,
params, and outputs, and XLA-inserted collectives riding ICI. There is no
user-managed NCCL/MPI backend to configure — the communication backend IS the
sharding layout (SURVEY.md §5 "Distributed communication backend").

Submodules:

- ``mesh``        — mesh construction (dp/tp/sp axes, host-major multi-host grid)
- ``partition``   — regex partition rules -> PartitionSpec pytrees
- ``distributed`` — jax.distributed.initialize seam for multi-host pods
- ``pipeline``    — GPipe-style pipeline parallelism over a "stage" axis
                    (stage-sharded stacked params, ppermute microbatch flow)

Sequence parallelism for long contexts lives at the op level:
``tpuserve.ops.ring_attention`` (shard_map + ppermute over the "seq" axis)
and ``tpuserve.ops.ulysses`` (head all-to-all).
"""

from tpuserve.parallel.distributed import init_distributed, process_info  # noqa: F401
from tpuserve.parallel.mesh import (  # noqa: F401
    MeshPlan,
    axis_size,
    can_shard,
    host_major_grid,
    make_mesh,
    batch_sharding,
    replicated_sharding,
    local_device_count,
    plan_for,
    select_devices,
)
from tpuserve.parallel.pipeline import (  # noqa: F401
    make_stage_mesh,
    pipeline_forward,
    stack_stage_params,
)
from tpuserve.parallel.partition import (  # noqa: F401
    match_partition_rules,
    named_leaves,
    shard_pytree,
    struct_shardings,
)
