"""Pipeline parallelism over a mesh "stage" axis (SURVEY.md §2.1 PP seam).

For models whose layer stack exceeds one device's memory, the remaining
partitioning axis after dp/tp/sp is DEPTH: split the stack into S equal
stages, one per device along a ``"stage"`` mesh axis, and stream
microbatches through GPipe-style. TPU-native realization:

- Stage parameters are a STACKED pytree — every leaf gains a leading
  ``(S, ...)`` dim sharded on the stage axis, so each device materializes
  only its own stage's weights (the point of PP: S-fold parameter memory).
- The schedule is one ``lax.scan`` over ``n_micro + S - 1`` ticks inside
  ``shard_map``: each tick, every stage ``ppermute``s its previous output to
  the next stage (nearest-neighbor ICI traffic, like the ring-attention
  rotation), then runs the stage function on what arrived — stage 0 feeds
  the next microbatch instead. The pipeline bubble is the standard
  ``(S - 1) / (n_micro + S - 1)`` fraction; raise ``n_micro`` to amortize.
- Outputs: only the last stage produces real results; a ``psum`` over the
  stage axis replicates them (fine at completed-activation sizes; a
  production variant for huge outputs would keep them stage-sharded).

``stage_fn`` must be shape/dtype-preserving — the homogeneous-transformer
case where depth splits into equal-shaped chunks, which is when PP applies.

SURVEY.md §2.1 scoped PP out of the v1 critical path because every judged
config fits one v5e core; this makes the seam real (compiled and executed
on the fake-device mesh in CI) for the models that don't.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from tpuserve.utils.compat import pcast_varying, shard_map
from jax.sharding import Mesh, PartitionSpec as P

STAGE_AXIS = "stage"


def make_stage_mesh(n_stages: int, devices: list | None = None) -> Mesh:
    """A 1-D ("stage",) mesh over the first n_stages devices."""
    devices = devices if devices is not None else jax.devices()
    if len(devices) < n_stages:
        raise ValueError(f"need {n_stages} devices, have {len(devices)}")
    grid = np.empty(n_stages, dtype=object)
    grid[:] = devices[:n_stages]
    return Mesh(grid, (STAGE_AXIS,))


def stack_stage_params(per_stage: list[Any]) -> Any:
    """[stage0_tree, stage1_tree, ...] -> one tree with leading (S, ...) leaves.

    All stages must share one tree structure (same block architecture).
    """
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage)


def _pp_body(params: Any, xs: jax.Array, *, stage_fn: Callable,
             n_stages: int, n_micro: int, axis_name: str) -> jax.Array:
    """Per-device GPipe schedule: my stage, every tick."""
    s = jax.lax.axis_index(axis_name)
    params = jax.tree_util.tree_map(lambda x: x[0], params)  # strip stage dim
    send_perm = [(i, i + 1) for i in range(n_stages - 1)]
    mb_shape = xs.shape[1:]

    def tick(prev_out, t):
        # What I computed last tick moves one stage down the line.
        recv = jax.lax.ppermute(prev_out, axis_name, send_perm)
        # Stage 0 feeds microbatch t; stage s>0 works on what arrived
        # (microbatch t - s, by induction).
        x0 = jax.lax.dynamic_index_in_dim(
            xs, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False)
        x_in = jnp.where(s == 0, x0, recv)
        y = stage_fn(params, x_in)
        # Idle ticks (pipeline fill/drain) must not leak garbage downstream.
        active = (t >= s) & (t < s + n_micro)
        y = jnp.where(active, y, jnp.zeros_like(y))
        out = jnp.where(active & (s == n_stages - 1), y,
                        jnp.zeros(mb_shape, y.dtype))
        return y, out

    # pcast: the zero init must carry the same varying-over-stage type the
    # loop outputs have (cf. the ring-attention scan carries).
    init = pcast_varying(jnp.zeros(mb_shape, xs.dtype), (axis_name,))
    _, outs = jax.lax.scan(tick, init, jnp.arange(n_micro + n_stages - 1))
    # Only the last stage contributed non-zeros; replicate its results.
    outs = jax.lax.psum(outs, axis_name)
    # Microbatch j completes at tick j + (S - 1).
    return outs[n_stages - 1:]


def pipeline_forward(stage_fn: Callable, stacked_params: Any, xs: jax.Array,
                     mesh: Mesh, axis_name: str = STAGE_AXIS) -> jax.Array:
    """Pipelined application of S stacked stages to microbatched input.

    Args:
      stage_fn: ``(stage_params, x) -> y`` with ``y.shape == x.shape`` and
        the same dtype (one stage's slice of a homogeneous layer stack).
      stacked_params: pytree whose leaves have leading dim S (see
        ``stack_stage_params``), sharded/shardable on ``axis_name``.
      xs: ``(n_micro, microbatch, ...)`` input microbatches.
      mesh: mesh containing ``axis_name`` of size S.

    Returns ``(n_micro, microbatch, ...)`` outputs, replicated.
    """
    n_stages = mesh.shape[axis_name]
    n_micro = int(xs.shape[0])
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            # An exact multiple would shard silently and run only every
            # k-th stage; make any mismatch loud.
            raise ValueError(
                f"stacked params have {leaf.shape[0]} stages but the "
                f"{axis_name!r} axis has {n_stages} devices")
    param_specs = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    body = partial(_pp_body, stage_fn=stage_fn, n_stages=n_stages,
                   n_micro=n_micro, axis_name=axis_name)
    fn = shard_map(body, mesh=mesh,
                   in_specs=(param_specs, P()), out_specs=P())
    return fn(stacked_params, xs)
