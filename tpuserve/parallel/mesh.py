"""Mesh construction over TPU devices (SURVEY.md §2.1).

Axis conventions used throughout tpuserve:

- ``"data"``  — data parallel: batches sharded across it, params replicated.
- ``"model"`` — tensor parallel: weight matrices sharded across it.
- ``"seq"``   — sequence/context parallel (ring attention) for long inputs.

An inference mesh is usually ``("data",)`` or ``("data", "model")``; the
training step used by the multi-chip dry run adds ``"seq"``. The same code
path handles 1 local core (dev box), 8 cores (v5e-8), and — via
``jax.distributed`` — multi-host slices: the mesh is always built from
``jax.devices()``, never hard-coded counts (SURVEY.md §7 hard part 7).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"


def local_device_count() -> int:
    return len(jax.devices())


@dataclass(frozen=True)
class MeshPlan:
    """How to carve the device grid into named axes."""

    dp: int = -1  # -1 = "everything not claimed by other axes"
    tp: int = 1
    sp: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int, int]:
        tp, sp = self.tp, self.sp
        if n_devices % (tp * sp) != 0:
            raise ValueError(f"{n_devices} devices not divisible by tp*sp={tp * sp}")
        dp = self.dp if self.dp != -1 else n_devices // (tp * sp)
        if dp * tp * sp != n_devices:
            raise ValueError(f"dp*tp*sp={dp * tp * sp} != device count {n_devices}")
        return dp, tp, sp


def make_mesh(plan: MeshPlan | None = None, devices: list | None = None) -> Mesh:
    """Build a Mesh with axes (data, model[, seq]).

    Axes of size 1 for model/seq are still materialized so PartitionSpecs
    mentioning them remain valid regardless of configuration; XLA treats a
    size-1 axis as free.
    """
    plan = plan or MeshPlan()
    devices = devices if devices is not None else jax.devices()
    dp, tp, sp = plan.resolve(len(devices))
    grid = np.asarray(devices).reshape(dp, tp, sp)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS, SEQ_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Inputs/outputs: shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Params (DP mode): fully replicated."""
    return NamedSharding(mesh, P())


def pad_batch_to_mesh(batch_size: int, mesh: Mesh) -> int:
    """Smallest batch >= batch_size divisible by the data-axis size."""
    d = mesh.shape[DATA_AXIS]
    return ((batch_size + d - 1) // d) * d
