"""Mesh construction over TPU devices (SURVEY.md §2.1).

Axis conventions used throughout tpuserve:

- ``"data"``  — data parallel: batches sharded across it, params replicated.
- ``"model"`` — tensor parallel: weight matrices sharded across it.
- ``"seq"``   — sequence/context parallel (ring attention) for long inputs.

An inference mesh is usually ``("data",)`` or ``("data", "model")``; the
training step used by the multi-chip dry run adds ``"seq"``. The same code
path handles 1 local core (dev box), 8 cores (v5e-8), and — via
``jax.distributed`` — multi-host slices: the mesh is always built from
``jax.devices()``, never hard-coded counts (SURVEY.md §7 hard part 7).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"


def local_device_count() -> int:
    return len(jax.devices())


@dataclass(frozen=True)
class MeshPlan:
    """How to carve the device grid into named axes."""

    dp: int = -1  # -1 = "everything not claimed by other axes"
    tp: int = 1
    sp: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int, int]:
        tp, sp = self.tp, self.sp
        if n_devices % (tp * sp) != 0:
            raise ValueError(f"{n_devices} devices not divisible by tp*sp={tp * sp}")
        dp = self.dp if self.dp != -1 else n_devices // (tp * sp)
        if dp * tp * sp != n_devices:
            raise ValueError(f"dp*tp*sp={dp * tp * sp} != device count {n_devices}")
        return dp, tp, sp


def host_major_grid(devices: list, dp: int, tp: int, sp: int) -> np.ndarray:
    """(dp, tp, sp) device grid with every (tp, sp) block inside one host.

    Multi-host layout rule (SURVEY.md §5 "Distributed comm backend"): the
    data axis is host-major — hosts ordered by ``process_index``, each host's
    devices filling whole dp rows — so tensor- and sequence-parallel
    collectives stay on a host's ICI domain and only data-parallel traffic
    crosses DCN. Single-host input (all ``process_index`` equal) reduces to a
    plain reshape, preserving device order.
    """
    hosts: dict[int, list] = {}
    for d in devices:
        hosts.setdefault(getattr(d, "process_index", 0), []).append(d)
    counts = {len(v) for v in hosts.values()}
    if len(counts) != 1:
        raise ValueError("hosts contribute unequal device counts: "
                         f"{ {h: len(v) for h, v in sorted(hosts.items())} }")
    if counts.pop() % (tp * sp) != 0:
        raise ValueError(
            f"tp*sp={tp * sp} must divide each host's device count "
            f"({len(devices) // len(hosts)}): tensor/sequence axes must not "
            "cross DCN")
    ordered = [d for _, host in sorted(hosts.items()) for d in host]
    grid = np.empty(len(ordered), dtype=object)
    grid[:] = ordered
    return grid.reshape(dp, tp, sp)


def make_mesh(plan: MeshPlan | None = None, devices: list | None = None) -> Mesh:
    """Build a Mesh with axes (data, model[, seq]).

    Axes of size 1 for model/seq are still materialized so PartitionSpecs
    mentioning them remain valid regardless of configuration; XLA treats a
    size-1 axis as free. Works unchanged from 1 local chip to a multi-host
    pod: the grid is host-major (see ``host_major_grid``), which for a
    single host is the identity layout.
    """
    plan = plan or MeshPlan()
    devices = devices if devices is not None else jax.devices()
    dp, tp, sp = plan.resolve(len(devices))
    return Mesh(host_major_grid(devices, dp, tp, sp),
                (DATA_AXIS, MODEL_AXIS, SEQ_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Inputs/outputs: shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Params (DP mode): fully replicated."""
    return NamedSharding(mesh, P())


def pad_batch_to_mesh(batch_size: int, mesh: Mesh) -> int:
    """Smallest batch >= batch_size divisible by the data-axis size."""
    d = mesh.shape[DATA_AXIS]
    return ((batch_size + d - 1) // d) * d


def axis_size(mesh: Mesh, axis: str) -> int:
    """Size of a named mesh axis (1 when the axis is free)."""
    return int(mesh.shape.get(axis, 1))


def can_shard(mesh: Mesh, axis: str, dim: int) -> bool:
    """True when ``dim`` divides evenly over a >1-sized mesh axis — the
    gate generative state specs apply before pinning a heads/pages dim to
    an axis, so a layout that doesn't divide falls back to replication
    instead of an XLA error."""
    n = axis_size(mesh, axis)
    return n > 1 and dim % n == 0


def select_devices(n_chips: int = 0, devices: list | None = None) -> list:
    """The device set a ``[parallel]`` plan serves on.

    ``n_chips = 0`` takes every visible device; a positive count takes the
    first ``n_chips`` (stable ``jax.devices()`` order, so replica indices
    in metrics map to the same physical chips across restarts). Asking for
    more devices than exist is a configuration error, not a silent clamp —
    a deployment that believes it serves on 8 chips must never quietly run
    on 1 (SURVEY.md §7 hard part 7: never hard-code counts, never lie
    about them either)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_chips <= 0:
        return devs
    if n_chips > len(devs):
        raise ValueError(
            f"parallel.n_chips={n_chips} but only {len(devs)} device(s) "
            "visible")
    return devs[:n_chips]


def plan_for(parallel: "object", tp: int = 1, sp: int = 1) -> MeshPlan:
    """MeshPlan for a sharded-batch serving mesh from a ``[parallel]``
    block (config.ParallelConfig): an explicit ``data`` pins the data-axis
    size, otherwise it derives from whatever device count ``select_devices``
    returned (dp = -1)."""
    data = int(getattr(parallel, "data", 0) or 0)
    return MeshPlan(dp=data if data > 0 else -1, tp=tp, sp=sp)
