"""Multi-host runtime initialization (SURVEY.md §5 "Distributed comm backend").

There is no user-managed collective backend on TPU — no NCCL/MPI/Gloo to
configure. Cross-chip traffic is XLA collectives over ICI; cross-host traffic
rides DCN, and the only runtime plumbing a multi-host deployment needs is
``jax.distributed.initialize`` so every process sees the global device set
and compiles identical SPMD programs. This module is that seam:

- ``init_distributed(cfg)`` — call ONCE, before any other JAX API touches a
  device (backend init freezes the topology). No-op unless
  ``DistributedConfig.coordinator_address`` is set, so single-host serving
  (the dev box, CI) never pays anything.
- ``process_info()`` — rank/host facts for /stats and logs.

Mesh layout for the multi-host case lives in ``tpuserve.parallel.mesh``: the
data axis is host-major (consecutive global batch shards stay on one host's
chips; DP gradient/collective hops cross DCN only between host blocks) and
tensor/sequence axes never leave a host's ICI domain.
"""

from __future__ import annotations

import logging

import jax

from tpuserve.config import DistributedConfig

log = logging.getLogger("tpuserve.distributed")


def init_distributed(cfg: DistributedConfig) -> bool:
    """Initialize the multi-process JAX runtime if configured.

    Returns True when ``jax.distributed.initialize`` was called. Must run
    before the first device-touching JAX call in the process; ``serve()``
    honors that ordering.
    """
    if not cfg.coordinator_address:
        return False
    kwargs: dict = {"coordinator_address": cfg.coordinator_address}
    # -1 means "let jax read the cluster environment" (TPU metadata, SLURM,
    # etc.) — only pin what the config explicitly sets.
    if cfg.num_processes >= 0:
        kwargs["num_processes"] = cfg.num_processes
    if cfg.process_id >= 0:
        kwargs["process_id"] = cfg.process_id
    jax.distributed.initialize(**kwargs)
    log.info("distributed runtime up: process %d/%d, %d global / %d local devices",
             jax.process_index(), jax.process_count(),
             len(jax.devices()), len(jax.local_devices()))
    return True


def process_info() -> dict:
    """Rank/topology facts for logs and the /stats ``topology`` block.

    ``process_index``/``process_count`` are this process's coordinates in
    the jax.distributed cluster (0/1 single-host); the device counts split
    what this process can SEE (global) from what it OWNS (local). Behind
    the router tier every worker serves this from its own /stats, so the
    host-domain layout and the device topology are inspectable side by
    side (ISSUE 13)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
        "platform": jax.devices()[0].platform,
    }
