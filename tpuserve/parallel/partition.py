"""Regex partition rules -> PartitionSpec pytrees (SURVEY.md §2 C7).

Pattern: a model family publishes an ordered list of ``(regex, PartitionSpec)``
rules; each param leaf's '/'-joined path is matched against the rules in order
and the first hit wins. This is the standard public-JAX idiom for assigning
shardings to large param trees (cf. SNIPPETS.md snippet [3], pattern only) and
replaces per-layer hand annotation.

Scalars and size-1 leaves are never partitioned. A final catch-all rule
(e.g. ``(".*", P())``) is recommended; without one, unmatched leaves raise.
"""

from __future__ import annotations

import re
from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _join_path(path, sep: str) -> str:
    parts = []
    for k in path:
        if isinstance(k, jax.tree_util.DictKey):
            parts.append(str(k.key))
        elif isinstance(k, jax.tree_util.SequenceKey):
            parts.append(str(k.idx))
        elif isinstance(k, jax.tree_util.GetAttrKey):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return sep.join(parts)


def named_leaves(tree: Any, sep: str = "/") -> list[tuple[str, Any]]:
    """Flatten a pytree into (path, leaf) pairs with readable '/' paths."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(_join_path(path, sep), leaf) for path, leaf in flat]


def tree_map_with_name(fn: Callable[[str, Any], Any], tree: Any, sep: str = "/") -> Any:
    """tree_map where fn also receives the '/'-joined path of each leaf."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    mapped = [fn(_join_path(path, sep), leaf) for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, mapped)


def spec_for_name(rules: list[tuple[str, P]], name: str, shape: tuple) -> P:
    """First rule whose regex matches `name` wins; scalars/size-1 replicate."""
    if len(shape) == 0 or int(np.prod(shape)) == 1:
        return P()
    for rule, spec in rules:
        if re.search(rule, name) is not None:
            return spec
    raise ValueError(f"no partition rule matched param {name!r}")


def match_partition_rules(rules: list[tuple[str, P]], params: Any) -> Any:
    """Return a pytree of PartitionSpec following ordered regex rules."""
    return tree_map_with_name(
        lambda name, leaf: spec_for_name(rules, name, getattr(leaf, "shape", ())),
        params)


def specs_to_shardings(specs: Any, mesh: Mesh) -> Any:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_pytree(params: Any, rules: list[tuple[str, P]], mesh: Mesh) -> Any:
    """Device-put every leaf with its rule-derived NamedSharding."""
    specs = match_partition_rules(rules, params)
    shardings = specs_to_shardings(specs, mesh)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)


def struct_shardings(mesh: Mesh, struct: Any, specs: Any = None) -> Any:
    """Shardings for a program-argument struct tree.

    ``specs=None`` replicates every leaf (the default for generative
    program arguments — slot indices, token blocks). A PartitionSpec tree
    pins leaves to axes (the sharded-decode state block puts KV heads on
    "model" and pages on "seq"); it may be a pytree prefix of ``struct``,
    which jax.jit broadcasts over the matching subtree.
    """
    if specs is None:
        repl = NamedSharding(mesh, P())
        return jax.tree_util.tree_map(lambda _s: repl, struct)
    return specs_to_shardings(specs, mesh)


# A catch-all: replicate everything (correct default for DP inference).
REPLICATED_RULES: list[tuple[str, P]] = [(".*", P())]
