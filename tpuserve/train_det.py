"""EfficientDet fine-tune path: produce a FULL detector checkpoint in-framework
(SURVEY.md §2 C6; VERDICT r3 next 2).

The only TF EfficientDet artifact importable in this environment is the
EfficientNet-B0 *backbone* (a classification checkpoint —
``EfficientDetServing.import_tf_variables``); BiFPN and the heads have no
published TF-executable counterpart here. This module closes the gap the
standard way detection models are deployed anyway: transfer-learn from the
imported backbone, fine-tune the whole detector on labeled boxes, and write
a full orbax checkpoint that serves end-to-end via ``weights = <ckpt>``.

Design (TPU-first, mirrors tpuserve.train's LM step):

- **Anchor matching on device, static shapes**: ground truth arrives padded
  to ``max_boxes`` per image with a valid mask; IoU matching, target
  encoding (the exact inverse of ``efficientdet.decode_boxes``), focal and
  Huber losses are all jittable with no data-dependent shapes, so the whole
  train step is ONE XLA executable sharded over the mesh "data" axis.
- RetinaNet-style assignment: IoU >= ``pos_iou`` positive, < ``neg_iou``
  background, in between ignored (zero loss weight).
- Sigmoid focal loss (alpha 0.25, gamma 1.5 — the EfficientDet paper's
  values) normalized by positive count; Huber box loss on positives.
- BatchNorm statistics stay frozen (``use_running_average=True`` in the
  modules): standard practice for short fine-tunes and it keeps the serving
  and training graphs identical.

Synthetic-data mode (no labeled datasets exist in this container) draws
colored rectangles on noise and asks the detector to find them — a real
learnable task that exercises the full loss surface; pass an ``.npz`` with
``images``/``boxes``/``classes``/``valid`` arrays for real data.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass
class DetTrainConfig:
    lr: float = 1e-3
    weight_decay: float = 1e-4
    focal_alpha: float = 0.25
    focal_gamma: float = 1.5
    box_weight: float = 50.0
    huber_delta: float = 0.1
    max_boxes: int = 16
    pos_iou: float = 0.5
    neg_iou: float = 0.4


# -- device-side target assignment -------------------------------------------

def _center_to_corners(a: jax.Array) -> jax.Array:
    yc, xc, h, w = a[:, 0], a[:, 1], a[:, 2], a[:, 3]
    return jnp.stack([yc - h / 2, xc - w / 2, yc + h / 2, xc + w / 2], axis=-1)


def _iou_matrix(anchors_c: jax.Array, boxes: jax.Array) -> jax.Array:
    """(A, 4) corners x (M, 4) corners -> (A, M) IoU."""
    area_a = jnp.maximum(anchors_c[:, 2] - anchors_c[:, 0], 0) * jnp.maximum(
        anchors_c[:, 3] - anchors_c[:, 1], 0)
    area_b = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * jnp.maximum(
        boxes[:, 3] - boxes[:, 1], 0)
    lt = jnp.maximum(anchors_c[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(anchors_c[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def encode_boxes(boxes: jax.Array, anchors: jax.Array) -> jax.Array:
    """Corner GT boxes -> [ty, tx, th, tw] regression targets: the exact
    inverse of ``efficientdet.decode_boxes`` (in pixels, un-normalized)."""
    yc = (boxes[:, 0] + boxes[:, 2]) / 2
    xc = (boxes[:, 1] + boxes[:, 3]) / 2
    h = jnp.maximum(boxes[:, 2] - boxes[:, 0], 1e-3)
    w = jnp.maximum(boxes[:, 3] - boxes[:, 1], 1e-3)
    return jnp.stack([
        (yc - anchors[:, 0]) / anchors[:, 2],
        (xc - anchors[:, 1]) / anchors[:, 3],
        jnp.log(h / anchors[:, 2]),
        jnp.log(w / anchors[:, 3]),
    ], axis=-1)


def match_anchors(anchors: jax.Array, boxes: jax.Array, classes: jax.Array,
                  valid: jax.Array, num_classes: int,
                  pos_iou: float, neg_iou: float):
    """Per-image static-shape target assignment.

    anchors (A, 4) center-size pixels; boxes (M, 4) corner pixels;
    classes (M,) int32; valid (M,) bool mask for padded GT slots.
    Returns cls_target (A, C), cls_weight (A,), box_target (A, 4),
    box_weight (A,).
    """
    anchors_c = _center_to_corners(anchors)
    iou = _iou_matrix(anchors_c, boxes) * valid[None, :].astype(jnp.float32)
    best_iou = jnp.max(iou, axis=1, initial=0.0)
    best_gt = jnp.argmax(iou, axis=1)
    pos = best_iou >= pos_iou
    neg = best_iou < neg_iou
    # Force-match: every valid GT claims its single best anchor even below
    # pos_iou, so no labeled box is unsupervised (standard RetinaNet detail).
    # Padded GT slots are routed to an out-of-range index and dropped — their
    # argmax degenerates to anchor 0 and a plain scatter would clobber a real
    # GT's claim there (duplicate-index .at[].set ordering is undefined).
    # Deterministic tie-break when two valid GTs share a best anchor: both
    # scatters take the max, so the highest GT index wins consistently.
    a_star = jnp.where(valid, jnp.argmax(iou, axis=0), anchors.shape[0])
    forced = jnp.zeros(anchors.shape[0], bool).at[a_star].max(
        valid, mode="drop")
    forced_gt = jnp.zeros(anchors.shape[0], jnp.int32).at[a_star].max(
        jnp.arange(boxes.shape[0], dtype=jnp.int32), mode="drop")
    pos = pos | forced
    best_gt = jnp.where(forced & (best_iou < pos_iou), forced_gt, best_gt)

    cls_of = classes[best_gt]
    cls_target = jax.nn.one_hot(cls_of, num_classes) * pos[:, None]
    cls_weight = (pos | neg).astype(jnp.float32)
    box_target = encode_boxes(boxes[best_gt], anchors)
    return cls_target, cls_weight, box_target, pos.astype(jnp.float32)


# -- losses -------------------------------------------------------------------

def focal_loss(logits, targets, weight, alpha, gamma):
    """Sigmoid focal CE, summed; (B, A, C) logits vs one-hot targets."""
    p = jax.nn.sigmoid(logits)
    ce = optax.sigmoid_binary_cross_entropy(logits, targets)
    p_t = p * targets + (1 - p) * (1 - targets)
    a_t = alpha * targets + (1 - alpha) * (1 - targets)
    return jnp.sum(a_t * ((1 - p_t) ** gamma) * ce * weight[..., None])


def det_loss_fn(serving, params, batch, tcfg: DetTrainConfig):
    """Full detector loss for a padded batch dict (jittable)."""
    x = serving.prepare_batch(batch["images"])
    cls_logits, box_reg = serving.module.apply(params, x)
    cls_logits = cls_logits.astype(jnp.float32)
    box_reg = box_reg.astype(jnp.float32)

    match = jax.vmap(partial(
        match_anchors, serving.anchors, num_classes=serving.det_classes,
        pos_iou=tcfg.pos_iou, neg_iou=tcfg.neg_iou))
    cls_t, cls_w, box_t, box_w = match(
        batch["boxes"], batch["classes"], batch["valid"])

    n_pos = jnp.maximum(jnp.sum(box_w), 1.0)
    cls_loss = focal_loss(cls_logits, cls_t, cls_w,
                          tcfg.focal_alpha, tcfg.focal_gamma) / n_pos
    huber = optax.huber_loss(box_reg, box_t, delta=tcfg.huber_delta)
    box_loss = jnp.sum(huber * box_w[..., None]) / n_pos
    return cls_loss + tcfg.box_weight * box_loss


# -- train state / step -------------------------------------------------------

def make_det_train_state(serving, mesh: Mesh, tcfg: DetTrainConfig):
    """Params from serving.load_params() (backbone import happens there when
    cfg.weights points at an EfficientNet checkpoint); replicated over the
    mesh; adamw over the "params" collection only (batch_stats frozen)."""
    params = serving.load_params()
    replicated = NamedSharding(mesh, P())
    params = jax.device_put(params, replicated)
    tx = optax.adamw(tcfg.lr, weight_decay=tcfg.weight_decay)
    opt_state = tx.init(params["params"])
    return params, tx, opt_state


def make_det_train_step(serving, tx, mesh: Mesh, tcfg: DetTrainConfig):
    replicated = NamedSharding(mesh, P())
    batch_sharding = {
        "images": NamedSharding(mesh, P("data")),
        "boxes": NamedSharding(mesh, P("data")),
        "classes": NamedSharding(mesh, P("data")),
        "valid": NamedSharding(mesh, P("data")),
    }

    def step(params, opt_state, batch):
        def loss_of(trainable):
            full = dict(params)
            full["params"] = trainable
            return det_loss_fn(serving, full, batch, tcfg)

        loss, grads = jax.value_and_grad(loss_of)(params["params"])
        updates, opt_state = tx.update(grads, opt_state, params["params"])
        new_params = dict(params)
        new_params["params"] = optax.apply_updates(params["params"], updates)
        return new_params, opt_state, loss

    return jax.jit(  # tps-ok[TPS501,TPS505]: setup-time factory, jitted once per run
        step,
        in_shardings=(replicated, None, batch_sharding),
        out_shardings=(replicated, None, None),
        donate_argnums=(0, 1),
    ), batch_sharding


# -- data ---------------------------------------------------------------------

def synthetic_det_batch(batch_size: int, wire: int, image_size: int,
                        num_classes: int, max_boxes: int, seed: int = 0) -> dict:
    """Colored rectangles on noise: class = color index. Box coords are in
    MODEL pixels (image_size), images at the wire shape — matching serving,
    where the host ships wire-sized uint8 and the device resizes."""
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 64, (batch_size, wire, wire, 3), np.uint8)
    boxes = np.zeros((batch_size, max_boxes, 4), np.float32)
    classes = np.zeros((batch_size, max_boxes), np.int32)
    valid = np.zeros((batch_size, max_boxes), bool)
    palette = np.linspace(96, 255, max(num_classes, 2)).astype(np.uint8)
    for b in range(batch_size):
        for m in range(rng.integers(1, min(3, max_boxes) + 1)):
            c = int(rng.integers(0, num_classes))
            h = int(rng.integers(wire // 4, wire // 2))
            w = int(rng.integers(wire // 4, wire // 2))
            y0 = int(rng.integers(0, wire - h))
            x0 = int(rng.integers(0, wire - w))
            images[b, y0:y0 + h, x0:x0 + w] = palette[c]
            scale = image_size / wire
            boxes[b, m] = (y0 * scale, x0 * scale,
                           (y0 + h) * scale, (x0 + w) * scale)
            classes[b, m] = c
            valid[b, m] = True
    return {"images": images, "boxes": boxes, "classes": classes,
            "valid": valid}


def load_npz_dataset(path: str) -> dict:
    """User data: .npz with images (N,E,E,3 u8), boxes (N,M,4 f32, model-pixel
    corners), classes (N,M i32), valid (N,M bool)."""
    z = np.load(path)
    need = {"images", "boxes", "classes", "valid"}
    missing = need - set(z.files)
    if missing:
        raise ValueError(f"npz dataset missing arrays: {sorted(missing)}")
    return {k: z[k] for k in need}


# -- entry point --------------------------------------------------------------

def finetune_detector(cfg, out_path: str, steps: int = 50, batch_size: int = 8,
                      tcfg: DetTrainConfig | None = None,
                      dataset: str | None = None, log_every: int = 10,
                      mesh: Mesh | None = None) -> float:
    """Fine-tune the detector and write a full orbax checkpoint to out_path.

    cfg: an EfficientDet ModelConfig; cfg.weights may point at an
    EfficientNet-B0 classification checkpoint (backbone transfer) or be
    unset (from-scratch tiny runs/tests). Returns the final loss.
    """
    from tpuserve import savedmodel
    from tpuserve.models import build
    from tpuserve.parallel import make_mesh

    tcfg = tcfg or DetTrainConfig()
    if cfg.wire_format != "rgb8":
        # prepare_batch would try to unpack YUV plane tuples from the single
        # (B, E, E, 3) training array — crash or silent garbage training.
        raise ValueError(
            "finetune_detector trains on rgb8 wire batches; set "
            'wire_format = "rgb8" for training (the serving config can still '
            "use yuv420 — weights are wire-format independent)")
    serving = build(cfg)
    mesh = mesh or make_mesh()
    # Batch shards over the mesh "data" axis; round up so it divides.
    d = int(mesh.shape["data"])
    batch_size = max(d, -(-batch_size // d) * d)
    params, tx, opt_state = make_det_train_state(serving, mesh, tcfg)
    step, _ = make_det_train_step(serving, tx, mesh, tcfg)

    data = load_npz_dataset(dataset) if dataset else None
    n = data["images"].shape[0] if data else 0
    loss = float("nan")
    for i in range(steps):
        if data:
            idx = np.random.default_rng(i).integers(0, n, batch_size)
            batch = {k: v[idx] for k, v in data.items()}
        else:
            batch = synthetic_det_batch(
                batch_size, cfg.wire_size, cfg.image_size,
                serving.det_classes, tcfg.max_boxes, seed=i)
        params, opt_state, loss = step(params, opt_state, batch)
        if log_every and (i + 1) % log_every == 0:
            print(f"# det finetune step {i + 1}/{steps}: loss {float(loss):.4f}")
    savedmodel.save_orbax(out_path, jax.device_get(params))
    return float(loss)
