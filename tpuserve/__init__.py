"""tpuserve — a TPU-native HTTP inference-serving framework.

A ground-up rebuild of the capabilities of ``zyin3/tensorflow_web_deploy``
(a TensorFlow-GPU web inference server: Flask/WSGI predict handler, request
batching, host-side image preprocessing, SavedModel-backed models) designed
idiomatically for JAX/XLA on TPU:

- asyncio HTTP layer (``tpuserve.server``) feeding
- a static-shape batching engine (``tpuserve.batcher``: padded batches,
  bucketed sequence lengths, deadline flush, dispatch pipelining) that runs
- AOT-compiled XLA executables (``tpuserve.runtime``) over a
- ``jax.sharding.Mesh`` (``tpuserve.parallel``: data-parallel sharded-batch,
  replica groups, tensor-parallel partition rules; ``tpuserve.ops`` adds ring
  attention for sequence-parallel long-context work), with
- on-device resize/normalize preprocessing (``tpuserve.preproc``),
- TF SavedModel weight import with parity checks (``tpuserve.savedmodel``),
- first-class observability (``tpuserve.obs``).

The reference project could not be read in the build environment (see
SURVEY.md §0 — the mount was empty); the capability surface implemented here
is the one recorded in SURVEY.md §2, derived from driver-authored metadata.
"""

__version__ = "0.1.0"
