"""Deferred-readback execution pool (SURVEY.md §2 C5/C12; VERDICT.md r1 item 2).

Motivation — measured on the dev tunnel (see BASELINE.md "Link physics"):
the PJRT relay that fronts the TPU buffers host->device transfers
asynchronously, but a DEPENDENT device->host read costs a ~190 ms round trip
(r3 measurement; 214 ms/batch observed vs 24 ms of compute for ResNet-50
batch 256). A serving process that reads results after every batch is
therefore latency-bound at ~5 batches/s regardless of TPU speed. (An r2
measurement also saw the first D2H permanently degrade the session's H2D
rate; the r3 re-measurement with fair warm-up did NOT reproduce that —
per-batch readback RTT alone is the standing justification.)

The TPU-native answer is to make device->host readback *rare* instead of
per-batch:

- **Worker processes** own one PJRT session each. A worker AOT-compiles the
  model (shared persistent XLA cache), then serves an *epoch* of batches
  append-only: every forward's outputs land in a device-resident accumulator
  via a donated `lax.dynamic_update_slice` executable — zero device->host
  traffic during the epoch.
- At **retirement** the worker does ONE bulk read of the accumulator (the
  only moment its session flips), ships the rows back over shared memory,
  and exits. A pre-warmed successor is already serving by then, so the drain
  overlaps the next epoch's compute.
- The **pool** (in the server process) routes batches to the active worker
  over shared-memory slots, rotates workers on an image/deadline budget, and
  resolves per-batch futures when the owning worker's rows arrive.

Honest scope (BASELINE.md r3): on this link, DIRECT mode with pipelined
dispatch measured an order of magnitude faster end-to-end than recycle
(639 vs ~35 img/s) — the direct path's small top-k readbacks overlap well
enough that the per-batch RTT amortizes. Recycle is therefore NOT the
default; it exists for bulk-epoch workloads (offline sweeps, mass
re-scoring) where results are consumed in batches anyway and the
~190 ms-per-batch readback tax genuinely dominates. On real TPU hardware
(no relay) always use `session_mode = "direct"`; "recycle" trades result
latency (bounded by `relay_epoch_ms`) for wire efficiency. The batcher API
is the same in both modes.

Protocol (pipe carries control, shared memory carries data):

    pool (server proc)                    worker proc (one PJRT session)
    ------------------                    ------------------------------
    fork()  ──────────────────────────▶   build model, AOT compile buckets,
                                          upload params, compile appends
    ◀─ {"op": "ready"} ────────────────
    write batch planes into shm slot
    ── {"op":"batch", slot, off} ─────▶   view slot (zero copy), device_put,
                                          forward, append(accum, off)
    ◀─ {"op":"ack", slot} ─────────────   (slot reusable)
    ── {"op":"retire"} ───────────────▶   np.asarray(accum)  ← the one read
    ◀─ {"op":"results", shm, shapes} ──   rows in a results shm it created
    scatter rows to batch futures
    ── {"op":"bye"} ──────────────────▶   unlink results shm, exit

Lock & thread-ownership map (three lock families on purpose; enforced by
``python -m tpuserve lint`` TPS301 and the TPUSERVE_LOCK_WITNESS runtime
witness — docs/ANALYSIS.md):

- **Event-loop-owned, no lock**: ``_active``, per-worker ``pending`` /
  ``rows_used`` / ``first_batch_t`` / ``retired`` / ``reader_started``,
  ``stats``, ``_spawning``, ``_bg_tasks``. Mutated only from coroutines or
  loop callbacks (``_on_msg`` arrives via ``call_soon_threadsafe``).
- **``_lock`` (asyncio.Lock, loop only)**: serializes ``enqueue`` end to
  end — slot pop, shm write (hopped to the executor WHILE the lock stays
  held, which is legal for an asyncio lock and exactly why it is not a
  threading lock), epoch bookkeeping, and the batch send.
- **``_roster_lock`` (threading, microseconds)**: guards the worker roster
  — ``_workers``, ``_warm``, ``_next_wid`` — which is mutated from BOTH the
  loop (``_next_warm`` via ``_ensure_active``, ``watchdog_sweep``) and
  executor threads (``_dry_acquire`` / ``_spawn_blocking`` replenish paths).
  Never held across anything slow.
- **``_spawn_mutex`` (threading, seconds)**: serializes worker *spawns*
  (concurrent ``Process.start()`` from two threads races pipe fds). Taken
  only on executor threads, never on the loop; may nest ``_roster_lock``
  inside it (spawn -> roster is the one sanctioned order), never the
  reverse.
- **``_PinnedShm`` internal lock (threading)**: pin/unpin/close accounting,
  taken from both the loop (close at retirement) and slot-writer threads.
"""

from __future__ import annotations

import asyncio
import logging
import multiprocessing as mp
import pickle
import threading
import time
from dataclasses import dataclass, field
from multiprocessing import shared_memory
from typing import Any

import numpy as np

from tpuserve.config import ModelConfig
from tpuserve.hostpipe import SlotPool, SlotsClosed
from tpuserve.utils.locks import new_async_lock, new_lock

log = logging.getLogger("tpuserve.deferred")


# ---------------------------------------------------------------------------
# Worker process
# ---------------------------------------------------------------------------

def _worker_main(mcfg: ModelConfig, cache_dir: str, conn,
                 batch_shm_name: str, slot_bytes: int, cap_rows: int) -> None:
    """Worker entry: one PJRT session, one epoch of batches, one readback."""
    try:
        _worker_run(mcfg, cache_dir, conn, batch_shm_name, slot_bytes, cap_rows)
    except Exception as e:  # noqa: BLE001 — report any death to the pool
        try:
            conn.send({"op": "died", "error": f"{type(e).__name__}: {e}"})
        except Exception:
            pass
    finally:
        try:
            conn.close()
        except Exception:
            pass


def _worker_run(mcfg, cache_dir, conn, batch_shm_name, slot_bytes, cap_rows) -> None:
    import os

    import jax
    import jax.numpy as jnp

    # Spawned children re-run sitecustomize, which may re-force a hardware
    # platform via jax.config; re-assert the env's platform choice before any
    # backend init (mirrors tests/conftest.py).
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from tpuserve.models import build
    from tpuserve.runtime import ModelRuntime

    model = build(mcfg)
    rt = ModelRuntime(model)
    rt.load_and_shard_params()
    rt.compile_all()
    params = rt.params_per_mesh[0]

    # Output row structure (shapes past the batch dim are bucket-independent).
    # _forward_fn, not model.forward: quantized params carry {"q8", "q8_scale"}
    # dict leaves the raw forward cannot consume.
    fwd = rt._forward_fn()
    sample_sig = model.input_signature(model.buckets()[0])
    out_struct = jax.eval_shape(fwd, params, sample_sig)
    out_leaves, out_treedef = jax.tree_util.tree_flatten(out_struct)

    acc = [
        jax.device_put(jnp.zeros((cap_rows,) + tuple(l.shape[1:]), l.dtype))
        for l in out_leaves
    ]

    def _append(acc_list, outs_list, off):
        return [
            jax.lax.dynamic_update_slice(a, o.astype(a.dtype), (off,) + (0,) * (a.ndim - 1))
            for a, o in zip(acc_list, outs_list)
        ]

    acc_struct = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in acc]
    appends = {}
    for bucket in model.buckets():
        sig = model.input_signature(bucket)
        bstruct = jax.tree_util.tree_flatten(
            jax.eval_shape(fwd, params, sig))[0]
        appends[bucket] = (
            jax.jit(_append, donate_argnums=(0,))
            .lower(acc_struct, bstruct, jax.ShapeDtypeStruct((), jnp.int32))
            .compile()
        )

    batch_shm = shared_memory.SharedMemory(name=batch_shm_name)
    sig_cache = {b: model.input_signature(b) for b in model.buckets()}
    # On the CPU backend device_put can alias host memory, so a device array
    # built over shm views may still read the slot after we ack it; copy the
    # views first there. On TPU the explicit block_until_ready below proves
    # the H2D transfer out of the slot has completed before the ack.
    copy_views = jax.default_backend() == "cpu"
    conn.send({"op": "ready"})

    results_shm = None
    try:
        while True:
            msg = conn.recv()
            op = msg["op"]
            if op == "batch":
                bucket = tuple(msg["bucket"])
                slot, off = msg["slot"], msg["off"]
                views = _views_from_slot(batch_shm.buf, slot * slot_bytes,
                                         sig_cache[bucket])
                if copy_views:
                    views = jax.tree_util.tree_map(np.array, views)
                exe = rt.executables[bucket][0]
                dev_batch = jax.tree_util.tree_map(jax.device_put, views,
                                                   exe.batch_sharding)
                jax.block_until_ready(dev_batch)  # slot no longer referenced
                # Release the shm views NOW: a lingering exported pointer
                # makes batch_shm.close() raise BufferError at retirement,
                # killing the worker with results still on device.
                del views
                out = exe.compiled(params, dev_batch)
                acc = appends[bucket](acc, jax.tree_util.tree_flatten(out)[0],
                                      jnp.int32(off))
                conn.send({"op": "ack", "slot": slot})
            elif op == "retire":
                jax.block_until_ready(acc)
                t0 = time.perf_counter()
                host = [np.asarray(a) for a in acc]  # THE readback
                read_s = time.perf_counter() - t0
                total = sum(h.nbytes for h in host)
                results_shm = shared_memory.SharedMemory(create=True,
                                                         size=max(1, total))
                offb = 0
                shapes = []
                for h in host:
                    flat = np.frombuffer(results_shm.buf, dtype=np.uint8,
                                         count=h.nbytes, offset=offb)
                    flat[:] = h.reshape(-1).view(np.uint8)
                    shapes.append((h.shape, str(h.dtype), offb))
                    offb += h.nbytes
                del flat  # exported pointer would break results_shm.close()
                conn.send({"op": "results", "shm": results_shm.name,
                           "shapes": shapes,
                           "treedef": pickle.dumps(out_treedef),
                           "read_s": read_s})
                conn.recv()  # "bye": pool has copied the rows out
                return
            elif op == "bye":
                return
    finally:
        batch_shm.close()
        if results_shm is not None:
            results_shm.close()
            results_shm.unlink()


def _views_from_slot(buf, base: int, sig) -> Any:
    """Zero-copy numpy views into a shm slot, laid out leaf-after-leaf."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(sig)
    views = []
    off = base
    for l in leaves:
        count = int(np.prod(l.shape))
        views.append(np.frombuffer(buf, dtype=l.dtype, count=count,
                                   offset=off).reshape(l.shape))
        off += count * np.dtype(l.dtype).itemsize
    return jax.tree_util.tree_unflatten(treedef, views)


# ---------------------------------------------------------------------------
# Pool (server process)
# ---------------------------------------------------------------------------

@dataclass
class _PendingBatch:
    off: int
    bucket: tuple
    future: asyncio.Future = field(repr=False)


class _PinnedShm:
    """SharedMemory whose close+unlink defers while slot writes are in flight.

    `_write_slot` runs in an executor thread; the epoch readback (and the
    worker-died path) run on the event loop and end in `_Worker.close()`.
    Without a pin, close() unlinks the segment mid-copy and the writer's
    `np.frombuffer(buf, ...)` dies with "buffer is smaller than requested
    size" — a 500 on an innocent request at every epoch rotation under load
    (judge-observed r4). The fix: writers pin before touching `buf`; close()
    only marks intent while pins are held, and the last unpin performs the
    deferred release. A pin attempt after close has been requested fails,
    telling the writer to route the batch to a live worker instead.
    """

    def __init__(self, size: int) -> None:
        self._shm = shared_memory.SharedMemory(create=True, size=size)
        self._lock = new_lock("deferred.pinned_shm")
        self._writes = 0
        self._close_requested = False
        self._released = False

    @property
    def name(self) -> str:
        return self._shm.name

    @property
    def buf(self):
        return self._shm.buf

    def pin(self) -> bool:
        """Claim the segment for one write; False once close was requested."""
        with self._lock:
            if self._close_requested:
                return False
            self._writes += 1
            return True

    def unpin(self) -> None:
        with self._lock:
            self._writes -= 1
            release = (self._close_requested and self._writes == 0
                       and not self._released)
            if release:
                self._released = True
        if release:
            self._release()

    def close(self) -> None:
        """Release now, or defer to the last unpin if a write is in flight."""
        with self._lock:
            self._close_requested = True
            release = self._writes == 0 and not self._released
            if release:
                self._released = True
        if release:
            self._release()

    def _release(self) -> None:
        try:
            self._shm.close()
            self._shm.unlink()
        except Exception:  # noqa: BLE001 — idempotent cleanup
            pass


class _Worker:
    """Supervisor-side handle for one worker process."""

    def __init__(self, mcfg: ModelConfig, cache_dir: str, slot_bytes: int,
                 n_slots: int, cap_rows: int, wid: int) -> None:
        self.wid = wid
        self.rows_used = 0
        self.first_batch_t: float | None = None
        self.pending: list[_PendingBatch] = []
        # Shared staging-slot abstraction (tpuserve.hostpipe.SlotPool): the
        # same bounded async slot pool the batcher's pipeline uses per
        # replica, here tracking the worker's shm batch slots. Retirement /
        # death closes it, waking any waiter with SlotsClosed.
        self.slots = SlotPool(n_slots)
        self.is_ready = False
        self.retired = False
        self.reader_started = False
        self.batch_shm = _PinnedShm(slot_bytes * n_slots)
        # fork is cheap (inherits warmed imports) and safe while this process
        # has no live XLA backend; once one exists (e.g. direct-mode models or
        # a test harness touched the device), forked children would inherit
        # its threads/locks mid-state — use spawn then.
        ctx = mp.get_context("spawn" if _backend_live() else "fork")
        self.conn, child_conn = ctx.Pipe()
        self.proc = ctx.Process(
            target=_worker_main,
            args=(mcfg, cache_dir, child_conn, self.batch_shm.name,
                  slot_bytes, cap_rows),
            daemon=True,
        )
        self.proc.start()
        child_conn.close()

    def close(self) -> None:
        self.batch_shm.close()  # defers unlink past any in-flight slot write
        if self.proc.is_alive():
            self.proc.terminate()


class DeferredPool:
    """Routes batches to session-recycling workers; resolves futures on epoch
    readback. One pool per recycle-mode model."""

    def __init__(self, mcfg: ModelConfig, cache_dir: str, model,
                 injector=None) -> None:
        import jax

        self.mcfg = mcfg
        self.cache_dir = cache_dir
        self.model = model
        # Deterministic chaos (tpuserve.faults.FaultInjector); None in prod.
        # Kind "worker_death" kills the active worker at enqueue time,
        # exercising the died path + batcher retry + watchdog replenish.
        self.injector = injector
        # A request's latency in recycle mode ~= its worker's remaining epoch;
        # a request timeout below the epoch would 504 most traffic (judge
        # finding r2). Keep timeout >= 2x epoch + readback headroom.
        floor_ms = 2.0 * mcfg.relay_epoch_ms + 1000.0
        if mcfg.request_timeout_ms < floor_ms:
            log.warning(
                "recycle mode: request_timeout_ms %.0f < epoch-safe floor %.0f; raising it",
                mcfg.request_timeout_ms, floor_ms)
            mcfg.request_timeout_ms = floor_ms
        self.n_workers = max(2, mcfg.relay_workers)
        self.n_slots = mcfg.relay_slots
        self.cap_rows = mcfg.relay_epoch_images
        self.epoch_s = mcfg.relay_epoch_ms / 1e3
        sig = model.input_signature(model.bucket_for(max(mcfg.batch_buckets)))
        self.slot_bytes = sum(
            int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
            for l in jax.tree_util.tree_flatten(sig)[0]
        )
        self._workers: list[_Worker] = []
        self._active: _Worker | None = None
        self._warm: list[_Worker] = []
        self._next_wid = 0
        self._loop: asyncio.AbstractEventLoop | None = None
        self._lock: asyncio.Lock | None = None
        self._spawning = 0  # background replenish spawns in flight
        self._stopping = False
        self._bg_tasks: set = set()
        # Serializes worker spawns across executor threads: concurrent
        # multiprocessing Process.start() from two threads races pipe fds
        # (children die at startup with EOF on the ready handshake). Slow
        # (seconds); executor threads only — never taken on the event loop.
        self._spawn_mutex = new_lock("deferred.spawn")
        # Guards the worker roster (_workers/_warm/_next_wid), which both
        # the loop and replenish threads mutate (see the module docstring's
        # ownership map; the old unguarded lists were a real pop-vs-remove
        # race surfaced by `tpuserve lint` TPS301). Microsecond hold times.
        self._roster_lock = new_lock("deferred.roster")
        self.stats = {"epochs": 0, "read_s_total": 0.0, "worker_respawns": 0,
                      "workers_prespawned": 0, "rows_total": 0}

    # -- lifecycle -----------------------------------------------------------
    def prewarm(self, n: int | None = None) -> None:
        """Fork n workers before serving. The first is warmed alone so it
        populates the persistent compile cache; the rest then hit it."""
        n = n or self.n_workers
        first = self._spawn()
        self._wait_ready_sync(first)
        with self._roster_lock:
            self._warm.append(first)
        rest = [self._spawn() for _ in range(n - 1)]
        for w in rest:
            self._wait_ready_sync(w)
            with self._roster_lock:
                self._warm.append(w)

    def _spawn(self) -> _Worker:
        """Start a worker process. NOT added to ``_warm`` here: a warming
        worker visible in ``_warm`` gets popped by ``_next_warm`` on the
        event loop, judged dead (``is_ready`` False), and closed — unlinking
        its batch shm under the still-starting child, which then dies with
        FileNotFoundError at attach (observed live in r5 verify). Callers
        append to ``_warm`` only after the ready handshake."""
        with self._roster_lock:
            wid = self._next_wid
            self._next_wid += 1
        w = _Worker(self.mcfg, self.cache_dir, self.slot_bytes, self.n_slots,
                    self.cap_rows, wid)
        with self._roster_lock:
            self._workers.append(w)
        return w

    def _spawn_ready(self) -> _Worker:
        """Spawn + ready handshake + register warm; on failure, close the
        half-built worker (unlinking its multi-MB batch shm) before
        re-raising — a retrying background replenisher must not accumulate
        leaked segments (ADVICE r4)."""
        w = self._spawn()
        try:
            self._wait_ready_sync(w)
        except Exception:
            with self._roster_lock:
                if w in self._workers:
                    self._workers.remove(w)
            w.close()
            raise
        with self._roster_lock:
            self._warm.append(w)
        return w

    def _wait_ready_sync(self, w: _Worker, timeout: float = 900.0) -> None:
        if w.conn.poll(timeout):
            msg = w.conn.recv()
            if msg.get("op") == "ready":
                w.is_ready = True
                return
            raise RuntimeError(f"worker {w.wid} failed at warmup: {msg}")
        raise TimeoutError(f"worker {w.wid} not ready after {timeout}s")

    def _next_warm(self) -> _Worker | None:
        """Pop the next live warm worker. Called from the loop
        (_ensure_active) AND from replenish threads (_dry_acquire): every
        pop goes through the roster lock; the slow close() of a dead
        candidate happens outside it."""
        while True:
            with self._roster_lock:
                if not self._warm:
                    return None
                w = self._warm.pop(0)
            if w.is_ready and w.proc.is_alive():
                return w
            w.close()

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._lock = new_async_lock("deferred.enqueue")
        for w in self._workers:
            self._start_reader(w)

    def _start_reader(self, w: _Worker) -> None:
        if w.reader_started:  # two readers on one pipe corrupt messages
            return
        w.reader_started = True
        threading.Thread(target=self._reader, args=(w,), daemon=True,
                         name=f"deferred-r{w.wid}").start()

    def _reader(self, w: _Worker) -> None:
        """Blocking pipe reader (one thread per worker, mostly idle)."""
        try:
            while True:
                msg = w.conn.recv()
                self._notify(w, msg)
                if msg["op"] in ("results", "died"):
                    return
        except (EOFError, OSError):
            self._notify(w, {"op": "died", "error": "pipe closed"})

    def _notify(self, w: _Worker, msg: dict) -> None:
        """Hand a worker message to the event loop; tolerate a closed loop
        (readers race server shutdown — judge-observed in r2)."""
        try:
            self._loop.call_soon_threadsafe(self._on_msg, w, msg)
        except RuntimeError:
            pass  # event loop already closed; shutdown path owns cleanup

    # -- serving -------------------------------------------------------------
    async def enqueue(self, bucket: tuple, host_batch: Any) -> asyncio.Future:
        """Write one assembled batch to the active worker and return a Future
        of its np output pytree, resolved at the worker's epoch readback.
        Blocks only for a free shm slot (backpressure)."""
        import jax

        # Validate size BEFORE taking a slot: raising after the pop would
        # leak the slot, and n_slots oversized requests on a fresh worker
        # (no timer armed yet) would deadlock every later enqueue in
        # _take_slot (r5 review finding).
        total = sum(np.asarray(l).nbytes
                    for l in jax.tree_util.tree_flatten(host_batch)[0])
        if total > self.slot_bytes:
            raise ValueError(
                f"batch totals {total} B but a shm slot holds "
                f"{self.slot_bytes} B (sized for the largest configured "
                "bucket); enqueue batches padded to a configured bucket")
        if (self.injector is not None and self._active is not None
                and self._active.proc.is_alive()
                and self.injector.fire("worker_death", self.model.name)):
            log.warning("chaos: killing active worker %d", self._active.wid)
            self._active.proc.kill()  # reader sees EOF -> died path
        async with self._lock:
            while True:
                w = await self._ensure_active(bucket)
                try:
                    slot = await self._take_slot(w)
                except _WorkerGone:
                    continue
                # The multi-MB shm memcpy runs in the executor so the event
                # loop stays responsive during it (VERDICT r3 weak 5); the
                # pool lock stays held so enqueues serialize. The await is
                # an interleave window: _epoch_deadline is a bare call_later
                # callback (no lock) and can retire w mid-copy — and a batch
                # message sent to a retiring worker would be consumed by its
                # retire branch as the "bye" handshake, fabricating zero-row
                # results. The copy pins the worker's shm so a readback-side
                # close() mid-copy defers the unlink (VERDICT r4 weak 1);
                # a False return or a retired worker re-routes the batch.
                try:
                    wrote = await self._loop.run_in_executor(
                        None, self._write_slot, w, slot, host_batch)
                except Exception:
                    # A failed write must not leak the popped slot: the
                    # worker is still serving other batches.
                    w.slots.release(slot)
                    raise
                if not wrote or w.retired or not w.proc.is_alive():
                    continue
                break
            off = w.rows_used
            w.rows_used += bucket[0]
            self.stats["rows_total"] += bucket[0]
            if w.first_batch_t is None:
                w.first_batch_t = time.perf_counter()
                self._loop.call_later(self.epoch_s, self._epoch_deadline, w)
            fut = self._loop.create_future()
            w.pending.append(_PendingBatch(off, bucket, fut))
            w.conn.send({"op": "batch", "slot": slot, "off": off,
                         "bucket": list(bucket)})
        return fut

    async def run_deferred(self, bucket: tuple, host_batch: Any) -> Any:
        """Enqueue + await the epoch readback (convenience for tests)."""
        return await (await self.enqueue(bucket, host_batch))

    async def _ensure_active(self, bucket: tuple) -> _Worker:
        w = self._active
        if w is not None and not w.retired and w.proc.is_alive()\
           and w.rows_used + bucket[0] <= self.cap_rows:
            return w
        if w is not None and not w.retired and w.proc.is_alive():
            self._retire(w)
        self._active = self._next_warm()
        if self._active is None:
            # Pool ran dry: acquire in a thread (slow — the background
            # replenisher below should normally prevent this). _dry_acquire
            # re-checks the warm list under the spawn mutex, so a replenish
            # that lands while we wait is used instead of a second spawn.
            self.stats["worker_respawns"] += 1
            self._active = await self._loop.run_in_executor(
                None, self._dry_acquire)
            self._start_reader(self._active)
        self._maybe_replenish()
        return self._active

    def _dry_acquire(self) -> _Worker:
        """Executor-thread path when no warm worker exists: wait for the
        spawn mutex, prefer a just-replenished warm worker, else spawn."""
        with self._spawn_mutex:
            w = self._next_warm()
            if w is None:
                w = self._spawn_ready()
                with self._roster_lock:
                    self._warm.remove(w)
            return w

    def _maybe_replenish(self) -> None:
        """Top the warm pool back up in the BACKGROUND after activation
        consumes a worker, so the next epoch rotation finds a prewarmed
        successor instead of stalling a synchronous spawn+compile+upload
        (measured ~13 s per rotation on the dev tunnel once the initial
        pool drained)."""
        target = max(1, self.n_workers - 1)  # spares beyond the active one
        with self._roster_lock:
            warm = list(self._warm)
        alive_warm = sum(1 for w in warm
                         if w.is_ready and w.proc.is_alive())
        if self._stopping or alive_warm + self._spawning >= target:
            return
        self._spawning += 1

        async def _bg() -> None:
            try:
                w = await self._loop.run_in_executor(None, self._spawn_blocking)
                if self._stopping:
                    w.close()
                    return
                self._start_reader(w)  # stays in _warm until activated
                self.stats["workers_prespawned"] += 1
            except Exception:  # noqa: BLE001 — next activation falls back
                log.exception("background worker replenish failed")
            finally:
                self._spawning -= 1

        task = self._loop.create_task(_bg())
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    def _spawn_blocking(self) -> _Worker:
        with self._spawn_mutex:
            return self._spawn_ready()

    async def _take_slot(self, w: _Worker) -> int:
        try:
            slot = await w.slots.acquire()
        except SlotsClosed:
            raise _WorkerGone() from None
        if w.retired or not w.proc.is_alive():
            w.slots.release(slot)
            raise _WorkerGone()
        return slot

    def _write_slot(self, w: _Worker, slot: int, host_batch: Any) -> bool:
        """Copy the batch into the worker's shm slot (executor thread).

        Returns False — without raising — when the worker's shm is already
        closing (epoch readback or death landed first); the caller re-routes
        the batch to a live worker. The pin keeps the segment mapped for the
        duration of the copy even if close() is requested mid-copy.
        """
        import jax

        leaves = jax.tree_util.tree_flatten(host_batch)[0]
        total = sum(np.asarray(l).nbytes for l in leaves)
        if total > self.slot_bytes:
            raise ValueError(
                f"batch totals {total} B but a shm slot holds "
                f"{self.slot_bytes} B (sized for the largest configured "
                "bucket); enqueue batches padded to a configured bucket")
        if not w.batch_shm.pin():
            return False
        try:
            # No ValueError catch here: with the pin held the buffer CANNOT
            # be invalidated mid-copy, so any exception now is a real bug
            # that must surface as a visible failed request, not loop
            # forever re-routing to the same live worker.
            off = slot * self.slot_bytes
            for leaf in leaves:
                b = np.ascontiguousarray(leaf)
                view = np.frombuffer(w.batch_shm.buf, dtype=np.uint8,
                                     count=b.nbytes, offset=off)
                view[:] = b.reshape(-1).view(np.uint8)
                off += b.nbytes
        finally:
            w.batch_shm.unpin()
        return True

    def _epoch_deadline(self, w: _Worker) -> None:
        if not w.retired and w.proc.is_alive() and w.pending:
            self._retire(w)
            if self._active is w:
                self._active = None

    def _retire(self, w: _Worker) -> None:
        w.retired = True
        try:
            w.conn.send({"op": "retire"})
        except (BrokenPipeError, OSError):
            pass
        w.slots.close()  # waiters re-route to a live worker (_WorkerGone)

    # -- worker messages (event loop) ----------------------------------------
    def _on_msg(self, w: _Worker, msg: dict) -> None:
        op = msg["op"]
        if op == "ack":
            w.slots.release(msg["slot"])
        elif op == "results":
            self._scatter_results(w, msg)
        elif op == "died":
            log.error("worker %d died: %s", w.wid, msg.get("error"))
            err = RuntimeError(f"worker {w.wid} died: {msg.get('error')}")
            for pb in w.pending:
                if not pb.future.done():
                    pb.future.set_exception(err)
            w.pending.clear()
            if self._active is w:
                self._active = None
            w.slots.close()
            w.close()

    def _scatter_results(self, w: _Worker, msg: dict) -> None:
        import jax

        treedef = pickle.loads(msg["treedef"])
        shm = shared_memory.SharedMemory(name=msg["shm"])
        try:
            leaves = []
            for shape, dtype, offb in msg["shapes"]:
                n = int(np.prod(shape))
                arr = np.frombuffer(shm.buf, dtype=np.dtype(dtype), count=n,
                                    offset=offb).reshape(shape).copy()
                leaves.append(arr)
        finally:
            shm.close()
        self.stats["epochs"] += 1
        self.stats["read_s_total"] += msg.get("read_s", 0.0)
        for pb in w.pending:
            if pb.future.done():
                continue
            rows = [l[pb.off:pb.off + pb.bucket[0]] for l in leaves]
            pb.future.set_result(jax.tree_util.tree_unflatten(treedef, rows))
        w.pending.clear()
        try:
            w.conn.send({"op": "bye"})
        except (BrokenPipeError, OSError):
            pass
        w.close()

    # -- admin ---------------------------------------------------------------
    def describe(self) -> dict:
        with self._roster_lock:
            workers, n_warm = list(self._workers), len(self._warm)
        return {
            "model": self.model.name,
            "family": self.mcfg.family,
            "mode": "recycle",
            "dtype": self.mcfg.dtype,
            "quantize": self.mcfg.quantize,
            "weights": self.mcfg.weights,
            "labels": self.mcfg.labels,
            "options": dict(self.mcfg.options),
            "workers_alive": len([w for w in workers if w.proc.is_alive()]),
            "warm": n_warm,
            "epoch_images": self.cap_rows,
            "epoch_ms": self.mcfg.relay_epoch_ms,
            "buckets": [list(b) for b in self.model.buckets()],
            "stats": dict(self.stats),
        }

    def watchdog_sweep(self) -> int:
        """Watchdog hook (event loop): reap dead worker handles and re-top
        the warm pool in the background.

        The per-worker reader threads normally deliver the "died" message;
        this is the backstop for a worker that dies without the reader
        noticing (and the bookkeeping that prunes exited workers from
        ``_workers``). Returns how many UN-retired workers were found dead —
        real failures; retired workers exiting is normal lifecycle."""
        died = 0
        with self._roster_lock:
            workers = list(self._workers)
        for w in workers:
            if w.proc.is_alive():
                continue
            with self._roster_lock:
                was_warm = w in self._warm
            if not w.retired and (w.pending or was_warm
                                  or w is self._active):
                died += 1
                self._on_msg(w, {"op": "died",
                                 "error": "watchdog: process not alive"})
            if not w.pending:
                with self._roster_lock:
                    if w in self._warm:
                        self._warm.remove(w)
                    if w in self._workers:
                        self._workers.remove(w)
                if self._active is w:
                    self._active = None
                w.close()
        if not self._stopping and self._loop is not None:
            self._maybe_replenish()
        return died

    def retire_active(self) -> None:
        """Early-retire every worker holding in-flight batches (fast, sync).

        Called at the start of server shutdown so batch futures resolve in
        readback time instead of at the epoch deadline; safe to call more
        than once."""
        with self._roster_lock:
            workers = list(self._workers)
        for w in workers:
            if w.proc.is_alive() and not w.retired and w.pending:
                self._retire(w)
                if self._active is w:
                    self._active = None

    async def stop(self) -> None:
        """Retire workers with in-flight batches and wait (bounded) for their
        epoch readback so pending requests resolve with results, not 'worker
        died' (ADVICE r2: the old 50 ms grace stranded every real epoch)."""
        self._stopping = True  # in-flight background spawns self-close
        self.retire_active()
        with self._roster_lock:
            workers = list(self._workers)
        waiting = [w for w in workers if w.pending]
        deadline = self._loop.time() + max(5.0, 2.0 * self.epoch_s)
        while waiting and self._loop.time() < deadline:
            await asyncio.sleep(0.05)
            waiting = [w for w in waiting if w.pending]
        err = RuntimeError("deferred pool stopped before epoch readback")
        with self._roster_lock:
            workers = list(self._workers)
        for w in workers:
            for pb in w.pending:
                if not pb.future.done():
                    pb.future.set_exception(err)
            w.pending.clear()
            w.close()


class _WorkerGone(Exception):
    """Active worker retired/died while a batch waited for a slot."""


def _backend_live() -> bool:
    """True if this process already initialized an XLA backend."""
    try:
        from jax._src import xla_bridge  # noqa: PLC0415 — no public probe exists

        return bool(xla_bridge._backends)
    except Exception:
        return False
