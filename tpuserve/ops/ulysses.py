"""Ulysses sequence parallelism: all-to-all head resharding (SURVEY.md §2.1).

The second of the two sequence-parallel schemes SURVEY.md §5 names (ring
attention being the first, ``tpuserve.ops.ring_attention``). Where the ring
keeps queries resident and rotates K/V blocks around the ICI ring in
``seq_devices`` steps, Ulysses pays one collective each way: an all-to-all
reshards activations from sequence-sharded/heads-replicated to
heads-sharded/sequence-complete, every device then runs ordinary dense
attention for its head slice over the FULL sequence, and a second all-to-all
restores sequence sharding. On TPU both all-to-alls ride ICI and cost
O(B*S*H*D / n) bytes per device — the same traffic the ring moves in total,
but concentrated in two dispatches instead of n, which wins when per-step
latency (not bandwidth) dominates, i.e. short-to-medium sequences on many
chips.

Trade-off vs ring, honestly stated: Ulysses holds the full (S, S/n-free)
sequence of K/V per device after the first all-to-all, so per-device memory
for activations is O(B*S*H/n*D) — fine until S^2 scores dominate (the local
dense attention still materializes (H/n, S, S) scores). Ring never holds more
than a (S/n, S/n) tile and wins for very long sequences. The two share one
interface so the train step can pick per config.

Constraint: attention heads (after any tensor-parallel split of the heads
dim) must be divisible by the seq-axis size, because the all-to-all deals
heads out across it.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from tpuserve.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tpuserve.ops.ring_attention import dense_attention


def _ulysses_body(q, k, v, kbias, axis_name: str, local_impl: str = "dense"):
    """Per-device: reshard seq->heads, attend the full sequence, reshard back.

    ``local_impl="flash"`` runs the per-device full-sequence attention
    through the fused Pallas kernel instead of a dense einsum — the local
    (H/n, S, S) score materialization was Ulysses's memory weak spot."""
    a2a = partial(jax.lax.all_to_all, axis_name=axis_name, tiled=True)
    # (B, S/n, H, D) -> (B, S, H/n, D): split the heads dim across the axis,
    # concatenate the sequence back together.
    qh = a2a(q, split_axis=2, concat_axis=1)
    kh = a2a(k, split_axis=2, concat_axis=1)
    vh = a2a(v, split_axis=2, concat_axis=1)
    # Per-key bias needs the full sequence on every device.
    bias = jax.lax.all_gather(kbias, axis_name, axis=1, tiled=True)  # (B, S)
    if local_impl == "flash":
        from tpuserve.ops.flash_attention import flash_attention

        out = flash_attention(qh, kh, vh, bias.astype(jnp.float32))
    else:
        out = dense_attention(qh, kh, vh,
                              bias[:, None, None, :].astype(jnp.float32))
    # (B, S, H/n, D) -> (B, S/n, H, D): the inverse deal. Cast back first:
    # the f32 bias promoted the scores, but the op's contract (shared with
    # ring_attention) is out.dtype == q.dtype.
    return a2a(out.astype(q.dtype), split_axis=1, concat_axis=2)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      mesh: Mesh, axis_name: str = "seq",
                      key_padding: jax.Array | None = None,
                      spec: P | None = None,
                      local_impl: str = "auto") -> jax.Array:
    """Sequence-parallel attention via head all-to-all; ring_attention's twin.

    Args:
      q, k, v: (batch, seq, heads, head_dim) global arrays, seq sharded on
        ``axis_name``.
      mesh: device mesh containing ``axis_name``.
      key_padding: optional (batch, seq) additive per-key bias (0 = attend,
        -1e9 = masked), sharded like K's seq dim.
      spec: optional full PartitionSpec for q/k/v (position 1 must be
        ``axis_name``), e.g. ``P("data", "seq", "model", None)``.

    Returns (batch, seq, heads, head_dim), sharded like q.
    """
    if key_padding is None:
        key_padding = jnp.zeros(k.shape[:2], jnp.float32)
    qkv_spec = spec if spec is not None else P(None, axis_name, None, None)
    if qkv_spec[1] != axis_name:
        raise ValueError(f"spec {qkv_spec} must put {axis_name!r} on the seq dim")
    n = mesh.shape[axis_name]
    h = q.shape[2]
    head_axes = qkv_spec[2]
    if head_axes is not None:
        for a in (head_axes if isinstance(head_axes, (tuple, list)) else [head_axes]):
            h //= mesh.shape[a]
    if h % n:
        raise ValueError(
            f"ulysses needs local heads ({h}) divisible by the {axis_name!r} "
            f"axis size ({n}); use ring_attention for this shape")
    if local_impl == "auto":
        # Memory-derived, shared with ring_attention (see its docstring and
        # BASELINE.md "Flash vs dense, chip level": dense measured FASTER
        # at every serving shape on v5e; flash is for when the full-seq
        # dense scores stop fitting). Ulysses' local attention sees the
        # FULL sequence with h/n heads per device; batch divides over
        # whatever the spec shards it on (h already divided above).
        from tpuserve.ops.ring_attention import _spec_axis_size, auto_local_impl

        b_loc = q.shape[0] // _spec_axis_size(mesh, qkv_spec[0])
        local_impl = auto_local_impl(b_loc, h // n, q.shape[1], q.shape[-1])
    elif local_impl not in ("dense", "flash"):
        raise ValueError(f"unknown local_impl {local_impl!r}")
    bias_spec = P(qkv_spec[0], axis_name)
    fn = shard_map(
        partial(_ulysses_body, axis_name=axis_name, local_impl=local_impl),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, bias_spec),
        out_specs=qkv_spec,
        # See ring_attention: the Pallas interpreter needs check_vma off.
        check_vma=local_impl != "flash",
    )
    return fn(q, k, v, key_padding)
