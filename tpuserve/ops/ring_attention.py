"""Ring attention: sequence-parallel attention over a mesh axis.

Long-context design (SURVEY.md §5 "Long-context/sequence parallelism"): for
sequences that exceed one device's HBM — or whose O(seq^2) score matrix does —
the sequence dim is sharded over the mesh's ``"seq"`` axis. Each device holds
one block of Q/K/V. K/V blocks then rotate around the ring with
``jax.lax.ppermute`` (nearest-neighbor ICI traffic, no all-gather), and every
device folds each visiting block into its queries' result with an online
softmax (running max ``m``, normalizer ``l``, weighted accumulator ``acc`` —
the same recurrence flash/blockwise attention uses). After ``seq_devices``
steps every query has attended to the full sequence while no device ever
materialized more than a (q_local, k_local) score tile.

The rotation runs inside ``lax.scan`` so XLA emits one compiled loop body;
``ppermute`` of the *next* block is issued before the current block's math,
letting the compiler overlap ICI transfer with MXU compute.

Layouts: (batch, seq, heads, head_dim) throughout — matching
``nn.MultiHeadDotProductAttention`` — with seq sharded and heads replicated.
Bidirectional (encoder) attention; an additive bias (e.g. padding mask) can be
passed sharded the same way as K.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from tpuserve.utils.compat import pcast_varying, shard_map
from jax.sharding import Mesh, PartitionSpec as P


# Dense-vs-flash local-math decision threshold: the per-device f32 score
# tile (x2 for the softmax temp XLA keeps alive). Above ~2 GiB dense
# attention starts evicting everything else from a 16 GiB v5e; below it,
# dense is simply FASTER (measured 1.4-2.2x at every serving shape —
# BASELINE.md "Flash vs dense, chip level", 2026-07-30).
DENSE_SCORE_BYTES_MAX = 2 << 30


def auto_local_impl(b_loc: int, h_loc: int, s_loc: int, d: int) -> str:
    """Memory-derived per-device attention impl choice (pure; unit-tested
    directly in tests/test_flash_attention.py because no CPU-testable
    shape can cross the threshold for real)."""
    kernel_ok = d % 64 == 0 and s_loc % 8 == 0
    dense_score_bytes = 2 * 4 * b_loc * h_loc * s_loc * s_loc
    return ("flash" if kernel_ok and dense_score_bytes > DENSE_SCORE_BYTES_MAX
            else "dense")


def _spec_axis_size(mesh: Mesh, entry) -> int:
    """Product of mesh-axis sizes a PartitionSpec entry shards over."""
    if entry is None:
        return 1
    axes = entry if isinstance(entry, (tuple, list)) else [entry]
    n = 1
    for a in axes:
        n *= int(mesh.shape[a])
    return n


def dense_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    bias: jax.Array | None = None) -> jax.Array:
    """Reference single-device attention, (B, S, H, D) layout.

    ``bias`` is additive on the scores, shaped (B, 1|H, S_q, S_k).
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _ring_body(q, k, v, kbias, axis_name: str, vary_axes: tuple = (),
               local_impl: str = "dense"):
    """Per-device ring loop: local Q stays, K/V (+ per-key bias) rotate.

    ``local_impl="flash"`` runs each visiting block's math through the fused
    Pallas kernel (``flash_attention(..., return_stats=True)``) instead of a
    dense einsum that materializes the (Sq_local, Sk_local) score tile — the
    composition VERDICT r3 next 3 asked for: the kernel is the single-device
    realization of the same online-softmax recurrence, so the ring merge
    just folds (o, m, l) triples.
    """
    n = jax.lax.psum(1, axis_name)
    scale = q.shape[-1] ** -0.5
    b, sq, h, d = q.shape

    # Online-softmax state, (B, H, Sq) / (B, Sq, H, D). pvary marks the
    # constants as varying over every sharded axis so scan carry types match
    # the loop outputs (which inherit q/k/v's varying axes).
    vary = vary_axes or (axis_name,)
    m0 = pcast_varying(jnp.full((b, h, sq), -jnp.inf, jnp.float32), vary)
    l0 = pcast_varying(jnp.zeros((b, h, sq), jnp.float32), vary)
    acc0 = pcast_varying(jnp.zeros((b, sq, h, d), jnp.float32), vary)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        k_blk, v_blk, bias_blk, m, l, acc = carry
        # Issue the rotation first so ICI overlaps the tile's compute.
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        bias_nxt = jax.lax.ppermute(bias_blk, axis_name, perm)

        if local_impl == "flash":
            from tpuserve.ops.flash_attention import flash_attention

            # Kernel returns the UNNORMALIZED f32 accumulator + (m, l): the
            # merge folds raw triples in f32 — no per-block divide (a fully
            # masked visiting block is a harmless zero contribution, not
            # 0/0 NaN) and no bf16 round-trip of partial results.
            acc_blk, m_blk, l_blk = flash_attention(
                q, k_blk, v_blk, bias_blk, return_stats=True)
            m_blk = m_blk.transpose(0, 2, 1)           # (B, H, Sq)
            l_blk = l_blk.transpose(0, 2, 1)
            m_new = jnp.maximum(m, m_blk)
            a_prev = jnp.exp(m - m_new)
            a_blk = jnp.exp(m_blk - m_new)
            l = l * a_prev + l_blk * a_blk
            acc = (acc * a_prev.transpose(0, 2, 1)[..., None]
                   + acc_blk * a_blk.transpose(0, 2, 1)[..., None])
        else:
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk).astype(jnp.float32) * scale
            s = s + bias_blk[:, None, None, :]  # (B, Sk) per-key additive bias
            m_new = jnp.maximum(m, s.max(axis=-1))
            alpha = jnp.exp(m - m_new)  # rescale of previous state
            p = jnp.exp(s - m_new[..., None])
            l = l * alpha + p.sum(axis=-1)
            acc = acc * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
                "bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
        return (k_nxt, v_nxt, bias_nxt, m_new, l, acc), None

    (_, _, _, _, l, acc), _ = jax.lax.scan(
        step, (k, v, kbias, m0, l0, acc0), None, length=n)
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mesh: Mesh, axis_name: str = "seq",
                   key_padding: jax.Array | None = None,
                   spec: P | None = None,
                   local_impl: str = "auto") -> jax.Array:
    """Sequence-parallel attention; call inside or outside jit.

    Args:
      q, k, v: (batch, seq, heads, head_dim), seq sharded on ``axis_name``
        (global arrays; shard_map slices them).
      mesh: the device mesh containing ``axis_name``.
      key_padding: optional (batch, seq) additive bias per key position
        (0 = attend, -inf/-1e9 = masked), sharded like K's seq dim.
      spec: optional full PartitionSpec for q/k/v, e.g.
        ``P("data", "seq", "model", None)`` to keep batch data-parallel and
        heads tensor-parallel through the ring (position 1 must be
        ``axis_name``). Default shards only the seq dim.
      local_impl: per-device block math — "dense" (einsum, materializes the
        local score tile), "flash" (fused Pallas kernel), or "auto".
        "auto" is MEMORY-derived, not speed-derived: the v5e measurement
        (BASELINE.md "Flash vs dense, chip level", 2026-07-30) shows dense
        FASTER at every serving shape (flash = 0.45-0.70x), so auto picks
        dense whenever the local score tile plausibly fits HBM and only
        switches to flash when the O(s_loc^2) dense scores grow into the
        GB range — the regime flash exists for (it also needs the usual
        kernel alignment: head_dim % 64 == 0, s_loc % 8 == 0).

    Returns (batch, seq, heads, head_dim), sharded like q.
    """
    if key_padding is None:
        key_padding = jnp.zeros(k.shape[:2], jnp.float32)
    qkv_spec = spec if spec is not None else P(None, axis_name, None, None)
    if qkv_spec[1] != axis_name:
        raise ValueError(f"spec {qkv_spec} must put {axis_name!r} on the seq dim")
    if local_impl == "auto":
        n = int(mesh.shape[axis_name])
        b, _, h, d = q.shape
        # The decision models the PER-DEVICE tile: divide batch and heads
        # by whatever mesh axes the spec shards them over (r5 review:
        # using global shapes overestimated by dp*tp and flipped sharded
        # serving onto the measured-slower kernel).
        b_loc = b // _spec_axis_size(mesh, qkv_spec[0])
        h_loc = h // _spec_axis_size(mesh, qkv_spec[2])
        local_impl = auto_local_impl(b_loc, h_loc, q.shape[1] // n, d)
    elif local_impl not in ("dense", "flash"):
        raise ValueError(f"unknown local_impl {local_impl!r}")
    bias_spec = P(qkv_spec[0], axis_name)
    vary_axes = []
    for entry in qkv_spec:
        if entry is None:
            continue
        vary_axes.extend(entry if isinstance(entry, (tuple, list)) else [entry])
    fn = shard_map(
        partial(_ring_body, axis_name=axis_name, vary_axes=tuple(vary_axes),
                local_impl=local_impl),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, bias_spec),
        out_specs=qkv_spec,
        # The Pallas interpreter can't propagate vma through its internal
        # block slicing (jax-ml/jax: "pass check_vma=False as a temporary
        # workaround"); the dense path keeps the stronger checking.
        check_vma=local_impl != "flash",
    )
    return fn(q, k, v, key_padding)
