"""Switch-style mixture-of-experts FFN with expert parallelism (EP).

Expert parallelism is the last axis in SURVEY.md §2.1's strategy table;
none of the judged configs is an MoE, so it was scoped out of v1 — this
module makes the seam real. Design is TPU-first throughout:

- **Everything static.** Top-1 (Switch) routing with a fixed per-expert
  capacity: dispatch and combine are dense one-hot tensors, the expert
  compute is three einsums — no gather/scatter, no dynamic shapes, all MXU
  work. Tokens past an expert's capacity are dropped (contribute zero; the
  caller's residual connection passes them through), the standard Switch
  trade.
- **Group-wise routing.** Each batch row routes independently with capacity
  ``C = ceil(S/E * capacity_factor)``, so the (group, S, E, C) routing
  tensors stay LINEAR in total tokens (a single global routing pool would
  be quadratic and OOM at real sequence lengths).
- **Padding-aware.** Masked tokens never claim expert capacity and don't
  drive the load-balancing aux loss — otherwise pad tokens evict real ones
  first-come-first-served and the router trains on garbage embeddings.
- **EP via shardings, not hand-written collectives.** The expert dim of the
  expert buffers and the ``(E, D, F)`` weights shards over the mesh's
  "model" axis (see ``tpuserve.train.TRAIN_PARTITION_RULES``); XLA lowers
  the dispatch/combine einsums to the token all-to-alls over ICI. The op
  stays a pure function — the same code runs 1-device and expert-parallel.

Reference: Switch Transformer (Fedus et al. 2021) routing math, re-derived
for the static-shape formulation.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


def switch_route(logits: jax.Array, capacity: int,
                 token_mask: jax.Array | None = None):
    """Top-1 routing of ONE group -> static (T, E, C) dispatch/combine.

    ``token_mask`` (T,): 0-tokens (padding) never claim capacity and are
    excluded from the aux statistics. Returns (dispatch, combine, aux):
    ``dispatch`` is 0/1 routing of token t to (expert e, queue slot c);
    ``combine`` additionally carries the gate probability; ``aux`` is the
    load-balancing loss (fraction-routed x gate mass per expert, scaled by
    E — Switch eq. 4).
    """
    n_experts = logits.shape[-1]
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # (T, E)
    expert = jnp.argmax(gates, axis=-1)                          # (T,)
    gate = jnp.max(gates, axis=-1)                               # (T,)
    onehot = jax.nn.one_hot(expert, n_experts, dtype=gates.dtype)
    if token_mask is None:
        token_mask = jnp.ones(logits.shape[0], gates.dtype)
    token_mask = token_mask.astype(gates.dtype)
    onehot = onehot * token_mask[:, None]
    # Position of each token in its expert's queue, -1 where unrouted.
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0
    pos = jnp.max(pos, axis=-1).astype(jnp.int32)                # (T,)
    keep = pos >= 0
    keep &= pos < capacity
    dispatch = (onehot * keep[:, None])[..., None] * jax.nn.one_hot(
        jnp.clip(pos, 0, capacity - 1), capacity, dtype=gates.dtype)[:, None, :]
    combine = dispatch * gate[:, None, None]
    # Load-balance aux over REAL tokens only (differentiable via the gates).
    n_real = jnp.maximum(token_mask.sum(), 1.0)
    frac_routed = onehot.sum(axis=0) / n_real
    gate_mass = (gates * token_mask[:, None]).sum(axis=0) / n_real
    aux = n_experts * jnp.sum(frac_routed * gate_mass)
    return dispatch, combine, aux


class SwitchFFN(nn.Module):
    """Drop-in MoE replacement for a transformer FFN block.

    Expert weights carry a leading (E, ...) dim; shard it on "model" for
    expert parallelism. bf16-safe: routing softmax/argmax in f32.
    """

    experts: int
    d_ff: int
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array,
                 mask: jax.Array | None = None) -> tuple[jax.Array, jax.Array]:
        b, s, d = x.shape
        # Per-group (batch-row) routing keeps the (b, s, E, C) routing
        # tensors linear in total tokens.
        capacity = int(math.ceil(s / self.experts * self.capacity_factor))
        router = self.param("router", nn.initializers.normal(0.02),
                            (d, self.experts))
        w_up = self.param("w_up", nn.initializers.normal(0.02),
                          (self.experts, d, self.d_ff))
        w_down = self.param("w_down", nn.initializers.normal(0.02),
                            (self.experts, self.d_ff, d))
        logits = jnp.einsum("gsd,de->gse", x.astype(jnp.float32),
                            router.astype(jnp.float32))
        if mask is None:
            mask = jnp.ones((b, s), jnp.float32)
        dispatch, combine, aux = jax.vmap(
            lambda lg, mg: switch_route(lg, capacity, mg))(logits, mask)
        dispatch = dispatch.astype(self.dtype)   # (g, s, E, C)
        combine = combine.astype(self.dtype)
        xe = jnp.einsum("gsec,gsd->gecd", dispatch, x.astype(self.dtype))
        h = nn.gelu(jnp.einsum("gecd,edf->gecf", xe, w_up.astype(self.dtype)))
        ye = jnp.einsum("gecf,efd->gecd", h, w_down.astype(self.dtype))
        y = jnp.einsum("gsec,gecd->gsd", combine, ye)
        # Token-weighted aux: fully/mostly padded rows must not dilute the
        # balance pressure.
        n_real = mask.astype(jnp.float32).sum(axis=1)
        aux = jnp.sum(aux * n_real) / jnp.maximum(jnp.sum(n_real), 1.0)
        return y.astype(x.dtype), aux
