"""Fused blockwise (flash) attention as a Pallas TPU kernel (SURVEY.md §7 M8).

Why a hand kernel here and nowhere else: attention is the one serving op
where XLA's fusion genuinely leaves HBM bandwidth on the table — dense
attention materializes the (Sq, Sk) score matrix to HBM twice (scores out,
softmax back in). This kernel keeps the whole online-softmax recurrence in
VMEM: for each query tile, K/V stream through the MXU in ``block_k`` tiles
while the running max ``m``, normalizer ``l``, and f32 accumulator live in
VMEM scratch — O(S) memory instead of O(S^2), one HBM write per output
tile. It is the single-device realization of the same recurrence
``tpuserve.ops.ring_attention`` runs *across* chips (there the blocks arrive
over ICI via ppermute; here they arrive from HBM via the BlockSpec pipeline).

Kernel shape: grid = (B*H, Sq/block_q, Sk/block_k). The TPU grid executes
the innermost dimension sequentially, so the k-block axis lives in the GRID
(the BlockSpec pipeline double-buffers the K/V tiles from HBM) and the
online-softmax state persists in scratch across k iterations — no in-kernel
dynamic slicing, which Mosaic rejects for some tile offsets. State is
initialized at ki == 0 and the output tile is written once at the last ki.

Interface matches the rest of the stack: (B, S, H, D) layout, optional
additive per-key bias (B, S) — exactly what BERT's padding mask lowers to.
Padded keys get -1e9 bias => exp underflows to 0 => they contribute nothing
to ``l`` or ``acc``; a row with at least one live key (BERT always has
[CLS]) never divides by zero.

CPU/test story: ``pallas_call(interpret=True)`` runs the kernel in the
Pallas interpreter, so the same code is unit-tested on the CI's fake-device
CPU mesh and compiled for real on TPU (``interpret=None`` auto-detects from
the effective default device, honoring ``jax.default_device(cpu)`` blocks
like the runtime's CPU-pinned param init).

When to use: measured on v5e, the kernel wins when head_dim is
lane-aligned (64/128/160+); at SD-UNet-style head dims 40/80 the padded
lanes waste the MXU and XLA's dense einsum is faster — which is why the
SD 1.5 UNet keeps dense attention and BERT (head_dim 64) exposes
``options.attention = "flash"``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref,
               m_ref, l_ref, acc_ref, *, scale: float):
    """One (query tile, key tile) grid cell; state carried in VMEM scratch."""
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32) * scale           # (bq, D)
    k_blk = k_ref[0].astype(jnp.float32)               # (bk, D)
    v_blk = v_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(                           # (bq, bk) on the MXU
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    s = s + bias_ref[0, 0, 0][None, :]

    m_prev = m_ref[:, :1]                              # (bq, 1)
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_q", "block_k", "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    bias: jax.Array | None = None, *,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool | None = None) -> jax.Array:
    """Blockwise fused attention, (B, S, H, D) in/out.

    ``bias``: optional additive per-key scores, (B, Sk) — e.g. a padding
    mask's (1 - mask) * -1e9. Block sizes clamp to divisors of the sequence
    lengths (exact for power-of-two-aligned buckets like {64, 128, 256, 512};
    192/320-style buckets fall back to 64-row blocks).
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    # Clamp blocks to divisors of the sequence lengths (gcd keeps the common
    # power-of-two alignment: 192 -> 64, 320 -> 64). TPU lowering needs tile
    # rows divisible by 8 unless the block spans the whole axis.
    block_q = math.gcd(min(block_q, sq), sq)
    block_k = math.gcd(min(block_k, sk), sk)
    for name, blk, size in (("query", block_q, sq), ("key", block_k, sk)):
        if blk != size and blk % 8:
            raise ValueError(
                f"seq_{name} {size} only admits a {blk}-row {name} block, "
                f"which the TPU lowering rejects; use a multiple of 8")
    if interpret is None:
        # The effective platform, honoring `with jax.default_device(cpu)`
        # (the runtime pins param init there): default_backend() alone would
        # still say 'tpu' and compile the TPU kernel for a CPU trace.
        dev = jax.config.jax_default_device
        platform = getattr(dev, "platform", None) or jax.default_backend()
        interpret = platform != "tpu"
    if bias is None:
        bias = jnp.zeros((b, sk), jnp.float32)

    # (B, S, H, D) -> (B*H, S, D): one grid row per (batch, head). Bias is
    # pre-split into k blocks, (B, nk, 1, block_k), so every BlockSpec's last
    # two dims equal the array's (the TPU divisible-or-whole rule) and the
    # kernel never slices dynamically.
    nk = sk // block_k
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    biasf = bias.astype(jnp.float32).reshape(b, nk, 1, block_k)

    kernel = functools.partial(_fa_kernel, scale=d ** -0.5)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, 1, 1, block_k),
                         lambda bh, qi, ki, h=h: (bh // h, ki, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),   # normalizer l
            pltpu.VMEM((block_q, d), jnp.float32),     # weighted accumulator
        ],
        interpret=interpret,
    )(qf, kf, vf, biasf)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
