"""Fused blockwise (flash) attention as a Pallas TPU kernel (SURVEY.md §7 M8).

Why a hand kernel here and nowhere else: **memory, not speed.** Dense
attention materializes the (Sq, Sk) score matrix — O(S^2) f32 per
(batch, head) — which caps the sequence length a device can run at all.
This kernel keeps the whole online-softmax recurrence in VMEM: for each
query tile, K/V stream through the MXU in ``block_k`` tiles while the
running max ``m``, normalizer ``l``, and f32 accumulator live in VMEM
scratch — O(S) memory, one HBM write per output tile. It is the
single-device realization of the same recurrence
``tpuserve.ops.ring_attention`` runs *across* chips (there the blocks arrive
over ICI via ppermute; here they arrive from HBM via the BlockSpec pipeline).

On raw speed the r5 measurement is unambiguous (BASELINE.md "Flash vs
dense"): XLA's dense path is FASTER at every judged serving shape on v5e
(this kernel = 0.45-0.70x), so serving defaults everywhere are dense and
``ring/ulysses local_impl="auto"`` switches here only when the dense score
tile would blow the HBM budget. The earlier "the kernel wins when head_dim
is lane-aligned" claim was measured false and is retracted.

Kernel shape: grid = (B*H, Sq/block_q, Sk/block_k). The TPU grid executes
the innermost dimension sequentially, so the k-block axis lives in the GRID
(the BlockSpec pipeline double-buffers the K/V tiles from HBM) and the
online-softmax state persists in scratch across k iterations — no in-kernel
dynamic slicing, which Mosaic rejects for some tile offsets. State is
initialized at ki == 0 and the output tile is written once at the last ki.

Interface matches the rest of the stack: (B, S, H, D) layout, optional
additive per-key bias (B, S) — exactly what BERT's padding mask lowers to.
Padded keys get -1e9 bias => exp underflows to 0 => they contribute nothing
to ``l`` or ``acc``; a row with at least one live key (BERT always has
[CLS]) never divides by zero.

CPU/test story: ``pallas_call(interpret=True)`` runs the kernel in the
Pallas interpreter, so the same code is unit-tested on the CI's fake-device
CPU mesh and compiled for real on TPU (``interpret=None`` auto-detects from
the effective default device, honoring ``jax.default_device(cpu)`` blocks
like the runtime's CPU-pinned param init).

When to use — MEASURED, see BASELINE.md:
- "SD 1.5 chip profile" (2026-07-30, v5e): at SD-UNet head dims 40/80 the
  zero-padded lanes waste 37-50% of the MXU and the kernel runs the UNet
  step 2.4-2.8x SLOWER than XLA's dense einsum — the SD 1.5 UNet
  therefore defaults to dense (``options.unet_attention = "flash"`` is
  opt-in, parity-tested, and exists for lane-aligned custom variants).
- "Flash vs dense, chip level" (same date): BERT-family numbers
  (head_dim 64, lane-aligned) per seq length; ``ring_attention``'s
  ``local_impl="auto"`` thresholds cite that table.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fa_step(q_ref, k_ref, v_ref, bias_ref, m_ref, l_ref, acc_ref,
             scale: float) -> None:
    """Shared online-softmax update for one (query tile, key tile) cell."""
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32) * scale           # (bq, D)
    k_blk = k_ref[0].astype(jnp.float32)               # (bk, D)
    v_blk = v_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(                           # (bq, bk) on the MXU
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    s = s + bias_ref[0, 0, 0][None, :]

    m_prev = m_ref[:, :1]                              # (bq, 1)
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)


def _fa_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref,
               m_ref, l_ref, acc_ref, *, scale: float):
    """Standard variant: normalized output only."""
    _fa_step(q_ref, k_ref, v_ref, bias_ref, m_ref, l_ref, acc_ref, scale)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


def _fa_kernel_stats(q_ref, k_ref, v_ref, bias_ref, o_ref, mo_ref, lo_ref,
                     m_ref, l_ref, acc_ref, *, scale: float):
    """Stats variant: emit the UNNORMALIZED f32 accumulator plus the
    online-softmax (m, l) per query row so a caller can merge this block's
    result with other blocks' — the recurrence ring attention runs ACROSS
    chips (blockwise-parallel combine). No divide happens in-kernel, which
    keeps fully-masked blocks harmless two different ways depending on the
    mask encoding (do NOT use l == 0 to detect masked blocks): with a true
    -inf bias the exps underflow and l really is 0, so skipping the divide
    avoids 0/0; with the conventional -1e9 padding bias (BERT masks) l is
    ~block_k and m is ~-1e9 — the zero contribution then comes from the
    exp(m_blk - m_new) weight underflowing in the CALLER'S merge against
    any live block. Either way the f32 accumulator never round-trips
    through the input dtype."""
    _fa_step(q_ref, k_ref, v_ref, bias_ref, m_ref, l_ref, acc_ref, scale)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _():
        o_ref[0] = acc_ref[:]
        mo_ref[0] = m_ref[:]
        lo_ref[0] = l_ref[:]


def _dense_stats(q, k, v, bias, return_stats):
    """Pure-XLA twin of the kernel's math: the VJP reference.

    Same function value as the kernel (scores = scaled q.k + per-key bias,
    online softmax); used only to define gradients, so the O(S^2) score
    materialization here costs backward passes, never serving."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32))
    s = s + bias[:, None, None, :].astype(jnp.float32)
    m = jnp.max(s, axis=-1)                              # (B, H, Sq)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    if return_stats:
        return (acc, m.transpose(0, 2, 1), l.transpose(0, 2, 1))
    return (acc / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, bias, block_q, block_k, interpret, return_stats):
    """Kernel dispatch with a dense-recompute VJP: forward runs the Pallas
    kernel; backward differentiates the mathematically-identical dense
    reference (a fused backward kernel is future work — training through
    flash pays the dense O(S^2) memory, serving never does)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    nk = sk // block_k
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    biasf = bias.astype(jnp.float32).reshape(b, nk, 1, block_k)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        pl.BlockSpec((1, 1, 1, block_k),
                     lambda bh, qi, ki, h=h: (bh // h, ki, 0, 0)),
    ]
    o_spec = pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0))
    scratch = [
        pltpu.VMEM((block_q, 128), jnp.float32),   # running max m
        pltpu.VMEM((block_q, 128), jnp.float32),   # normalizer l
        pltpu.VMEM((block_q, d), jnp.float32),     # weighted accumulator
    ]
    grid = (b * h, sq // block_q, nk)

    # Inside shard_map (the sharded-BERT / ring-local composition) outputs
    # must declare which mesh axes they vary over; inherit the inputs' union
    # (outside shard_map these are empty sets — no-op).
    vma = frozenset()
    typeof = getattr(jax, "typeof", None)
    if typeof is not None:  # jax < 0.6 has no typeof (and no vma on avals)
        for x in (q, k, v, bias):
            vma = vma | getattr(typeof(x), "vma", frozenset())

    def out_struct(shape, dtype):
        if vma:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        return jax.ShapeDtypeStruct(shape, dtype)

    if not return_stats:
        out = pl.pallas_call(
            functools.partial(_fa_kernel, scale=d ** -0.5),
            grid=grid, in_specs=in_specs, out_specs=o_spec,
            out_shape=out_struct((b * h, sq, d), q.dtype),
            scratch_shapes=scratch, interpret=interpret,
        )(qf, kf, vf, biasf)
        return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)

    # Stats outputs mirror the scratch layout: (B*H, Sq, 128) f32 with the
    # row value broadcast along the 128 lane dim (Mosaic-aligned tiles);
    # lane 0 is sliced out after the call. The accumulator comes back
    # UNNORMALIZED in f32 (see _fa_kernel_stats).
    stat_spec = pl.BlockSpec((1, block_q, 128), lambda bh, qi, ki: (bh, qi, 0))
    out, m, l = pl.pallas_call(
        functools.partial(_fa_kernel_stats, scale=d ** -0.5),
        grid=grid, in_specs=in_specs,
        out_specs=(o_spec, stat_spec, stat_spec),
        out_shape=(out_struct((b * h, sq, d), jnp.float32),
                   out_struct((b * h, sq, 128), jnp.float32),
                   out_struct((b * h, sq, 128), jnp.float32)),
        scratch_shapes=scratch, interpret=interpret,
    )(qf, kf, vf, biasf)
    out = out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    m = m[..., 0].reshape(b, h, sq).transpose(0, 2, 1)   # (B, Sq, H)
    l = l[..., 0].reshape(b, h, sq).transpose(0, 2, 1)
    return out, m, l


def _flash_fwd(q, k, v, bias, block_q, block_k, interpret, return_stats):
    out = _flash(q, k, v, bias, block_q, block_k, interpret, return_stats)
    return out, (q, k, v, bias)


def _flash_bwd(block_q, block_k, interpret, return_stats, res, ct):
    q, k, v, bias = res
    _, vjp = jax.vjp(
        lambda a, b_, c, d_: _dense_stats(a, b_, c, d_, return_stats),
        q, k, v, bias)
    return vjp(ct)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.jit,
                   static_argnames=("block_q", "block_k", "interpret",
                                    "return_stats"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    bias: jax.Array | None = None, *,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool | None = None,
                    return_stats: bool = False):
    """Blockwise fused attention, (B, S, H, D) in/out.

    ``bias``: optional additive per-key scores, (B, Sk) — e.g. a padding
    mask's (1 - mask) * -1e9. Block sizes clamp to divisors of the sequence
    lengths (exact for power-of-two-aligned buckets like {64, 128, 256, 512};
    192/320-style buckets fall back to 64-row blocks).

    Differentiable: the VJP recomputes through the dense reference
    (O(S^2) memory on backward only — fine for fine-tuning, not for
    long-context pretraining; a fused backward kernel is the upgrade path).

    ``return_stats=True`` returns ``(acc, m, l)`` — the UNNORMALIZED f32
    accumulator plus the online-softmax row stats (B, Sq, H) — letting the
    caller merge this result with other key blocks (ring attention's
    per-device inner step) without NaN on fully-masked blocks and without
    rounding partial results to the input dtype. The merge is::

        m12 = max(m1, m2); a1 = exp(m1-m12); a2 = exp(m2-m12)
        l12 = l1*a1 + l2*a2
        o12 = (acc1*a1 + acc2*a2) / l12
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    # Clamp blocks to divisors of the sequence lengths (gcd keeps the common
    # power-of-two alignment: 192 -> 64, 320 -> 64). TPU lowering needs tile
    # rows divisible by 8 unless the block spans the whole axis.
    block_q = math.gcd(min(block_q, sq), sq)
    block_k = math.gcd(min(block_k, sk), sk)
    for name, blk, size in (("query", block_q, sq), ("key", block_k, sk)):
        if blk != size and blk % 8:
            raise ValueError(
                f"seq_{name} {size} only admits a {blk}-row {name} block, "
                "which the TPU lowering rejects; use a multiple of 8")
    if interpret is None:
        # The effective platform, honoring `with jax.default_device(cpu)`
        # (the runtime pins param init there): default_backend() alone would
        # still say 'tpu' and compile the TPU kernel for a CPU trace.
        dev = jax.config.jax_default_device
        platform = getattr(dev, "platform", None) or jax.default_backend()
        interpret = platform != "tpu"
    if bias is None:
        bias = jnp.zeros((b, sk), jnp.float32)
    return _flash(q, k, v, bias, block_q, block_k, interpret, return_stats)
