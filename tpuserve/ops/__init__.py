"""Device-level ops that go beyond plain jnp calls (SURVEY.md §5 long-context).

- ``ring_attention`` — sequence-parallel blockwise attention: the sequence is
  sharded over a mesh axis and K/V blocks rotate around the ICI ring via
  ``jax.lax.ppermute`` while each device accumulates its queries' output with
  an online (streaming) softmax. Memory per device is O(seq/devices), enabling
  contexts far beyond one chip's HBM.
- ``ulysses_attention`` — the alternative sequence-parallel scheme: one
  all-to-all deals heads across the seq axis so each device dense-attends its
  head slice over the full sequence, then an inverse all-to-all restores seq
  sharding. Lower step latency than the ring for short/medium sequences; the
  ring wins on memory for very long ones.
- ``flash_attention`` — the single-device realization of the same recurrence
  as a fused Pallas TPU kernel: K/V stream through VMEM in blocks, the score
  matrix never touches HBM. Used by BERT via ``options.attention = "flash"``.
- ``moe`` — Switch-style mixture-of-experts FFN: static top-1 routing with
  fixed capacity (all einsums, no dynamic shapes), expert dim sharded on
  "model" for expert parallelism (XLA inserts the token all-to-alls).
"""

from tpuserve.ops.flash_attention import flash_attention  # noqa: F401
from tpuserve.ops.moe import SwitchFFN, switch_route  # noqa: F401
from tpuserve.ops.ring_attention import dense_attention, ring_attention  # noqa: F401
from tpuserve.ops.ulysses import ulysses_attention  # noqa: F401
