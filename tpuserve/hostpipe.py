"""Pipelined host execution engine primitives (ISSUE 3; PAPERS.md P3/P4).

BENCH_r05 measured the chip sustaining ~10,628 img/s while HTTP serving
delivered 606: the gap was the host path, where one shared ThreadPoolExecutor
ran assemble -> device_put -> blocking fetch sequentially per batch, so stage
time summed instead of overlapping and the "compute" phase absorbed the whole
wire wait. Clockwork (P3) treats each serving stage as deterministic-duration
work that must be scheduled, not queued behind unrelated stages; Orca (P4)
re-forms work at stage granularity. This module provides the three primitives
the batcher composes into that staged pipeline:

- :class:`StageExecutors` — one dedicated thread pool per pipeline stage
  (``assemble`` / ``h2d`` / ``fetch`` / ``postproc``), so consecutive batches
  occupy *different* stages concurrently instead of contending for one shared
  pool. Per-(model, stage) queue-depth gauges feed /metrics and /stats.
- :class:`AssemblyArena` — preallocated per-bucket host-batch buffers recycled
  through a free-list, replacing the per-batch ``np.stack`` allocation on the
  hot path. A buffer is only returned to the free-list when its batch's D2H
  fetch has completed (on the CPU backend ``device_put`` may alias host
  memory, so the buffer must outlive the compute that reads it).
- :class:`SlotPool` — a bounded pool of integer slots with async acquire.
  The batcher uses one per replica to keep a configurable depth-k of batches
  in flight on the device ([h2d..fetch]); the deferred pool uses it for its
  per-worker shared-memory batch slots (the shared staging-slot abstraction).

Knobs live in ``config.PipelineConfig`` (``[pipeline]`` TOML); semantics and
how to read the metrics are documented in docs/PERFORMANCE.md.
"""

from __future__ import annotations

import asyncio
import concurrent.futures as cf
import logging
from collections import deque
from typing import Any, Callable

import jax
import numpy as np

from tpuserve.config import PipelineConfig
from tpuserve.obs import PIPELINE_STAGES, Metrics
from tpuserve.utils.locks import new_lock

log = logging.getLogger("tpuserve.hostpipe")


class SlotsClosed(Exception):
    """The pool was closed while (or before) a waiter held on for a slot."""


class SlotPool:
    """Fixed set of integer slots [0, n) with async acquire.

    Event-loop-side only (no thread safety needed): ``acquire`` waits until a
    slot frees, bounded by ``timeout`` (raises ``asyncio.TimeoutError``);
    ``close`` wakes every waiter with :class:`SlotsClosed`. Construction
    touches no event loop, so pools can be built from executor threads (the
    deferred pool spawns workers off-loop)."""

    def __init__(self, n: int) -> None:
        self.capacity = max(1, n)
        self._free: list[int] = list(range(self.capacity))
        self._waiters: deque[asyncio.Future] = deque()
        self._closed = False

    @property
    def in_use(self) -> int:
        return self.capacity - len(self._free)

    def _wake_one(self) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                return

    def try_acquire(self) -> int | None:
        if self._closed or not self._free:
            return None
        return self._free.pop()

    async def acquire(self, timeout_s: float | None = None) -> int:
        while True:
            if self._closed:
                raise SlotsClosed("slot pool closed")
            if self._free:
                return self._free.pop()
            fut = asyncio.get_running_loop().create_future()
            self._waiters.append(fut)
            try:
                await asyncio.wait_for(fut, timeout_s)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                if fut in self._waiters:
                    self._waiters.remove(fut)
                # Pass the baton: if a release woke us concurrently with the
                # timeout, another waiter must get the free slot we abandon.
                if self._free:
                    self._wake_one()
                raise

    def release(self, slot: int) -> None:
        self._free.append(slot)
        self._wake_one()

    def close(self) -> None:
        """Wake every waiter with SlotsClosed; held slots may still be
        released afterwards (no-op bookkeeping)."""
        self._closed = True
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_exception(SlotsClosed("slot pool closed"))


class StageExecutors:
    """Dedicated thread pool per pipeline stage (PIPELINE_STAGES).

    One instance is shared by every direct-mode batcher in the server
    (stage-granularity scheduling, P4): an h2d transfer for model A never
    queues behind a blocking fetch for model B the way the old single shared
    pool allowed. ``run`` hops the callable onto the stage's pool and keeps
    per-(model, stage) submitted-but-unfinished counts as
    ``pipeline_stage_depth{model=,stage=}`` gauges."""

    def __init__(self, cfg: PipelineConfig | None = None,
                 metrics: Metrics | None = None) -> None:
        cfg = cfg or PipelineConfig()
        sizes = {
            "assemble": cfg.assemble_workers,
            "h2d": cfg.h2d_workers,
            "fetch": cfg.fetch_workers,
            "postproc": cfg.postproc_workers,
        }
        assert set(sizes) == set(PIPELINE_STAGES)
        self.metrics = metrics
        self._pools = {
            stage: cf.ThreadPoolExecutor(
                max_workers=max(1, n), thread_name_prefix=f"pipe-{stage}")
            for stage, n in sizes.items()
        }
        self.workers = {s: max(1, n) for s, n in sizes.items()}
        self._depth: dict[tuple[str, str], int] = {}
        self._submitted: dict[str, int] = {s: 0 for s in PIPELINE_STAGES}
        self._shut = False

    async def run(self, model: str, stage: str, fn: Callable, *args) -> Any:
        """Run ``fn(*args)`` on the stage's pool; returns its result."""
        loop = asyncio.get_running_loop()
        key = (model, stage)
        self._depth[key] = self._depth.get(key, 0) + 1
        self._submitted[stage] += 1
        if self.metrics is not None:
            self.metrics.gauge(
                f"pipeline_stage_depth{{model={model},stage={stage}}}"
            ).set(self._depth[key])
        try:
            return await loop.run_in_executor(self._pools[stage], fn, *args)
        finally:
            self._depth[key] -= 1
            if self.metrics is not None:
                self.metrics.gauge(
                    f"pipeline_stage_depth{{model={model},stage={stage}}}"
                ).set(self._depth[key])

    def stats(self) -> dict:
        per_stage_depth = {s: 0 for s in PIPELINE_STAGES}
        for (_, stage), d in self._depth.items():
            per_stage_depth[stage] += d
        return {
            "workers": dict(self.workers),
            "depth": per_stage_depth,
            "submitted_total": dict(self._submitted),
        }

    def shutdown(self) -> None:
        if self._shut:
            return
        self._shut = True
        for p in self._pools.values():
            p.shutdown(wait=False, cancel_futures=True)


class _ArenaLease:
    """One acquired assembly buffer; hand back via AssemblyArena.release."""

    __slots__ = ("bucket", "buf", "pooled")

    def __init__(self, bucket: tuple, buf: Any, pooled: bool) -> None:
        self.bucket = bucket
        self.buf = buf
        self.pooled = pooled


class AssemblyArena:
    """Preallocated host-batch buffers per bucket, recycled via a free-list.

    Buffers are pytrees of np arrays shaped like ``model.input_signature``
    for the bucket (the host batch layout — the same contract the deferred
    pool's shm slots rely on). ``acquire`` never blocks and never hands out a
    buffer that is currently leased: when the per-bucket pool (``slots``
    buffers, allocated lazily) is exhausted it falls back to a fresh
    *overflow* allocation that is GC'd instead of pooled, counted in
    ``arena_overflow_total{model=}`` — persistent overflow means the arena is
    undersized relative to the admission depth ([pipeline] arena_slots)."""

    def __init__(self, model: Any, slots: int,
                 metrics: Metrics | None = None) -> None:
        self.model = model
        self.slots = max(1, slots)
        self.metrics = metrics
        self._lock = new_lock("hostpipe.AssemblyArena")
        self._free: dict[tuple, list] = {}
        self._made: dict[tuple, int] = {}
        self.overflow_total = 0
        self.leased = 0

    def _alloc(self, bucket: tuple) -> Any:
        sig = self.model.input_signature(bucket)
        return jax.tree_util.tree_map(
            lambda s: np.zeros(tuple(s.shape), s.dtype), sig)

    def acquire(self, bucket: tuple) -> _ArenaLease:
        with self._lock:
            self.leased += 1
            free = self._free.setdefault(bucket, [])
            if free:
                return _ArenaLease(bucket, free.pop(), True)
            if self._made.get(bucket, 0) < self.slots:
                self._made[bucket] = self._made.get(bucket, 0) + 1
                pooled = True
            else:
                pooled = False
                self.overflow_total += 1
        if not pooled and self.metrics is not None:
            self.metrics.counter(
                f"arena_overflow_total{{model={self.model.name}}}").inc()
        # Allocation outside the lock: zeroing a multi-MB buffer must not
        # serialize concurrent acquires for other buckets.
        return _ArenaLease(bucket, self._alloc(bucket), pooled)

    def release(self, lease: _ArenaLease) -> None:
        """Return a lease. Only call once the device is provably done reading
        the buffer (after the batch's D2H fetch) — on the CPU backend
        ``device_put`` may alias this host memory."""
        with self._lock:
            self.leased -= 1
            if lease.pooled:
                self._free[lease.bucket].append(lease.buf)

    def stats(self) -> dict:
        with self._lock:
            return {
                "slots_per_bucket": self.slots,
                "leased": self.leased,
                "overflow_total": self.overflow_total,
                "buckets": {
                    str(list(b)): {"pooled": self._made.get(b, 0),
                                   "free": len(free)}
                    for b, free in self._free.items()
                },
            }
