#!/usr/bin/env python
"""Round benchmark harness (driver-run, real TPU).

Serves ResNet-50 (random weights — no pretrained artifacts in the container)
through the full production path — aiohttp HTTP -> batcher -> AOT-compiled
XLA executable on the local TPU — drives it with the asyncio load generator,
and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N}

Baseline for vs_baseline: the driver target is 12,000 img/s on v5e-8
(BASELINE.md); this box exposes a single v5e core, so the per-chip share is
12000/8 = 1500 img/s. vs_baseline = value / (1500 * n_local_chips).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

TARGET_V5E8_IMG_S = 12_000.0
CHIPS_IN_TARGET = 8


def main() -> int:
    import jax

    n_chips = max(1, len(jax.devices()))
    per_chip_target = TARGET_V5E8_IMG_S / CHIPS_IN_TARGET * n_chips

    from tpuserve.config import ModelConfig, ServerConfig
    from tpuserve.server import ServerState, make_app
    from tpuserve.bench.loadgen import run_load, synthetic_image_jpeg, synthetic_image_npy

    cfg = ServerConfig(
        host="127.0.0.1",
        port=18321,
        decode_threads=16,
        startup_canary=False,
        models=[
            ModelConfig(
                name="resnet50",
                family="resnet50",
                batch_buckets=[64, 128],
                deadline_ms=50.0,
                dtype="bfloat16",
                parallelism="sharded",
                request_timeout_ms=60_000.0,
                max_inflight=2,
                wire_size=224,  # wire bytes dominate through the dev tunnel
            )
        ],
    )

    t0 = time.time()
    state = ServerState(cfg)
    state.build()
    print(f"# build+compile took {time.time() - t0:.1f}s", file=sys.stderr)

    async def run() -> dict:
        from aiohttp import web

        app = make_app(state)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, cfg.host, cfg.port)
        await site.start()
        try:
            if os.environ.get("BENCH_PAYLOAD", "jpeg") == "jpeg":
                payload = synthetic_image_jpeg()
                ctype = "image/jpeg"
            else:
                payload = synthetic_image_npy()
                ctype = "application/x-npy"
            print(f"# payload: {len(payload)} bytes ({ctype})", file=sys.stderr)
            url = f"http://{cfg.host}:{cfg.port}/v1/models/resnet50:classify"
            duration = float(os.environ.get("BENCH_DURATION", "15"))
            concurrency = int(os.environ.get("BENCH_CONCURRENCY", "256"))
            warmup = float(os.environ.get("BENCH_WARMUP", "5"))
            def debug_stats() -> None:
                if not os.environ.get("BENCH_DEBUG"):
                    return
                stats = state.metrics.summary()
                for section in ("latency", "counters", "gauges"):
                    for k, v in sorted(stats[section].items()):
                        print(f"# {k}: {v}", file=sys.stderr)

            if os.environ.get("BENCH_INPROC"):
                result = await run_load(url, payload, ctype, duration, concurrency, warmup)
                debug_stats()
                return result.summary()
            # Default: load generator in a separate process so client-side
            # socket/JSON work doesn't share the GIL with the serving process.
            import tempfile

            with tempfile.NamedTemporaryFile(suffix=".bin", delete=False) as f:
                f.write(payload)
                payload_path = f.name
            proc = await asyncio.create_subprocess_exec(
                sys.executable, "-m", "tpuserve", "bench",
                "--url", f"http://{cfg.host}:{cfg.port}",
                "--model", "resnet50", "--verb", "classify",
                "--duration", str(duration), "--warmup", str(warmup),
                "--concurrency", str(concurrency),
                "--payload", payload_path, "--content-type", ctype,
                stdout=asyncio.subprocess.PIPE,
                cwd=os.path.dirname(os.path.abspath(__file__)),
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            out, _ = await proc.communicate()
            os.unlink(payload_path)
            debug_stats()
            return json.loads(out.decode())
        finally:
            await runner.cleanup()

    summary = asyncio.run(run())
    print(f"# load result: {summary}", file=sys.stderr)

    value = summary["throughput_per_s"]
    line = {
        "metric": "resnet50_http_throughput",
        "value": value,
        "unit": "img/s",
        "vs_baseline": round(value / per_chip_target, 4),
        "p50_ms": summary["p50_ms"],
        "p99_ms": summary["p99_ms"],
        "n_chips": n_chips,
        "errors": summary["n_err"],
    }
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
