#!/usr/bin/env python
"""Round benchmark harness (driver-run, real TPU).

Serves ResNet-50 (random weights — no pretrained artifacts in the container)
through the full production path — aiohttp HTTP -> batcher -> XLA executables
on the local TPU — drives it with the out-of-process load generator, and
prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N, ...}

What the harness does, in order (all knobs env-overridable, defaults sane):

1. Measures the REAL host->device link rate in a fresh subprocess (the dev
   tunnel buffers writes; only a dependent read reveals the sustained rate —
   see BASELINE.md "Link physics"). This gives the wire-bound ceiling.
2. Serves wire_format="yuv420" (1.5 B/px vs RGB's 3) with the native libjpeg
   plane decoder. BENCH_MODE picks the execution path; the default is
   "direct" with pipelined dispatch, which measured an order of magnitude
   faster than "recycle" here (639 vs ~35 img/s, r3) — the direct path's
   small top-k readbacks pipeline well enough that deferred epoch readback
   (~8 s/epoch bulk-read RTT on this tunnel) doesn't pay on this link. Set
   BENCH_MODE=recycle to measure the deferred pool.
3. Closed-loop load for peak throughput — passes extend (capped) until the
   best consecutive window of 3 agrees within 15%, and the headline is that
   window's median; then open-loop at ~70% of it for honest latency
   percentiles at a stated offered rate. The headline run serves the int8
   weight-only variant by default (BENCH_QUANTIZE="" restores fp).
4. ALWAYS prints the phase breakdown (queue/preproc/h2d/compute/postproc),
   link ceiling math, and config to stderr — where every millisecond goes —
   and ships a "roofline" block in the JSON: per-bucket raw-executable
   probes, per-phase pct-of-ceiling, and the compute phase split into
   device-time vs host-wait (docs/PERFORMANCE.md "Reading the roofline").

Baseline for vs_baseline: the driver target is 12,000 img/s on v5e-8
(BASELINE.md); this box exposes one chip, so the per-chip share is 1,500.
The chip itself sustains ~10,000 img/s (BASELINE.md, measured); on this dev
box the HTTP path is bound by the ~12 MB/s tunnel and the single host core,
so the honest figures here are achieved img/s AND achieved/wire-ceiling.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

# Pure helpers (math only — safe before any backend/env decisions).
from tpuserve.bench import roofline as _rl

TARGET_V5E8_IMG_S = 12_000.0
CHIPS_IN_TARGET = 8


def env_f(name: str, default: float) -> float:
    return float(os.environ.get(name, default))


def measure_link_rate_mbps(chunk_bytes: int = 8 << 20) -> float:
    """Real sustained H2D rate, measured in a virgin subprocess: buffered
    writes + one dependent read = wall-clock truth (shared probe source:
    tpuserve.bench.probes). ``chunk_bytes`` sizes each probe transfer —
    pass the serving path's per-batch bytes for a ceiling the served
    numbers can honestly be compared against (see wire-ceiling self-check)."""
    from tpuserve.bench.probes import measure_h2d_mbps

    try:
        r = measure_h2d_mbps("virgin",
                             cwd=os.path.dirname(os.path.abspath(__file__)),
                             chunk_bytes=chunk_bytes)
    except Exception as e:  # noqa: BLE001
        r = {"error": str(e)}
    if "mbps" in r:
        return round(r["mbps"], 1)
    print(f"# link probe failed ({r.get('error')}); ceiling math unavailable",
          file=sys.stderr)
    return 0.0


def device_seconds_snapshot(metrics, model: str) -> dict[int, float]:
    """Per-replica device_seconds_total values for one model (ISSUE 14):
    the ledger the utilization block differences over the measured
    window."""
    import re as _re

    pat = _re.compile(
        rf"^device_seconds_total\{{model={_re.escape(model)},"
        rf"replica=(\d+)\}}$")
    with metrics._lock:
        counters = dict(metrics._counters)
    out: dict[int, float] = {}
    for name, c in counters.items():
        m = pat.match(name)
        if m is not None:
            out[int(m.group(1))] = c.value
    return out


def utilization_block(before: dict[int, float], after: dict[int, float],
                      wall_s: float, n_chips: int) -> dict:
    """The bench `utilization` block: per-replica busy fraction (device
    seconds / wall) over the measured window, plus the aggregate across
    the chips the run occupied."""
    per_replica = {}
    total = 0.0
    for rep in sorted(after):
        delta = max(0.0, after[rep] - before.get(rep, 0.0))
        total += delta
        per_replica[str(rep)] = round(delta / wall_s, 4) if wall_s > 0 else 0.0
    return {
        "wall_s": round(wall_s, 2),
        "device_seconds": round(total, 2),
        "n_chips": n_chips,
        "per_replica": per_replica,
        # Aggregate busy fraction of the occupied chip set: device seconds
        # spread over n_chips × wall — 1.0 means every chip busy the whole
        # window, low values name the starvation the roofline must explain.
        "mean_utilization": round(total / (wall_s * n_chips), 4)
        if wall_s > 0 and n_chips else 0.0,
    }


def burn_from_snapshots(bounds, before: dict, after: dict,
                        objective_ms: float, availability: float
                        ) -> "float | None":
    """One pass's SLO burn rate from latency-histogram snapshots: the
    pass's delta counts → bad fraction over the objective → / budget
    (tpuserve.telemetry.slo math, applied bench-side per pass)."""
    from tpuserve.telemetry.slo import good_fraction

    delta = [max(0, a - b) for a, b in zip(after["counts"],
                                           before["counts"])]
    good = good_fraction(list(bounds), delta, objective_ms)
    if good is None:
        return None
    return round((1.0 - good) / (1.0 - availability), 3)


def warmup_is_stable(values: list[float], tol: float = 0.10) -> bool:
    """True once the last two warmup passes agree within ``tol`` (relative
    to the larger): the signal that executable warmup, arena ramp, and TCP
    slow-start have washed out and measurement may begin (ISSUE 5 satellite:
    r05's pass 1 of 3 was consistently ~27% cold despite one warmup pass)."""
    if len(values) < 2:
        return False
    a, b = values[-2], values[-1]
    hi = max(a, b)
    return hi > 0 and abs(a - b) / hi <= tol


def bench_self_check(line: dict) -> list[str]:
    """Internal-consistency asserts on the final JSON (printed to stderr,
    nonzero exit). >110% of the wire ceiling means the ceiling math is
    wrong, not that the server beat physics (BENCH_r05 reported 162.7%:
    the link rate was measured at a transfer size the serving path never
    uses); a visible hit rate on the miss-only passes means the distinct
    payload pool failed and cache hits are inflating the headline."""
    failures = []
    pct = line.get("pct_of_wire_ceiling")
    if pct is not None and pct > 110:
        failures.append(
            f"pct_of_wire_ceiling={pct} > 110: achieved throughput exceeds "
            "the measured wire ceiling — link_mbps and the per-image wire "
            "bytes are inconsistent")
    mhr = line.get("miss_pass_hit_rate")
    if mhr is not None and mhr > 0.05:
        failures.append(
            f"miss_pass_hit_rate={mhr} > 0.05: the miss-only passes hit the "
            "result cache; the headline is not pure model throughput")
    delta = line.get("compile_delta_measured")
    if delta is not None and delta != 0:
        failures.append(
            f"compile_delta_measured={delta} != 0: the measured passes "
            "recompiled — the variant registry's zero-steady-state-"
            "recompile obligation does not hold at the served config")
    return failures


def build_state(mode: str, wire_format: str, wire: int, buckets: list[int],
                quantize: str | None, parallel_mode: str = "",
                parallel_chips: int = 0, ingest_loops: int = 1):
    from tpuserve.config import (CacheConfig, ModelConfig, ParallelConfig,
                                 ServerConfig)
    from tpuserve.server import ServerState

    cfg = ServerConfig(
        host="127.0.0.1",
        port=int(os.environ.get("BENCH_PORT", 18321)),
        decode_threads=4,
        # Parallel ingest (ISSUE 11): N accept loops via SO_REUSEPORT so
        # one asyncio read loop is not the choke point feeding the mesh.
        ingest_loops=max(1, ingest_loops),
        # Multi-chip serving plan (ISSUE 7): BENCH_PARALLEL flips the whole
        # run between sharded-batch (default via the model's parallelism)
        # and replica-per-chip; BENCH_NCHIPS bounds the device set.
        parallel=ParallelConfig(mode=parallel_mode, n_chips=parallel_chips),
        # Demand-shaping layer (ISSUE 5): result cache + coalescing armed,
        # with a capacity deliberately SMALLER than the miss-pass distinct
        # pool so the measured passes are provably miss-only (LRU
        # round-robin thrash) while the hit-heavy pass measures the cache.
        # Adaptive batching is on by default ([adaptive] in config.py).
        cache=CacheConfig(
            enabled=bool(int(env_f("BENCH_CACHE", 1))),
            capacity=int(env_f("BENCH_CACHE_CAPACITY", 16)),
        ),
        # 1-core dev host: the executor hop only adds latency. Set
        # BENCH_DECODE_INLINE=0 on hosts with real CPU parallelism.
        decode_inline=bool(int(os.environ.get("BENCH_DECODE_INLINE", "1"))),
        startup_canary=False,
        compilation_cache_dir=os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".jaxcache"),
        models=[
            ModelConfig(
                name="resnet50",
                family="resnet50",
                batch_buckets=buckets,
                deadline_ms=env_f("BENCH_DEADLINE_MS", 100.0),
                dtype="bfloat16",
                # Always shard over the data axis: on one chip this equals
                # single-device serving, and on a v5e-8 it uses every chip —
                # the vs_baseline math scales the target by len(jax.devices()),
                # so the served path must scale with it too.
                parallelism="sharded",
                request_timeout_ms=60_000.0,
                max_inflight=4,
                wire_size=wire,
                wire_format=wire_format,
                # Weight-only int8 serves the headline run by default
                # (ISSUE 6: quantize on the measured hot path; halves HBM
                # weight streaming + param upload). BENCH_QUANTIZE="" -> fp.
                quantize=quantize,
                session_mode="recycle" if mode == "recycle" else "direct",
                relay_workers=int(env_f("BENCH_WORKERS", 3)),
                relay_slots=int(env_f("BENCH_SLOTS", 6)),
                relay_epoch_images=int(env_f("BENCH_EPOCH_IMAGES", 2048)),
                relay_epoch_ms=env_f("BENCH_EPOCH_MS", 3000.0),
            )
        ],
    )
    state = ServerState(cfg)
    state.build()
    return state, cfg


async def run_load(cfg, payload: bytes, ctype: str, duration: float,
                   warmup: float, concurrency: int, rate: float | None,
                   client_batch: int = 0, distinct: int = 0,
                   synth: str = "jpeg", edge: int = 0,
                   wire_proto: str = "", frame_kind: str = "yuv420",
                   procs: int = 1) -> dict:
    """Drive the (already running) server with the out-of-process loadgen.

    ``distinct > 1`` switches to a pool of that many distinct synthetic
    payloads (miss-only cache workload; the loadgen generates them from
    ``synth``/``edge``); otherwise the single ``payload`` repeats
    (hit-heavy once the cache is warm). ``wire_proto="frame"`` sends
    framed multi-item bodies (the ingest fast path; ``client_batch`` items
    per frame), and ``procs > 1`` fans the load over that many worker
    processes with disjoint seed pools (offered-load calibration: the
    bottleneck must be the server, not one client event loop)."""
    import tempfile

    payload_path = None
    args = [
        sys.executable, "-m", "tpuserve", "bench",
        "--url", f"http://{cfg.host}:{cfg.port}",
        "--model", "resnet50", "--verb", "classify",
        "--duration", str(duration), "--warmup", str(warmup),
        "--concurrency", str(concurrency),
        "--content-type", ctype,
    ]
    if procs > 1:
        args += ["--procs", str(procs)]
    if wire_proto == "frame":
        args += ["--wire", "frame", "--frame-kind", frame_kind,
                 "--edge", str(edge)]
        if distinct > 1:
            args += ["--distinct", str(distinct)]
    elif distinct > 1:
        args += ["--distinct", str(distinct), "--synthetic", synth,
                 "--edge", str(edge)]
    else:
        with tempfile.NamedTemporaryFile(suffix=".bin", delete=False) as f:
            f.write(payload)
            payload_path = f.name
        args += ["--payload", payload_path]
    if client_batch > 1:
        args += ["--batch", str(client_batch)]
    if rate:
        args += ["--rate", str(rate)]
    try:
        proc = await asyncio.create_subprocess_exec(
            *args,
            stdout=asyncio.subprocess.PIPE,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        out, _ = await proc.communicate()
        return json.loads(out.decode())
    finally:
        if payload_path is not None:
            os.unlink(payload_path)


def print_breakdown(state, header: str) -> None:
    """Always-on phase breakdown (VERDICT r2 item 1): stderr, not opt-in."""
    s = state.metrics.summary()
    print(f"# --- {header}: phase breakdown (ms) ---", file=sys.stderr)
    for key in sorted(s["latency"]):
        v = s["latency"][key]
        print(f"#   {key}: n={v['n']} mean={v['mean_ms']:.1f} "
              f"p50={v['p50_ms']:.1f} p99={v['p99_ms']:.1f}", file=sys.stderr)
    for key in sorted(s["counters"]):
        print(f"#   {key}: {s['counters'][key]:.0f}", file=sys.stderr)
    for name, rt in state.runtimes.items():
        d = rt.describe()
        if "stats" in d:
            print(f"#   {name} pool: {d['stats']}", file=sys.stderr)


def _gen_model_config(bench_model: str):
    """The generative model served by BENCH_MODEL=textgen|sd15 (ISSUE 9).
    Sizes are the family defaults (env-overridable); buckets size the
    engine's slot block via [genserve] slots = 0."""
    from tpuserve.config import ModelConfig

    slots = int(env_f("BENCH_GEN_SLOTS", 8))
    if bench_model == "textgen":
        return ModelConfig(
            name="textgen", family="textgen",
            batch_buckets=[1, max(2, slots // 2), slots],
            dtype="bfloat16", parallelism="single",
            request_timeout_ms=120_000.0,
            options=dict(
                layers=int(env_f("BENCH_GEN_LAYERS", 4)),
                d_model=int(env_f("BENCH_GEN_DMODEL", 256)),
                prompt_len=int(env_f("BENCH_GEN_PROMPT", 32)),
                max_new_tokens=int(env_f("BENCH_GEN_MAX_NEW", 64)),
                attention=os.environ.get("BENCH_GEN_ATTENTION", "dense"),
            ))
    return ModelConfig(
        name="sd15", family="sd15", batch_buckets=[1, max(2, slots)],
        dtype="bfloat16", parallelism="single",
        image_size=int(env_f("BENCH_SD_IMAGE", 512)),
        request_timeout_ms=600_000.0,
        options=dict(steps=int(env_f("BENCH_SD_STEPS", 20))))


async def _run_gen_load(cfg, model: str, duration: float, warmup: float,
                        concurrency: int, distinct: int, synth: str,
                        max_new_hi: int) -> dict:
    """Out-of-process mixed-length prompt load against a running server."""
    args = [
        sys.executable, "-m", "tpuserve", "bench",
        "--url", f"http://{cfg.host}:{cfg.port}",
        "--model", model, "--verb", "generate",
        "--duration", str(duration), "--warmup", str(warmup),
        "--concurrency", str(concurrency),
        "--content-type", "application/json",
        "--distinct", str(distinct), "--synthetic", synth,
        "--max-new", f"2,{max_new_hi}",
    ]
    proc = await asyncio.create_subprocess_exec(
        *args, stdout=asyncio.subprocess.PIPE,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    out, _ = await proc.communicate()
    return json.loads(out.decode())


def main_generative(bench_model: str) -> int:
    """BENCH_MODEL=textgen|sd15: the generative headline (ISSUE 9).

    Two passes over the SAME mixed-output-length prompt pool:

    1. **engine** — [genserve] on: iteration-level continuous batching
       (finished sequences exit early, queued work folds in mid-flight).
       Headline = tokens/s (textgen) or images/min (sd15), computed from
       the server's ``gen_units_total`` delta — counting requests would
       hide the mixed lengths the engine exists for.
    2. **locked** — the same model through the static batcher: every lane
       pays the full generation loop (textgen's fori_loop cap / the
       one-executable denoise). ``speedup_vs_locked`` is the iteration-
       level scheduling gain at this workload mix.

    The roofline block attributes PER-ITERATION phases (insert = prefill/
    encode, step = one decode/denoise iteration, extract = tail decode)
    from the engine's gen_*_ms histograms."""
    import jax

    from tpuserve.config import GenserveConfig, ServerConfig
    from tpuserve.server import ServerState, make_app

    t_all = time.time()
    duration = env_f("BENCH_DURATION", 20)
    warmup = env_f("BENCH_WARMUP", 4)
    concurrency = int(env_f("BENCH_CONCURRENCY", 16))
    distinct = int(env_f("BENCH_DISTINCT", 64))
    slots = int(env_f("BENCH_GEN_SLOTS", 8))
    synth = "prompt" if bench_model == "textgen" else "sd-prompt"
    mcfg = _gen_model_config(bench_model)
    max_new_hi = int(mcfg.options.get("max_new_tokens", 64)) \
        if bench_model == "textgen" else 0
    cache_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jaxcache")

    async def one_pass(genserve_on: bool, parallel_mode: str = "",
                       n_chips: int = 0) -> tuple[dict, dict, "ServerState"]:
        from aiohttp import web

        from tpuserve.config import ParallelConfig

        cfg = ServerConfig(
            host="127.0.0.1", port=int(os.environ.get("BENCH_PORT", 18321)),
            decode_threads=4, startup_canary=False,
            decode_inline=bool(int(os.environ.get("BENCH_DECODE_INLINE", "1"))),
            compilation_cache_dir=cache_dir,
            # Mesh legs (ISSUE 20): BENCH_PARALLEL flips generation between
            # replica-per-chip engines and the sharded decode, BENCH_NCHIPS
            # bounds the device set — same knobs as the one-shot bench.
            parallel=ParallelConfig(mode=parallel_mode, n_chips=n_chips),
            genserve=GenserveConfig(enabled=genserve_on, slots=slots),
            models=[_gen_model_config(bench_model)])
        state = ServerState(cfg)
        t0 = time.time()
        leg = parallel_mode or ("engine" if genserve_on else "locked")
        state.build()
        print(f"# {leg} build took {time.time() - t0:.1f}s", file=sys.stderr)
        runner = web.AppRunner(make_app(state), access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, cfg.host, cfg.port)
        await site.start()
        name = cfg.models[0].name
        try:
            u0 = state.metrics.counter(
                f"gen_units_total{{model={name}}}").value
            i0 = state.metrics.counter(f"items_total{{model={name}}}").value
            c0 = state.metrics.counter(
                f"runtime_compiles_total{{model={name}}}").value
            res = await _run_gen_load(cfg, name, duration, warmup,
                                      concurrency, distinct, synth,
                                      max_new_hi)
            counters = {
                "units": state.metrics.counter(
                    f"gen_units_total{{model={name}}}").value - u0,
                "items": state.metrics.counter(
                    f"items_total{{model={name}}}").value - i0,
                # Steady-state compile delta over the measured load — the
                # zero-recompile obligation, proven per leg.
                "compiles_delta": state.metrics.counter(
                    f"runtime_compiles_total{{model={name}}}").value - c0,
            }
            summary = state.metrics.summary()
            print_breakdown(state, leg)
            return res, {"counters": counters, "summary": summary}, state
        finally:
            await runner.cleanup()

    async def run() -> dict:
        eng_res, eng_side, eng_state = await one_pass(True)
        if eng_res.get("n_err"):
            print(f"# engine pass errors: {eng_res}", file=sys.stderr)
        # Output units per request from the engine pass's own server-side
        # accounting (the pool mixes lengths, so a constant would lie).
        c = eng_side["counters"]
        units_per_req = c["units"] / c["items"] if c["items"] else 0.0
        eng_rps = eng_res["throughput_per_s"]
        eng_units_s = eng_rps * units_per_req

        locked = None
        if int(env_f("BENCH_GEN_BASELINE", 1)):
            locked_res, _locked_side, _ = await one_pass(False)
            locked = {
                "requests_per_s": locked_res["throughput_per_s"],
                "p50_ms": locked_res["p50_ms"],
                "p99_ms": locked_res["p99_ms"],
                "n_err": locked_res["n_err"],
            }

        # Mesh legs (ISSUE 20): the same prompt pool through replica-per-
        # chip engines and/or the sharded decode. On a TPU-less box these
        # run on forced host devices — scheduling-fidelity evidence
        # (balance, compile delta), never throughput claims; the backend
        # block and the artifact label say so.
        mesh_modes = [m.strip() for m in
                      os.environ.get("BENCH_PARALLEL", "").split(",")
                      if m.strip()]
        mesh_chips = int(env_f("BENCH_NCHIPS", 0))
        mesh_legs: dict = {}
        for mode in mesh_modes:
            m_res, m_side, m_state = await one_pass(
                True, parallel_mode=mode, n_chips=mesh_chips)
            mc = m_side["counters"]
            m_upr = mc["units"] / mc["items"] if mc["items"] else 0.0
            m_units_s = m_res["throughput_per_s"] * m_upr
            m_name = m_state.cfg.models[0].name
            m_rt = m_state.runtimes[m_name]
            n_chips_real = int(getattr(m_rt, "n_chips", 1))
            m_gs = m_state.engines[m_name].pipeline_stats()
            unit_key = ("per_chip_tokens_s" if bench_model == "textgen"
                        else "per_chip_images_min")
            m_value = (m_units_s if bench_model == "textgen"
                       else m_units_s * 60.0)
            mesh_legs[mode] = {
                "value": round(m_value, 2),
                unit_key: round(m_value / max(1, n_chips_real), 2),
                "requests_per_s": round(m_res["throughput_per_s"], 2),
                "p50_ms": m_res["p50_ms"],
                "p99_ms": m_res["p99_ms"],
                "n_err": m_res["n_err"],
                "compiles_delta": mc["compiles_delta"],
                "parallel": {
                    "mode": str(getattr(m_rt, "parallel_signature", mode)),
                    "n_chips": n_chips_real,
                },
                "per_replica": m_gs.get("per_replica"),
            }

        lat = eng_side["summary"]["latency"]
        name = bench_model if bench_model == "textgen" else "sd15"

        def p50(metric: str):
            row = lat.get(f"{metric}{{model={name}}}")
            return round(row["p50_ms"], 3) if row else None

        gs = eng_state.engines[name].pipeline_stats()
        if bench_model == "textgen":
            metric, value, unit = "textgen_tokens_s", eng_units_s, "tok/s"
        else:
            metric, value, unit = "sd15_images_min", eng_units_s * 60.0, "img/min"
        line = {
            "metric": metric,
            "value": round(value, 2),
            "unit": unit,
            "requests_per_s": round(eng_rps, 2),
            "units_per_request": round(units_per_req, 2),
            "p50_ms": eng_res["p50_ms"],
            "p99_ms": eng_res["p99_ms"],
            "n_err": eng_res["n_err"],
            "mixed_lengths": {"distinct": distinct,
                              "max_new_range": [2, max_new_hi]
                              if max_new_hi else None},
            "genserve": {
                "slots": slots,
                "iterations_total": gs["iterations_total"],
                "fold_ins_total": gs["fold_ins_total"],
                "early_exits_total": gs["early_exits_total"],
                "evictions_total": gs["evictions_total"],
            },
            # Per-iteration phase attribution (the gen roofline): what one
            # admission (prefill/encode), one iteration, and one tail
            # extract cost at p50 on this config.
            "roofline": {
                "insert_ms_p50": p50("gen_insert_ms"),
                "step_ms_p50": p50("gen_step_ms"),
                "extract_ms_p50": p50("gen_extract_ms"),
                "steps_per_request_ewma": gs["iters_per_request_ewma"],
            },
            "locked_batch": locked,
            "speedup_vs_locked": round(
                eng_rps / locked["requests_per_s"], 2)
            if locked and locked["requests_per_s"] else None,
            "mesh": {
                "n_chips_requested": mesh_chips,
                "legs": mesh_legs,
                "note": ("cpu-backend forced-host-device legs measure "
                         "scheduling fidelity (balance, compile delta), "
                         "not TPU throughput"
                         if jax.default_backend() == "cpu" else None),
            } if mesh_legs else None,
            "backend": {
                "platform": jax.default_backend(),
                "device_count": jax.device_count(),
                "jax_version": jax.__version__,
            },
            "config": {"model": bench_model, "duration_s": duration,
                       "concurrency": concurrency,
                       "options": dict(mcfg.options)},
            "wall_s": round(time.time() - t_all, 1),
        }
        return line

    line = asyncio.run(run())
    print(json.dumps(line))
    return 0 if line["n_err"] == 0 and line["value"] > 0 else 1


async def _run_stream_load(cfg, model: str, duration: float, warmup: float,
                           concurrency: int, distinct: int, max_new_hi: int,
                           long_every: int = 0, long_words: int = 16) -> dict:
    """Out-of-process STREAMING prompt load; ``long_every`` > 0 skews the
    pool with max-length prompts (the paged-KV workload)."""
    args = [
        sys.executable, "-m", "tpuserve", "bench",
        "--url", f"http://{cfg.host}:{cfg.port}",
        "--model", model, "--verb", "generate", "--stream",
        "--duration", str(duration), "--warmup", str(warmup),
        "--concurrency", str(concurrency),
        "--content-type", "application/json",
        "--distinct", str(distinct), "--synthetic", "prompt",
        "--max-new", f"2,{max_new_hi}",
        "--long-every", str(long_every), "--long-words", str(long_words),
    ]
    proc = await asyncio.create_subprocess_exec(
        *args, stdout=asyncio.subprocess.PIPE,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    out, _ = await proc.communicate()
    return json.loads(out.decode())


def main_paged_kv() -> int:
    """BENCH_KV_PAGING=1 (textgen): the long-context paged-KV headline
    (ISSUE 18). Three passes:

    1. **paged / unloaded** — streaming load over a uniform short-prompt
       pool: the baseline inter-token gap distribution.
    2. **paged / loaded** — the SAME rate of shorts with a max-length
       prompt injected every BENCH_KV_LONG_EVERY bodies, so chunked
       prefills continuously interleave with decode. The headline
       tokens/s comes from this pass, and ``gap_p99_loaded_vs_unloaded``
       is the flatness ratio the smoke gates on.
    3. **dense comparison** — kv_paging off, same skewed pool:
       ``paged_vs_dense_tokens_s`` is the end-to-end win (or cost) of
       paging at this geometry.

    The JSON adds ``max_concurrent_slots`` (peak simultaneously-active
    slots, server-side) and ``kv_bytes_per_slot`` (device KV bytes over
    that peak) — the capacity claim paging exists for."""
    import jax

    from tpuserve.config import GenserveConfig, ServerConfig
    from tpuserve.server import ServerState, make_app

    t_all = time.time()
    duration = env_f("BENCH_DURATION", 20)
    warmup = env_f("BENCH_WARMUP", 4)
    concurrency = int(env_f("BENCH_CONCURRENCY", 16))
    distinct = int(env_f("BENCH_DISTINCT", 64))
    slots = int(env_f("BENCH_GEN_SLOTS", 8))
    page_tokens = int(env_f("BENCH_KV_PAGE_TOKENS", 16))
    prefill_chunk = int(env_f("BENCH_KV_CHUNK", 8))
    long_every = int(env_f("BENCH_KV_LONG_EVERY", 4))
    mcfg = _gen_model_config("textgen")
    max_new_hi = int(mcfg.options.get("max_new_tokens", 64))
    long_words = int(mcfg.options.get("prompt_len", 32))
    cache_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jaxcache")

    async def serve(paged: bool):
        from aiohttp import web

        cfg = ServerConfig(
            host="127.0.0.1", port=int(os.environ.get("BENCH_PORT", 18321)),
            decode_threads=4, startup_canary=False,
            decode_inline=bool(int(os.environ.get("BENCH_DECODE_INLINE",
                                                  "1"))),
            compilation_cache_dir=cache_dir,
            genserve=GenserveConfig(
                enabled=True, slots=slots, kv_paging=paged,
                kv_page_tokens=page_tokens,
                prefill_chunk=prefill_chunk if paged else 0),
            models=[_gen_model_config("textgen")])
        state = ServerState(cfg)
        t0 = time.time()
        state.build()
        print(f"# {'paged' if paged else 'dense'} build took "
              f"{time.time() - t0:.1f}s", file=sys.stderr)
        runner = web.AppRunner(make_app(state), access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, cfg.host, cfg.port)
        await site.start()
        return cfg, state, runner

    async def run() -> dict:
        cfg, state, runner = await serve(paged=True)
        try:
            unloaded = await _run_stream_load(
                cfg, "textgen", duration, warmup, concurrency, distinct,
                max_new_hi)
            loaded = await _run_stream_load(
                cfg, "textgen", duration, warmup, concurrency, distinct,
                max_new_hi, long_every=long_every, long_words=long_words)
            gs = state.engines["textgen"].pipeline_stats()
            print_breakdown(state, "paged")
        finally:
            await runner.cleanup()

        dense_tokens_s = None
        if int(env_f("BENCH_GEN_BASELINE", 1)):
            cfg, state, runner = await serve(paged=False)
            try:
                dense = await _run_stream_load(
                    cfg, "textgen", duration, warmup, concurrency, distinct,
                    max_new_hi, long_every=long_every,
                    long_words=long_words)
                dense_tokens_s = dense["tokens_per_s"]
                print_breakdown(state, "dense")
            finally:
                await runner.cleanup()

        peak = int(gs.get("peak_active", 0))
        kv_bytes = int(gs.get("kv", {}).get("kv_bytes", 0))
        u99, l99 = (unloaded["inter_token_gap_p99_ms"],
                    loaded["inter_token_gap_p99_ms"])

        def gap_block(s: dict) -> dict:
            return {k: s[k] for k in
                    ("inter_token_gap_p50_ms", "inter_token_gap_p99_ms",
                     "inter_token_gap_max_ms", "inter_token_gap_hist_ms",
                     "tokens_per_s", "first_token_p50_ms", "n_ok", "n_err",
                     "torn_streams")}

        line = {
            "metric": "pagedkv_tokens_s",
            "value": loaded["tokens_per_s"],
            "unit": "tok/s",
            "max_concurrent_slots": peak,
            "kv_bytes_per_slot": round(kv_bytes / peak) if peak else None,
            "unloaded": gap_block(unloaded),
            "loaded": gap_block(loaded),
            "gap_p99_loaded_vs_unloaded": round(l99 / u99, 3)
            if u99 else None,
            "paged_vs_dense_tokens_s": round(
                loaded["tokens_per_s"] / dense_tokens_s, 3)
            if dense_tokens_s else None,
            "dense_tokens_s": dense_tokens_s,
            "genserve": {
                "slots": slots,
                "kv": gs.get("kv"),
                "iterations_total": gs["iterations_total"],
                "fold_ins_total": gs["fold_ins_total"],
            },
            "backend": {
                "platform": jax.default_backend(),
                "device_count": jax.device_count(),
                "jax_version": jax.__version__,
            },
            "config": {"model": "textgen", "duration_s": duration,
                       "concurrency": concurrency,
                       "page_tokens": page_tokens,
                       "prefill_chunk": prefill_chunk,
                       "long_every": long_every,
                       "options": dict(mcfg.options)},
            "wall_s": round(time.time() - t_all, 1),
        }
        return line

    line = asyncio.run(run())
    print(json.dumps(line))
    ok = (line["value"] > 0
          and line["loaded"]["torn_streams"] == 0
          and line["unloaded"]["torn_streams"] == 0)
    return 0 if ok else 1


def main() -> int:
    t_all = time.time()
    bench_model = os.environ.get("BENCH_MODEL", "")
    if int(env_f("BENCH_KV_PAGING", 0)):
        if bench_model not in ("", "textgen"):
            print(f"# BENCH_KV_PAGING needs BENCH_MODEL=textgen, "
                  f"got {bench_model!r}", file=sys.stderr)
            return 2
        return main_paged_kv()
    if bench_model:
        if bench_model not in ("textgen", "sd15"):
            print(f"# unknown BENCH_MODEL={bench_model!r}; "
                  "use textgen|sd15 or unset", file=sys.stderr)
            return 2
        return main_generative(bench_model)
    mode = os.environ.get("BENCH_MODE", "direct")
    wire_format = os.environ.get("BENCH_WIRE_FORMAT", "yuv420")
    wire = int(env_f("BENCH_WIRE", 160))
    # Client wire protocol (ISSUE 11): "frame" (default) POSTs framed
    # binary multi-item bodies (application/x-tpuserve-frame, parsed
    # zero-copy, each frame filling one device bucket); "jpeg"/"npy"
    # restore the single-image reference-shaped POST.
    wire_proto = os.environ.get("BENCH_WIRE_PROTO", "frame")
    if wire_proto not in ("frame", "jpeg", "npy"):
        print(f"# unknown BENCH_WIRE_PROTO={wire_proto!r}; "
              "use frame|jpeg|npy", file=sys.stderr)
        return 2
    duration = env_f("BENCH_DURATION", 20)
    warmup = env_f("BENCH_WARMUP", 6)

    # Weight-only int8 serves the headline run by default (ISSUE 6); set
    # BENCH_QUANTIZE="" for full-precision, "int8c" for int8 compute.
    quantize = os.environ.get("BENCH_QUANTIZE", "int8") or None

    # Multi-chip plan (ISSUE 7): serving mode override + chip bound, plus
    # the chip count probed in a FRESH subprocess (this process must not
    # take the accelerator before its own link/chip probes run). The count
    # shapes the offered load below — an 8-chip mesh driven with a
    # single-chip connection count is demand-starved by construction.
    parallel_mode = os.environ.get("BENCH_PARALLEL", "")
    parallel_chips = int(env_f("BENCH_NCHIPS", 0))
    from tpuserve.bench.probes import probe_device_count

    n_chips = parallel_chips or probe_device_count(
        cwd=os.path.dirname(os.path.abspath(__file__)))
    print(f"# devices: {n_chips} visible "
          f"(parallel mode {parallel_mode or 'per-model sharded'})",
          file=sys.stderr)

    link_mbps = measure_link_rate_mbps()
    # Per-item wire bytes at the SERVED format — with the framed protocol
    # this is frame.item_nbytes (1.5 B/px yuv420), the bytes an item
    # actually costs on BOTH links: the HTTP body carries exactly the
    # device planes (no npy 3 B/px RGB detour, ISSUE 11), and the H2D
    # transfer ships the same bytes into the mesh.
    from tpuserve import frame as frame_wire

    frame_kind = frame_wire.KIND_BY_WIRE_FORMAT[wire_format]
    if wire_proto == "frame":
        img_bytes = frame_wire.item_nbytes(frame_kind, wire)
    else:
        bpp = 1.5 if wire_format == "yuv420" else 3.0
        img_bytes = int(wire * wire * bpp)
    ceiling = link_mbps * 1e6 / img_bytes if link_mbps else float("nan")
    print(f"# link: {link_mbps} MB/s real sustained; wire {img_bytes} B/img "
          f"-> wire-bound ceiling {ceiling:.0f} img/s", file=sys.stderr)

    # Batch buckets and loadgen concurrency adapt to the measured link unless
    # pinned: the tunnel swings 2-25 MB/s hour to hour, and when it is slow a
    # 256-wide bucket is ~5 s of wire per batch — pure queueing (the chip is
    # idle either way), no throughput. Size the top bucket to ~0.25 s of wire
    # and keep ~3 batches in flight: on a wire-bound link a batch's own
    # transfer dominates its compute-phase wall time, so halving the batch
    # halves per-batch latency at unchanged throughput (the pipeline keeps
    # the link saturated with depth x h2d workers; ISSUE 6 — the serving
    # compute-phase p50 is a headline number now, not just the img/s).
    if "BENCH_BUCKETS" in os.environ:
        buckets = [int(b) for b in os.environ["BENCH_BUCKETS"].split(",")]
    else:
        top = 8
        if ceiling > 0:
            while top * 2 <= min(256, ceiling * 0.25):
                top *= 2
        else:
            top = 256
        buckets = sorted({max(8, top // 2), top})
    # Connection count scales with the chip count (ISSUE 7 satellite:
    # ~3 top-bucket batches of closed-loop demand in flight PER CHIP).
    from tpuserve.bench.loadgen import closed_loop_concurrency

    concurrency = int(env_f("BENCH_CONCURRENCY",
                            closed_loop_concurrency(buckets, n_chips)))
    # Framed multi-item POSTs: each frame fills one top device bucket, so
    # a connection's in-flight demand is a whole batch — scale the
    # connection count down accordingly (the closed-loop math above is
    # per-ITEM demand).
    frame_items = 0
    if wire_proto == "frame":
        frame_items = int(env_f("BENCH_FRAME_ITEMS", max(buckets)))
        concurrency = int(env_f("BENCH_CONCURRENCY", max(
            8, concurrency // max(1, frame_items))))

    # Offered-load calibration (ISSUE 11 satellite): one asyncio client
    # process is ~one core of HTTP work — feeding 8 chips it becomes the
    # measured bottleneck. Fan the loadgen over worker processes when the
    # host has cores for it (each with a disjoint synthetic seed pool).
    load_procs = int(env_f("BENCH_LOAD_PROCS", min(
        4, max(1, n_chips // 2), max(1, (os.cpu_count() or 1) // 2))))

    # Parallel ingest loops for the served process (ISSUE 11): default one
    # extra accept loop per 4 chips, bounded by host cores.
    ingest_loops = int(env_f("BENCH_INGEST_LOOPS", min(
        4, max(1, n_chips // 4 + 1), max(1, (os.cpu_count() or 1) // 2))))

    print(f"# config: mode={mode} wire={wire_proto}:{wire_format}@{wire} "
          f"buckets={buckets} concurrency={concurrency} quantize={quantize} "
          f"n_chips={n_chips} frame_items={frame_items} "
          f"load_procs={load_procs} ingest_loops={ingest_loops}",
          file=sys.stderr)

    # Fresh per-run chip-compute probes (VERDICT r3 weak 2 banned the stale
    # hardcoded constant), in their own subprocesses BEFORE the server takes
    # the chip, sharing the server's persistent XLA cache so each bucket's
    # probe compiles once EVER. The batch-256 probe is the chip ceiling for
    # vs-baseline continuity; the per-bucket probes at the SERVED config
    # (wire/quantize) are the device-time terms of the roofline's compute
    # split. BENCH_CHIP_PROBE=0 skips all (fields become null, never stale).
    chip = {}
    raw_by_bucket: dict[int, float | None] = {}
    cache_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jaxcache")
    if int(env_f("BENCH_CHIP_PROBE", 1)):
        from tpuserve.bench.probes import measure_chip_img_s

        chip = measure_chip_img_s(batch=int(env_f("BENCH_CHIP_BATCH", 256)),
                                  cache_dir=cache_dir)
        print(f"# chip probe: {chip}", file=sys.stderr)
        if int(env_f("BENCH_ROOFLINE", 1)):
            for b in buckets:
                r = measure_chip_img_s(
                    batch=b, iters=int(env_f("BENCH_ROOFLINE_ITERS", 32)),
                    cache_dir=cache_dir,
                    mcfg_extra={"wire_size": wire, "wire_format": wire_format,
                                "quantize": quantize})
                print(f"# raw-executable probe bucket {b}: {r}",
                      file=sys.stderr)
                raw_by_bucket[b] = r.get("ms_per_batch")

    t0 = time.time()
    state, cfg = build_state(mode, wire_format, wire, buckets, quantize,
                             parallel_mode=parallel_mode,
                             parallel_chips=parallel_chips,
                             ingest_loops=ingest_loops)
    print(f"# build+compile+prewarm took {time.time() - t0:.1f}s", file=sys.stderr)

    from tpuserve.bench.loadgen import (
        synthetic_frame,
        synthetic_image_jpeg,
        synthetic_image_npy,
        synthetic_image_npy_batch,
    )

    # Payload shape. Framed wire (default): one application/x-tpuserve-frame
    # body of frame_items images per POST — the multi-item ingest fast
    # path; throughput counts items. BENCH_WIRE_PROTO=jpeg/npy restores the
    # reference-shaped single-image POST (BENCH_CLIENT_BATCH for npy client
    # batches).
    client_batch = int(env_f("BENCH_CLIENT_BATCH", 0))
    if wire_proto == "frame":
        client_batch = frame_items
        payload = synthetic_frame(wire, frame_items, wire_format)
        ctype = frame_wire.CONTENT_TYPE
    elif client_batch > 1:
        payload, ctype = synthetic_image_npy_batch(wire, client_batch), "application/x-npy"
    elif wire_proto == "jpeg" and os.environ.get("BENCH_PAYLOAD", "jpeg") == "jpeg":
        payload, ctype = synthetic_image_jpeg(wire), "image/jpeg"
    else:
        payload, ctype = synthetic_image_npy(wire), "application/x-npy"
    print(f"# payload: {len(payload)}-byte {wire}x{wire} {ctype}"
          + (f" x{client_batch}/POST" if client_batch > 1 else ""), file=sys.stderr)

    # Miss-only measured passes (ISSUE 5): a pool of distinct payloads
    # larger than the server's cache capacity, so the headline can never be
    # inflated by cache hits even with the cache armed. 0 restores the
    # single repeated payload (which with BENCH_CACHE=1 measures the cache,
    # not the model — that is what the separate hit-heavy pass is for).
    distinct = int(env_f("BENCH_DISTINCT", 64))
    synth_kind = ("jpeg" if client_batch <= 1
                  and os.environ.get("BENCH_PAYLOAD", "jpeg") == "jpeg"
                  else "npy")

    from tpuserve.cache import counter_snapshot, hit_rate

    async def run() -> dict:
        # ONE server lifecycle for every load phase: app cleanup tears down
        # the model state, so the server must outlive every loadgen run.
        from aiohttp import web

        from tpuserve.server import (make_app, start_ingest_loops,
                                     stop_ingest_loops)

        runner = web.AppRunner(make_app(state), access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, cfg.host, cfg.port,
                           reuse_port=True if cfg.ingest_loops > 1 else None)
        await site.start()
        # Parallel accept loops (ISSUE 11): same port via SO_REUSEPORT.
        ingest_threads = start_ingest_loops(state, cfg.host, cfg.port)
        for t in ingest_threads:
            await asyncio.get_running_loop().run_in_executor(
                None, t.wait_ready)
        try:
            # Discarded warmup passes, extended until stable (ISSUE 5
            # satellite; r05 pass 1 of 3 was still ~27% cold after ONE
            # warmup pass): keep warming until two consecutive passes land
            # within 10%, bounded by BENCH_MAX_WARMUP_PASSES. Every warmup
            # pass prints to stderr and the list + count ship in the JSON;
            # none enters the median.
            warmups: list[dict] = []
            if int(env_f("BENCH_WARMUP_PASS", 1)):
                max_wu = max(1, int(env_f("BENCH_MAX_WARMUP_PASSES", 4)))
                for i in range(max_wu):
                    w = await run_load(
                        cfg, payload, ctype, min(duration, 10.0),
                        warmup if i == 0 else 2, concurrency, None,
                        client_batch=client_batch, distinct=distinct,
                        synth=synth_kind, edge=wire, wire_proto=wire_proto,
                        frame_kind=wire_format, procs=load_procs)
                    warmups.append(w)
                    print(f"# warmup pass {i + 1} (discarded): {w}",
                          file=sys.stderr)
                    if warmup_is_stable(
                            [x["throughput_per_s"] for x in warmups]):
                        break
            # Median-of-3 measured closed-loop passes: the tunnel's rate
            # drifts on minute scales, so a single 20 s window under- or
            # over-draws it. The headline is the MEDIAN pass (max-of-N was
            # upward-biased — VERDICT r3 weak 3 / ADVICE r3); every pass
            # goes to stderr and the full list + spread ship in the JSON.
            # Measured closed-loop passes, extended until converged
            # (ISSUE 6 satellite: r05's three passes spread 480/658/606 —
            # 29% — so the headline was a lucky pass). Run at least
            # BENCH_CLOSED_PASSES; keep adding passes (capped at
            # BENCH_MAX_CLOSED_PASSES) until the best CONSECUTIVE window
            # of 3 agrees within BENCH_SPREAD_TARGET_PCT. The headline is
            # the MEDIAN of that window; the window, its spread, and its
            # CV all ship in the JSON.
            from tpuserve.bench.roofline import best_window, spread_pct

            min_passes = max(1, int(env_f("BENCH_CLOSED_PASSES", 3)))
            max_passes = max(min_passes,
                             int(env_f("BENCH_MAX_CLOSED_PASSES", 6)))
            spread_target = env_f("BENCH_SPREAD_TARGET_PCT", 15.0)
            win_k = min(3, min_passes)
            miss_c0 = counter_snapshot(state.metrics, "resnet50")
            # Zero-steady-state-recompile proof across the MEASURED window
            # (acceptance: the registry obligation holds at the served
            # 8-chip framed-wire config, not just in unit tests).
            rt_bench = state.runtimes.get("resnet50")
            comp0 = getattr(rt_bench, "compiles_total", None)
            # Telemetry evidence for the measured window (ISSUE 14): the
            # per-replica device-seconds ledger deltas over the window's
            # wall time become the `utilization` block, and each pass's
            # latency-histogram delta becomes a burn rate against the
            # bench SLO (BENCH_SLO_MS objective / BENCH_SLO_AVAIL target)
            # — the next TPU round lands with chip-occupancy proof
            # attached, not just a throughput number.
            slo_ms = env_f("BENCH_SLO_MS", 1000.0)
            slo_avail = env_f("BENCH_SLO_AVAIL", 0.999)
            total_hist = state.metrics.histogram(
                "latency_ms{model=resnet50,phase=total}")
            util0 = device_seconds_snapshot(state.metrics, "resnet50")
            wall0 = time.perf_counter()
            pass_burns: list[float | None] = []
            passes = []
            while True:
                # Pass-boundary independence: every pass regenerates the
                # SAME distinct pool (seeds 0..N-1), so a short pass that
                # issues fewer requests than the pool would leave entries
                # the next pass re-hits. Clearing makes miss-only passes
                # miss-only regardless of pass length; within a pass the
                # LRU round-robin thrash (pool > capacity) does the job.
                for c in state.caches.values():
                    c.clear()
                hist_before = total_hist.snapshot()
                res = await run_load(
                    cfg, payload, ctype, duration,
                    2 if warmups or passes else warmup,
                    concurrency, None, client_batch=client_batch,
                    distinct=distinct, synth=synth_kind, edge=wire,
                    wire_proto=wire_proto, frame_kind=wire_format,
                    procs=load_procs)
                pass_burns.append(burn_from_snapshots(
                    total_hist.bounds, hist_before, total_hist.snapshot(),
                    slo_ms, slo_avail))
                print(f"# closed-loop pass {len(passes) + 1}: {res} "
                      f"(burn {pass_burns[-1]})", file=sys.stderr)
                passes.append(res)
                if len(passes) < min_passes:
                    continue
                vals = [p["throughput_per_s"] for p in passes]
                _, win = best_window(vals, k=win_k)
                if spread_pct(win) < spread_target:
                    break
                if len(passes) >= max_passes:
                    print(f"# WARNING: pass spread {spread_pct(win):.1f}% "
                          f"never converged under {spread_target}% within "
                          f"{max_passes} passes", file=sys.stderr)
                    break
            measured_wall_s = time.perf_counter() - wall0
            util1 = device_seconds_snapshot(state.metrics, "resnet50")
            utilization = utilization_block(util0, util1, measured_wall_s,
                                            getattr(rt_bench, "n_chips", 1)
                                            or 1)
            miss_c1 = counter_snapshot(state.metrics, "resnet50")
            comp1 = getattr(rt_bench, "compiles_total", None)
            compile_delta = (comp1 - comp0) if comp0 is not None else None
            miss_delta = {k: miss_c1[k] - miss_c0[k] for k in miss_c1}
            vals = [p["throughput_per_s"] for p in passes]
            win_start, win_vals = best_window(vals, k=win_k)
            win_passes = passes[win_start:win_start + len(win_vals)]
            by_tp = sorted(win_passes, key=lambda r: r["throughput_per_s"])
            closed = by_tp[len(by_tp) // 2] if len(by_tp) % 2 else by_tp[len(by_tp) // 2 - 1]

            # Hit-heavy pass: ONE payload repeated, so after the first batch
            # every request answers from the cache (reported separately —
            # never the headline).
            hit_block = None
            if cfg.cache.enabled and int(env_f("BENCH_HIT_PASS", 1)):
                c0 = counter_snapshot(state.metrics, "resnet50")
                hit_res = await run_load(
                    cfg, payload, ctype, min(duration, 10.0), 2,
                    concurrency, None, client_batch=client_batch,
                    edge=wire, wire_proto=wire_proto,
                    frame_kind=wire_format, procs=load_procs)
                c1 = counter_snapshot(state.metrics, "resnet50")
                delta = {k: c1[k] - c0[k] for k in c1}
                hit_block = {
                    "throughput_per_s": hit_res["throughput_per_s"],
                    "p50_ms": hit_res["p50_ms"],
                    "p99_ms": hit_res["p99_ms"],
                    "n_err": hit_res["n_err"],
                    "cache_hit_rate": hit_rate(delta),
                    # null when the miss pass recorded nothing (degenerate
                    # short windows) — a ratio against ~0 is meaningless.
                    "speedup_vs_miss": (round(
                        hit_res["throughput_per_s"]
                        / closed["throughput_per_s"], 2)
                        if closed["throughput_per_s"] > 0 else None),
                }
                print(f"# hit-heavy pass: {hit_block}", file=sys.stderr)

            open_res = None
            # Open-loop rate is REQUESTS/s; closed throughput counts items.
            # Derived from the measured closed-loop rate, so it scales with
            # the chip count automatically — an 8-chip run is probed at 70%
            # of its own 8-chip throughput, not of a single-chip profile.
            rate = env_f("BENCH_OPEN_RATE", 0.0) or round(
                0.7 * closed["throughput_per_s"] / max(1, client_batch))
            if rate >= 1:
                open_res = await run_load(
                    cfg, payload, ctype, min(duration, 15), 3, concurrency,
                    rate, client_batch=client_batch, distinct=distinct,
                    synth=synth_kind, edge=wire, wire_proto=wire_proto,
                    frame_kind=wire_format, procs=load_procs)
                print(f"# open-loop @ {rate}/s: {open_res}", file=sys.stderr)
            ingest_stats = {
                str(i): {"requests": ih.requests.value,
                         "bytes": ih.bytes.value}
                for i, ih in sorted(state.ingest.items())}
            return {"closed": closed, "open": open_res, "passes": passes,
                    "window": {"start": win_start, "values": win_vals},
                    "warmups": warmups, "hit": hit_block,
                    "miss_hit_rate": hit_rate(miss_delta),
                    "compile_delta": compile_delta,
                    "ingest": ingest_stats,
                    "utilization": utilization,
                    "slo": {"objective_latency_ms": slo_ms,
                            "availability": slo_avail,
                            "per_pass_burn": pass_burns,
                            "worst_burn": max(
                                (b for b in pass_burns if b is not None),
                                default=None)}}
        finally:
            await stop_ingest_loops(ingest_threads)
            await runner.cleanup()

    r = asyncio.run(run())
    closed, open_res, passes, warmups = (r["closed"], r["open"], r["passes"],
                                         r["warmups"])
    print_breakdown(state, f"mode={mode}")

    # Backend provenance (ISSUE 6 satellite: BENCH_r05 said n_chips=1 while
    # MULTICHIP_r05 saw 8 devices — a reader could not tell a CPU run from
    # a TPU run). Recorded from the serving process's own backend; n_chips
    # is the count the serving path actually OCCUPIED (the runtime's mesh
    # footprint), which [parallel] n_chips may bound below the visible set.
    backend = {}
    try:
        import jax

        devs = jax.devices()
        backend = {
            "platform": jax.default_backend(),
            "device_kind": devs[0].device_kind if devs else None,
            "device_count": len(devs),
            "jax_version": jax.__version__,
        }
    except Exception as e:  # noqa: BLE001
        backend = {"error": str(e)}
    rt = state.runtimes.get("resnet50")
    served = getattr(rt, "n_chips", 0)
    n_chips = max(1, served or n_chips)
    parallel_info = {
        "mode": getattr(rt, "parallel_signature", mode),
        "n_chips": n_chips,
        "replicas": getattr(rt, "n_replicas", 1),
        "replica_batches_total": (rt.replica_batches()
                                  if hasattr(rt, "replica_batches") else None),
    }
    per_chip_target = TARGET_V5E8_IMG_S / CHIPS_IN_TARGET * n_chips

    # Wire-ceiling consistency (ISSUE 5 satellite; r05 reported 162.7% of
    # ceiling): the startup probe measures 8 MiB streaming chunks, but the
    # serving path transfers one BATCH at a time — on a high-latency link
    # the two rates differ enough to put "achieved" above "ceiling". Re-probe
    # at the actual per-batch transfer size and take the better of the two
    # measurements as the ceiling estimate (also absorbs tunnel rate drift
    # between the startup probe and the measured passes).
    link_mbps_matched = None
    if ceiling == ceiling and int(env_f("BENCH_LINK_REPROBE", 1)):
        batch_bytes = max(buckets) * img_bytes
        link_mbps_matched = measure_link_rate_mbps(chunk_bytes=batch_bytes)
        print(f"# link re-probe at serving batch size ({batch_bytes} B): "
              f"{link_mbps_matched} MB/s", file=sys.stderr)
    best_link = max(link_mbps, link_mbps_matched or 0.0)
    ceiling = best_link * 1e6 / img_bytes if best_link else float("nan")

    value = closed["throughput_per_s"]
    line = {
        "metric": "resnet50_http_throughput",
        "value": value,
        "unit": "img/s",
        "vs_baseline": round(value / per_chip_target, 4),
        "p50_ms": closed["p50_ms"],
        "p99_ms": closed["p99_ms"],
        "n_chips": n_chips,
        # Per-chip breakdown (ISSUE 7): the aggregate divided over the
        # chips the run occupied, next to the per-replica dispatch counts
        # in `parallel` so a starved chip is visible in the headline JSON.
        "per_chip_img_s": round(value / n_chips, 1),
        "parallel": parallel_info,
        "backend": backend,
        "errors": closed["n_err"],
        "mode": mode,
        "wire": (f"frame:{wire_format}@{wire}x{frame_items}"
                 if wire_proto == "frame" else f"{wire_format}@{wire}"),
        "quantize": quantize,
        # Ingest fast path (ISSUE 11): accept-loop fan-out of the served
        # process, load-generator worker processes, items per framed POST,
        # per-loop request balance, and the zero-recompile proof across
        # the measured passes (must be 0 — self-checked).
        "ingest_loops": cfg.ingest_loops,
        "load_workers": load_procs,
        "frame_items_per_post": frame_items or None,
        "ingest": r["ingest"],
        "compile_delta_measured": r["compile_delta"],
        # Miss-only workload shape: >1 means the measured passes cycled a
        # distinct-payload pool bigger than the cache (headline = model).
        "distinct_payloads": distinct,
        "closed_passes": [p["throughput_per_s"] for p in passes],
        # Variance discipline (ISSUE 6 satellite): the headline is the
        # median of the best CONSECUTIVE window of passes, not of whatever
        # three happened to run; spread/CV are over that window so the
        # reader can judge convergence (spread_converged says whether the
        # 15% target was met before the pass cap).
        "measured_window": r["window"]["values"],
        "measured_window_start": r["window"]["start"],
        "closed_spread_per_s": round(
            max(r["window"]["values"]) - min(r["window"]["values"]), 1)
        if r["window"]["values"] else None,
        "closed_spread_pct": round(_rl.spread_pct(r["window"]["values"]), 1),
        "closed_cv_pct": round(_rl.cv_pct(r["window"]["values"]), 1),
        "spread_converged": _rl.spread_pct(r["window"]["values"])
        < env_f("BENCH_SPREAD_TARGET_PCT", 15.0),
        # Discarded warmup passes (never in the median); extended until two
        # consecutive agreed within 10% (warmup_is_stable).
        "warmup_passes_discarded": len(warmups),
        "warmup_passes_per_s": [w["throughput_per_s"] for w in warmups],
        "warmup_pass_per_s": (warmups[-1]["throughput_per_s"]
                              if warmups else None),
        "link_mbps_measured": link_mbps,
        "link_mbps_matched": link_mbps_matched,
        "wire_ceiling_img_s": round(ceiling, 1) if ceiling == ceiling else None,
        "pct_of_wire_ceiling": round(100 * value / ceiling, 1) if ceiling == ceiling else None,
        # Cache accounting, always separate from the headline (ISSUE 5):
        # hit rate observed during the miss-only measured passes (~0 by
        # construction) and the dedicated hit-heavy pass block.
        "cache_enabled": cfg.cache.enabled,
        "miss_pass_hit_rate": r["miss_hit_rate"],
        "cache_hit_rate": (r["hit"] or {}).get("cache_hit_rate"),
        # Measured fresh THIS run (subprocess probe; null if skipped/failed).
        # chip_compute_img_s is ONE chip's compute rate; the aggregate
        # multiplies it over every chip the serving path occupies — the
        # ceiling an 8-chip run is honestly measured against (ISSUE 7).
        "chip_compute_img_s": chip.get("img_s"),
        "aggregate_chip_img_s": (round(chip["img_s"] * n_chips, 1)
                                 if chip.get("img_s") else None),
        "chip_ms_per_batch": chip.get("ms_per_batch"),
        # Roofline attribution (ISSUE 6, docs/PERFORMANCE.md "Reading the
        # roofline"): per-bucket raw-executable ms vs wire ms, per-phase
        # observed p50 vs its physical ceiling, and the serving compute
        # phase split into device-time vs host-wait — the 465-vs-24 gap of
        # r05 as named numbers, so the next PR attacks the binding phase.
        "roofline": _rl.build_roofline(
            state.metrics.summary()["latency"], "resnet50", buckets,
            raw_by_bucket, best_link, img_bytes,
            chip.get("img_s"), value, n_chips=n_chips,
            # Ingest-aware attribution: the body_read phase priced at the
            # ACTUAL framed request-body size (items x item bytes + header
            # + offset table), same link the h2d ceiling uses.
            req_bytes=(frame_wire.frame_nbytes(frame_kind, wire, frame_items)
                       if wire_proto == "frame" and frame_items else None)),
    }
    # Telemetry evidence (ISSUE 14): chip-occupancy over the measured
    # window next to the throughput it bought, and the per-pass SLO burn
    # summary — the roofline carries the same utilization block so its
    # ceiling percentages are read against how busy the chips really were.
    line["utilization"] = r["utilization"]
    line["slo"] = r["slo"]
    if isinstance(line.get("roofline"), dict):
        line["roofline"]["utilization"] = r["utilization"]
    if r["hit"]:
        line["hit_heavy"] = r["hit"]
    if open_res:
        line["open_loop"] = {
            "offered_per_s": open_res.get("offered_rate_per_s"),
            "throughput_per_s": open_res.get("throughput_per_s"),
            "p50_ms": open_res.get("p50_ms"),
            "p99_ms": open_res.get("p99_ms"),
        }
    print(f"# total bench wall time {time.time() - t_all:.0f}s", file=sys.stderr)
    print(json.dumps(line))
    failures = bench_self_check(line)
    for msg in failures:
        print(f"# SELF-CHECK FAILED: {msg}", file=sys.stderr)
    assert not failures, "; ".join(failures)
    return 0


if __name__ == "__main__":
    sys.exit(main())
