"""Tenant-containment tests (ISSUE 16): the TenantLedger's identity /
rate / quota / fair-share admission, its usage view, and the result
cache's tenant-weighted capacity partition. Pure in-process — no server.
"""

import pytest

from tpuserve.cache import ModelCache
from tpuserve.config import CacheConfig, TenantConfig, TenantsConfig
from tpuserve.obs import Metrics
from tpuserve.scheduler.tenants import TenantLedger


def tenants_cfg(**over) -> TenantsConfig:
    base = dict(
        enabled=True,
        window_s=60.0,
        tenants=[
            TenantConfig(name="alpha", api_key="key-alpha", weight=3.0),
            TenantConfig(name="beta", api_key="key-beta", weight=1.0,
                         quota_device_s=2.0, rate_per_s=2.0, burst=2.0),
        ],
    )
    base.update(over)
    return TenantsConfig(**base)


def ledger(**over) -> TenantLedger:
    return TenantLedger(tenants_cfg(**over), Metrics())


# -- identity -----------------------------------------------------------------

@pytest.mark.parametrize("key,expect", [
    ("key-alpha", "alpha"),
    ("key-beta", "beta"),
    ("key-nope", None),
    ("", None),
    (None, None),
])
def test_resolve(key, expect):
    assert ledger().resolve(key) == expect


def test_resolve_anonymous_fallback():
    led = ledger(allow_anonymous="anon")
    assert led.resolve(None) == "anon"
    assert led.resolve("key-nope") == "anon"
    assert led.resolve("key-alpha") == "alpha"  # known keys still win
    assert "anon" in led.names()
    # The anonymous tenant rides with default weight and no envelope.
    assert led.weight_of("anon") == 1.0
    assert led.admit("anon") is None


def test_shed_unknown_is_401():
    shed = ledger().shed_unknown()
    assert shed.status == 401 and shed.reason == "tenant_unknown"


def test_names_weights():
    led = ledger()
    assert led.names() == ["alpha", "beta"]
    assert led.weights() == {"alpha": 3.0, "beta": 1.0}
    assert led.weight_of("alpha") == 3.0
    assert led.weight_of("ghost") == 1.0  # harmless default


# -- rate ---------------------------------------------------------------------

def test_rate_token_bucket_exhausts():
    led = ledger()
    # burst=2: two admits drain the bucket, the third 429s with a hint.
    assert led.admit("beta") is None
    assert led.admit("beta") is None
    shed = led.admit("beta")
    assert shed is not None and shed.status == 429
    assert shed.reason == "tenant_rate_exceeded"
    assert shed.retry_after is not None and shed.retry_after >= 1


def test_no_rate_limit_when_unset():
    led = ledger()
    for _ in range(100):
        assert led.admit("alpha") is None  # alpha has no rate/quota


# -- quota --------------------------------------------------------------------

def test_quota_window_device_seconds():
    led = ledger()
    led.record("beta", 2.5)  # past the 2.0 device-second allowance
    shed = led.admit("beta")
    assert shed is not None and shed.status == 429
    assert shed.reason == "tenant_quota_exceeded"
    assert shed.retry_after is not None and 1 <= shed.retry_after <= 30
    # The neighbor is untouched — containment, not collective punishment.
    assert led.admit("alpha") is None


def test_quota_under_allowance_admits():
    led = ledger()
    led.record("beta", 1.0)
    assert led.admit("beta") is None


def test_record_clamps_negative_charge():
    led = ledger()
    led.record("beta", -5.0)
    assert led.usage()["tenants"]["beta"]["window_device_s"] == 0.0


# -- fair share ---------------------------------------------------------------

def test_share_shed_only_under_saturation():
    led = ledger()
    # beta (weight 1 of 4) hogs the whole observed window.
    led.record("beta", 1.5)
    led.record("alpha", 0.01)
    assert led.admit("beta") is None  # not saturated: quota/rate only
    led.saturated_fn = lambda: True
    shed = led.admit("beta")
    assert shed is not None and shed.reason == "tenant_share_exceeded"
    # The heavyweight neighbor is within its share and keeps flowing.
    assert led.admit("alpha") is None


def test_share_shed_disabled_by_zero_slack():
    led = ledger(share_slack=0.0)
    led.saturated_fn = lambda: True
    led.record("beta", 1.5)
    assert led.admit("beta") is None


# -- usage view ---------------------------------------------------------------

def test_usage_shape_and_counts():
    led = ledger()
    assert led.admit("alpha") is None
    led.record("alpha", 0.25)
    u = led.usage()
    assert u["enabled"] is True and u["window_s"] == 60.0
    row = u["tenants"]["alpha"]
    assert row["weight"] == 3.0
    assert row["admitted_total"] == 1
    assert row["window_device_s"] == pytest.approx(0.25)
    assert row["device_seconds_total"] == pytest.approx(0.25)
    # Refusals never count as admissions.
    led.record("beta", 99.0)
    assert led.admit("beta") is not None
    assert led.usage()["tenants"]["beta"]["admitted_total"] == 0


# -- config validation --------------------------------------------------------

@pytest.mark.parametrize("kw", [
    dict(name=""),
    dict(api_key=""),
    dict(weight=0.0),
    dict(weight=-1.0),
    dict(quota_device_s=-1.0),
    dict(rate_per_s=-1.0),
])
def test_tenant_config_rejects(kw):
    base = dict(name="t", api_key="k")
    base.update(kw)
    with pytest.raises(ValueError):
        TenantConfig(**base)


def test_tenants_config_rejects_duplicates():
    with pytest.raises(ValueError):
        TenantsConfig(tenants=[
            TenantConfig(name="a", api_key="k1"),
            TenantConfig(name="a", api_key="k2")])
    with pytest.raises(ValueError):
        TenantsConfig(tenants=[
            TenantConfig(name="a", api_key="k"),
            TenantConfig(name="b", api_key="k")])


# -- cache partition ----------------------------------------------------------

def cache(capacity=8) -> ModelCache:
    return ModelCache("toy", CacheConfig(enabled=True, capacity=capacity),
                      Metrics(), lambda: 1)


def test_cache_shares_follow_weights():
    c = cache(capacity=8)
    c.set_tenant_weights({"alpha": 3.0, "beta": 1.0})
    stats = c.stats()
    assert stats["tenants"]["alpha"]["share"] == 6
    assert stats["tenants"]["beta"]["share"] == 2


def test_cache_tenant_churn_evicts_own_entries_only():
    c = cache(capacity=8)
    c.set_tenant_weights({"alpha": 3.0, "beta": 1.0})
    for i in range(3):
        c.put(f"a{i}", {"v": i}, tenant="alpha")
    # beta churns far past its 2-entry share...
    for i in range(10):
        c.put(f"b{i}", {"v": i}, tenant="beta")
    stats = c.stats()["tenants"]
    assert stats["beta"]["entries"] == 2  # capped at its share
    # ...and every alpha entry survived the neighbor's churn.
    assert stats["alpha"]["entries"] == 3
    for i in range(3):
        assert c.get(f"a{i}") is not None
    # beta keeps its own NEWEST entries.
    assert c.get("b9") is not None and c.get("b0") is None


def test_cache_min_share_is_one():
    c = cache(capacity=4)
    c.set_tenant_weights({"whale": 1000.0, "minnow": 1.0})
    assert c.stats()["tenants"]["minnow"]["share"] == 1


def test_cache_unpartitioned_without_weights():
    c = cache(capacity=2)
    c.put("x", {"v": 1})
    c.put("y", {"v": 2})
    c.put("z", {"v": 3})
    assert "tenants" not in c.stats()
    assert c.get("x") is None  # plain LRU beyond capacity
