"""Iteration-level continuous batching (ISSUE 9): scheduler invariants,
slot-arena safety, deadline eviction mid-generation, zero recompiles across
admit/retire/reload churn, engine-vs-locked-batch parity, the generative
cache-key contract, and the HTTP front door (textgen + SD 1.5 through the
engine). docs/PERFORMANCE.md "The generation engine"."""

import asyncio
import json

import pytest

from tpuserve.batcher import DeadlineExceeded, QueueFull
from tpuserve.config import (GenserveConfig, ModelConfig, ServerConfig,
                             load_config)
from tpuserve.genserve import GenEngine, SlotArena, SlotCorrupted, SlotInfo
from tpuserve.models import build
from tpuserve.obs import Metrics
from tpuserve.runtime import build_runtime

TG_OPTS = dict(layers=1, d_model=32, heads=2, d_ff=64, vocab_size=512,
               prompt_len=16, max_new_tokens=64)


def tg_cfg(**over) -> ModelConfig:
    base = dict(name="tg", family="textgen", batch_buckets=[1, 2, 4],
                dtype="float32", parallelism="single", max_queue=64,
                request_timeout_ms=60_000.0, options=dict(TG_OPTS))
    base.update(over)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def tg_rt():
    """One compiled textgen model+runtime for every engine test (the three
    gen programs compile once; engines over it are cheap)."""
    model = build(tg_cfg())
    rt = build_runtime(model, compile_forward=False)
    eng = GenEngine(model, rt, Metrics(), GenserveConfig(slots=4))
    eng.compile()
    return model, rt


def make_engine(tg_rt, metrics=None, slots=4, **gc_over):
    model, rt = tg_rt
    m = metrics or Metrics()
    eng = GenEngine(model, rt, m, GenserveConfig(slots=slots, **gc_over))
    eng.compile()  # reuses the runtime's registered programs
    return eng, m


def prompt_item(model, prompt="hello world", seed=0, max_new=8, temp=0.0):
    body = {"prompt": prompt, "seed": seed, "max_new_tokens": max_new}
    if temp:
        body["temperature"] = temp
    return model.host_decode(json.dumps(body).encode(), "application/json")


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# SlotArena: never double-hands
# ---------------------------------------------------------------------------

def test_slot_arena_never_double_hands():
    a = SlotArena(2)
    s0 = a.acquire(SlotInfo(item=None, future=None))
    s1 = a.acquire(SlotInfo(item=None, future=None))
    assert {s0, s1} == {0, 1} and a.n_free == 0
    with pytest.raises(IndexError):
        a.acquire(SlotInfo(item=None, future=None))
    a.release(s0)
    with pytest.raises(SlotCorrupted):
        a.release(s0)  # double release
    # A corrupted free-list (same slot twice) is caught at acquire.
    a._free.append(s1)
    with pytest.raises(SlotCorrupted):
        a.acquire(SlotInfo(item=None, future=None))


def test_slot_arena_release_all():
    a = SlotArena(3)
    infos = [a.acquire(SlotInfo(item=i, future=None)) for i in range(3)]
    assert len(infos) == 3
    out = a.release_all()
    assert [i.item for i in out] == [0, 1, 2]
    assert a.n_free == 3 and a.n_active == 0


# ---------------------------------------------------------------------------
# Scheduler invariants
# ---------------------------------------------------------------------------

def test_short_after_long_finishes_first(tg_rt):
    """THE continuous-batching property: a 2-token request admitted AFTER a
    60-token one completes FIRST — a locked batch would hold it hostage."""
    model, _ = tg_rt
    eng, _m = make_engine(tg_rt)

    async def go():
        await eng.start()
        order = []
        long_f = eng.submit(prompt_item(model, "long", seed=1, max_new=60))
        long_f.add_done_callback(lambda f: order.append("long"))
        await asyncio.sleep(0.02)  # the long one is mid-generation now
        short_f = eng.submit(prompt_item(model, "short", seed=2, max_new=2))
        short_f.add_done_callback(lambda f: order.append("short"))
        rl, rs = await asyncio.gather(long_f, short_f)
        await eng.stop()
        assert order == ["short", "long"], order
        assert rl["n_tokens"] == 60 and rs["n_tokens"] == 2

    run(go())


def test_fold_in_and_early_exit_counters(tg_rt):
    model, _ = tg_rt
    eng, m = make_engine(tg_rt)

    async def go():
        await eng.start()
        long_f = eng.submit(prompt_item(model, "marathon", seed=3, max_new=60))
        await asyncio.sleep(0.02)
        shorts = [eng.submit(prompt_item(model, f"s{i}", seed=10 + i,
                                         max_new=2)) for i in range(3)]
        await asyncio.gather(long_f, *shorts)
        await eng.stop()
        assert m.counter("gen_fold_ins_total{model=tg}").value >= 3
        assert m.counter("gen_early_exits_total{model=tg}").value >= 3
        assert m.counter("gen_iterations_total{model=tg}").value > 0

    run(go())


def test_engine_matches_locked_batch_tokens(tg_rt):
    """Engine path == locked-batch forward path, token for token: both
    share _prefill/_decode_step and the positional (seed, position)
    sampling fold, so identical requests are bit-identical across the two
    schedulers (and across batch compositions)."""
    model, _ = tg_rt
    eng, _m = make_engine(tg_rt)

    async def go():
        await eng.start()
        res = await eng.submit(prompt_item(model, "parity check run",
                                           seed=5, max_new=17))
        await eng.stop()
        return res

    engine_res = run(go())
    rt2 = build_runtime(model)  # forward buckets (the locked path)
    item = prompt_item(model, "parity check run", seed=5, max_new=17)
    out = rt2.fetch(rt2.run((1,), model.assemble([item], (1,))))
    locked = model.host_postprocess(out, 1)[0]
    assert locked["tokens"] == engine_res["tokens"]
    assert locked["n_tokens"] == 17


def test_deadline_eviction_mid_generation(tg_rt):
    """A request whose deadline lands mid-generation 504s at the stamped
    instant (within one iteration of it) and frees its slot for queued
    work. Iterations are chaos-slowed to 10 ms so the 60-token run
    provably spans the 80 ms deadline on any host speed."""
    import time

    from tpuserve.faults import FaultInjector

    model, _ = tg_rt
    eng, m = make_engine(tg_rt)
    eng.injector = FaultInjector.single("slow_dispatch", delay_ms=10.0)

    async def go():
        await eng.start()
        t0 = time.perf_counter()
        doomed = eng.submit(prompt_item(model, "doomed", seed=6, max_new=60),
                            deadline_at=t0 + 0.08)
        with pytest.raises(DeadlineExceeded):
            await doomed
        elapsed = time.perf_counter() - t0
        # At the stamped instant (within ~one slowed iteration), not at
        # generation end: 60 iterations x 10 ms would be >= 600 ms.
        assert 0.08 <= elapsed < 0.4, elapsed
        assert m.counter("gen_evictions_total{model=tg}").value == 1
        assert m.counter("deadline_exceeded_total{model=tg}").value == 1
        # The freed slot serves the next request.
        eng.injector = None
        ok = await eng.submit(prompt_item(model, "alive", seed=7, max_new=2))
        assert ok["n_tokens"] == 2
        await eng.stop()

    run(go())


def test_queued_deadline_expires_without_admission(tg_rt):
    """Deadline already expired while queued -> fast 504, never admitted."""
    import time

    model, _ = tg_rt
    eng, m = make_engine(tg_rt)

    async def go():
        await eng.start()
        fut = eng.submit(prompt_item(model, "late", seed=8, max_new=4),
                         deadline_at=time.perf_counter() - 0.001)
        with pytest.raises(DeadlineExceeded):
            await fut
        assert m.counter("gen_admitted_total{model=tg}").value == 0
        await eng.stop()

    run(go())


def test_zero_recompiles_across_churn_and_reload(tg_rt):
    """The acceptance bar: sustained admit/retire churn with mixed lengths,
    plus a publish AND a rollback mid-churn, with runtime_compiles_total
    delta exactly 0 — slot churn and version churn reuse the registered
    step/insert/extract programs."""
    model, rt = tg_rt
    eng, _m = make_engine(tg_rt)
    c0 = rt.compiles_total
    assert c0 >= 3  # insert/step/extract registered

    async def go():
        await eng.start()
        futs = [eng.submit(prompt_item(model, f"p{i}", seed=i,
                                       max_new=2 + (i % 9)))
                for i in range(8)]
        rt.publish(rt.stage_params())  # reload mid-churn
        futs += [eng.submit(prompt_item(model, f"q{i}", seed=100 + i,
                                        max_new=2 + (i % 5)))
                 for i in range(8)]
        rt.rollback()
        futs += [eng.submit(prompt_item(model, f"r{i}", seed=200 + i,
                                        max_new=3)) for i in range(4)]
        res = await asyncio.gather(*futs)
        await eng.stop()
        return res

    res = run(go())
    assert len(res) == 20 and all(r["n_tokens"] >= 1 for r in res)
    assert rt.compiles_total == c0, (rt.compiles_total, c0)
    # Slot accounting survived the churn exactly.
    assert eng.arena.n_active == 0 and eng.arena.n_free == eng.slots


def test_queue_full_sheds(tg_rt):
    model, _ = tg_rt
    eng, m = make_engine(tg_rt)
    eng.cfg.max_queue = 2

    async def go():
        await eng.start()
        try:
            # Not yet admitted: the loop hasn't run between submits.
            eng.submit(prompt_item(model, "a", max_new=2))
            eng.submit(prompt_item(model, "b", max_new=2))
            with pytest.raises(QueueFull):
                eng.submit(prompt_item(model, "c", max_new=2))
            assert m.counter("shed_total{model=tg}").value == 1
        finally:
            eng.cfg.max_queue = 64
            await eng.stop()

    run(go())


def test_cancelled_request_frees_slot(tg_rt):
    model, _ = tg_rt
    eng, _m = make_engine(tg_rt)

    async def go():
        await eng.start()
        fut = eng.submit(prompt_item(model, "gone", seed=9, max_new=60))
        await asyncio.sleep(0.02)
        assert eng.arena.n_active >= 1
        fut.cancel()
        ok = await eng.submit(prompt_item(model, "here", seed=10, max_new=2))
        assert ok["n_tokens"] == 2
        # The cancelled slot was reaped by the loop.
        for _ in range(50):
            if eng.arena.n_active == 0:
                break
            await asyncio.sleep(0.01)
        assert eng.arena.n_active == 0
        await eng.stop()

    run(go())


def test_step_failure_contained_and_loop_survives(tg_rt):
    """An injected step failure fails the in-flight set with the cause,
    resets the state block, and the engine keeps serving."""
    from tpuserve.faults import FaultInjected, FaultInjector

    model, _ = tg_rt
    eng, m = make_engine(tg_rt)

    async def go():
        await eng.start()
        eng.injector = FaultInjector.single("batch_error", count=1)
        with pytest.raises(FaultInjected):
            await eng.submit(prompt_item(model, "boom", seed=11, max_new=8))
        assert m.counter("batch_errors_total{model=tg}").value == 1
        ok = await eng.submit(prompt_item(model, "fine", seed=12, max_new=3))
        assert ok["n_tokens"] == 3
        eng.injector = None
        await eng.stop()

    run(go())


def test_watchdog_revives_dead_step_loop(tg_rt):
    from tpuserve.faults import FaultInjector

    model, _ = tg_rt
    eng, _m = make_engine(tg_rt)

    async def go():
        await eng.start()
        eng.injector = FaultInjector.single("kill_group_loop", count=1)
        fut = eng.submit(prompt_item(model, "stalled", seed=13, max_new=2))
        for _ in range(100):
            if eng._loop_task.done():
                break
            await asyncio.sleep(0.01)
        assert eng._loop_task.done()  # chaos killed the loop
        eng.injector = None
        assert eng.revive_group_loops() == 1
        res = await asyncio.wait_for(fut, timeout=10)
        assert res["n_tokens"] == 2
        assert eng.revive_group_loops() == 0  # healthy loop: no-op
        await eng.stop()

    run(go())


def test_drain_waits_for_mid_generation_work(tg_rt):
    model, _ = tg_rt
    eng, _m = make_engine(tg_rt)

    async def go():
        await eng.start()
        fut = eng.submit(prompt_item(model, "draining", seed=14, max_new=20))
        await asyncio.sleep(0.02)
        loop = asyncio.get_running_loop()
        ok = await eng.drain(loop.time() + 30.0)
        assert ok and fut.done() and (await fut)["n_tokens"] == 20
        await eng.stop()

    run(go())


def test_staged_canary_runs_short_generation(tg_rt):
    """The lifecycle's staged-canary hook: a candidate tree proves itself
    on a real end-to-end generation without touching the live state."""
    model, rt = tg_rt
    eng, _m = make_engine(tg_rt)
    staged = rt.stage_params()
    eng.staged_canary_sync(staged)  # must not raise
    c0 = rt.compiles_total
    eng.staged_canary_sync(staged)
    assert rt.compiles_total == c0  # canaries never compile


def test_flash_prefill_matches_dense(tg_rt):
    """options.attention='flash' routes the bidirectional prompt prefill
    through the seeded Pallas kernel; greedy token streams must match the
    dense twin exactly (same seeded weights)."""
    model_d, _ = tg_rt
    model_f = build(tg_cfg(options={**TG_OPTS, "attention": "flash"}))
    rt_d = build_runtime(model_d)
    rt_f = build_runtime(model_f)
    item = prompt_item(model_d, "flash parity prompt", seed=21, max_new=9)
    out_d = rt_d.fetch(rt_d.run((1,), model_d.assemble([item], (1,))))
    out_f = rt_f.fetch(rt_f.run((1,), model_f.assemble([item], (1,))))
    res_d = model_d.host_postprocess(out_d, 1)[0]
    res_f = model_f.host_postprocess(out_f, 1)[0]
    assert res_d["tokens"] == res_f["tokens"]


def test_textgen_option_validation():
    with pytest.raises(ValueError, match="attention"):
        build(tg_cfg(options={**TG_OPTS, "attention": "magic"}))
    with pytest.raises(ValueError, match="divisible by 8"):
        build(tg_cfg(options={**TG_OPTS, "attention": "flash",
                              "prompt_len": 12}))
    with pytest.raises(ValueError, match="heads"):
        build(tg_cfg(options={**TG_OPTS, "d_model": 33}))


# ---------------------------------------------------------------------------
# Generative cache-key contract (ISSUE 9 satellite)
# ---------------------------------------------------------------------------

def test_generation_cache_keys_include_sampling_params(tg_rt):
    """Two prompts differing ONLY in seed / temperature / max_new_tokens
    digest to distinct cache keys — the item carries every sampling param,
    so aliasing is structurally impossible."""
    from tpuserve.cache import item_digest

    model, _ = tg_rt
    base = prompt_item(model, "same prompt", seed=1, max_new=8)
    digests = {
        item_digest(base),
        item_digest(prompt_item(model, "same prompt", seed=2, max_new=8)),
        item_digest(prompt_item(model, "same prompt", seed=1, max_new=9)),
        item_digest(prompt_item(model, "same prompt", seed=1, max_new=8,
                                temp=0.7)),
    }
    assert len(digests) == 4
    # And identical params digest identically (the hit path exists).
    assert item_digest(base) == item_digest(
        prompt_item(model, "same prompt", seed=1, max_new=8))


def test_sd15_cache_keys_include_seed():
    from tpuserve.cache import item_digest
    from tpuserve.models import build as mbuild

    sd = mbuild(ModelConfig(
        name="sd", family="sd15", batch_buckets=[1], dtype="float32",
        parallelism="single", image_size=32,
        options=dict(steps=2, vocab_size=128, text_layers=1, text_d_model=16,
                     text_heads=2, unet_ch=8, unet_mults=[1, 2], unet_res=1,
                     unet_attn_levels=[0], unet_heads=2, vae_ch=8,
                     vae_mults=[1, 2])))
    a = sd.host_decode(b'{"prompt": "x", "seed": 1}', "application/json")
    b = sd.host_decode(b'{"prompt": "x", "seed": 2}', "application/json")
    assert item_digest(a) != item_digest(b)


def test_cacheable_false_skips_server_cache():
    from tpuserve.config import CacheConfig
    from tpuserve.server import ServerState

    cfg = ServerConfig(
        decode_threads=2, startup_canary=False,
        cache=CacheConfig(enabled=True),
        models=[ModelConfig(name="toy", family="toy", batch_buckets=[1, 2],
                            dtype="float32", num_classes=10,
                            parallelism="single", cacheable=False)])
    state = ServerState(cfg)
    state.build()

    async def go():
        await state.start()
        try:
            # cacheable=false: no ModelCache built despite [cache] enabled.
            assert state.caches == {}
        finally:
            await state.stop()

    run(go())


def test_cacheable_false_skips_router_cache():
    """Router-side generation-key contract: the wire cache digests the raw
    body (seed differences always split keys), and a cacheable=false model
    gets NO router cache at all."""
    from tpuserve.config import CacheConfig
    from tpuserve.workerproc.router import RouterState

    cfg = ServerConfig(
        cache=CacheConfig(enabled=True),
        models=[
            ModelConfig(name="gen", family="textgen", cacheable=False),
            ModelConfig(name="tg", family="textgen"),
        ])
    cfg.router.enabled = True
    state = RouterState(cfg)
    assert "gen" not in state.caches   # opted out
    cache = state.caches["tg"]         # cacheable (params ride in the body)
    k1 = cache.key_for(("generate", "application/json",
                        b'{"prompt": "p", "seed": 1}'))
    k2 = cache.key_for(("generate", "application/json",
                        b'{"prompt": "p", "seed": 2}'))
    assert k1 != k2


def test_genserve_config_toml(tmp_path):
    p = tmp_path / "g.toml"
    p.write_text("""
[genserve]
enabled = true
slots = 6
admit_per_step = 2

[[model]]
name = "tg"
family = "textgen"
cacheable = false
""")
    cfg = load_config(str(p))
    assert cfg.genserve.enabled and cfg.genserve.slots == 6
    assert cfg.genserve.admit_per_step == 2
    assert cfg.models[0].cacheable is False
    with pytest.raises(ValueError, match="admit_per_step"):
        GenserveConfig(admit_per_step=-1)


# ---------------------------------------------------------------------------
# HTTP front door through the engine
# ---------------------------------------------------------------------------

def _gen_server(**over):
    from tpuserve.server import ServerState

    base = dict(
        decode_threads=2,
        genserve=GenserveConfig(enabled=True, slots=4),
        models=[ModelConfig(name="tg", family="textgen",
                            batch_buckets=[1, 2, 4], dtype="float32",
                            parallelism="single",
                            request_timeout_ms=60_000.0,
                            options=dict(TG_OPTS))])
    base.update(over)
    cfg = ServerConfig(**base)
    state = ServerState(cfg)
    state.build()
    return state


def test_http_textgen_through_engine():
    from aiohttp.test_utils import TestClient, TestServer
    from tpuserve.server import make_app

    state = _gen_server()

    async def go():
        client = TestClient(TestServer(make_app(state)))
        await client.start_server()
        try:
            r = await client.post(
                "/v1/models/tg:generate",
                data=json.dumps({"prompt": "hello", "seed": 4,
                                 "max_new_tokens": 6}),
                headers={"Content-Type": "application/json"})
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["n_tokens"] == 6 and len(body["tokens"]) == 6
            # Engine-served model: forward buckets were never compiled,
            # only the three gen programs.
            assert state.runtimes["tg"].compile_forward is False
            variants = {tuple(v["bucket"]) for v in
                        state.runtimes["tg"].variants_summary()}
            assert variants == {("extract", 4), ("insert", 4), ("step", 4)}
            # /stats carries the genserve block; /metrics the counters.
            stats = await (await client.get("/stats")).json()
            assert stats["genserve"]["tg"]["mode"] == "genserve"
            assert stats["pipeline"]["models"]["tg"]["mode"] == "genserve"
            metrics = await (await client.get("/metrics")).text()
            assert 'gen_iterations_total{model="tg"}' in metrics
            # Bad sampling params reject at decode (400), not mid-engine.
            bad = await client.post(
                "/v1/models/tg:generate",
                data=json.dumps({"prompt": "x", "max_new_tokens": 10_000}),
                headers={"Content-Type": "application/json"})
            assert bad.status == 400
            # Per-request deadline -> fast 504 through the engine, with
            # iterations chaos-slowed so the generation provably outlives
            # the 50 ms budget on any host.
            from tpuserve.faults import FaultInjector

            state.batchers["tg"].injector = FaultInjector.single(
                "slow_dispatch", delay_ms=10.0)
            try:
                slow = await client.post(
                    "/v1/models/tg:generate?timeout_ms=50",
                    data=json.dumps({"prompt": "slow", "seed": 1,
                                     "max_new_tokens": 64}),
                    headers={"Content-Type": "application/json"})
                assert slow.status == 504, await slow.text()
            finally:
                state.batchers["tg"].injector = None
        finally:
            await client.close()

    run(go())


def test_http_reload_engine_staged_canary():
    """:reload on an engine-served model runs the engine's staged canary
    (a short real generation) and publishes with zero recompiles; an
    injected regression rejects at the staged_canary gate with the old
    version serving."""
    from aiohttp.test_utils import TestClient, TestServer
    from tpuserve.faults import FaultInjector
    from tpuserve.server import make_app

    state = _gen_server()

    async def go():
        client = TestClient(TestServer(make_app(state)))
        await client.start_server()
        try:
            c0 = state.metrics.counter(
                "runtime_compiles_total{model=tg}").value
            r = await client.post("/admin/models/tg:reload")
            assert r.status == 200, await r.text()
            assert (await r.json())["version"] == 2
            assert state.metrics.counter(
                "runtime_compiles_total{model=tg}").value == c0
            # Regressed candidate: rejected at the staged canary, v2 serves.
            state.lifecycles["tg"].injector = FaultInjector.single(
                "reload_regressed", count=1)
            r2 = await client.post("/admin/models/tg:reload")
            assert r2.status == 409, await r2.text()
            assert (await r2.json())["stage"] == "staged_canary"
            ok = await client.post(
                "/v1/models/tg:generate",
                data=json.dumps({"prompt": "still here", "seed": 2,
                                 "max_new_tokens": 3}),
                headers={"Content-Type": "application/json"})
            assert ok.status == 200
            assert state.runtimes["tg"].version == 2
        finally:
            state.lifecycles["tg"].injector = None
            await client.close()

    run(go())


def test_http_cache_hits_generative(tg_rt):
    from aiohttp.test_utils import TestClient, TestServer
    from tpuserve.config import CacheConfig
    from tpuserve.server import make_app

    state = _gen_server(cache=CacheConfig(enabled=True))

    async def go():
        client = TestClient(TestServer(make_app(state)))
        await client.start_server()
        try:
            body = json.dumps({"prompt": "cache me", "seed": 7,
                               "max_new_tokens": 4})
            hdrs = {"Content-Type": "application/json"}
            r1 = await client.post("/v1/models/tg:generate", data=body,
                                   headers=hdrs)
            b1 = await r1.read()
            r2 = await client.post("/v1/models/tg:generate", data=body,
                                   headers=hdrs)
            assert await r2.read() == b1
            c = state.caches["tg"].stats()
            assert c["hits"] == 1 and c["misses"] == 1
            # Seed change -> different key -> a second real generation.
            r3 = await client.post(
                "/v1/models/tg:generate",
                data=json.dumps({"prompt": "cache me", "seed": 8,
                                 "max_new_tokens": 4}), headers=hdrs)
            assert r3.status == 200
            assert state.caches["tg"].stats()["misses"] == 2
        finally:
            await client.close()

    run(go())


@pytest.mark.slow
def test_http_sd15_through_engine():
    """SD 1.5 (tiny variant) serves txt2img through the iteration-level
    engine: PNG out, deterministic in (prompt, seed), per-slot step
    counters visible in /stats."""
    from aiohttp.test_utils import TestClient, TestServer
    from tpuserve.server import ServerState, make_app

    cfg = ServerConfig(
        decode_threads=2,
        genserve=GenserveConfig(enabled=True, slots=2),
        models=[ModelConfig(
            name="sd", family="sd15", batch_buckets=[1, 2], dtype="float32",
            parallelism="single", image_size=32,
            request_timeout_ms=120_000.0,
            options=dict(steps=3, guidance=5.0, vocab_size=512,
                         text_layers=1, text_d_model=32, text_heads=2,
                         unet_ch=16, unet_mults=[1, 2], unet_res=1,
                         unet_attn_levels=[0], unet_heads=2, vae_ch=16,
                         vae_mults=[1, 2]))])
    state = ServerState(cfg)
    state.build()

    async def go():
        client = TestClient(TestServer(make_app(state)))
        await client.start_server()
        try:
            hdrs = {"Content-Type": "application/json"}
            body = json.dumps({"prompt": "a red fox", "seed": 7})
            r1, r2 = await asyncio.gather(
                client.post("/v1/models/sd:generate", data=body,
                            headers=hdrs),
                client.post("/v1/models/sd:generate",
                            data=json.dumps({"prompt": "blue", "seed": 9}),
                            headers=hdrs))
            assert r1.status == 200 and r2.status == 200
            png1 = await r1.read()
            assert png1[:8] == b"\x89PNG\r\n\x1a\n"
            assert r1.content_type == "image/png"
            # Deterministic: same (prompt, seed) -> byte-identical PNG.
            r1b = await client.post("/v1/models/sd:generate", data=body,
                                    headers=hdrs)
            assert await r1b.read() == png1
            stats = await (await client.get("/stats")).json()
            assert stats["genserve"]["sd"]["iterations_total"] > 0
        finally:
            await client.close()

    run(go())
