"""TF-vs-JAX golden parity for the SavedModel import path (SURVEY.md §4-4,
"non-negotiable"; VERDICT.md r2 item 6).

Builds the real Keras-applications ResNet50 on TF-CPU with randomized
weights (including the conv biases and BatchNorm moving stats that exercise
the bias->BN-mean fold), exports a SavedModel, imports it through the full
``ModelConfig.weights`` serving path, and asserts the Flax network reproduces
the TF network's logits on the same inputs.

TF is CPU-only in this container (SURVEY.md §0.1) and the test takes ~2
minutes — it is the integration proof that real TF weight artifacts serve
correctly, not a unit test.
"""


import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import jax  # noqa: E402

from tpuserve.config import ModelConfig  # noqa: E402
from tpuserve.models import build  # noqa: E402

pytestmark = pytest.mark.slow


def _randomize(model: "tf.keras.Model", seed: int = 7, skip=None) -> None:
    """Give every variable a non-degenerate seeded value: zero biases or
    unit moving stats would let a broken bias-fold / stats mapping pass."""
    rng = np.random.default_rng(seed)
    for w in model.weights:
        shape = tuple(w.shape)
        name = getattr(w, "path", getattr(w, "name", ""))
        if "float" not in str(w.dtype):  # e.g. dropout seed_generator_state
            continue
        if skip is not None and skip(name):
            continue
        if "moving_variance" in name:
            w.assign(rng.uniform(0.5, 1.5, shape).astype(np.float32))
        elif "gamma" in name:
            w.assign(rng.uniform(0.8, 1.2, shape).astype(np.float32))
        else:  # kernels, betas, conv biases, moving means
            w.assign((rng.standard_normal(shape) * 0.05).astype(np.float32))


@pytest.fixture(scope="module")
def keras_savedmodel(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("rn50") / "sm")
    # classifier_activation=None: compare raw logits (the default bakes a
    # softmax into the Keras head that our serving module applies later).
    keras_model = tf.keras.applications.ResNet50(weights=None,
                                                 classifier_activation=None)
    _randomize(keras_model)
    keras_model.export(path, verbose=False)
    return keras_model, path


def serving_cfg(weights: str | None = None) -> ModelConfig:
    # Keras-applications convention: stride-2 on the block's first 1x1 and
    # BN eps 1.001e-5 (resnet.py docstring).
    return ModelConfig(
        name="rn50", family="resnet50", dtype="float32", num_classes=1000,
        weights=weights,
        options={"v1_downsample": True, "bn_eps": 1.001e-5},
    )


def test_imported_tree_matches_init_structure(keras_savedmodel):
    _, path = keras_savedmodel
    model = build(serving_cfg(weights=path))
    imported = model.load_params()  # full path: detect -> extract -> import
    want = jax.eval_shape(model.init_params, jax.random.key(0))
    assert (jax.tree_util.tree_structure(imported)
            == jax.tree_util.tree_structure(want))
    for got, exp in zip(jax.tree_util.tree_leaves(imported),
                        jax.tree_util.tree_leaves(want)):
        assert got.shape == exp.shape


def test_tf_and_jax_logits_agree(keras_savedmodel):
    keras_model, path = keras_savedmodel
    model = build(serving_cfg(weights=path))
    params = model.load_params()

    x = np.random.default_rng(0).uniform(0, 1, (2, 224, 224, 3)).astype(np.float32)
    y_tf = keras_model(x, training=False).numpy()
    y_jax = np.asarray(jax.jit(model.module.apply)(params, x))

    assert y_tf.shape == y_jax.shape == (2, 1000)
    # f32 end-to-end: relative 1e-4-grade agreement (SURVEY §4-4). The
    # randomized deep net amplifies activations to logit scale ~1e3, so the
    # budget is relative; measured max diff is ~1e-3 at that scale (1e-6 rel).
    np.testing.assert_allclose(y_jax, y_tf, rtol=1e-4, atol=5e-3)
    assert (y_jax.argmax(-1) == y_tf.argmax(-1)).all()


@pytest.fixture(scope="module")
def mnv3_savedmodel(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("mnv3") / "sm")
    # include_preprocessing=False: compare the networks on identical float
    # inputs (the default bakes a /127.5 - 1 rescaling into the Keras graph;
    # our serving equivalent lives in the device preproc stage, not the net).
    keras_model = tf.keras.applications.MobileNetV3Large(
        weights=None, classes=1000, classifier_activation=None,
        include_preprocessing=False)
    _randomize(keras_model)
    keras_model.export(path, verbose=False)
    return keras_model, path


def mnv3_cfg(weights: str | None = None) -> ModelConfig:
    return ModelConfig(name="mnv3", family="mobilenetv3", dtype="float32",
                       num_classes=1000, weights=weights)


def test_mnv3_imported_tree_matches_init_structure(mnv3_savedmodel):
    _, path = mnv3_savedmodel
    model = build(mnv3_cfg(weights=path))
    imported = model.load_params()
    want = jax.eval_shape(model.init_params, jax.random.key(0))
    assert (jax.tree_util.tree_structure(imported)
            == jax.tree_util.tree_structure(want))
    for got, exp in zip(jax.tree_util.tree_leaves(imported),
                        jax.tree_util.tree_leaves(want)):
        assert got.shape == exp.shape


def test_mnv3_tf_and_jax_logits_agree(mnv3_savedmodel):
    """Depthwise (H,W,C,1)->(H,W,1,C) and SE/post-pool-1x1 mappings are exact
    (SURVEY.md §7 hard part 3 names depthwise layouts as the fiddly case)."""
    keras_model, path = mnv3_savedmodel
    model = build(mnv3_cfg(weights=path))
    params = model.load_params()

    x = np.random.default_rng(0).uniform(-1, 1, (2, 224, 224, 3)).astype(np.float32)
    y_tf = keras_model(x, training=False).numpy()
    y_jax = np.asarray(jax.jit(model.module.apply)(params, x))

    assert y_tf.shape == y_jax.shape == (2, 1000)
    np.testing.assert_allclose(y_jax, y_tf, rtol=1e-4, atol=5e-3)
    assert (y_jax.argmax(-1) == y_tf.argmax(-1)).all()


@pytest.fixture(scope="module")
def bert_savedmodel(tmp_path_factory):
    transformers = pytest.importorskip("transformers")
    path = str(tmp_path_factory.mktemp("bert") / "sm")
    # The TF model's vocab must equal the serving module's (tokenizer-derived;
    # the synthetic dev vocab has a floor of ~275 entries) — exactly as real
    # BERT artifacts pair a vocab.txt with a matching embedding table.
    vocab_size = build(bert_cfg()).module.vocab_size
    cfg = transformers.BertConfig(
        vocab_size=vocab_size, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=64, num_labels=3,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    tf_model = transformers.TFBertForSequenceClassification(cfg)
    tf_model(np.zeros((1, 8), np.int32), training=False)  # build variables
    rng = np.random.default_rng(11)
    for w in tf_model.weights:
        if "float" in str(w.dtype):
            w.assign((rng.standard_normal(tuple(w.shape)) * 0.05).astype(np.float32))
    tf.saved_model.save(tf_model, path)
    return tf_model, path


def bert_cfg(weights: str | None = None) -> ModelConfig:
    return ModelConfig(
        name="bert", family="bert", dtype="float32", num_classes=3,
        weights=weights, seq_buckets=[64],
        options={"layers": 2, "d_model": 32, "heads": 2, "d_ff": 64,
                 "vocab_size": 128})


def test_bert_imported_tree_matches_init_structure(bert_savedmodel):
    _, path = bert_savedmodel
    model = build(bert_cfg(weights=path))
    imported = model.load_params()
    want = jax.eval_shape(model.init_params, jax.random.key(0))
    assert (jax.tree_util.tree_structure(imported)
            == jax.tree_util.tree_structure(want))
    for got, exp in zip(jax.tree_util.tree_leaves(imported),
                        jax.tree_util.tree_leaves(want)):
        assert got.shape == exp.shape


def test_bert_tf_and_jax_logits_agree(bert_savedmodel):
    """HF (d,d)->(d,H,HD) attention reshapes, token-type fold, and LN-eps
    faithfulness: logits parity incl. a padded-lane attention mask."""
    tf_model, path = bert_savedmodel
    model = build(bert_cfg(weights=path))
    params = model.load_params()

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (2, 16)).astype(np.int32)
    mask = np.ones((2, 16), np.int32)
    mask[1, 10:] = 0  # one padded row: exercises the additive bias path
    y_tf = tf_model(ids, attention_mask=mask, training=False).logits.numpy()
    y_jax = np.asarray(jax.jit(model.module.apply)(params, ids, mask))

    assert y_tf.shape == y_jax.shape == (2, 3)
    np.testing.assert_allclose(y_jax, y_tf, rtol=1e-4, atol=1e-5)
    assert (y_jax.argmax(-1) == y_tf.argmax(-1)).all()


def test_int8c_accuracy_on_imported_bert(bert_savedmodel):
    """Extend the imported-weight accuracy gate to the int8 COMPUTE path
    (VERDICT r4 next 5): the TF-imported BERT served with quantize='int8c'
    (FFN matmuls int8 x int8 -> int32 on the MXU, dynamic activation
    scales) must keep top-1 identical to the full-precision serving path
    with bounded prob drift. Same import, layouts, and runtime wiring as
    production — only the weights are randomized (no artifacts in this
    container)."""
    from tpuserve.runtime import build_runtime

    _, path = bert_savedmodel

    def serve(quantize):
        cfg = bert_cfg(weights=path)
        cfg.parallelism = "single"
        cfg.batch_buckets = [2]
        cfg.seq_buckets = [16]
        cfg.quantize = quantize
        cfg.quantize_min_size = 256
        model = build(cfg)
        rt = build_runtime(model)
        (bucket,) = rt.executables
        items = [model.host_decode(b'{"text": "imported weights int8c"}',
                                   "application/json")] * 2
        return rt.fetch(rt.run(bucket, model.assemble(items, bucket)))

    out_fp = serve(None)
    out_c = serve("int8c")
    assert (out_c["indices"][0][0] == out_fp["indices"][0][0]).all()
    drift = float(np.abs(out_c["probs"] - out_fp["probs"]).max())
    print("# int8c-vs-f32 on imported BERT: top-1 equal, "
          f"max prob drift {drift:.4f}")
    assert drift < 3e-2


def test_bert_rejects_vocab_mismatch(bert_savedmodel):
    """A checkpoint whose vocab differs from the serving tokenizer's must
    fail at load time, not serve silently-wrong logits."""
    _, path = bert_savedmodel
    cfg = bert_cfg(weights=path)
    cfg.options = {**cfg.options, "vocab_size": 8192}  # bigger synthetic vocab
    model = build(cfg)
    with pytest.raises(ValueError, match="vocab"):
        model.load_params()


@pytest.fixture(scope="module")
def effb0_savedmodel(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("effb0") / "sm")
    keras_model = tf.keras.applications.EfficientNetB0(weights=None, include_top=False)
    # normalization mean/variance are input-preproc stats, not weights;
    # randomizing variance negative would NaN the whole net.
    _randomize(keras_model, seed=13, skip=lambda n: "normalization" in n)
    keras_model.export(path, verbose=False)
    return keras_model, path


def test_efficientdet_backbone_import_parity(effb0_savedmodel):
    """EfficientNet-B0 classification weights transfer into the detector
    backbone exactly: C3/C4/C5 feature maps match Keras intermediate
    activations (depthwise transpose + SE mapping, SURVEY §7 hard part 3)."""
    import jax.numpy as jnp

    from tpuserve.models.efficientdet import EfficientNetFeatures

    keras_model, path = effb0_savedmodel
    det = build(ModelConfig(name="d0", family="efficientdet", dtype="float32",
                            weights=path, image_size=224))
    full = det.load_params()

    taps = ["block3b_add", "block5c_add", "block7a_project_bn"]  # C3/C4/C5
    sub = tf.keras.Model(keras_model.input,
                         [keras_model.get_layer(n).output for n in taps])
    x = np.random.default_rng(0).uniform(0, 255, (1, 224, 224, 3)).astype(np.float32)
    tf_feats = [np.asarray(t) for t in sub(x, training=False)]

    # Keras preproc with weights=None: Rescaling(1/255) + identity
    # Normalization (mean 0, var 1) — replicate, then run our backbone alone.
    feats = EfficientNetFeatures(dtype=jnp.float32).apply(
        {"params": full["params"]["backbone"],
         "batch_stats": full["batch_stats"]["backbone"]},
        jnp.asarray(x / 255.0))
    for lvl, want in zip([3, 4, 5], tf_feats):
        got = np.asarray(feats[lvl])
        assert got.shape == want.shape, (lvl, got.shape, want.shape)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_int8_accuracy_on_imported_weights(keras_savedmodel):
    """Close quantize.py's 'sub-percent movement' claim on IMPORTED weights
    (VERDICT r3 next 6): weight-only int8 over the real TF-imported
    ResNet-50 must keep top-1 identical to the bf16 serving path and move
    the class distribution by < 1% absolute. (Weights are randomized — no
    pretrained artifacts in this container — but the import path, layouts,
    and quantization math are the production ones.)"""
    import jax.numpy as jnp

    from tpuserve import quantize as qz

    _, path = keras_savedmodel
    cfg = serving_cfg(weights=path)
    cfg.dtype = "bfloat16"
    model = build(cfg)
    params = model.load_params()

    x = np.random.default_rng(2).uniform(0, 1, (4, 224, 224, 3)).astype(np.float32)
    y_bf16 = np.asarray(jax.jit(model.module.apply)(
        params, x)).astype(np.float32)

    qparams = qz.quantize_tree(jax.device_get(params))
    y_int8 = np.asarray(jax.jit(lambda p, xx: model.module.apply(
        qz.dequantize_tree(p, jnp.bfloat16), xx))(qparams, x)).astype(np.float32)

    p_bf16 = np.asarray(jax.nn.softmax(y_bf16, axis=-1))
    p_int8 = np.asarray(jax.nn.softmax(y_int8, axis=-1))
    drift = float(np.abs(p_int8 - p_bf16).max())
    rel_logit = float(np.abs(y_int8 - y_bf16).max() / np.abs(y_bf16).max())
    print("# int8-vs-bf16 on imported ResNet-50: top-1 equal, "
          f"max prob drift {drift:.4f}, rel logit drift {rel_logit:.4f}")
    assert (y_int8.argmax(-1) == y_bf16.argmax(-1)).all()
    assert drift < 1e-2, drift  # "sub-percent movement", measured not claimed


def test_bf16_serving_close_to_tf(keras_savedmodel):
    """The production dtype (bf16 convs) stays within the SURVEY bf16 budget
    (<=1e-2) of the TF-f32 reference."""
    keras_model, path = keras_savedmodel
    cfg = serving_cfg(weights=path)
    cfg.dtype = "bfloat16"
    model = build(cfg)
    params = model.load_params()
    x = np.random.default_rng(1).uniform(0, 1, (2, 224, 224, 3)).astype(np.float32)
    y_tf = keras_model(x, training=False).numpy()
    y_jax = np.asarray(jax.jit(model.module.apply)(params, x)).astype(np.float32)
    # bf16 budget (SURVEY §4-4, <=1e-2) applies to the class distribution,
    # not raw logits whose scale here is ~1e3.
    p_tf = np.asarray(jax.nn.softmax(y_tf, axis=-1))
    p_jax = np.asarray(jax.nn.softmax(y_jax, axis=-1))
    np.testing.assert_allclose(p_jax, p_tf, atol=1e-2)
    assert (y_jax.argmax(-1) == y_tf.argmax(-1)).all()
