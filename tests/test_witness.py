"""Runtime lock-order witness suite (ISSUE 4): the witness must catch a
seeded AB/BA inversion (same-thread, cross-thread, and async) and a
threading lock held across an await — and stay silent on the toy serving
path end to end (the same property the CI chaos drill asserts at scale
with TPUSERVE_LOCK_WITNESS=1)."""

import asyncio
import threading

import pytest

from tpuserve.analysis import witness


@pytest.fixture(autouse=True)
def _forced_witness():
    witness.force(True)
    witness.reset()
    yield
    witness.reset()
    witness.force(None)


# ---------------------------------------------------------------------------
# Lock-order graph
# ---------------------------------------------------------------------------

def test_ab_ba_inversion_raises():
    a, b = witness.WitnessLock("wit_a"), witness.WitnessLock("wit_b")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(witness.LockOrderViolation) as exc:
            with a:
                pass
    assert "wit_a" in str(exc.value) and "wit_b" in str(exc.value)
    assert witness.snapshot()["violations"], "violation not recorded"


def test_inversion_detected_across_threads():
    # AB observed on a worker thread, BA attempted on the main thread: the
    # order graph is global, so the inversion is still a cycle.
    a, b = witness.WitnessLock("xt_a"), witness.WitnessLock("xt_b")

    def worker():
        with a:
            with b:
                pass

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    with b:
        with pytest.raises(witness.LockOrderViolation):
            a.acquire()


def test_same_site_instances_share_a_node():
    # Two instances created at one site (same name) inverted against another
    # lock still close a cycle: nodes are names, not instances.
    pool1, pool2 = witness.WitnessLock("pool"), witness.WitnessLock("pool")
    other = witness.WitnessLock("other")
    with pool1:
        with other:
            pass
    with other:
        with pytest.raises(witness.LockOrderViolation):
            pool2.acquire()


def test_consistent_order_is_clean():
    a, b = witness.WitnessLock("ok_a"), witness.WitnessLock("ok_b")
    for _ in range(3):
        with a:
            with b:
                pass
    snap = witness.snapshot()
    assert snap["violations"] == []
    assert ["ok_a", "ok_b", 3] in snap["edges"]


def test_witness_lock_is_a_real_lock():
    lock = witness.WitnessLock("mutex")
    assert lock.acquire()
    assert lock.locked()
    assert not lock.acquire(blocking=False)
    lock.release()
    assert not lock.locked()


# ---------------------------------------------------------------------------
# Held-across-await (the task-driver instrumentation)
# ---------------------------------------------------------------------------

def test_threading_lock_across_await_raises():
    lock = witness.WitnessLock("held")

    async def bad():
        with lock:
            await asyncio.sleep(0)

    async def main():
        witness.install()
        task = asyncio.get_running_loop().create_task(bad())
        with pytest.raises(witness.LockHeldAcrossAwait) as exc:
            await task
        assert "held" in str(exc.value)
        # The driver unwound the offender: the lock must not stay taken.
        assert not lock.locked()

    asyncio.run(main())


def test_release_before_await_is_clean_and_values_pass_through():
    lock = witness.WitnessLock("brief")

    async def good():
        with lock:
            x = 41
        await asyncio.sleep(0)
        return x + 1

    async def main():
        witness.install()
        assert await asyncio.get_running_loop().create_task(good()) == 42
        # Exceptions propagate unchanged through the driver.
        async def boom():
            await asyncio.sleep(0)
            raise ValueError("boom")

        with pytest.raises(ValueError, match="boom"):
            await asyncio.get_running_loop().create_task(boom())
        # Cancellation still works on driven tasks.
        async def hang():
            await asyncio.sleep(30)

        task = asyncio.get_running_loop().create_task(hang())
        await asyncio.sleep(0)
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task

    asyncio.run(main())
    assert witness.snapshot()["violations"] == []


def test_async_lock_across_await_is_allowed_and_ordered():
    async def main():
        witness.install()
        a = witness.WitnessAsyncLock("aio_a")
        b = witness.WitnessAsyncLock("aio_b")
        async with a:
            await asyncio.sleep(0)  # legal for asyncio locks
            async with b:
                pass
        async with b:
            with pytest.raises(witness.LockOrderViolation):
                async with a:
                    pass

    asyncio.run(main())


# ---------------------------------------------------------------------------
# Wiring: env-driven constructors + the toy serving path stays clean
# ---------------------------------------------------------------------------

def test_new_lock_respects_env(monkeypatch):
    witness.force(None)  # hand control back to the environment variable
    from tpuserve.utils import locks

    monkeypatch.setenv("TPUSERVE_LOCK_WITNESS", "1")
    assert isinstance(locks.new_lock("env_t"), witness.WitnessLock)
    assert isinstance(locks.new_async_lock("env_a"), witness.WitnessAsyncLock)
    monkeypatch.delenv("TPUSERVE_LOCK_WITNESS")
    assert not isinstance(locks.new_lock("env_t2"), witness.WitnessLock)


def test_toy_serving_path_clean_under_witness():
    """End-to-end: a real ServerState built with witnessed locks serves a
    request with the suspension check armed — zero violations, and the
    witness actually saw lock traffic (the pass is not vacuous)."""
    import io

    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer

    from tpuserve.config import ModelConfig, ServerConfig
    from tpuserve.server import ServerState, make_app

    cfg = ServerConfig(
        decode_threads=2, startup_canary=False,
        models=[ModelConfig(name="toy", family="toy", batch_buckets=[1, 2],
                            deadline_ms=2.0, dtype="float32", num_classes=10,
                            parallelism="single", wire_size=8,
                            request_timeout_ms=10_000.0)])
    state = ServerState(cfg)
    state.build()
    app = make_app(state)
    buf = io.BytesIO()
    np.save(buf, np.zeros((8, 8, 3), dtype=np.uint8))

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/v1/models/toy:predict", data=buf.getvalue(),
                                  headers={"Content-Type": "application/x-npy"})
            assert r.status == 200, await r.text()
            stats = await (await client.get("/stats")).json()
            assert "lock_witness" in stats["robustness"]
            assert stats["robustness"]["lock_witness"]["violations"] == []
        finally:
            await client.close()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(go())
    finally:
        loop.close()
    snap = witness.snapshot()
    assert snap["violations"] == []
    assert snap["acquisitions"] > 0 and snap["locks"], snap
