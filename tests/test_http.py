"""HTTP integration (C1): real aiohttp app, toy model, full request path.
SURVEY.md §4-5: responses, error paths, /metrics, /healthz, /stats, trace.

No pytest-asyncio in the image: one module-level event loop drives a real
TestServer/TestClient pair, and each test runs coroutines on it explicitly.
"""

import asyncio
import io

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from tpuserve.config import ModelConfig, ServerConfig
from tpuserve.server import ServerState, make_app


@pytest.fixture(scope="module")
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture(scope="module")
def client(loop):
    cfg = ServerConfig(
        models=[ModelConfig(name="toy", family="toy", batch_buckets=[1, 2],
                            deadline_ms=5.0, dtype="float32", num_classes=10,
                            parallelism="single", request_timeout_ms=10_000.0)],
        decode_threads=2,
    )
    state = ServerState(cfg)
    state.build()
    app = make_app(state)

    async def setup():
        client = TestClient(TestServer(app))
        await client.start_server()
        return client

    client = loop.run_until_complete(setup())
    yield lambda coro: loop.run_until_complete(coro), client
    loop.run_until_complete(client.close())


def npy_bytes(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


def toy_image() -> bytes:
    return npy_bytes(np.random.default_rng(0).integers(0, 255, (8, 8, 3), dtype=np.uint8))


def test_predict_roundtrip(client):
    run, c = client

    async def go():
        resp = await c.post("/v1/models/toy:predict", data=toy_image(),
                            headers={"Content-Type": "application/x-npy"})
        assert resp.status == 200
        body = await resp.json()
        assert len(body["top_k"]) == 3
        assert all(0 <= e["class"] < 10 for e in body["top_k"])

    run(go())


def test_classify_alias(client):
    run, c = client

    async def go():
        resp = await c.post("/v1/models/toy:classify", data=toy_image(),
                            headers={"Content-Type": "application/x-npy"})
        assert resp.status == 200

    run(go())


def test_jpeg_body(client):
    from PIL import Image

    run, c = client
    buf = io.BytesIO()
    Image.new("RGB", (32, 32), (120, 30, 200)).save(buf, format="JPEG")

    async def go():
        resp = await c.post("/v1/models/toy:predict", data=buf.getvalue(),
                            headers={"Content-Type": "image/jpeg"})
        assert resp.status == 200

    run(go())


def test_unknown_model_404(client):
    run, c = client

    async def go():
        resp = await c.post("/v1/models/nope:predict", data=b"x")
        assert resp.status == 404

    run(go())


def test_bad_payload_400(client):
    run, c = client

    async def go():
        resp = await c.post("/v1/models/toy:predict", data=b"this is not an image",
                            headers={"Content-Type": "image/jpeg"})
        assert resp.status == 400

    run(go())


def test_health_metrics_stats_trace(client):
    run, c = client

    async def go():
        await c.post("/v1/models/toy:predict", data=toy_image(),
                     headers={"Content-Type": "application/x-npy"})

        resp = await c.get("/healthz")
        assert resp.status == 200
        assert (await resp.json())["status"] == "ok"

        resp = await c.get("/metrics")
        text = await resp.text()
        assert "requests_total" in text
        assert "latency_ms_bucket" in text

        resp = await c.get("/stats")
        stats = await resp.json()
        assert stats["counters"]["requests_total{model=toy}"] >= 1

        resp = await c.get("/v1/models")
        models = await resp.json()
        assert models["toy"]["buckets"] == [[1], [2]]

        resp = await c.get("/debug/trace")
        assert resp.status == 200
        assert "traceEvents" in await resp.text()

    run(go())


def test_index_page(client):
    run, c = client

    async def go():
        resp = await c.get("/")
        assert resp.status == 200
        assert "tpuserve" in await resp.text()

    run(go())


def test_client_batch_npy(client):
    """(N, H, W, 3) npy body -> {"results": [N per-item results]}, matching
    what each image returns individually."""
    run, c = client
    rng = np.random.default_rng(5)
    batch = rng.integers(0, 255, (3, 8, 8, 3), dtype=np.uint8)

    async def go():
        resp = await c.post("/v1/models/toy:classify", data=npy_bytes(batch),
                            headers={"Content-Type": "application/x-npy"})
        assert resp.status == 200, await resp.text()
        body = await resp.json()
        assert set(body) == {"results"} and len(body["results"]) == 3
        # item 1 served alone must answer identically
        solo = await c.post("/v1/models/toy:classify", data=npy_bytes(batch[1]),
                            headers={"Content-Type": "application/x-npy"})
        assert (await solo.json()) == body["results"][1]

    run(go())


def test_client_batch_of_one_keeps_batch_shape(client):
    run, c = client
    one = np.random.default_rng(6).integers(0, 255, (1, 8, 8, 3), dtype=np.uint8)

    async def go():
        resp = await c.post("/v1/models/toy:classify", data=npy_bytes(one),
                            headers={"Content-Type": "application/x-npy"})
        assert resp.status == 200
        body = await resp.json()
        assert set(body) == {"results"} and len(body["results"]) == 1

    run(go())


def test_client_batch_over_limit_400(client):
    run, c = client
    big = np.zeros((1025, 2, 2, 3), dtype=np.uint8)

    async def go():
        resp = await c.post("/v1/models/toy:classify", data=npy_bytes(big),
                            headers={"Content-Type": "application/x-npy"})
        assert resp.status == 400
        assert "limit" in (await resp.json())["error"]

    run(go())


@pytest.mark.slow
def test_two_models_one_server():
    """Two families behind one server: independent batchers/runtimes,
    per-model routing and metrics."""
    import json as _json

    cfg = ServerConfig(
        models=[
            ModelConfig(name="toy", family="toy", batch_buckets=[1, 2],
                        deadline_ms=5.0, dtype="float32", num_classes=10,
                        parallelism="single", request_timeout_ms=10_000.0),
            ModelConfig(name="bert", family="bert", batch_buckets=[1],
                        seq_buckets=[8], deadline_ms=5.0, dtype="float32",
                        num_classes=3, parallelism="single",
                        request_timeout_ms=10_000.0,
                        options=dict(layers=1, d_model=16, heads=2, d_ff=32,
                                     vocab_size=512)),
        ],
        decode_threads=2,
    )
    state = ServerState(cfg)
    state.build()
    app = make_app(state)
    loop = asyncio.new_event_loop()

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r1 = await client.post("/v1/models/toy:classify", data=toy_image(),
                                   headers={"Content-Type": "application/x-npy"})
            assert r1.status == 200
            r2 = await client.post(
                "/v1/models/bert:classify",
                data=_json.dumps({"text": "two models"}).encode(),
                headers={"Content-Type": "application/json"})
            assert r2.status == 200, await r2.text()
            inv = await (await client.get("/v1/models")).json()
            assert set(inv) == {"toy", "bert"}
            metrics = await (await client.get("/metrics")).text()
            assert 'items_total{model="toy"}' in metrics
            assert 'items_total{model="bert"}' in metrics
        finally:
            await client.close()

    loop.run_until_complete(go())
    loop.close()


def test_admin_reload_endpoint(client):
    """Hot weight reload over HTTP: 200 with timing + canary, 404 unknown."""
    run, c = client

    async def go():
        resp = await c.post("/admin/models/toy:reload")
        assert resp.status == 200, await resp.text()
        body = await resp.json()
        assert body["model"] == "toy" and body["reload_ms"] > 0
        assert body["canary_ok"] is True
        # still serving after the swap
        ok = await c.post("/v1/models/toy:classify", data=toy_image(),
                          headers={"Content-Type": "application/x-npy"})
        assert ok.status == 200
        missing = await c.post("/admin/models/nosuch:reload")
        assert missing.status == 404

    run(go())


def test_class_labels_in_responses(tmp_path, loop):
    """cfg.labels maps class indices to names in classify responses and
    shows up in the /v1/models inventory. CRLF endings and trailing blank
    lines must not corrupt the label values."""
    labels = tmp_path / "labels.txt"
    labels.write_bytes("".join(f"name-{i}\r\n" for i in range(10)).encode() + b"\n")
    cfg = ServerConfig(
        models=[ModelConfig(name="toy", family="toy", batch_buckets=[1, 2],
                            deadline_ms=5.0, dtype="float32", num_classes=10,
                            parallelism="single", request_timeout_ms=10_000.0,
                            labels=str(labels))],
        decode_threads=2,
    )
    state = ServerState(cfg)
    state.build()
    app = make_app(state)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/v1/models/toy:classify", data=toy_image(),
                                  headers={"Content-Type": "application/x-npy"})
            assert r.status == 200
            body = await r.json()
            for entry in body["top_k"]:
                assert entry["label"] == f"name-{entry['class']}"
            inv = await (await client.get("/v1/models")).json()
            assert inv["toy"]["labels"] == str(labels)
        finally:
            await client.close()

    loop.run_until_complete(go())


def test_periodic_canary_degrades_and_recovers(loop):
    """canary_interval_s > 0: /healthz reflects live failures (503) and
    recovers when the model serves again."""
    cfg = ServerConfig(
        models=[ModelConfig(name="toy", family="toy", batch_buckets=[1, 2],
                            deadline_ms=5.0, dtype="float32", num_classes=10,
                            parallelism="single", request_timeout_ms=10_000.0)],
        decode_threads=2, canary_interval_s=0.15,
    )
    state = ServerState(cfg)
    state.build()
    app = make_app(state)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            assert (await client.get("/healthz")).status == 200
            # Live failure: every batch dispatch now raises.
            from tpuserve.faults import FaultInjector

            state.batchers["toy"].injector = FaultInjector.single("batch_error")
            await asyncio.sleep(0.5)
            r = await client.get("/healthz")
            assert r.status == 503, await r.text()
            assert (await r.json())["status"] == "degraded"
            # Recovery.
            state.batchers["toy"].injector = None
            await asyncio.sleep(0.5)
            assert (await client.get("/healthz")).status == 200
        finally:
            await client.close()

    loop.run_until_complete(go())


def test_canary_timeout_floored_at_request_timeout():
    """A small canary_interval_s must not shrink a slow model's canary
    timeout below its own request_timeout_ms (ADVICE r3: sd15-class models
    with ~1.6 s+ per-image device time flapped /healthz at 2 s)."""
    cfg = ServerConfig(
        models=[
            ModelConfig(name="slow", family="toy", batch_buckets=[1],
                        dtype="float32", num_classes=10, parallelism="single",
                        request_timeout_ms=30_000.0),
            ModelConfig(name="fast", family="toy", batch_buckets=[1],
                        dtype="float32", num_classes=10, parallelism="single",
                        request_timeout_ms=500.0),
        ],
        decode_threads=2, canary_interval_s=0.25,
    )
    state = ServerState(cfg)
    state.build()
    t = state.canary_timeouts()
    assert t["slow"] == 30.0          # floored at its request timeout
    assert t["fast"] == 2.0           # interval bound still applies


def test_canary_shed_without_prior_status(loop):
    """A shed canary with no prior status (startup_canary=False) reports
    healthy instead of KeyError-ing (review regression)."""
    from tpuserve.batcher import QueueFull

    cfg = ServerConfig(
        models=[ModelConfig(name="toy", family="toy", batch_buckets=[1, 2],
                            deadline_ms=5.0, dtype="float32", num_classes=10,
                            parallelism="single", request_timeout_ms=10_000.0)],
        decode_threads=2, startup_canary=False,
    )
    state = ServerState(cfg)
    state.build()
    app = make_app(state)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            def full_submit(*a, **kw):
                raise QueueFull("full")
            state.batchers["toy"].submit = full_submit
            assert await state.run_canary("toy") is True
            assert (await client.get("/healthz")).status == 200
        finally:
            await client.close()

    loop.run_until_complete(go())


# ---------------------------------------------------------------------------
# Demand-shaping layer over HTTP (ISSUE 5): result cache + coalescing
# ---------------------------------------------------------------------------

def _cache_state(**cache_over):
    from tpuserve.config import CacheConfig

    cfg = ServerConfig(
        models=[ModelConfig(name="toy", family="toy", batch_buckets=[1, 2, 4],
                            deadline_ms=5.0, dtype="float32", num_classes=10,
                            parallelism="single",
                            request_timeout_ms=10_000.0)],
        decode_threads=2,
        cache=CacheConfig(enabled=True, **cache_over),
    )
    state = ServerState(cfg)
    state.build()
    return state


def test_cache_hit_serves_identical_body():
    """The second identical request answers from the cache — byte-identical
    body via the pre-serialized fast path, counted as a hit, never a second
    batch submission."""
    state = _cache_state()
    loop = asyncio.new_event_loop()

    async def go():
        client = TestClient(TestServer(make_app(state)))
        await client.start_server()
        try:
            payload = toy_image()
            hdrs = {"Content-Type": "application/x-npy"}
            r1 = await client.post("/v1/models/toy:classify", data=payload,
                                   headers=hdrs)
            assert r1.status == 200
            body1 = await r1.read()
            batches0 = state.metrics.counter(
                "batches_total{model=toy}").value
            r2 = await client.post("/v1/models/toy:classify", data=payload,
                                   headers=hdrs)
            assert r2.status == 200
            assert await r2.read() == body1  # pre-serialized hit body
            c = state.caches["toy"].stats()
            assert c["hits"] == 1 and c["misses"] == 1
            # A hit costs zero model work.
            assert state.metrics.counter(
                "batches_total{model=toy}").value == batches0
            # /stats exposes the accounting.
            stats = await (await client.get("/stats")).json()
            assert stats["cache"]["toy"]["hits"] == 1
        finally:
            await client.close()

    loop.run_until_complete(go())
    loop.close()


def test_client_batch_merges_hits_and_misses_in_order():
    """A client batch mixing cached, duplicate, and fresh items preserves
    result order: hits fill their slots from the cache, duplicates coalesce
    onto one flight, and only genuine misses reach the batcher."""
    import numpy as np

    state = _cache_state()
    loop = asyncio.new_event_loop()

    async def go():
        client = TestClient(TestServer(make_app(state)))
        await client.start_server()
        try:
            rng = np.random.default_rng(3)
            a = rng.integers(0, 255, (8, 8, 3), dtype=np.uint8)
            bimg = rng.integers(0, 255, (8, 8, 3), dtype=np.uint8)
            hdrs = {"Content-Type": "application/x-npy"}
            # Prime the cache with A alone.
            r = await client.post("/v1/models/toy:classify",
                                  data=npy_bytes(np.stack([a])), headers=hdrs)
            assert r.status == 200
            res_a = (await r.json())["results"][0]
            # Batch [A, B, B]: A is a pure hit, first B leads a flight, the
            # duplicate B coalesces onto it.
            r = await client.post(
                "/v1/models/toy:classify",
                data=npy_bytes(np.stack([a, bimg, bimg])), headers=hdrs)
            assert r.status == 200
            results = (await r.json())["results"]
            assert len(results) == 3
            assert results[0] == res_a  # slot 0 answered from the cache
            assert results[1] == results[2]  # coalesced duplicates agree
            c = state.caches["toy"].stats()
            assert c["hits"] == 1      # A in the mixed batch
            assert c["misses"] == 2    # A's prime + B's flight
            assert c["coalesced"] == 1  # the duplicate B
        finally:
            await client.close()

    loop.run_until_complete(go())
    loop.close()


def test_cache_disabled_path_untouched():
    """With [cache] off (the default) no ModelCache is built and repeated
    identical requests each reach the model."""
    cfg = ServerConfig(
        models=[ModelConfig(name="toy", family="toy", batch_buckets=[1, 2],
                            deadline_ms=5.0, dtype="float32", num_classes=10,
                            parallelism="single",
                            request_timeout_ms=10_000.0)],
        decode_threads=2,
    )
    state = ServerState(cfg)
    state.build()
    assert state.caches == {}
    loop = asyncio.new_event_loop()

    async def go():
        client = TestClient(TestServer(make_app(state)))
        await client.start_server()
        try:
            hdrs = {"Content-Type": "application/x-npy"}
            payload = toy_image()
            items0 = state.metrics.counter("items_total{model=toy}").value
            for _ in range(2):
                r = await client.post("/v1/models/toy:classify",
                                      data=payload, headers=hdrs)
                assert r.status == 200
            # Both identical requests reached the model (no dedup layer).
            assert state.metrics.counter(
                "items_total{model=toy}").value == items0 + 2
        finally:
            await client.close()

    loop.run_until_complete(go())
    loop.close()
