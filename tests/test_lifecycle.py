"""Versioned model lifecycle (ISSUE 2): integrity-checked, canary-gated
weight hot-swap with automatic rollback, plus per-request deadlines.

Everything runs on CPU with the toy family against real aiohttp servers.
The invariants under test are the state-path counterparts of PR 1's
request-path guarantees: a bad candidate (corrupt / NaN / regressed) never
answers one request, the old version keeps serving through every rejection,
and rollback restores version N-1 exactly.
"""

import asyncio
import io
import json
import shutil
import time

import jax
import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from tpuserve.config import (FaultRuleConfig, FaultsConfig, LifecycleConfig,
                             ModelConfig, ServerConfig)
from tpuserve.faults import FaultInjector
from tpuserve.models import build
from tpuserve.runtime import NaNDetected, build_runtime
from tpuserve.savedmodel import (IntegrityError, manifest_path, save_orbax,
                                 tree_digests, verify_manifest_if_present,
                                 write_manifest)
from tpuserve.server import ServerState, make_app

NPY = {"Content-Type": "application/x-npy"}


def toy_model_cfg(**over) -> ModelConfig:
    base = dict(name="toy", family="toy", batch_buckets=[1, 2, 4],
                deadline_ms=5.0, dtype="float32", num_classes=10,
                parallelism="single", request_timeout_ms=10_000.0)
    base.update(over)
    return ModelConfig(**base)


def toy_server_cfg(model_over=None, **over) -> ServerConfig:
    base = dict(models=[toy_model_cfg(**(model_over or {}))], decode_threads=2)
    base.update(over)
    return ServerConfig(**base)


def npy_image(seed: int = 0) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.random.default_rng(seed).integers(
        0, 200, (8, 8, 3), dtype=np.uint8))
    return buf.getvalue()


def toy_params(key: int = 1):
    return build(toy_model_cfg()).init_params(jax.random.key(key))


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


async def _serving_client(state):
    client = TestClient(TestServer(make_app(state)))
    await client.start_server()
    return client


async def _probs(client) -> list:
    """Top-k probs for a fixed input: the weight-identity fingerprint."""
    r = await client.post("/v1/models/toy:predict", data=npy_image(7),
                          headers=NPY)
    assert r.status == 200, await r.text()
    return [e["prob"] for e in (await r.json())["top_k"]]


# ---------------------------------------------------------------------------
# Sidecar checksum manifest
# ---------------------------------------------------------------------------

def test_save_orbax_writes_manifest_and_verifies(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    params = toy_params()
    save_orbax(ckpt, params)
    import os
    assert os.path.exists(manifest_path(ckpt))
    assert verify_manifest_if_present(ckpt, jax.device_get(params)) is True
    # Any flipped leaf fails the digest comparison.
    bad = jax.device_get(params)
    bad["w1"] = np.asarray(bad["w1"]).copy()
    bad["w1"][0, 0] += 1.0
    with pytest.raises(IntegrityError, match="corrupt"):
        verify_manifest_if_present(ckpt, bad)


def test_manifest_missing_skips_unless_required(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    params = jax.device_get(toy_params())
    save_orbax(ckpt, params)
    import os
    os.remove(manifest_path(ckpt))
    assert verify_manifest_if_present(ckpt, params) is False  # skipped
    with pytest.raises(IntegrityError, match="require_manifest"):
        verify_manifest_if_present(ckpt, params, require=True)


def test_tree_digests_stable_and_sensitive():
    params = jax.device_get(toy_params())
    a, b = tree_digests(params), tree_digests(params)
    assert a == b
    changed = dict(params, b1=np.asarray(params["b1"]) + 1)
    assert tree_digests(changed) != a


# ---------------------------------------------------------------------------
# Runtime version bookkeeping
# ---------------------------------------------------------------------------

def test_runtime_versions_monotonic_and_rollback():
    model = build(toy_model_cfg())
    rt = build_runtime(model)
    assert rt.version == 1
    rt.publish(rt.stage_params())
    assert rt.version == 2
    rt.publish(rt.stage_params())
    assert rt.version == 3
    info = rt.rollback()
    assert info == {"model": "toy", "version": 2, "rolled_back_from": 3}
    with pytest.raises(ValueError, match="no retained previous"):
        rt.rollback()
    # Version numbers are never reused after a rollback.
    rt.publish(rt.stage_params())
    assert rt.version == 4


def test_stage_params_rejects_nan_tree():
    model = build(toy_model_cfg())
    rt = build_runtime(model)
    good = model.load_params
    poisoned = jax.device_get(model.init_params(jax.random.key(0)))
    poisoned["w2"] = np.asarray(poisoned["w2"]).copy()
    poisoned["w2"][3, 3] = np.nan
    model.load_params = lambda: poisoned
    try:
        with pytest.raises(NaNDetected, match="NaN/Inf"):
            rt.stage_params()
    finally:
        model.load_params = good
    assert rt.version == 1  # nothing published


# ---------------------------------------------------------------------------
# HTTP: rejection gates keep the old version serving
# ---------------------------------------------------------------------------

def test_checksum_mismatch_rejected_old_version_serves(tmp_path, loop):
    """Overwrite the checkpoint but keep the stale manifest (bit-rot /
    torn-copy stand-in): the reload 409s at the integrity gate and the
    in-memory version keeps serving identical outputs."""
    ckpt = str(tmp_path / "ckpt")
    save_orbax(ckpt, toy_params(1))
    with open(manifest_path(ckpt), encoding="utf-8") as f:
        stale_manifest = f.read()
    state = ServerState(toy_server_cfg(model_over=dict(weights=ckpt)))
    state.build()

    async def go():
        client = await _serving_client(state)
        try:
            before = await _probs(client)
            shutil.rmtree(ckpt)
            save_orbax(ckpt, toy_params(2))
            with open(manifest_path(ckpt), "w", encoding="utf-8") as f:
                f.write(stale_manifest)
            r = await client.post("/admin/models/toy:reload")
            assert r.status == 409, await r.text()
            body = await r.json()
            assert body["stage"] == "integrity"
            assert body["rolled_back"] is False
            assert body["version"] == 1
            assert await _probs(client) == before  # old weights untouched
            stats = await (await client.get("/stats")).json()
            assert stats["lifecycle"]["toy"]["live_version"] == 1
            assert stats["counters"][
                "reload_rejected_total{model=toy,stage=integrity}"] == 1
            assert stats["gauges"]["model_version{model=toy}"] == 1.0
        finally:
            await client.close()

    loop.run_until_complete(go())


def test_nan_checkpoint_rejected_old_version_serves(tmp_path, loop):
    ckpt = str(tmp_path / "ckpt")
    save_orbax(ckpt, toy_params(1))
    state = ServerState(toy_server_cfg(model_over=dict(weights=ckpt)))
    state.build()

    async def go():
        client = await _serving_client(state)
        try:
            before = await _probs(client)
            poisoned = jax.device_get(toy_params(2))
            poisoned["w1"] = np.asarray(poisoned["w1"]).copy()
            poisoned["w1"][0, 0] = np.inf
            shutil.rmtree(ckpt)
            save_orbax(ckpt, poisoned)  # manifest matches: integrity passes
            r = await client.post("/admin/models/toy:reload")
            assert r.status == 409, await r.text()
            body = await r.json()
            assert body["stage"] == "nan_scan" and body["version"] == 1
            assert await _probs(client) == before
        finally:
            await client.close()

    loop.run_until_complete(go())


def test_staged_canary_failure_never_publishes(loop):
    """reload_regressed injected at 100%: the staged canary fails, the
    candidate never publishes, and zero requests are answered by it."""
    cfg = toy_server_cfg(faults=FaultsConfig(enabled=True, rules=[
        FaultRuleConfig(kind="reload_regressed", model="toy")]))
    state = ServerState(cfg)
    state.build()

    async def go():
        client = await _serving_client(state)
        try:
            before = await _probs(client)
            for _ in range(3):
                r = await client.post("/admin/models/toy:reload")
                assert r.status == 409, await r.text()
                body = await r.json()
                assert body["stage"] == "staged_canary"
                assert body["version"] == 1
                assert await _probs(client) == before
            stats = await (await client.get("/stats")).json()
            assert stats["counters"][
                "reload_rejected_total{model=toy,stage=staged_canary}"] == 3
            history = stats["lifecycle"]["toy"]["history"]
            assert [h["status"] for h in history] == \
                ["live", "rejected", "rejected", "rejected"]
        finally:
            await client.close()

    loop.run_until_complete(go())


def test_post_publish_canary_failure_rolls_back(loop):
    """The PR-1 hole, closed: a canary failure after publish no longer
    answers 200 with bad weights live — the lifecycle rolls back and the
    response says so."""
    cfg = toy_server_cfg(faults=FaultsConfig(enabled=True, rules=[
        FaultRuleConfig(kind="canary_fail", model="toy")]))
    state = ServerState(cfg)
    state.build()

    async def go():
        client = await _serving_client(state)
        try:
            r = await client.post("/admin/models/toy:reload")
            assert r.status == 500, await r.text()
            body = await r.json()
            assert body["stage"] == "post_canary"
            assert body["rolled_back"] is True
            assert body["version"] == 1  # back on the last known good
            stats = await (await client.get("/stats")).json()
            assert stats["counters"][
                "rollbacks_total{model=toy,reason=post_publish_canary}"] == 1
            assert stats["gauges"]["model_version{model=toy}"] == 1.0
            # Serving never stopped.
            ok = await client.post("/v1/models/toy:predict",
                                   data=npy_image(), headers=NPY)
            assert ok.status == 200
        finally:
            await client.close()

    loop.run_until_complete(go())


# ---------------------------------------------------------------------------
# Rollback endpoint + soak window
# ---------------------------------------------------------------------------

def test_rollback_endpoint_restores_previous_version(tmp_path, loop):
    ckpt = str(tmp_path / "ckpt")
    params_a = jax.device_get(toy_params(1))
    save_orbax(ckpt, params_a)
    state = ServerState(toy_server_cfg(model_over=dict(weights=ckpt)))
    state.build()

    async def go():
        client = await _serving_client(state)
        try:
            probs_a = await _probs(client)
            params_b = jax.tree_util.tree_map(lambda x: x + 0.25, params_a)
            shutil.rmtree(ckpt)
            save_orbax(ckpt, params_b)
            r = await client.post("/admin/models/toy:reload")
            assert r.status == 200, await r.text()
            assert (await r.json())["version"] == 2
            probs_b = await _probs(client)
            assert probs_b != probs_a  # genuinely new weights
            r = await client.post("/admin/models/toy:rollback")
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["version"] == 1 and body["rolled_back_from"] == 2
            assert await _probs(client) == probs_a  # bit-identical restore
            v = await (await client.get("/admin/models/toy/versions")).json()
            assert v["live_version"] == 1
            assert v["previous_version"] is None
            assert [h["status"] for h in v["history"]][-1] == "live"
            # Nothing retained anymore: second rollback conflicts.
            r = await client.post("/admin/models/toy:rollback")
            assert r.status == 409
        finally:
            await client.close()

    loop.run_until_complete(go())


def test_breaker_trip_in_soak_window_auto_rolls_back(loop):
    cfg = toy_server_cfg(
        model_over=dict(breaker_threshold=2),
        lifecycle=LifecycleConfig(soak_s=5.0, soak_poll_s=0.05))
    state = ServerState(cfg)
    state.build()

    async def go():
        client = await _serving_client(state)
        try:
            r = await client.post("/admin/models/toy:reload")
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["version"] == 2 and body["soak_s"] == 5.0
            v = await (await client.get("/admin/models/toy/versions")).json()
            assert v["soaking"] is True

            # Total outage below the HTTP layer: dispatches fail, the
            # breaker trips, and the soak monitor must revert to v1.
            state.batchers["toy"].injector = FaultInjector.single("batch_error")
            for _ in range(2):
                bad = await client.post("/v1/models/toy:predict",
                                        data=npy_image(), headers=NPY)
                assert bad.status == 500
            assert state.breakers["toy"].state == "open"
            deadline = time.perf_counter() + 3.0
            while time.perf_counter() < deadline:
                if state.runtimes["toy"].version == 1:
                    break
                await asyncio.sleep(0.02)
            assert state.runtimes["toy"].version == 1, "soak did not roll back"
            state.batchers["toy"].injector = None
            stats = await (await client.get("/stats")).json()
            assert stats["counters"][
                "rollbacks_total{model=toy,reason=soak_breaker}"] == 1
            assert stats["lifecycle"]["toy"]["soaking"] is False
        finally:
            await client.close()

    loop.run_until_complete(go())


def test_failed_canary_in_soak_window_auto_rolls_back(loop):
    """The other soak trigger: a failed periodic-canary verdict inside the
    window reverts the publish and ticks rollbacks_total with reason
    "soak_canary" (docs/REFERENCE.md)."""
    cfg = toy_server_cfg(
        lifecycle=LifecycleConfig(soak_s=5.0, soak_poll_s=0.05))
    state = ServerState(cfg)
    state.build()

    async def go():
        client = await _serving_client(state)
        try:
            r = await client.post("/admin/models/toy:reload")
            assert r.status == 200, await r.text()
            assert (await r.json())["version"] == 2
            v = await (await client.get("/admin/models/toy/versions")).json()
            assert v["soaking"] is True
            # The periodic canary's verdict goes bad mid-soak; the soak
            # monitor (not the breaker — it stays closed) must revert.
            state.canary_ok["toy"] = False
            deadline = time.perf_counter() + 3.0
            while time.perf_counter() < deadline:
                if state.runtimes["toy"].version == 1:
                    break
                await asyncio.sleep(0.02)
            assert state.runtimes["toy"].version == 1, "soak did not roll back"
            stats = await (await client.get("/stats")).json()
            assert stats["counters"][
                "rollbacks_total{model=toy,reason=soak_canary}"] == 1
            assert stats["lifecycle"]["toy"]["soaking"] is False
        finally:
            await client.close()

    loop.run_until_complete(go())


def test_soak_window_passes_quietly(loop):
    """A healthy reload with a short soak window stays on the new version."""
    cfg = toy_server_cfg(lifecycle=LifecycleConfig(soak_s=0.2,
                                                   soak_poll_s=0.05))
    state = ServerState(cfg)
    state.build()

    async def go():
        client = await _serving_client(state)
        try:
            r = await client.post("/admin/models/toy:reload")
            assert r.status == 200
            await asyncio.sleep(0.4)  # outlive the soak window
            v = await (await client.get("/admin/models/toy/versions")).json()
            assert v["live_version"] == 2 and v["soaking"] is False
        finally:
            await client.close()

    loop.run_until_complete(go())


# ---------------------------------------------------------------------------
# Reload under load: zero accepted requests dropped
# ---------------------------------------------------------------------------

def test_reload_under_load_drops_nothing(tmp_path, loop):
    ckpt = str(tmp_path / "ckpt")
    params_a = jax.device_get(toy_params(1))
    save_orbax(ckpt, params_a)
    state = ServerState(toy_server_cfg(model_over=dict(weights=ckpt)))
    state.build()

    async def go():
        client = await _serving_client(state)
        try:
            async def one(i: int) -> int:
                r = await client.post("/v1/models/toy:predict",
                                      data=npy_image(i), headers=NPY)
                return r.status

            first = [asyncio.ensure_future(one(i)) for i in range(24)]
            shutil.rmtree(ckpt)
            save_orbax(ckpt, jax.tree_util.tree_map(lambda x: x + 0.25,
                                                    params_a))
            reload_task = asyncio.ensure_future(
                client.post("/admin/models/toy:reload"))
            second = [asyncio.ensure_future(one(100 + i)) for i in range(24)]
            statuses = await asyncio.gather(*first, *second)
            assert statuses == [200] * 48  # zero dropped, zero errored
            assert (await reload_task).status == 200
            assert state.runtimes["toy"].version == 2
        finally:
            await client.close()

    loop.run_until_complete(go())


# ---------------------------------------------------------------------------
# Per-request deadlines over HTTP (P3)
# ---------------------------------------------------------------------------

def test_timeout_ms_expired_in_queue_fast_504(loop):
    """A queued request whose client deadline expires behind a slow batch
    gets the batcher's fast deadline_exceeded 504, counted as such."""
    cfg = toy_server_cfg(
        model_over=dict(max_inflight=1),
        # No startup canary: it would consume the one-shot slow_dispatch.
        startup_canary=False,
        faults=FaultsConfig(enabled=True, rules=[
            FaultRuleConfig(kind="slow_dispatch", delay_ms=400.0, count=1)]))
    state = ServerState(cfg)
    state.build()

    async def go():
        client = await _serving_client(state)
        try:
            slow = asyncio.ensure_future(client.post(
                "/v1/models/toy:predict", data=npy_image(), headers=NPY))
            await asyncio.sleep(0.1)  # dispatched, holding the inflight slot
            r = await client.post("/v1/models/toy:predict?timeout_ms=50",
                                  data=npy_image(), headers=NPY)
            assert r.status == 504, await r.text()
            assert "deadline" in (await r.json())["error"]
            assert (await slow).status == 200  # the slow batch still lands
            stats = await (await client.get("/stats")).json()
            assert stats["counters"]["deadline_exceeded_total{model=toy}"] >= 1
        finally:
            await client.close()

    loop.run_until_complete(go())


def test_timeout_ms_accepted_from_json_body(loop):
    """JSON bodies can carry timeout_ms without breaking model decode; an
    ample deadline serves normally."""
    from tpuserve.server import _requested_timeout_ms

    class Req:
        query: dict = {}
        headers: dict = {}

    body = json.dumps({"text": "hi", "timeout_ms": 1234.0}).encode()
    assert _requested_timeout_ms(Req(), body, "application/json") == 1234.0
    assert _requested_timeout_ms(Req(), b'{"text": "hi"}',
                                 "application/json") is None
    assert _requested_timeout_ms(Req(), b"\x93NUMPY...",
                                 "application/x-npy") is None
    with pytest.raises(ValueError, match="positive"):
        _requested_timeout_ms(Req(), json.dumps({"timeout_ms": -5}).encode(),
                              "application/json")


def test_timeout_ms_rejected_when_malformed(loop):
    state = ServerState(toy_server_cfg())
    state.build()

    async def go():
        client = await _serving_client(state)
        try:
            r = await client.post("/v1/models/toy:predict?timeout_ms=nope",
                                  data=npy_image(), headers=NPY)
            assert r.status == 400
            assert "timeout_ms" in (await r.json())["error"]
            ok = await client.post("/v1/models/toy:predict?timeout_ms=5000",
                                   data=npy_image(), headers=NPY)
            assert ok.status == 200
        finally:
            await client.close()

    loop.run_until_complete(go())


# ---------------------------------------------------------------------------
# Result cache under lifecycle churn (ISSUE 5): version-keyed entries mean a
# publish/rollback can never serve a stale-version hit, and failed batches
# populate nothing.
# ---------------------------------------------------------------------------

def _cached_state(model_over=None, **over):
    from tpuserve.config import CacheConfig

    over.setdefault("cache", CacheConfig(enabled=True))
    state = ServerState(toy_server_cfg(model_over=model_over, **over))
    state.build()
    return state


def test_cache_never_serves_stale_version_across_publish_and_rollback(
        tmp_path, loop):
    """End to end: a hit before a publish, a forced MISS right after it (the
    key carries the live version), and post-rollback answers bit-identical
    to the original weights — at no point does any response mix versions."""
    ckpt = str(tmp_path / "ckpt")
    params_a = jax.device_get(toy_params(1))
    save_orbax(ckpt, params_a)
    state = _cached_state(model_over=dict(weights=ckpt))

    async def go():
        client = await _serving_client(state)
        cache = state.caches["toy"]
        try:
            probs_a = await _probs(client)
            assert await _probs(client) == probs_a  # cache answers v1
            pre = cache.stats()
            assert pre["hits"] >= 1

            # Publish genuinely different weights.
            shutil.rmtree(ckpt)
            save_orbax(ckpt, jax.tree_util.tree_map(lambda x: x + 0.25,
                                                    params_a))
            r = await client.post("/admin/models/toy:reload")
            assert r.status == 200, await r.text()
            probs_b = await _probs(client)
            post = cache.stats()
            # The identical payload after the publish was a MISS under the
            # new version key — zero stale-version hits, new weights answer.
            assert post["hits"] == pre["hits"], (pre, post)
            assert post["misses"] > pre["misses"]
            assert probs_b != probs_a

            # Rollback restores v1 bit-identically; v1-keyed entries are
            # live again and correct BY CONSTRUCTION (same weights).
            r = await client.post("/admin/models/toy:rollback")
            assert r.status == 200, await r.text()
            assert await _probs(client) == probs_a
            assert await _probs(client) != probs_b
        finally:
            await client.close()

    loop.run_until_complete(go())


def test_mid_flight_publish_drops_result_coalesced_waiters_still_answered(
        loop):
    """A flight admitted under v1 that completes after a publish to v2 must
    fan its result to every coalesced waiter (same answer an uncached
    request spanning the publish would get) but never populate the cache —
    no future lookup under either version may observe it."""
    state = _cached_state()

    async def go():
        client = await _serving_client(state)
        cache = state.caches["toy"]
        try:
            key = cache.key_for(np.zeros((8, 8, 3), np.uint8))
            assert key.startswith("1:")
            base = asyncio.get_running_loop().create_future()
            waiters = [cache.submit_through(key, lambda: base)
                       for _ in range(3)]
            # Publish lands while the flight is in the air.
            r = await client.post("/admin/models/toy:reload")
            assert r.status == 200, await r.text()
            assert state.runtimes["toy"].version == 2
            base.set_result({"top_k": [{"class": 0, "prob": 1.0}]})
            res = await asyncio.gather(*waiters)
            assert all(r_ == res[0] for r_ in res)  # every waiter answered
            stats = cache.stats()
            assert stats["stale_drops"] == 1
            assert cache.get(key) is None  # not under the old key
            assert cache.get(cache.key_for(
                np.zeros((8, 8, 3), np.uint8))) is None  # nor the new one
        finally:
            await client.close()

    loop.run_until_complete(go())


def test_poison_split_failure_populates_nothing(loop):
    """PR-1 containment meets the cache: a batch that fails through retry +
    poison-split isolation must leave ZERO cache entries — the next
    identical request is a fresh miss that reaches the model."""
    state = _cached_state(model_over=dict(batch_retry=True,
                                          retry_split=True))

    async def go():
        client = await _serving_client(state)
        cache = state.caches["toy"]
        try:
            entries0 = cache.stats()["entries"]
            state.batchers["toy"].injector = FaultInjector.single(
                "batch_error")
            r = await client.post("/v1/models/toy:predict",
                                  data=npy_image(42), headers=NPY)
            assert r.status == 500
            failed = cache.stats()
            assert failed["entries"] == entries0  # failure cached NOTHING
            assert failed["misses"] >= 1

            state.batchers["toy"].injector = None
            r = await client.post("/v1/models/toy:predict",
                                  data=npy_image(42), headers=NPY)
            assert r.status == 200, await r.text()
            ok = cache.stats()
            # The retry was a genuine model execution (miss), not a hit on
            # the failed flight's ghost.
            assert ok["misses"] == failed["misses"] + 1
            assert ok["hits"] == failed["hits"]
            assert ok["entries"] == entries0 + 1
        finally:
            state.batchers["toy"].injector = None
            await client.close()

    loop.run_until_complete(go())
