"""Weight-only int8 quantization (tpuserve/quantize.py): numerics, spec
mirroring for tensor parallelism, and the end-to-end serving path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpuserve import quantize as qz
from tpuserve.config import ModelConfig
from tpuserve.models import build
from tpuserve.runtime import build_runtime


def test_roundtrip_error_bounded_per_channel():
    rng = np.random.default_rng(0)
    w = rng.normal(0, 0.3, (64, 96)).astype(np.float32)
    q = qz.quantize_leaf(w)
    assert q[qz.QKEY].dtype == np.int8 and q[qz.QKEY].shape == w.shape
    assert q[qz.SKEY].shape == (1, 96)
    deq = q[qz.QKEY].astype(np.float32) * q[qz.SKEY]
    # Symmetric rounding: error <= scale/2 per element, channel-wise.
    assert (np.abs(deq - w) <= q[qz.SKEY] / 2 + 1e-7).all()


def test_depthwise_uses_second_to_last_axis():
    w = np.random.default_rng(1).normal(size=(3, 3, 512, 1)).astype(np.float32)
    q = qz.quantize_leaf(w)
    assert q[qz.SKEY].shape == (1, 1, 512, 1)


def test_small_int_and_1d_leaves_untouched():
    tree = {
        "kernel": np.zeros((128, 64), np.float32),
        "bias": np.zeros((64,), np.float32),
        "small": np.zeros((4, 4), np.float32),
        "table": np.zeros((128, 64), np.int32),
    }
    out = qz.quantize_tree(tree, min_size=1024)
    assert qz.is_quantized(out["kernel"])
    assert out["bias"] is tree["bias"]
    assert out["small"] is tree["small"]
    assert out["table"] is tree["table"]


def test_zero_weight_channel_dequantizes_to_zero():
    w = np.zeros((64, 64), np.float32)
    q = qz.quantize_leaf(w)
    assert (q[qz.QKEY] == 0).all() and (q[qz.SKEY] == 1.0).all()


def test_specs_for_tree_mirror_tp_sharding():
    params = qz.quantize_tree({
        "up": np.zeros((256, 128), np.float32),    # TP on last axis
        "down": np.zeros((128, 256), np.float32),  # TP on first axis
        "bias": np.zeros((128,), np.float32),
    }, min_size=1024)
    rules = [("up", P(None, "model")), ("down", P("model", None)), (".*", P())]
    out = qz.specs_for_tree(rules, params)
    assert out["up"] == {qz.QKEY: P(None, "model"), qz.SKEY: P(None, "model")}
    # down's channel axis is the last (unsharded) one; its scale replicates.
    assert out["down"] == {qz.QKEY: P("model", None), qz.SKEY: P(None, None)}
    assert out["bias"] == P()


def test_dequantize_tree_matches_numpy():
    rng = np.random.default_rng(2)
    tree = {"k": rng.normal(size=(64, 80)).astype(np.float32),
            "b": rng.normal(size=(80,)).astype(np.float32)}
    qtree = qz.quantize_tree(tree, min_size=1024)
    deq = jax.jit(lambda t: qz.dequantize_tree(t, np.float32))(qtree)
    ref = qtree["k"][qz.QKEY].astype(np.float32) * qtree["k"][qz.SKEY]
    np.testing.assert_allclose(np.asarray(deq["k"]), ref, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(deq["b"]), tree["b"], rtol=1e-6)


def _toy_cfg(**kw) -> ModelConfig:
    return ModelConfig(name="toy", family="toy", batch_buckets=[2],
                       dtype="float32", num_classes=10, parallelism="single",
                       **kw)


def test_end_to_end_toy_matches_fp_serving():
    """Quantized serving agrees with full-precision serving on the same
    weights, and the compiled params really are int8."""
    img = np.random.default_rng(3).integers(0, 255, (8, 8, 3), np.uint8)

    def run(cfg):
        model = build(cfg)
        rt = build_runtime(model)
        bucket = model.buckets()[0]
        batch = model.assemble([img], bucket)
        return rt, rt.fetch(rt.run(bucket, batch))

    rt_fp, out_fp = run(_toy_cfg())
    rt_q, out_q = run(_toy_cfg(quantize="int8", quantize_min_size=1024))

    leaves = jax.tree_util.tree_leaves(rt_q.params_per_mesh[0])
    assert any(x.dtype == np.int8 for x in leaves), "nothing was quantized"
    np.testing.assert_allclose(out_q["probs"], out_fp["probs"], atol=5e-3)
    # Top-1 agreement.
    assert out_q["indices"][0][0] == out_fp["indices"][0][0]


@pytest.mark.parametrize("mode", ["int8", "int8c"])
def test_tp_sharded_quantized_bert_runs(mode):
    """Quantized weights + TP: scales shard with their weights over the
    model axis and the forward stays finite (8 fake CPU devices). The
    int8c variant additionally proves the int8 dot_general partitions
    under GSPMD with the FFN kernels kept quantized (the int8-compute x
    tensor-parallel composition)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs multi-device mesh")
    from tpuserve.parallel import make_mesh
    from tpuserve.parallel.mesh import MeshPlan

    mesh = make_mesh(MeshPlan(tp=2), devices=jax.devices()[:4])
    cfg = ModelConfig(
        name="bert", family="bert", parallelism="sharded", tp=2,
        batch_buckets=[2], seq_buckets=[16], dtype="float32", num_classes=4,
        quantize=mode, quantize_min_size=256,
        options={"layers": 1, "d_model": 32, "heads": 2, "d_ff": 64,
                 "vocab_size": 512},
    )
    model = build(cfg)
    rt = build_runtime(model, mesh=mesh)
    (bucket,) = rt.executables
    item = model.host_decode(b'{"text": "quantized tensor parallel"}',
                             "application/json")
    out = rt.fetch(rt.run(bucket, model.assemble([item, item], bucket)))
    assert np.isfinite(out["probs"]).all()
    if mode == "int8c":
        # The kept-quantized FFN kernels really are sharded int8 on device.
        q8 = rt.params_per_mesh[0]["params"]["layer0"]["mlp_up"]["kernel"]["q8"]
        assert q8.dtype == np.int8
        assert len(q8.addressable_shards) >= 2


def test_int8_matmul_matches_dequant_dense():
    """int8 x int8 -> int32 with dynamic activation scales tracks the
    dequantize-then-dense product to quantization tolerance."""
    from tpuserve.quantize import int8_matmul, quantize_leaf

    rng = np.random.default_rng(7)
    x = rng.standard_normal((4, 96)).astype(np.float32)
    w = rng.standard_normal((96, 128)).astype(np.float32)
    q = quantize_leaf(w)
    ref = x @ (q["q8"].astype(np.float32) * q["q8_scale"])
    got = np.asarray(int8_matmul(jnp.asarray(x), jnp.asarray(q["q8"]),
                                 jnp.asarray(q["q8_scale"]), jnp.float32))
    # int8c adds only activation rounding on top of the weight rounding;
    # bound the error against the output scale (elementwise relative error
    # is meaningless where the dot products cancel to ~0).
    assert np.isfinite(got).all()
    assert np.abs(got - ref).max() < 0.02 * np.abs(ref).max()


def test_int8c_bert_serves_with_bounded_drift():
    """quantize='int8c' (FFN matmuls on the MXU's int8 path) serves with
    top-1 agreement and bounded prob drift vs full precision, and the
    unsupported-family config fails with guidance."""
    def bert_cfg(**over):
        base = dict(
            name="b", family="bert", parallelism="single",
            batch_buckets=[2], seq_buckets=[16], dtype="float32",
            num_classes=4, quantize_min_size=256,
            options={"layers": 2, "d_model": 32, "heads": 2, "d_ff": 64,
                     "vocab_size": 512},
        )
        base.update(over)
        return ModelConfig(**base)

    def run(cfg):
        model = build(cfg)
        rt = build_runtime(model)
        (bucket,) = rt.executables
        item = model.host_decode(b'{"text": "int8 compute on the mxu"}',
                                 "application/json")
        return rt.fetch(rt.run(bucket, model.assemble([item, item], bucket)))

    out_fp = run(bert_cfg())
    out_c = run(bert_cfg(quantize="int8c"))
    assert out_c["indices"][0][0] == out_fp["indices"][0][0]
    # d_model=32 random net with unit-scale init: quantization noise is
    # proportionally larger than at real widths (per-head-dim scales over
    # 16-wide heads); with FFN + attention projections both int8 the
    # observed drift is ~4e-2 with stable top-1. This IS the binding
    # accuracy bound for the full int8c path — the imported-weight gate in
    # test_tf_parity uses 0.05-scale weights whose drift (~3e-5) sits far
    # under its 3e-2 assert, so it checks wiring, not noise margins.
    np.testing.assert_allclose(out_c["probs"], out_fp["probs"], atol=6e-2)

    with pytest.raises(ValueError, match="int8c.*not.*supported|weight-only"):
        build_runtime(build(_toy_cfg(quantize="int8c")))


@pytest.mark.slow  # two full ResNet-50 AOT compiles
def test_int8c_resnet_serves_with_bounded_drift():
    """ResNet-50's int8c site (bottleneck 1x1 convs via Int8Conv1x1,
    including the strided v1-downsample and projection variants): top-1
    agreement and bounded prob drift vs full precision through the
    production runtime."""
    def rn_cfg(**over):
        base = dict(
            name="rn", family="resnet50", parallelism="single",
            batch_buckets=[2], dtype="float32", num_classes=10,
            image_size=32, wire_size=32, quantize_min_size=256,
            options={"v1_downsample": True},
        )
        base.update(over)
        return ModelConfig(**base)

    img = np.random.default_rng(5).integers(0, 255, (32, 32, 3), np.uint8)

    def run(cfg):
        model = build(cfg)
        rt = build_runtime(model)
        (bucket,) = rt.executables
        return rt.fetch(rt.run(bucket, model.assemble([img, img], bucket)))

    out_fp = run(rn_cfg())
    out_c = run(rn_cfg(quantize="int8c"))
    assert out_c["indices"][0][0] == out_fp["indices"][0][0]
    np.testing.assert_allclose(out_c["probs"], out_fp["probs"], atol=3e-2)


def test_int8_conv1x1_matches_dense_conv():
    """Int8Conv1x1's strided matmul == nn.Conv 1x1 with the same
    (dequantized) kernel, on both stride variants."""
    import flax.linen as nn

    from tpuserve.quantize import Int8Conv1x1, quantize_leaf

    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 16)).astype(np.float32))
    w = rng.standard_normal((1, 1, 16, 24)).astype(np.float32)
    for strides in ((1, 1), (2, 2)):
        conv = nn.Conv(24, (1, 1), strides=strides, use_bias=False,
                       dtype=jnp.float32)
        q = quantize_leaf(w)
        wdq = q["q8"].astype(np.float32) * q["q8_scale"]
        ref = conv.apply({"params": {"kernel": jnp.asarray(wdq)}}, x)
        mod = Int8Conv1x1(24, strides=strides, dtype=jnp.float32)
        got = mod.apply({"params": {"kernel": {"q8": jnp.asarray(q["q8"]),
                                               "q8_scale": jnp.asarray(q["q8_scale"])}}}, x)
        assert got.shape == ref.shape
        assert np.abs(np.asarray(got) - np.asarray(ref)).max() \
            < 0.02 * np.abs(np.asarray(ref)).max()


@pytest.mark.slow
def test_recycle_mode_with_int8_weights():
    """Regression: the deferred worker must compile the dequant-wrapped
    forward, not raw model.forward, when weights are stored int8."""
    import asyncio

    from tpuserve.deferred import DeferredPool

    cfg = ModelConfig(
        name="toy", family="toy", batch_buckets=[2], deadline_ms=10.0,
        dtype="float32", num_classes=10, parallelism="single",
        session_mode="recycle", relay_workers=1, relay_slots=2,
        relay_epoch_images=4, relay_epoch_ms=300.0,
        request_timeout_ms=30_000.0, quantize="int8", quantize_min_size=1024,
    )
    model = build(cfg)
    pool = DeferredPool(cfg, "", model)
    pool.prewarm()
    loop = asyncio.new_event_loop()
    loop.run_until_complete(pool.start())
    try:
        imgs = np.random.default_rng(5).integers(0, 255, (2, 8, 8, 3), np.uint8)

        out = loop.run_until_complete(pool.run_deferred((2,), np.asarray(imgs)))
        assert np.isfinite(out["probs"]).all()
    finally:
        loop.run_until_complete(pool.stop())
        loop.close()


def test_quantize_tree_is_idempotent():
    tree = {"k": np.random.default_rng(6).normal(size=(4096, 8)).astype(np.float32)}
    once = qz.quantize_tree(tree, min_size=1024)
    twice = qz.quantize_tree(once, min_size=1)  # would re-quantize any leaf
    assert qz.is_quantized(twice["k"])
    np.testing.assert_array_equal(twice["k"][qz.QKEY], once["k"][qz.QKEY])
    np.testing.assert_array_equal(twice["k"][qz.SKEY], once["k"][qz.SKEY])


@pytest.mark.slow
def test_quantized_orbax_checkpoint_roundtrip(tmp_path):
    """An int8 orbax checkpoint restores and serves; its outputs match
    quantize-at-load serving exactly (same scheme, same weights)."""
    from tpuserve import savedmodel

    img = np.random.default_rng(7).integers(0, 255, (8, 8, 3), np.uint8)

    # Reference: quantize-at-load serving from raw init weights.
    model_ref = build(_toy_cfg(quantize="int8", quantize_min_size=1024))
    rt_ref = build_runtime(model_ref)
    bucket = model_ref.buckets()[0]
    out_ref = rt_ref.fetch(rt_ref.run(bucket, model_ref.assemble([img], bucket)))

    # Write the quantized checkpoint (what import-model --quantize emits).
    raw = build(_toy_cfg()).load_params()
    ckpt = tmp_path / "toy_q8"
    savedmodel.save_orbax(str(ckpt),
                          qz.quantize_tree(jax.device_get(raw), 1024))

    model_q = build(_toy_cfg(weights=str(ckpt), quantize="int8",
                             quantize_min_size=1024))
    rt_q = build_runtime(model_q)
    out_q = rt_q.fetch(rt_q.run(bucket, model_q.assemble([img], bucket)))
    np.testing.assert_allclose(out_q["probs"], out_ref["probs"], rtol=1e-6)

    leaves = jax.tree_util.tree_leaves(rt_q.params_per_mesh[0])
    assert any(x.dtype == np.int8 for x in leaves)


def test_quantized_checkpoint_without_flag_gives_guidance(tmp_path):
    from tpuserve import savedmodel

    raw = build(_toy_cfg()).load_params()
    ckpt = tmp_path / "toy_q8"
    savedmodel.save_orbax(str(ckpt),
                          qz.quantize_tree(jax.device_get(raw), 1024))
    model = build(_toy_cfg(weights=str(ckpt)))
    with pytest.raises(ValueError, match='quantize = "int8"'):
        model.load_params()


def test_unquantized_checkpoint_serves_with_int8_flag(tmp_path):
    """quantize="int8" over a raw checkpoint quantizes at load (the
    documented fallback)."""
    from tpuserve import savedmodel

    raw = build(_toy_cfg()).load_params()
    ckpt = tmp_path / "toy_raw"
    savedmodel.save_orbax(str(ckpt), jax.device_get(raw))
    model = build(_toy_cfg(weights=str(ckpt), quantize="int8",
                           quantize_min_size=1024))
    rt = build_runtime(model)
    leaves = jax.tree_util.tree_leaves(rt.params_per_mesh[0])
    assert any(x.dtype == np.int8 for x in leaves)


def test_checkpoint_metadata_bridges_min_size_mismatch(tmp_path):
    """A checkpoint quantized at min_size=1024 serves under the default
    quantize_min_size: the restore target comes from checkpoint metadata,
    not from the serving config's quantization settings."""
    from tpuserve import savedmodel

    raw = build(_toy_cfg()).load_params()
    ckpt = tmp_path / "toy_q8"
    savedmodel.save_orbax(str(ckpt),
                          qz.quantize_tree(jax.device_get(raw), 1024))

    model = build(_toy_cfg(weights=str(ckpt), quantize="int8"))  # default 4096
    rt = build_runtime(model)
    leaves = jax.tree_util.tree_leaves(rt.params_per_mesh[0])
    assert any(x.dtype == np.int8 for x in leaves)


def test_mismatched_checkpoint_gives_guidance(tmp_path):
    """A checkpoint from a different model shape fails with guidance, not an
    opaque downstream compile error."""
    from tpuserve import savedmodel

    raw = build(_toy_cfg(options={"hidden": 16})).load_params()
    ckpt = tmp_path / "toy16"
    savedmodel.save_orbax(str(ckpt), jax.device_get(raw))
    with pytest.raises(ValueError, match="does not match"):
        build(_toy_cfg(weights=str(ckpt))).load_params()  # hidden=32 default
