"""Content-addressed result cache + single-flight coalescing (ISSUE 5):
digest stability, LRU/TTL bookkeeping, coalescing fan-out, version-churn
stale drops, and the honest-accounting invariants bench.py relies on.

Everything here is unit-level against ModelCache with hand-driven futures;
the HTTP integration (hit fast path, client-batch slot merge) lives in
test_http.py and the lifecycle-churn end-to-end in test_lifecycle.py.
"""

import asyncio
import json

import numpy as np
import pytest

from tpuserve.cache import (CacheEntry, ModelCache, counter_snapshot,
                            hit_rate, item_digest)
from tpuserve.config import CacheConfig
from tpuserve.obs import Metrics


def make_cache(version=1, **cfg_over) -> tuple[ModelCache, Metrics, list]:
    """Cache with a mutable version cell: bump live_version[0] to simulate a
    lifecycle publish/rollback."""
    live_version = [version]
    metrics = Metrics()
    cache = ModelCache("toy", CacheConfig(enabled=True, **cfg_over), metrics,
                       version_fn=lambda: live_version[0])
    return cache, metrics, live_version


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


# ---------------------------------------------------------------------------
# Content digest
# ---------------------------------------------------------------------------

def test_item_digest_stable_across_copies():
    a = np.arange(192, dtype=np.uint8).reshape(8, 8, 3)
    assert item_digest(a) == item_digest(a.copy())
    # Non-contiguous views digest by content, not layout.
    assert item_digest(a[:, ::1]) == item_digest(np.ascontiguousarray(a))


def test_item_digest_sensitive_to_content_shape_dtype():
    a = np.arange(64, dtype=np.uint8)
    b = a.copy()
    b[0] += 1
    assert item_digest(a) != item_digest(b)
    # Same bytes, different shape / dtype must not collide.
    assert item_digest(a) != item_digest(a.reshape(8, 8))
    assert item_digest(a) != item_digest(a.view(np.int8))


def test_item_digest_structures():
    a = np.arange(16, dtype=np.float32)
    # dict key order is canonicalized; tuple vs list is distinguished.
    assert (item_digest({"x": a, "y": 1})
            == item_digest({"y": 1, "x": a}))
    assert item_digest((a, 1)) != item_digest([a, 1])
    assert item_digest("1") != item_digest(1)


def test_key_for_binds_live_version():
    cache, _, live_version = make_cache(version=3)
    a = np.arange(8, dtype=np.uint8)
    k3 = cache.key_for(a)
    live_version[0] = 4
    assert cache.key_for(a) != k3
    assert k3.startswith("3:")


# ---------------------------------------------------------------------------
# get / put bookkeeping
# ---------------------------------------------------------------------------

def test_put_get_and_hit_counting():
    cache, metrics, _ = make_cache()
    cache.put("k", {"top_k": [1, 2]})
    e = cache.get("k")
    assert e is not None and e.value == {"top_k": [1, 2]}
    assert cache.get("missing") is None
    # Hits count; a miss in get() does NOT (the miss is counted at
    # submit_through, where exactly one leader exists per flight).
    assert metrics.counter("cache_hits_total{model=toy}").value == 1
    assert metrics.counter("cache_misses_total{model=toy}").value == 0


def test_lru_eviction_prefers_stale_entries():
    cache, metrics, _ = make_cache(capacity=2)
    cache.put("a", 1)
    cache.put("b", 2)
    assert cache.get("a") is not None  # touch: "a" is now most-recent
    cache.put("c", 3)  # evicts "b", the least-recently-used
    assert cache.get("b") is None
    assert cache.get("a") is not None and cache.get("c") is not None
    assert metrics.counter("cache_evictions_total{model=toy}").value == 1
    assert metrics.gauge("cache_entries{model=toy}").value == 2


def test_ttl_expiry():
    cache, _, _ = make_cache(ttl_s=10.0)
    cache.put("k", 1)
    assert cache.get("k") is not None
    # Backdate the entry past the TTL instead of sleeping.
    cache._entries["k"] = CacheEntry(1, None, cache._entries["k"].at - 11.0)
    assert cache.get("k") is None
    assert cache.stats()["entries"] == 0


def test_put_preserializes_json_body():
    cache, _, _ = make_cache()
    val = {"top_k": [{"class": 1, "prob": 0.5}]}
    cache.put("k", val)
    assert cache.get("k").body == json.dumps(val).encode()
    # Oversized and non-JSON values cache by value only (body None).
    big_cache, _, _ = make_cache(max_body_bytes=4)
    big_cache.put("k", val)
    assert big_cache.get("k").body is None
    cache.put("png", b"\x89PNG")
    assert cache.get("png").body is None and cache.get("png").value == b"\x89PNG"


# ---------------------------------------------------------------------------
# Single-flight coalescing
# ---------------------------------------------------------------------------

def test_single_flight_coalesces_identical_misses():
    async def go():
        cache, metrics, _ = make_cache()
        loop = asyncio.get_running_loop()
        base = loop.create_future()
        calls = []

        def submit():
            calls.append(1)
            return base

        waiters = [cache.submit_through("k", submit) for _ in range(4)]
        assert len(calls) == 1  # ONE batch slot for four identical requests
        base.set_result({"top_k": [7]})
        res = await asyncio.gather(*waiters)
        assert res == [{"top_k": [7]}] * 4
        assert metrics.counter("cache_misses_total{model=toy}").value == 1
        assert metrics.counter("cache_coalesced_total{model=toy}").value == 3
        # The flight populated the cache and is no longer inflight.
        assert cache.get("k").value == {"top_k": [7]}
        assert cache.stats()["inflight"] == 0

    run(go())


def test_failed_flight_fans_error_and_populates_nothing():
    async def go():
        cache, metrics, _ = make_cache()
        base = asyncio.get_running_loop().create_future()
        waiters = [cache.submit_through("k", lambda: base) for _ in range(3)]
        base.set_exception(RuntimeError("poison batch"))
        for w in waiters:
            with pytest.raises(RuntimeError, match="poison batch"):
                await w
        assert cache.get("k") is None  # a failed batch caches NOTHING
        assert cache.stats()["entries"] == 0
        # The next identical request leads a fresh flight (no stuck state).
        base2 = asyncio.get_running_loop().create_future()
        w2 = cache.submit_through("k", lambda: base2)
        base2.set_result(1)
        assert await w2 == 1
        assert metrics.counter("cache_misses_total{model=toy}").value == 2

    run(go())


def test_mid_flight_version_change_drops_result_from_cache():
    async def go():
        cache, metrics, live_version = make_cache(version=1)
        key = cache.key_for(np.arange(8, dtype=np.uint8))
        base = asyncio.get_running_loop().create_future()
        w = cache.submit_through(key, lambda: base)
        live_version[0] = 2  # publish lands while the batch is in flight
        base.set_result({"top_k": [1]})
        # The waiter still gets its result (same as an uncached request
        # spanning the publish) but no future lookup can observe it.
        assert await w == {"top_k": [1]}
        assert cache.get(key) is None
        assert cache.stats()["entries"] == 0
        assert metrics.counter(
            "cache_stale_drops_total{model=toy}").value == 1

    run(go())


def test_waiter_cancellation_never_cancels_the_flight():
    async def go():
        cache, _, _ = make_cache()
        base = asyncio.get_running_loop().create_future()
        w1 = cache.submit_through("k", lambda: base)
        w2 = cache.submit_through("k", lambda: base)
        w1.cancel()  # client disconnect
        assert not base.cancelled()
        base.set_result(42)
        assert await w2 == 42  # the other waiter is unaffected
        assert cache.get("k").value == 42  # and the flight still populated

    run(go())


def test_submit_exception_propagates_with_nothing_registered():
    async def go():
        cache, metrics, _ = make_cache()

        def submit():
            raise RuntimeError("queue full")

        with pytest.raises(RuntimeError, match="queue full"):
            cache.submit_through("k", submit)
        assert cache.stats()["inflight"] == 0
        assert metrics.counter("cache_misses_total{model=toy}").value == 0

    run(go())


def test_coalesce_disabled_every_miss_submits():
    async def go():
        cache, metrics, _ = make_cache(coalesce=False)
        loop = asyncio.get_running_loop()
        bases, calls = [], []

        def submit():
            calls.append(1)
            bases.append(loop.create_future())
            return bases[-1]

        w1 = cache.submit_through("k", submit)
        w2 = cache.submit_through("k", submit)
        assert len(calls) == 2  # no flight registry: both lead
        for b in bases:
            b.set_result(1)
        assert await asyncio.gather(w1, w2) == [1, 1]
        assert metrics.counter("cache_coalesced_total{model=toy}").value == 0

    run(go())


# ---------------------------------------------------------------------------
# Accounting helpers (shared by bench.py and the cache smoke)
# ---------------------------------------------------------------------------

def test_hit_rate_definition():
    assert hit_rate({"hits": 0, "misses": 0, "coalesced": 0}) is None
    assert hit_rate({"hits": 3, "misses": 1, "coalesced": 0}) == 0.75
    # Coalesced waiters are NOT hits: they occupied a real flight.
    assert hit_rate({"hits": 0, "misses": 1, "coalesced": 3}) == 0.0


def test_counter_snapshot_roundtrip():
    cache, metrics, _ = make_cache()
    cache.put("k", 1)
    cache.get("k")
    snap = counter_snapshot(metrics, "toy")
    assert snap == {"hits": 1.0, "misses": 0.0, "coalesced": 0.0}
