"""Weight import/export (C6): orbax round-trip, TF SavedModel/GraphDef
extraction, format detection."""

import numpy as np
import pytest

from tpuserve import savedmodel
from tpuserve.config import ModelConfig
from tpuserve.models import build


@pytest.fixture()
def toy_model():
    return build(ModelConfig(name="toy", family="toy", dtype="float32", num_classes=10))


def test_orbax_roundtrip(tmp_path, toy_model):
    import jax

    params = toy_model.init_params(jax.random.key(0))
    path = str(tmp_path / "ckpt")
    savedmodel.save_orbax(path, params)
    assert savedmodel.detect_format(path) == "orbax"

    restored = savedmodel.load_orbax(path, toy_model)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, restored,
    )


def test_load_orbax_rejects_wrong_dtype_class(tmp_path, toy_model):
    """A checkpoint with an int leaf in a float weight slot must fail at
    restore with guidance, not later via a cast surprise (ADVICE r3).
    bf16-vs-f32 differences stay legal — only the dtype CLASS is checked."""
    import jax

    params = toy_model.init_params(jax.random.key(0))
    bad = dict(params)
    bad["w1"] = np.asarray(params["w1"]).astype(np.int32)
    path = str(tmp_path / "ckpt_bad")
    savedmodel.save_orbax(path, bad)
    with pytest.raises(ValueError, match="dtype classes"):
        savedmodel.load_orbax(path, toy_model)

    # Same-shape float16 AND bfloat16 restore fine (class matches; numpy
    # alone would call bf16 non-floating — jnp.issubdtype handles it)
    import jax.numpy as jnp

    for dt, tag in ((np.float16, "f16"), (jnp.bfloat16, "bf16")):
        ok = dict(params)
        ok["w1"] = np.asarray(params["w1"]).astype(dt)
        path2 = str(tmp_path / f"ckpt_{tag}")
        savedmodel.save_orbax(path2, ok)
        savedmodel.load_orbax(path2, toy_model)


def test_load_params_via_weights_config(tmp_path, toy_model):
    import jax

    params = toy_model.init_params(jax.random.key(0))
    path = str(tmp_path / "ckpt")
    savedmodel.save_orbax(path, params)

    cfg = ModelConfig(name="toy2", family="toy", dtype="float32", num_classes=10,
                      weights=path)
    m2 = build(cfg)
    loaded = m2.load_params()
    np.testing.assert_array_equal(np.asarray(loaded["w1"]), np.asarray(params["w1"]))


def test_saved_model_extraction(tmp_path):
    tf = pytest.importorskip("tensorflow")

    class M(tf.Module):
        def __init__(self):
            super().__init__()
            self.w = tf.Variable(np.arange(6, dtype=np.float32).reshape(2, 3), name="dense/kernel")
            self.b = tf.Variable(np.zeros(3, np.float32), name="dense/bias")

        @tf.function(input_signature=[tf.TensorSpec([None, 2], tf.float32)])
        def __call__(self, x):
            return x @ self.w + self.b

    path = str(tmp_path / "sm")
    tf.saved_model.save(M(), path)
    assert savedmodel.detect_format(path) == "saved_model"
    flat = savedmodel.extract_saved_model_variables(path)
    # keys are object-graph attribute paths ("w", "b")
    assert "w" in flat and "b" in flat, sorted(flat)
    np.testing.assert_array_equal(flat["w"], np.arange(6, dtype=np.float32).reshape(2, 3))


def test_graphdef_extraction(tmp_path):
    tf = pytest.importorskip("tensorflow")

    gd = tf.compat.v1.GraphDef()
    with tf.Graph().as_default() as g:
        tf.constant(np.ones((2, 2), np.float32), name="layer/const_w")
        gd = g.as_graph_def()
    path = str(tmp_path / "frozen.pb")
    with open(path, "wb") as f:
        f.write(gd.SerializeToString())
    assert savedmodel.detect_format(path) == "graphdef"
    flat = savedmodel.extract_graphdef_constants(path)
    np.testing.assert_array_equal(flat["layer/const_w"], np.ones((2, 2)))


def test_unknown_format(tmp_path):
    with pytest.raises(ValueError):
        savedmodel.detect_format(str(tmp_path / "nope.weights"))


def test_torch_formats_detected(tmp_path):
    for suffix in (".safetensors", ".ckpt", ".pt", ".pth", ".bin"):
        assert savedmodel.detect_format(str(tmp_path / f"w{suffix}")) == "torch"


def test_import_torch_variables_default_raises(toy_model):
    with pytest.raises(NotImplementedError, match="torch"):
        toy_model.import_torch_variables({"w": np.zeros(2)})


def test_import_tf_variables_default_raises(toy_model):
    with pytest.raises(NotImplementedError, match="orbax"):
        toy_model.import_tf_variables({"w": np.zeros(2)})
