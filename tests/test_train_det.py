"""EfficientDet fine-tune path (tpuserve.train_det): target assignment
correctness, loss decrease on the synthetic task, and the headline
guarantee — the produced orbax checkpoint serves the FULL detector
end-to-end via ModelConfig.weights (VERDICT r3 next 2's EfficientDet half)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuserve.config import ModelConfig
from tpuserve.models import build
from tpuserve.models.efficientdet import decode_boxes
from tpuserve.train_det import (
    DetTrainConfig,
    encode_boxes,
    finetune_detector,
    make_det_train_state,
    make_det_train_step,
    match_anchors,
    synthetic_det_batch,
)


def det_cfg(**over) -> ModelConfig:
    base = dict(
        name="det", family="efficientdet", batch_buckets=[1, 2],
        deadline_ms=2.0, dtype="float32", parallelism="single",
        request_timeout_ms=60_000.0, image_size=64, wire_size=64,
        options=dict(det_classes=5, fpn_channels=16, fpn_repeats=1,
                     head_repeats=1, max_level=5, pre_nms=32, max_dets=8,
                     backbone_width=0.25, backbone_depth=0.35,
                     score_thresh=0.005),
    )
    base.update(over)
    return ModelConfig(**base)


def test_encode_decode_roundtrip():
    """encode_boxes is the exact inverse of the serving decode."""
    rng = np.random.default_rng(0)
    anchors = np.stack([
        rng.uniform(10, 50, 32), rng.uniform(10, 50, 32),
        rng.uniform(8, 24, 32), rng.uniform(8, 24, 32)], axis=-1).astype(np.float32)
    boxes = np.stack([
        rng.uniform(0, 20, 32), rng.uniform(0, 20, 32),
        rng.uniform(30, 60, 32), rng.uniform(30, 60, 32)], axis=-1).astype(np.float32)
    reg = encode_boxes(jnp.asarray(boxes), jnp.asarray(anchors))
    back = decode_boxes(reg, jnp.asarray(anchors), image_size=64) * 64
    np.testing.assert_allclose(np.asarray(back), boxes, rtol=1e-4, atol=1e-3)


def test_match_anchors_assignment():
    # Anchor 0 sits exactly on the GT box; anchor 1 far away; anchor 2 half
    # overlaps (ignored band).
    anchors = jnp.asarray([
        [16.0, 16.0, 16.0, 16.0],   # exact match (IoU 1)
        [48.0, 48.0, 16.0, 16.0],   # IoU 0 -> background
        [24.0, 16.0, 16.0, 16.0],   # IoU 1/3 -> ignored band (0.3..0.6)
    ])
    boxes = jnp.asarray([[8.0, 8.0, 24.0, 24.0], [0, 0, 0, 0]])
    classes = jnp.asarray([3, 0], jnp.int32)
    valid = jnp.asarray([True, False])
    cls_t, cls_w, box_t, box_w = match_anchors(
        anchors, boxes, classes, valid, num_classes=5,
        pos_iou=0.6, neg_iou=0.3)
    assert box_w[0] == 1.0 and cls_t[0, 3] == 1.0       # positive, class 3
    assert box_w[1] == 0.0 and cls_w[1] == 1.0          # negative (bg)
    assert float(jnp.abs(box_t[0]).max()) < 1e-5        # exact match -> zero reg
    assert cls_w[2] == 0.0                              # ignored band
    # padded GT slot must not create positives anywhere
    assert float(cls_t[:, 0].sum()) == 0.0


def test_force_match_rescues_low_iou_gt():
    """A GT overlapping no anchor above pos_iou still claims its best one."""
    anchors = jnp.asarray([[16.0, 16.0, 32.0, 32.0], [48.0, 48.0, 32.0, 32.0]])
    boxes = jnp.asarray([[14.0, 14.0, 18.0, 18.0]])  # tiny box, IoU ~0.016
    cls_t, cls_w, _, box_w = match_anchors(
        anchors, boxes, jnp.asarray([2], jnp.int32), jnp.asarray([True]),
        num_classes=5, pos_iou=0.5, neg_iou=0.4)
    assert box_w[0] == 1.0 and cls_t[0, 2] == 1.0


def test_padded_gt_does_not_clobber_forced_match():
    """Padded GT slots argmax to anchor 0; a plain scatter would overwrite a
    real GT's force-match there (review regression). The real GT whose best
    anchor IS anchor 0 must keep its claim."""
    anchors = jnp.asarray([[16.0, 16.0, 32.0, 32.0], [48.0, 48.0, 32.0, 32.0]])
    boxes = jnp.asarray([[14.0, 14.0, 18.0, 18.0],   # best anchor 0, low IoU
                         [0.0, 0.0, 0.0, 0.0],        # padded
                         [0.0, 0.0, 0.0, 0.0]])       # padded
    classes = jnp.asarray([4, 0, 0], jnp.int32)
    valid = jnp.asarray([True, False, False])
    cls_t, _, _, box_w = match_anchors(
        anchors, boxes, classes, valid, num_classes=5,
        pos_iou=0.5, neg_iou=0.4)
    assert box_w[0] == 1.0 and cls_t[0, 4] == 1.0  # forced match survives
    assert box_w[1] == 0.0                          # padded slots claim nothing


@pytest.mark.slow
def test_finetune_loss_decreases_and_checkpoint_serves(tmp_path):
    from tpuserve.parallel import make_mesh

    cfg = det_cfg()
    serving = build(cfg)
    mesh = make_mesh()
    tcfg = DetTrainConfig(lr=3e-3, max_boxes=4)
    params, tx, opt_state = make_det_train_state(serving, mesh, tcfg)
    step, _ = make_det_train_step(serving, tx, mesh, tcfg)

    bs = int(mesh.shape["data"])  # batch shards over "data" (8 fake devices)
    losses = []
    for i in range(8):
        batch = synthetic_det_batch(bs, cfg.wire_size, cfg.image_size,
                                    serving.det_classes, tcfg.max_boxes, seed=i)
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses

    # The full-path entry: finetune_detector writes a checkpoint that the
    # serving stack restores as a complete detector (backbone + BiFPN +
    # heads — nothing seeded).
    out = str(tmp_path / "det_ckpt")
    finetune_detector(cfg, out, steps=2, batch_size=2, tcfg=tcfg, log_every=0)

    served = build(det_cfg(name="det2", weights=out))
    restored = served.load_params()
    want = jax.eval_shape(served.init_params, jax.random.key(0))
    assert (jax.tree_util.tree_structure(restored)
            == jax.tree_util.tree_structure(want))
    batch = synthetic_det_batch(2, cfg.wire_size, cfg.image_size,
                                serving.det_classes, 4, seed=99)
    outp = jax.jit(served.forward)(restored, jnp.asarray(batch["images"]))
    assert outp["boxes"].shape == (2, 8, 4)
    assert int(outp["n"][0]) >= 0


def _box_iou(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """IoU matrix between (N, 4) and (M, 4) corner boxes (y0, x0, y1, x1)."""
    area_a = np.maximum(a[:, 2] - a[:, 0], 0) * np.maximum(a[:, 3] - a[:, 1], 0)
    area_b = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(b[:, 3] - b[:, 1], 0)
    y0 = np.maximum(a[:, None, 0], b[None, :, 0])
    x0 = np.maximum(a[:, None, 1], b[None, :, 1])
    y1 = np.minimum(a[:, None, 2], b[None, :, 2])
    x1 = np.minimum(a[:, None, 3], b[None, :, 3])
    inter = np.maximum(y1 - y0, 0) * np.maximum(x1 - x0, 0)
    return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-9)


@pytest.mark.slow
def test_trained_detector_finds_boxes_recall():
    """The fine-tune must produce a detector that FINDS the synthetic
    rectangles, not just a loss that slopes down (VERDICT r4 weak 5): after
    training, recall@IoU>=0.5 on a HELD-OUT synthetic batch, measured
    through the full serving path (device resize -> heads -> decode_boxes ->
    NMS), must clear a threshold a background-collapsed detector cannot.
    BASELINE.md records the measured value ("Synthetic detection quality")."""
    from tpuserve.parallel import make_mesh

    cfg = det_cfg()
    serving = build(cfg)
    mesh = make_mesh()
    tcfg = DetTrainConfig(lr=3e-3, max_boxes=4)
    params, tx, opt_state = make_det_train_state(serving, mesh, tcfg)
    step, _ = make_det_train_step(serving, tx, mesh, tcfg)

    bs = int(mesh.shape["data"])
    for i in range(60):
        batch = synthetic_det_batch(bs, cfg.wire_size, cfg.image_size,
                                    serving.det_classes, tcfg.max_boxes,
                                    seed=i)
        params, opt_state, _ = step(params, opt_state, batch)

    # Held-out images (seeds never trained on), through the serving forward.
    fwd = jax.jit(serving.forward)
    total, found = 0, 0
    for seed in (1000, 1001):
        ev = synthetic_det_batch(bs, cfg.wire_size, cfg.image_size,
                                 serving.det_classes, tcfg.max_boxes,
                                 seed=seed)
        out = fwd(params, jnp.asarray(ev["images"]))
        boxes = np.asarray(out["boxes"]) * cfg.image_size  # [0,1] -> pixels
        n_det = np.asarray(out["n"])
        for b in range(bs):
            gt = ev["boxes"][b][ev["valid"][b]]
            if not len(gt):
                continue
            det = boxes[b, : int(n_det[b])]
            total += len(gt)
            if len(det):
                found += int((_box_iou(gt, det).max(axis=1) >= 0.5).sum())
    recall = found / total
    assert recall >= 0.6, f"recall@0.5 = {recall:.2f} ({found}/{total})"


@pytest.mark.slow
def test_finetune_det_cli(tmp_path):
    from tpuserve.cli import main

    out = str(tmp_path / "cli_ckpt")
    rc = main(["finetune-det", "--out", out, "--steps", "2", "--batch", "2",
               "--opt", "image_size=64", "--opt", "wire_size=64",
               "--opt", "det_classes=5", "--opt", "fpn_channels=16",
               "--opt", "fpn_repeats=1", "--opt", "head_repeats=1",
               "--opt", "max_level=5", "--opt", "pre_nms=32",
               "--opt", "max_dets=8", "--opt", "backbone_width=0.25",
               "--opt", "backbone_depth=0.35"])
    assert rc == 0
    import os

    assert os.path.isdir(out)
