"""Ulysses attention (tpuserve.ops.ulysses) on the 8-fake-device mesh.

Same correctness bar as ring attention (tests/test_ring.py): the all-to-all
head-resharded result must match dense single-device attention, with and
without key padding, under combined dp+sp sharding, and must reject head
counts the seq axis can't deal out.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuserve.ops import dense_attention, ulysses_attention
from tpuserve.parallel import make_mesh
from tpuserve.parallel.mesh import MeshPlan

pytestmark = pytest.mark.slow


def _qkv(rng, b=2, s=16, h=4, d=8):
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, h, d)).astype(np.float32)
    v = rng.normal(size=(b, s, h, d)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.fixture
def mesh():
    # 8 devices -> dp=2, tp=2, sp=2: all axes live, like the ring tests.
    return make_mesh(MeshPlan(tp=2, sp=2))


def test_matches_dense(mesh, rng):
    q, k, v = _qkv(rng)
    out = ulysses_attention(q, k, v, mesh)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_matches_dense_with_key_padding(mesh, rng):
    q, k, v = _qkv(rng)
    pad = np.zeros((2, 16), np.float32)
    pad[:, 12:] = -1e9
    out = ulysses_attention(q, k, v, mesh, key_padding=jnp.asarray(pad))
    ref = dense_attention(q, k, v, bias=jnp.asarray(pad)[:, None, None, :])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_dp_plus_sp_spec(mesh, rng):
    q, k, v = _qkv(rng)
    spec = P("data", "seq", None, None)
    sh = NamedSharding(mesh, spec)
    q, k, v = jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh)
    out = jax.jit(lambda a, b, c: ulysses_attention(a, b, c, mesh, spec=spec))(q, k, v)
    ref = dense_attention(*_qkv(np.random.default_rng(0)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_tp_heads_through_ulysses(mesh, rng):
    """Heads sharded on "model" AND dealt over "seq": both divisions hold."""
    q, k, v = _qkv(rng, h=8)  # 8 heads / tp=2 = 4 local, / sp=2 = 2 per deal
    spec = P("data", "seq", "model", None)
    sh = NamedSharding(mesh, spec)
    q, k, v = jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh)
    out = jax.jit(lambda a, b, c: ulysses_attention(a, b, c, mesh, spec=spec))(q, k, v)
    ref = dense_attention(*_qkv(np.random.default_rng(0), h=8))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_bf16_dtype_preserved(mesh, rng):
    """Contract shared with ring_attention: out.dtype == q.dtype."""
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(rng))
    out = ulysses_attention(q, k, v, mesh)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out).astype(np.float32),
                               np.asarray(ref), atol=2e-2)


def test_output_stays_seq_sharded(mesh, rng):
    q, k, v = _qkv(rng)
    spec = P(None, "seq", None, None)
    sh = NamedSharding(mesh, spec)
    q, k, v = jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh)
    out = jax.jit(lambda a, b, c: ulysses_attention(a, b, c, mesh, spec=spec),
                  out_shardings=sh)(q, k, v)
    assert out.sharding.spec == spec


def test_indivisible_heads_rejected(mesh, rng):
    q, k, v = _qkv(rng, h=3)  # 3 heads over sp=2: cannot deal
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, mesh)


def test_bad_spec_rejected(mesh, rng):
    q, k, v = _qkv(rng)
    with pytest.raises(ValueError, match="seq dim"):
        ulysses_attention(q, k, v, mesh, spec=P("seq", None, None, None))


def test_long_context_serving_2048_ulysses():
    """Symmetry with the ring test: a (batch, 2048) bucket served with
    Ulysses head all-to-all over sp=4 through the production runtime."""
    from tpuserve.config import ModelConfig
    from tpuserve.models import build
    from tpuserve.runtime import build_runtime

    sp_mesh = make_mesh(MeshPlan(sp=4), devices=jax.devices()[:8])
    cfg = ModelConfig(
        name="bert-long-u", family="bert", parallelism="sharded", sp=4,
        batch_buckets=[2], seq_buckets=[2048], dtype="float32", num_classes=4,
        options={"layers": 1, "d_model": 32, "heads": 4, "d_ff": 64,
                 "vocab_size": 512, "attention": "ulysses"},
    )
    model = build(cfg)
    rt = build_runtime(model, mesh=sp_mesh)
    (bucket,) = rt.executables
    item = model.host_decode(b'{"text": "' + b"ulysses context " * 80 + b'"}',
                             "application/json")
    out = rt.fetch(rt.run(bucket, model.assemble([item, item], bucket)))
    assert out["probs"].shape == (2, model.top_k)
    assert np.isfinite(out["probs"]).all()
