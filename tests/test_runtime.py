"""Runtime AOT compilation and execution (C5) on fake CPU devices."""

import jax
import numpy as np
import pytest

from tpuserve.config import ModelConfig
from tpuserve.models import build
from tpuserve.runtime import build_runtime


@pytest.fixture(scope="module")
def toy_runtime():
    cfg = ModelConfig(name="toy", family="toy", batch_buckets=[1, 2, 4],
                      dtype="float32", num_classes=10, parallelism="single")
    model = build(cfg)
    return model, build_runtime(model)


def test_compiles_all_buckets(toy_runtime):
    _, rt = toy_runtime
    assert sorted(rt.executables) == [(1,), (2,), (4,)]


def test_sharded_buckets_mesh_aligned():
    """Sharded mode rounds buckets up to data-axis multiples (8 fake devs)."""
    cfg = ModelConfig(name="toys", family="toy", batch_buckets=[1, 2, 4, 16],
                      dtype="float32", num_classes=10, parallelism="sharded")
    rt = build_runtime(build(cfg))
    assert sorted(rt.executables) == [(8,), (16,)]


def test_run_and_fetch(toy_runtime):
    model, rt = toy_runtime
    batch = np.random.default_rng(0).integers(0, 255, size=(4, 8, 8, 3), dtype=np.uint8)
    out = rt.fetch(rt.run((4,), batch))
    assert out["probs"].shape == (4, 3)
    assert out["indices"].shape == (4, 3)
    np.testing.assert_allclose(out["probs"].sum(axis=-1) <= 1.0, True)


def test_deterministic(toy_runtime):
    model, rt = toy_runtime
    batch = np.full((2, 8, 8, 3), 17, dtype=np.uint8)
    a = rt.fetch(rt.run((2,), batch))
    b = rt.fetch(rt.run((2,), batch))
    np.testing.assert_array_equal(a["indices"], b["indices"])
    np.testing.assert_allclose(a["probs"], b["probs"], rtol=1e-6)


def test_sharded_batch_across_mesh():
    """Batch dim sharded over the data axis of the 8-device mesh runs + matches."""
    cfg = ModelConfig(name="toy8", family="toy", batch_buckets=[8],
                      dtype="float32", num_classes=10, parallelism="sharded")
    model = build(cfg)
    rt8 = build_runtime(model)
    assert rt8.meshes[0].shape["data"] == 8
    batch = np.random.default_rng(2).integers(0, 255, (8, 8, 8, 3), dtype=np.uint8)
    out = rt8.fetch(rt8.run((8,), batch.copy()))
    assert out["probs"].shape == (8, 3)

    # sharded result == single-device result on identical params/batch
    cfg1 = ModelConfig(name="toy1", family="toy", batch_buckets=[8],
                       dtype="float32", num_classes=10, parallelism="single")
    rt1 = build_runtime(build(cfg1))
    out1 = rt1.fetch(rt1.run((8,), batch.copy()))
    np.testing.assert_allclose(out["probs"], out1["probs"], rtol=1e-5)
    np.testing.assert_array_equal(out["indices"], out1["indices"])


def test_replica_mode():
    cfg = ModelConfig(name="toyr", family="toy", batch_buckets=[1],
                      dtype="float32", num_classes=10, parallelism="replica")
    rt = build_runtime(build(cfg))
    assert len(rt.meshes) == len(jax.devices())
    batch = np.zeros((1, 8, 8, 3), dtype=np.uint8)
    outs = [rt.fetch(rt.run((1,), batch)) for _ in range(3)]
    for o in outs[1:]:
        np.testing.assert_allclose(o["probs"], outs[0]["probs"], rtol=1e-6)


def test_padding_lanes_do_not_affect_real_lanes(toy_runtime):
    """Core static-shape invariant (SURVEY.md §4-1)."""
    model, rt = toy_runtime
    item = np.random.default_rng(1).integers(0, 255, size=(8, 8, 3), dtype=np.uint8)
    solo = model.assemble([item], (1,))
    padded = model.assemble([item], (4,))
    out1 = rt.fetch(rt.run((1,), solo))
    out4 = rt.fetch(rt.run((4,), padded))
    np.testing.assert_allclose(out1["probs"][0], out4["probs"][0], rtol=1e-5)
    np.testing.assert_array_equal(out1["indices"][0], out4["indices"][0])


def test_hot_reload_swaps_weights_without_recompile(tmp_path):
    """Write ckpt A, serve, overwrite with ckpt B at the same path, reload:
    outputs change, no recompilation (executable objects identical)."""
    from tpuserve.savedmodel import save_orbax

    ckpt = str(tmp_path / "ckpt")
    cfg = ModelConfig(name="toy", family="toy", batch_buckets=[2],
                      dtype="float32", num_classes=10, parallelism="single",
                      weights=ckpt)
    model = build(cfg)
    params_a = model.init_params(jax.random.key(1))
    save_orbax(ckpt, params_a)
    rt = build_runtime(model)
    exe_before = rt.executables[(2,)][0].compiled

    batch = np.full((2, 8, 8, 3), 50, dtype=np.uint8)
    out_a = rt.fetch(rt.run((2,), batch))

    params_b = jax.tree_util.tree_map(lambda x: x + 0.5, params_a)
    import shutil

    shutil.rmtree(ckpt)
    save_orbax(ckpt, params_b)
    info = rt.reload_params()
    assert info["reload_ms"] > 0

    out_b = rt.fetch(rt.run((2,), batch))
    assert rt.executables[(2,)][0].compiled is exe_before  # no recompile
    assert not np.allclose(out_a["probs"], out_b["probs"])


def test_hot_reload_rejects_mismatched_tree(toy_runtime):
    model, rt = toy_runtime
    before = rt.params_per_mesh
    orig = model.load_params
    model.load_params = lambda: {"w1": np.zeros((4, 4), np.float32)}
    try:
        with pytest.raises(ValueError, match="old params kept"):
            rt.reload_params()
    finally:
        model.load_params = orig
    assert rt.params_per_mesh is before  # still serving the old weights
    batch = np.full((2, 8, 8, 3), 9, dtype=np.uint8)
    assert rt.fetch(rt.run((2,), batch))["probs"].shape == (2, 3)
