"""Pallas fused blockwise attention (SURVEY.md §7 M8): parity with the dense
reference in interpret mode on CPU, padding-bias semantics, block clamping,
and the BERT "attention=flash" option end-to-end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuserve.ops.flash_attention import flash_attention
from tpuserve.ops.ring_attention import dense_attention


def rand_qkv(rng, b=2, s=256, h=4, d=64):
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.normal(size=(b, s, h, d)).astype(np.float32))
    return mk(), mk(), mk()


def test_matches_dense_reference(rng):
    q, k, v = rand_qkv(rng)
    out = np.asarray(flash_attention(q, k, v))
    ref = np.asarray(dense_attention(q, k, v))
    np.testing.assert_allclose(out, ref, atol=2e-6)


def test_padding_bias_matches_and_masks(rng):
    q, k, v = rand_qkv(rng)
    mask = np.ones((2, 256), np.float32)
    mask[:, 200:] = 0.0
    bias = jnp.asarray((1.0 - mask) * -1e9)
    out = np.asarray(flash_attention(q, k, v, bias))
    ref = np.asarray(dense_attention(q, k, v, bias[:, None, None, :]))
    np.testing.assert_allclose(out, ref, atol=2e-6)
    # Masked keys must not influence the output at all: perturbing them
    # changes nothing.
    k2 = k.at[:, 200:].set(0.0)
    v2 = v.at[:, 200:].set(0.0)
    out2 = np.asarray(flash_attention(q, k2, v2, bias))
    np.testing.assert_allclose(out, out2, atol=2e-6)


def test_block_clamp_small_sequences(rng):
    """Seq 64 < default block 128: blocks clamp instead of erroring."""
    q, k, v = rand_qkv(rng, s=64)
    out = np.asarray(flash_attention(q, k, v))
    ref = np.asarray(dense_attention(q, k, v))
    np.testing.assert_allclose(out, ref, atol=2e-6)


def test_non_power_of_two_seq_clamps_to_divisor(rng):
    """192 isn't a multiple of 128: blocks clamp to gcd (64) and still match."""
    q, k, v = rand_qkv(rng, s=192)
    out = np.asarray(flash_attention(q, k, v))
    ref = np.asarray(dense_attention(q, k, v))
    np.testing.assert_allclose(out, ref, atol=2e-6)


def test_unalignable_seq_rejected(rng):
    q, k, v = rand_qkv(rng, s=96)  # gcd(64, 96) = 32 ok; gcd(36, 96) = 12 bad
    with pytest.raises(ValueError, match="TPU lowering rejects"):
        flash_attention(q, k, v, block_q=36)


def test_bf16_inputs(rng):
    q, k, v = (x.astype(jnp.bfloat16) for x in rand_qkv(rng, s=128))
    raw = flash_attention(q, k, v)
    assert raw.dtype == jnp.bfloat16  # out_shape follows q.dtype
    ref = np.asarray(dense_attention(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32)))
    np.testing.assert_allclose(np.asarray(raw).astype(np.float32), ref, atol=2e-2)


def test_stats_variant_merges_across_key_blocks(rng):
    """return_stats=True exposes the unnormalized accumulator + online-
    softmax (m, l) so two key-block results merge to the full answer — the
    contract ring attention's per-device step relies on."""
    q, k, v = rand_qkv(rng, s=128)
    a_1, m1, l1 = flash_attention(q, k[:, :64], v[:, :64], return_stats=True)
    a_2, m2, l2 = flash_attention(q, k[:, 64:], v[:, 64:], return_stats=True)
    a_1, m1, l1, a_2, m2, l2 = (np.asarray(x) for x in (a_1, m1, l1, a_2, m2, l2))
    m12 = np.maximum(m1, m2)
    w1, w2 = np.exp(m1 - m12), np.exp(m2 - m12)
    l12 = l1 * w1 + l2 * w2
    merged = (a_1 * w1[..., None] + a_2 * w2[..., None]) / l12[..., None]
    ref = np.asarray(dense_attention(q, k, v))
    np.testing.assert_allclose(merged, ref, atol=2e-6)


def test_ring_flash_fully_masked_block_stays_finite(rng):
    """A device block whose keys are ALL masked (-inf per-key bias over a
    whole shard) must contribute zero, not NaN (review regression: the
    normalized kernel output was 0/0 there)."""
    from tpuserve.ops.ring_attention import ring_attention
    from tpuserve.parallel import make_mesh
    from tpuserve.parallel.mesh import MeshPlan

    mesh = make_mesh(MeshPlan(sp=4))
    q, k, v = rand_qkv(rng, b=2, s=256, h=4, d=64)
    mask = np.ones((2, 256), np.float32)
    mask[:, 192:] = 0.0  # the 4th device's whole 64-key block
    bias = jnp.asarray(np.where(mask > 0, 0.0, -np.inf).astype(np.float32))
    out_f = np.asarray(ring_attention(q, k, v, mesh, key_padding=bias,
                                      local_impl="flash"))
    ref = np.asarray(dense_attention(q, k, v, bias[:, None, None, :]))
    assert np.isfinite(out_f[:, :192]).all()
    np.testing.assert_allclose(out_f[:, :192], ref[:, :192], atol=2e-5)


def test_flash_attention_is_differentiable(rng):
    """jax.grad through the kernel works (dense-recompute VJP): the training
    path reaches ring/ulysses with auto-selected flash locals (review
    regression: the raw pallas_call had no autodiff rule)."""
    from tpuserve.ops.ring_attention import ring_attention
    from tpuserve.parallel import make_mesh
    from tpuserve.parallel.mesh import MeshPlan

    q, k, v = rand_qkv(rng, b=1, s=64, h=2, d=64)

    g = jax.grad(lambda q_: flash_attention(q_, k, v).sum())(q)
    g_ref = jax.grad(lambda q_: dense_attention(q_, k, v).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=2e-5)

    # And through the ring with flash locals (the train.py path shape).
    mesh = make_mesh(MeshPlan(sp=4))
    q2, k2, v2 = rand_qkv(rng, b=2, s=256, h=4, d=64)
    gr = jax.grad(lambda q_: ring_attention(
        q_, k2, v2, mesh, local_impl="flash").astype(jnp.float32).sum())(q2)
    gr_ref = jax.grad(lambda q_: dense_attention(
        q_, k2, v2).astype(jnp.float32).sum())(q2)
    np.testing.assert_allclose(np.asarray(gr), np.asarray(gr_ref), atol=2e-4)


def test_ring_local_flash_matches_dense_local(rng):
    """ring_attention's per-device inner step through the Pallas kernel
    (local_impl='flash') == the dense-einsum inner step == full dense."""
    from tpuserve.ops.ring_attention import ring_attention
    from tpuserve.parallel import make_mesh
    from tpuserve.parallel.mesh import MeshPlan

    mesh = make_mesh(MeshPlan(sp=4))
    q, k, v = rand_qkv(rng, b=2, s=256, h=4, d=64)
    mask = np.ones((2, 256), np.float32)
    mask[:, 230:] = 0.0
    bias = jnp.asarray((1.0 - mask) * -1e9)
    out_f = np.asarray(ring_attention(q, k, v, mesh, key_padding=bias,
                                      local_impl="flash"))
    out_d = np.asarray(ring_attention(q, k, v, mesh, key_padding=bias,
                                      local_impl="dense"))
    ref = np.asarray(dense_attention(q, k, v, bias[:, None, None, :]))
    np.testing.assert_allclose(out_f, out_d, atol=2e-5)
    np.testing.assert_allclose(out_f, ref, atol=2e-5)
    # auto at this (tiny) shape picks DENSE — the memory-derived threshold
    # (see test_auto_local_impl_decision) is unreachable on CPU shapes, so
    # this line only proves auto composes; the flash branch of the decision
    # is unit-tested directly below.
    out_a = np.asarray(ring_attention(q, k, v, mesh, key_padding=bias))
    np.testing.assert_allclose(out_a, ref, atol=2e-5)


def test_auto_local_impl_decision():
    """The memory-derived dense/flash choice, unit-tested with hypothetical
    shapes a CPU test cannot materialize (BASELINE.md 'Flash vs dense':
    dense is faster whenever it fits; flash exists for when it doesn't)."""
    from tpuserve.ops.ring_attention import DENSE_SCORE_BYTES_MAX, auto_local_impl

    # Serving shapes (measured table): dense everywhere.
    assert auto_local_impl(32, 12, 128, 64) == "dense"
    assert auto_local_impl(4, 12, 2048, 64) == "dense"
    # 32k local seq, 12 heads: 2*4*1*12*32768^2 ~ 103 GB of dense scores
    # -> only the O(S) kernel can run it.
    assert auto_local_impl(1, 12, 32768, 64) == "flash"
    # Just over the threshold flips exactly at the documented constant.
    s = 16384
    b_over = DENSE_SCORE_BYTES_MAX // (2 * 4 * 1 * s * s) + 1
    assert auto_local_impl(b_over, 1, s, 64) == "flash"
    assert auto_local_impl(max(b_over - 1, 1), 1, s, 64) == "dense"
    # Kernel-hostile shapes never pick flash, regardless of size.
    assert auto_local_impl(64, 32, 32768, 40) == "dense"   # head_dim
    assert auto_local_impl(64, 32, 32771, 64) == "dense"   # row alignment


def test_ulysses_local_flash_matches_dense_local(rng):
    from tpuserve.ops.ulysses import ulysses_attention
    from tpuserve.parallel import make_mesh
    from tpuserve.parallel.mesh import MeshPlan

    mesh = make_mesh(MeshPlan(sp=4))
    q, k, v = rand_qkv(rng, b=2, s=256, h=4, d=64)
    out_f = np.asarray(ulysses_attention(q, k, v, mesh, local_impl="flash"))
    ref = np.asarray(dense_attention(q, k, v))
    np.testing.assert_allclose(out_f, ref, atol=2e-5)


@pytest.mark.slow
def test_bert_sharded_flash_serving_matches_dense():
    """attention='flash' + parallelism='sharded' on the 8-fake-device mesh:
    the kernel runs per device under shard_map (the r3 build-time rejection,
    now supported); logits match dense and the AOT-compiled path serves."""
    import json

    from tpuserve.config import ModelConfig
    from tpuserve.models import build
    from tpuserve.runtime import build_runtime

    def cfg(attn, par="single"):
        return ModelConfig(
            name="b", family="bert", dtype="float32", num_classes=4,
            batch_buckets=[8], seq_buckets=[64], parallelism=par,
            request_timeout_ms=30_000.0,
            options={"layers": 2, "d_model": 64, "heads": 2, "d_ff": 128,
                     "vocab_size": 512, "attention": attn})

    flash = build(cfg("flash", par="sharded"))
    rt = build_runtime(flash)  # binds the mesh + AOT-compiles the shard_map
    dense = build(cfg("dense"))
    params = dense.init_params(jax.random.key(0))
    items = [dense.host_decode(
        json.dumps({"text": f"sharded flash {i}"}).encode(),
        "application/json") for i in range(5)]  # 5 of 8 lanes real
    batch = dense.assemble(items, (8, 64))
    o_f = np.asarray(jax.jit(flash.forward)(params, batch)["probs"])
    o_d = np.asarray(jax.jit(dense.forward)(params, batch)["probs"])
    np.testing.assert_allclose(o_f, o_d, atol=1e-5)
    assert np.asarray(rt.run((8, 64), batch)["probs"]).shape == (8, 4)


@pytest.mark.slow
def test_bert_flash_option_matches_dense():
    """cfg.options['attention']='flash' serves identical logits (same params)."""
    from tpuserve.config import ModelConfig
    from tpuserve.models import build

    def cfg(attn):
        return ModelConfig(
            name="b", family="bert", dtype="float32", num_classes=4,
            batch_buckets=[2], seq_buckets=[64],
            options={"layers": 2, "d_model": 64, "heads": 2, "d_ff": 128,
                     "vocab_size": 512, "attention": attn})

    dense = build(cfg("dense"))
    flash = build(cfg("flash"))
    params = dense.init_params(jax.random.key(0))
    item = dense.host_decode(b'{"text": "flash attention parity"}',
                             "application/json")
    batch = dense.assemble([item, item], (2, 64))
    o_d = np.asarray(jax.jit(dense.forward)(params, batch)["probs"])
    o_f = np.asarray(jax.jit(flash.forward)(params, batch)["probs"])
    np.testing.assert_allclose(o_f, o_d, atol=1e-5)


def test_bert_rejects_unknown_attention_option():
    from tpuserve.config import ModelConfig
    from tpuserve.models import build

    with pytest.raises(ValueError, match="dense.*flash"):
        build(ModelConfig(name="b", family="bert",
                          options={"attention": "Flash"}))


def test_check_vma_false_still_required_canary():
    """ring_attention (and bert's flash-under-shard_map) pass
    check_vma=False because the Pallas interpreter cannot propagate vma
    through its internal block slicing (upstream jax workaround). This
    canary re-tries the composition WITH check_vma=True on every run: the
    day a jax upgrade makes it pass, this test fails loudly — the signal to
    delete the check_vma=False escapes in tpuserve/ops/ring_attention.py
    and tpuserve/models/bert.py and regain the stronger collective
    checking (VERDICT r4 weak 7 asked for exactly this tripwire)."""
    try:
        from jax import shard_map
    except ImportError:
        pytest.skip("this jax predates vma tracking (check_rep era); the "
                    "escapes route through tpuserve.utils.compat instead")
    from jax.sharding import PartitionSpec as P

    from tpuserve.parallel import make_mesh
    from tpuserve.parallel.mesh import MeshPlan

    if len(jax.devices()) < 4:
        pytest.skip("needs the fake multi-device mesh")
    mesh = make_mesh(MeshPlan(sp=1))
    rng = np.random.default_rng(11)
    q, k, v = rand_qkv(rng, b=len(jax.devices()), s=128, h=2, d=64)
    spec = P("data", None, None, None)
    try:
        f = shard_map(
            lambda q_, k_, v_: flash_attention(q_, k_, v_),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=True)
        np.asarray(f(q, k, v))
    except ValueError as e:
        # Only the KNOWN failure keeps the escapes justified; any other
        # error (e.g. a shard_map API change raising TypeError) must fail
        # this test rather than silently reading as "still required".
        assert "check_vma" in str(e) or "varying" in str(e), (
            f"unexpected failure shape from the vma canary: {e}")
        return
    pytest.fail(
        "shard_map(flash_attention, check_vma=True) now WORKS on this jax: "
        "remove the check_vma=False escapes in tpuserve/ops/"
        "ring_attention.py, tpuserve/ops/ulysses.py, and tpuserve/models/"
        "bert.py, then update this canary")
