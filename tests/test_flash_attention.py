"""Pallas fused blockwise attention (SURVEY.md §7 M8): parity with the dense
reference in interpret mode on CPU, padding-bias semantics, block clamping,
and the BERT "attention=flash" option end-to-end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuserve.ops.flash_attention import flash_attention
from tpuserve.ops.ring_attention import dense_attention


def rand_qkv(rng, b=2, s=256, h=4, d=64):
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.normal(size=(b, s, h, d)).astype(np.float32))
    return mk(), mk(), mk()


def test_matches_dense_reference(rng):
    q, k, v = rand_qkv(rng)
    out = np.asarray(flash_attention(q, k, v))
    ref = np.asarray(dense_attention(q, k, v))
    np.testing.assert_allclose(out, ref, atol=2e-6)


def test_padding_bias_matches_and_masks(rng):
    q, k, v = rand_qkv(rng)
    mask = np.ones((2, 256), np.float32)
    mask[:, 200:] = 0.0
    bias = jnp.asarray((1.0 - mask) * -1e9)
    out = np.asarray(flash_attention(q, k, v, bias))
    ref = np.asarray(dense_attention(q, k, v, bias[:, None, None, :]))
    np.testing.assert_allclose(out, ref, atol=2e-6)
    # Masked keys must not influence the output at all: perturbing them
    # changes nothing.
    k2 = k.at[:, 200:].set(0.0)
    v2 = v.at[:, 200:].set(0.0)
    out2 = np.asarray(flash_attention(q, k2, v2, bias))
    np.testing.assert_allclose(out, out2, atol=2e-6)


def test_block_clamp_small_sequences(rng):
    """Seq 64 < default block 128: blocks clamp instead of erroring."""
    q, k, v = rand_qkv(rng, s=64)
    out = np.asarray(flash_attention(q, k, v))
    ref = np.asarray(dense_attention(q, k, v))
    np.testing.assert_allclose(out, ref, atol=2e-6)


def test_non_power_of_two_seq_clamps_to_divisor(rng):
    """192 isn't a multiple of 128: blocks clamp to gcd (64) and still match."""
    q, k, v = rand_qkv(rng, s=192)
    out = np.asarray(flash_attention(q, k, v))
    ref = np.asarray(dense_attention(q, k, v))
    np.testing.assert_allclose(out, ref, atol=2e-6)


def test_unalignable_seq_rejected(rng):
    q, k, v = rand_qkv(rng, s=96)  # gcd(64, 96) = 32 ok; gcd(36, 96) = 12 bad
    with pytest.raises(ValueError, match="TPU lowering rejects"):
        flash_attention(q, k, v, block_q=36)


def test_bf16_inputs(rng):
    q, k, v = (x.astype(jnp.bfloat16) for x in rand_qkv(rng, s=128))
    raw = flash_attention(q, k, v)
    assert raw.dtype == jnp.bfloat16  # out_shape follows q.dtype
    ref = np.asarray(dense_attention(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32)))
    np.testing.assert_allclose(np.asarray(raw).astype(np.float32), ref, atol=2e-2)


@pytest.mark.slow
def test_bert_flash_option_matches_dense():
    """cfg.options['attention']='flash' serves identical logits (same params)."""
    from tpuserve.config import ModelConfig
    from tpuserve.models import build

    def cfg(attn):
        return ModelConfig(
            name="b", family="bert", dtype="float32", num_classes=4,
            batch_buckets=[2], seq_buckets=[64],
            options={"layers": 2, "d_model": 64, "heads": 2, "d_ff": 128,
                     "vocab_size": 512, "attention": attn})

    dense = build(cfg("dense"))
    flash = build(cfg("flash"))
    params = dense.init_params(jax.random.key(0))
    item = dense.host_decode(b'{"text": "flash attention parity"}',
                             "application/json")
    batch = dense.assemble([item, item], (2, 64))
    o_d = np.asarray(jax.jit(dense.forward)(params, batch)["probs"])
    o_f = np.asarray(jax.jit(flash.forward)(params, batch)["probs"])
    np.testing.assert_allclose(o_f, o_d, atol=1e-5)


def test_bert_rejects_unknown_attention_option():
    from tpuserve.config import ModelConfig
    from tpuserve.models import build

    with pytest.raises(ValueError, match="dense.*flash"):
        build(ModelConfig(name="b", family="bert",
                          options={"attention": "Flash"}))
