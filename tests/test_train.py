"""Sharded train step (dp/tp/sp) on the 8-fake-device mesh; graft entries."""

import jax
import numpy as np

from tpuserve.parallel import make_mesh
import pytest

from tpuserve.train import (
    TrainConfig,
    dryrun,
    make_train_state,
    make_train_step,
    mesh_plan_for,
    restore_train_state,
    save_train_state,
    synthetic_batch,
)

pytestmark = pytest.mark.slow


def test_mesh_plan_factors():
    assert mesh_plan_for(8).resolve(8) == (2, 2, 2)
    assert mesh_plan_for(2).resolve(2) == (1, 2, 1)
    assert mesh_plan_for(1).resolve(1) == (1, 1, 1)


def test_dryrun_8dev():
    loss = dryrun(jax.devices(), steps=1)
    assert np.isfinite(loss)


def test_loss_decreases():
    mesh = make_mesh(mesh_plan_for(len(jax.devices())))
    cfg = TrainConfig(n_layers=1, d_model=32, d_ff=64, vocab=64, max_seq=16)
    model, params, tx, opt_state, shardings = make_train_state(mesh, cfg)
    step, _ = make_train_step(model, tx, mesh, shardings)
    batch = synthetic_batch(cfg, 8, seed=0)
    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, dict(batch))
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_train_step_with_ulysses_attention():
    """Full sharded train step with seq_attention="ulysses" converges too."""
    mesh = make_mesh(mesh_plan_for(8))
    cfg = TrainConfig(n_layers=1, d_model=32, d_ff=64, vocab=64, max_seq=16,
                      seq_attention="ulysses")
    model, params, tx, opt_state, shardings = make_train_state(mesh, cfg)
    step, _ = make_train_step(model, tx, mesh, shardings)
    batch = synthetic_batch(cfg, 8, seed=0)
    losses = []
    for _ in range(6):
        params, opt_state, loss = step(params, opt_state, dict(batch))
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_tp_params_actually_sharded():
    mesh = make_mesh(mesh_plan_for(8))
    cfg = TrainConfig()
    _, params, _, _, _ = make_train_state(mesh, cfg)
    from jax.sharding import PartitionSpec as P

    assert params["block0"]["up"]["kernel"].sharding.spec == P(None, "model")


def test_checkpoint_resume_is_bitwise_continuation(tmp_path):
    """Save at step 2, restore into the sharded mesh, and the next step must
    equal the uninterrupted run: params, opt state, and loss (SURVEY.md §5
    checkpoint/resume, training side)."""
    mesh = make_mesh(mesh_plan_for(8))
    cfg = TrainConfig(n_layers=1, d_model=32, d_ff=64, vocab=64, max_seq=16)
    model, params, tx, opt_state, shardings = make_train_state(mesh, cfg)
    step, _ = make_train_step(model, tx, mesh, shardings)
    for i in range(2):
        params, opt_state, _ = step(params, opt_state, synthetic_batch(cfg, 8, seed=i))

    path = str(tmp_path / "ckpt")
    save_train_state(path, params, opt_state, step=1)  # periodic-loop shape:
    save_train_state(path, params, opt_state, step=2)  # overwrite must work
    loss_cont = step(params, opt_state, synthetic_batch(cfg, 8, seed=2))[2]

    model_r, params_r, tx_r, opt_r, shardings_r, at = restore_train_state(
        path, mesh, cfg)
    assert at == 2
    # Restored leaves land with their original shardings (no host gather) —
    # including the optimizer moments, which mirror the param tree.
    from jax.sharding import PartitionSpec as P

    assert params_r["block0"]["up"]["kernel"].sharding.spec == P(None, "model")
    assert opt_r[0].mu["block0"]["up"]["kernel"].sharding.spec == P(None, "model")
    step_r, _ = make_train_step(model_r, tx_r, mesh, shardings_r)
    loss_resumed = step_r(params_r, opt_r, synthetic_batch(cfg, 8, seed=2))[2]
    np.testing.assert_array_equal(np.asarray(loss_cont), np.asarray(loss_resumed))


def test_graft_entry_single_chip():
    import __graft_entry__ as g

    fn, (params, batch) = g.entry()
    out = jax.jit(fn)(params, batch)
    assert out["indices"].shape == (8, 5)


def test_graft_dryrun_multichip():
    import __graft_entry__ as g

    g.dryrun_multichip(8)
