"""TPS101 must descend into async generators: a blocking call inside an
``async def`` generator body (or reached through its ``async for``
consumer) stalls the event loop exactly like one in a plain coroutine.
Positive cases are ``bad_*``; ``good_*`` must stay clean."""

import asyncio
import time


class Streamer:
    async def bad_gen(self):
        for i in range(3):
            time.sleep(0.1)  # blocks the loop mid-stream
            yield i

    async def bad_consumer(self):
        out = []
        async for item in self.bad_gen():  # reaches the blocking body
            out.append(item)
        return out

    async def good_gen(self):
        for i in range(3):
            await asyncio.sleep(0.1)
            yield i

    async def good_consumer(self):
        out = []
        async for item in self.good_gen():
            out.append(item)
        return out

    async def good_done_guarded(self, task):
        # .result() under an explicit done() guard cannot block.
        if task.done():
            return task.result()
        return await task
