"""TPS5xx positive/negative cases: trace-discipline hazards that
reintroduce retrace churn or forced host transfers. Positive cases are
``bad_*``; ``good_*`` must stay clean (the whole-tree gate depends on the
rules not crying wolf on the repo's own idioms)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from tpuserve.models.base import GenerativeModel


# -- TPS502 / TPS503: host forcing + Python control flow in traced bodies --

@jax.jit
def bad_host_forcing(x):
    v = float(jnp.sum(x))  # host-forcing float() on a traced value
    s = x.mean()
    s = s.item()  # host-forcing .item() (taint flows through .mean())
    print("tracing")  # fires at trace time only
    return np.log(x) + v + s  # np.* on a traced value


@jax.jit
def bad_traced_branch(x):
    if jnp.sum(x) > 0:  # Python `if` on a traced value
        x = -x
    acc = x * 2
    while jnp.any(acc > 0):  # Python `while` on a traced value
        acc = acc - 1
    return acc


@jax.jit
def good_static_reads(x, n: int):
    if n > 3:  # int-annotated param: host-static by declaration
        x = x * 2
    if x.shape[0] > 1:  # shape is static trace-time metadata
        x = x[:1]
    if x is None:  # structural check, static under trace
        return jnp.zeros(())
    if len(x) > 2:  # len() of a tracer is its static leading dim
        x = x[:2]
    return jnp.sum(x)


@functools.partial(jax.jit, static_argnames=("mode",))
def good_kwonly_static(x, *, mode="fast"):
    if mode == "fast":  # kwonly args are the repo's static convention
        return x * 2
    return x


@jax.jit
def good_sanctioned(x):
    if jnp.sum(x) > 0:  # tps-ok[TPS503]: fixture for the sanction filter
        return x
    return -x


# -- TPS503 via the conventional-model entry points ------------------------

class ToyGen(GenerativeModel):
    def step(self, state):
        done = jnp.all(state["done"])
        if done:  # traced entry point by convention: Python `if` flagged
            return state
        return state


# -- TPS501: per-call-fresh compile-cache entries --------------------------

def scale_kernel(a, factor):
    return a * factor


def bad_jit_lambda(x):
    f = jax.jit(lambda a: a * 2)  # fresh function object -> fresh entry
    return f(x)


def bad_jit_local_def(x):
    def body(a):
        return a + 1

    g = jax.jit(body)  # local def: fresh per enclosing call
    return g(x)


def bad_fresh_static(x):
    k = jax.jit(scale_kernel, static_argnames=("factor",))
    return k(x, factor={"gain": 2.0})  # fresh dict in a static position


def good_aot_local(x):
    def body(a):
        return a + 1

    g = jax.jit(body)
    return g.lower(x).compile()  # AOT-consumed: no dispatch cache


# -- TPS504 / TPS505: retrace-by-closure -----------------------------------

def bad_capture_arg(rt, n):
    def stepper(state):
        return state + n  # enclosing arg baked as a trace constant

    rt.register_program("stepper", stepper)


def bad_capture_fresh_array(rt, n):
    table = jnp.arange(n)

    def gather(state):
        return state + table  # per-call array baked as a constant

    rt.register_program("gather", gather)


def good_pass_as_operand(rt, n):
    table = jnp.arange(n)

    def gather(state, tbl):
        return state + tbl  # the table rides as a traced operand

    rt.register_program("gather", gather, table)
