"""TPS301 fixture: instance state written from executor threads AND the
event loop — with and without a common lock (including a guard held by the
caller rather than at the write site, which must count)."""

import threading


class Racy:
    def __init__(self):
        self.items = []
        self.count = 0

    def kick(self, loop, pool):
        loop.run_in_executor(pool, self._work)

    def _work(self):
        self.items.append(1)  # TPS301: executor-thread write, no lock
        self.count += 1  # TPS301

    async def serve(self):
        self.items.pop()  # the loop-side half of the race
        self.count -= 1


class Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []

    def kick(self, loop, pool):
        loop.run_in_executor(pool, self._work)

    def _work(self):
        with self._lock:
            self.items.append(1)

    async def serve(self):
        with self._lock:
            self.items.pop()


class EntryHeld:
    """The guard is held by every CALLER of the mutator, never lexically at
    the write site — context propagation must still see it as guarded."""

    def __init__(self):
        self._lock = threading.Lock()
        self.roster = []

    def kick(self, loop, pool):
        loop.run_in_executor(pool, self._thread_side)

    def _thread_side(self):
        with self._lock:
            self._mutate()

    def _mutate(self):
        self.roster.append(1)

    async def serve(self):
        with self._lock:
            self._mutate()
