"""TPS101/TPS102 fixture: blocking calls in (or reachable from) async code.

Not imported by anything — parsed by tests/test_analysis.py through the
analyzer. ``bad_*`` symbols must be flagged; ``good_*`` must not.
"""

import asyncio
import threading
import time


class Handler:
    def __init__(self):
        self._lock = threading.Lock()
        self._alock = asyncio.Lock()

    async def bad_sleep(self):
        time.sleep(0.1)  # TPS101: blocks the event loop

    async def bad_result(self, fut):
        return fut.result()  # TPS101: sync future wait on the loop

    async def bad_acquire(self):
        self._lock.acquire()  # TPS101: blocking acquire of a threading lock

    async def bad_held_across_await(self):
        with self._lock:  # TPS102: threading lock held across await
            await asyncio.sleep(0)

    async def bad_reachable(self):
        self._helper()  # TPS101: helper blocks, called directly on the loop

    def _helper(self):
        time.sleep(0.5)

    async def good_async_lock(self):
        async with self._alock:  # asyncio locks may span awaits
            await asyncio.sleep(0)

    async def good_awaited(self):
        await asyncio.sleep(0.1)

    async def good_executor(self, loop, pool):
        # A reference handed to an executor is not a call edge.
        await loop.run_in_executor(pool, self._helper)

    async def good_lock_released_before_await(self):
        with self._lock:
            x = 1
        await asyncio.sleep(0)
        return x

    def good_sync_sleep(self):
        time.sleep(0.1)  # sync helper never called from an async body here
