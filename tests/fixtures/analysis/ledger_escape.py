"""TPS601 positive/negative cases: ledger acquire/release balance along
the AST. Positive cases are ``bad_*``; ``good_*`` must stay clean — the
protection patterns here are exactly the ones the serving path uses
(try/finally, handler release, one-level funnels, guard-and-bail,
ownership transfer by return)."""

from tpuserve.genserve.arena import SlotArena
from tpuserve.genserve.pages import PageLedger


class Engine:
    def __init__(self, slots, pages):
        self.arena = SlotArena(slots)
        self.pages = PageLedger(pages, 16)

    async def bad_await_while_held(self, info):
        slot = self.arena.acquire(info)
        await self.insert(slot)  # an exception here leaks the slot
        return slot

    def bad_raise_while_held(self, info):
        slot = self.arena.acquire(info)
        if info is None:
            raise ValueError("rejected")  # leaks: no handler releases
        return slot

    async def bad_call_while_held(self, info):
        pages = self.pages.acquire(info.slot, info.n)
        self.bookkeep(pages)  # any raise out of here leaks the pages
        return pages

    async def good_finally(self, info):
        slot = self.arena.acquire(info)
        try:
            await self.insert(slot)
        finally:
            self.arena.release(slot)

    async def good_handler_release(self, info):
        slot = self.arena.acquire(info)
        try:
            await self.insert(slot)
        except Exception:
            self.arena.release(slot)
            raise
        return slot

    async def good_release_funnel(self, info):
        slot = self.arena.acquire(info)
        try:
            await self.insert(slot)
        except Exception:
            self._free(slot)  # one-level same-class funnel
            raise
        return slot

    def _free(self, slot):
        self.arena.release(slot)

    def good_guard_and_bail(self, info):
        slot = self.arena.acquire(info)
        if info is None:
            self.arena.release(slot)
            return None
        return slot  # ownership transfers to the caller

    def good_immediate_return(self, info):
        return self.arena.acquire(info)

    async def good_sanctioned(self, info):
        slot = self.arena.acquire(info)  # tps-ok[TPS601]: reaper releases
        await self.insert(slot)
        return slot

    async def insert(self, slot):
        pass

    def bookkeep(self, pages):
        pass
