"""TPS201 fixture: AB/BA lock-order inversions, nested and via a call."""

import threading


class Inverted:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._b:
            with self._a:  # TPS201: closes the a->b / b->a cycle
                pass


class CrossCall:
    def __init__(self):
        self._m = threading.Lock()
        self._n = threading.Lock()

    def outer(self):
        with self._m:
            self.inner()  # acquires n while m is held (call edge)

    def inner(self):
        with self._n:
            pass

    def reversed_order(self):
        with self._n:
            with self._m:  # TPS201: n->m against the m->n call edge
                pass


class Ordered:
    def __init__(self):
        self._x = threading.Lock()
        self._y = threading.Lock()

    def one(self):
        with self._x:
            with self._y:
                pass

    def two(self):
        with self._x:
            with self._y:  # same order everywhere: clean
                pass
