"""Fleet scheduler suite (ISSUE 10): cross-model SLO admission, priority
classes over the device-seconds ledger, warm/cold weight paging, and the
isolation-drill logic.

Three layers, mirroring the chaos/lifecycle suites:

- pure units against stub batchers (predictor math, saturation, the
  priority floor, the ledger window, the warm/cold state machine);
- real-batcher units (the raw-vs-clamped queue-clear split the scheduler
  depends on — ISSUE 10's bugfix satellite);
- HTTP end-to-end against real toy-family servers (unmeetable-deadline
  504 before enqueue, cold boot -> first-request warm-up -> idle demotion
  -> zero-recompile re-warm, the ``:warm`` admin endpoint, the
  ``/stats scheduler`` block, priority shed under saturation, and the
  fleet isolation drill).
"""

import asyncio
import io
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from tpuserve.batcher import clamp_retry_after_s
from tpuserve.config import (ModelConfig, SchedulerConfig, ServerConfig,
                             load_config)
from tpuserve.obs import Metrics
from tpuserve.scheduler import FleetScheduler, run_fleet_drill
from tpuserve.server import ServerState, make_app

pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnraisableExceptionWarning")

NPY = {"Content-Type": "application/x-npy"}


def npy_image(seed: int = 0, edge: int = 8) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.random.default_rng(seed).integers(
        0, 200, (edge, edge, 3), dtype=np.uint8))
    return buf.getvalue()


def toy_model_cfg(name: str = "toy", **over) -> ModelConfig:
    base = dict(family="toy", batch_buckets=[1, 2, 4], deadline_ms=5.0,
                dtype="float32", num_classes=10, parallelism="single",
                request_timeout_ms=10_000.0, wire_size=8)
    base.update(over)
    return ModelConfig(name=name, **base)


def sched_server_cfg(models, **over) -> ServerConfig:
    base = dict(models=models, decode_threads=2, startup_canary=False,
                scheduler=SchedulerConfig(enabled=True))
    base.update(over)
    return ServerConfig(**base)


class StubBatcher:
    """Minimal batcher surface the scheduler consumes."""

    def __init__(self, clear=None, service=None, pending=0):
        self.clear = clear
        self.service = service
        self.pending = pending
        self.device_time_cb = None

    def estimate_clear_s(self):
        return self.clear

    def predicted_service_s(self, n_items=1):
        return self.service


def make_sched(**cfg_over) -> FleetScheduler:
    base = dict(enabled=True)
    base.update(cfg_over)
    return FleetScheduler(SchedulerConfig(**base), Metrics())


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


# ---------------------------------------------------------------------------
# Satellite bugfix: raw estimate vs clamped Retry-After hint
# ---------------------------------------------------------------------------

def test_estimate_clear_raw_and_clamped_hint(loop):
    """estimate_clear_s stays RAW for the scheduler's admission math;
    clamp_retry_after_s owns the [1, 30] s client hint. A 90 s backlog
    clamped to 30 would admit work that provably cannot meet a 45 s
    deadline — the two must be separate numbers."""
    cfg = ServerConfig(models=[toy_model_cfg()], decode_threads=2,
                       startup_canary=False)
    state = ServerState(cfg)
    state.build()

    async def go():
        await state.start()
        b = state.batchers["toy"]
        b._ewma_ms[(1,)] = 1000.0  # 1 item/s demonstrated
        b._pending = 90
        assert b.estimate_clear_s() == pytest.approx(90.0)  # raw, unclamped
        assert clamp_retry_after_s(b.estimate_clear_s()) == 30  # the hint
        assert state.queue_retry_after("toy") == 30
        b._pending = 2
        assert b.estimate_clear_s() == pytest.approx(2.0)
        assert clamp_retry_after_s(b.estimate_clear_s()) == 2
        b._pending = 1
        b._ewma_ms[(1,)] = 10.0
        assert b.estimate_clear_s() == pytest.approx(0.01)
        assert clamp_retry_after_s(b.estimate_clear_s()) == 1  # floor
        assert clamp_retry_after_s(None) is None
        await state.stop()

    loop.run_until_complete(go())


def test_predicted_service_picks_covering_bucket(loop):
    """predicted_service_s: the EWMA of the smallest bucket covering the
    request; largest-observed fallback; None before evidence."""
    cfg = ServerConfig(models=[toy_model_cfg()], decode_threads=2,
                       startup_canary=False)
    state = ServerState(cfg)
    state.build()

    async def go():
        await state.start()
        b = state.batchers["toy"]
        assert b.predicted_service_s() is None
        b._ewma_ms[(1,)] = 10.0
        b._ewma_ms[(4,)] = 40.0
        assert b.predicted_service_s(1) == pytest.approx(0.010)
        assert b.predicted_service_s(3) == pytest.approx(0.040)
        # Nothing covers 8 items: fall back to the largest observed.
        assert b.predicted_service_s(8) == pytest.approx(0.040)
        await state.stop()

    loop.run_until_complete(go())


# ---------------------------------------------------------------------------
# Predictor + admission units (stub batchers)
# ---------------------------------------------------------------------------

def test_predict_completion_combines_clear_and_service():
    sched = make_sched()
    sched.register("m", StubBatcher(clear=2.0, service=0.5),
                   toy_model_cfg("m"))
    assert sched.predict_completion_s("m") == pytest.approx(2.5)
    sched.register("empty", StubBatcher(clear=None, service=None),
                   toy_model_cfg("empty"))
    assert sched.predict_completion_s("empty") is None  # no evidence: admit
    sched.register("idle", StubBatcher(clear=None, service=0.3),
                   toy_model_cfg("idle"))
    assert sched.predict_completion_s("idle") == pytest.approx(0.3)


def test_deadline_unmeetable_shed_unit():
    sched = make_sched()
    sched.register("m", StubBatcher(clear=2.0, service=1.0, pending=5),
                   toy_model_cfg("m"))
    now = time.perf_counter()
    shed = sched.check_deadline("m", now + 1.0)  # 1 s left, 3 s predicted
    assert shed is not None and shed.status == 504
    assert shed.reason == "deadline_unmeetable"
    assert shed.retry_after == 2  # clamp of the raw 2.0 s clear estimate
    assert sched._entries["m"].shed_counters[
        "deadline_unmeetable"].value == 1
    assert sched.check_deadline("m", now + 10.0) is None  # meetable
    assert sched.check_deadline("m", None) is None  # no deadline stamped


def test_deadline_headroom_grace():
    """headroom_ms is grace BEYOND the prediction before the shed fires."""
    sched = make_sched(headroom_ms=2000.0)
    sched.register("m", StubBatcher(clear=2.0, service=1.0),
                   toy_model_cfg("m"))
    now = time.perf_counter()
    # 1.5 s remaining vs 3.0 s predicted: within the 2 s grace -> admit.
    assert sched.check_deadline("m", now + 1.5) is None
    assert sched.check_deadline("m", now + 0.5) is not None


def test_priority_shed_and_floor_under_saturation(loop):
    """Under saturation batch-class sheds first; the min_share floor
    sheds the device-time hog's traffic while a starved model with
    queued work catches up — and stops shedding once it has."""
    async def go():
        sched = make_sched(overload_clear_s=0.5, min_share=0.2)
        hot = StubBatcher(clear=5.0, service=0.5, pending=10)
        quiet = StubBatcher(clear=0.0, service=0.01, pending=1)
        sched.register("hot", hot, toy_model_cfg("hot"))
        sched.register("quiet", quiet, toy_model_cfg("quiet"))
        # Feed the ledger: hot consumed ~99% of the windowed device time.
        hot.device_time_cb(0.99)
        quiet.device_time_cb(0.01)
        assert sched.saturated()
        assert sched.share("hot") > 0.9

        shed = sched.check_admission("hot", "batch")
        assert shed is not None and shed.reason == "priority_shed"
        assert shed.status == 503 and shed.retry_after >= 1
        shed = sched.check_admission("quiet", "batch")
        assert shed is not None and shed.reason == "priority_shed"

        # The floor: quiet has pending work below min_share, hot is over
        # its allowance (1 - 0.2) -> hot's interactive sheds too...
        shed = sched.check_admission("hot", "interactive")
        assert shed is not None and shed.reason == "share_exceeded"
        # ...while quiet's interactive is never starved.
        assert sched.check_admission("quiet", "interactive") is None

        # Once quiet caught up past the floor, hot admits again.
        quiet.device_time_cb(0.5)
        assert sched.share("quiet") > 0.2
        assert sched.check_admission("hot", "interactive") is None

    loop.run_until_complete(go())


def test_unsaturated_fleet_admits_everything(loop):
    async def go():
        sched = make_sched(overload_clear_s=1.0)
        sched.register("m", StubBatcher(clear=0.2, service=0.1, pending=1),
                       toy_model_cfg("m"))
        assert not sched.saturated()
        assert sched.check_admission("m", "batch") is None
        assert sched.check_admission("m", "interactive") is None

    loop.run_until_complete(go())


def test_ledger_window_trims_and_counts():
    sched = make_sched(window_s=0.1)
    b = StubBatcher()
    sched.register("m", b, toy_model_cfg("m"))
    b.device_time_cb(0.5)
    assert sched._entries["m"].window_sum == pytest.approx(0.5)
    assert sched._entries["m"].device_seconds_total.value == pytest.approx(0.5)
    time.sleep(0.15)
    assert sched.share("m") == 0.0  # window expired
    assert sched._entries["m"].window_sum == pytest.approx(0.0)
    # The monotonic counter never trims.
    assert sched._entries["m"].device_seconds_total.value == pytest.approx(0.5)


def test_resolve_priority_header_default_and_junk():
    sched = make_sched()
    sched.register("m", StubBatcher(),
                   toy_model_cfg("m", priority="batch"))
    assert sched.resolve_priority("m", None) == "batch"  # model default
    assert sched.resolve_priority("m", "Interactive") == "interactive"
    assert sched.resolve_priority("m", "batch") == "batch"
    with pytest.raises(ValueError, match="X-Priority"):
        sched.resolve_priority("m", "urgent")


def test_scheduler_config_validation_and_toml(tmp_path):
    with pytest.raises(ValueError, match="min_share"):
        SchedulerConfig(min_share=0.6)
    with pytest.raises(ValueError, match="window_s"):
        SchedulerConfig(window_s=0.0)
    with pytest.raises(ValueError, match="priority"):
        ModelConfig(name="m", priority="urgent")
    with pytest.raises(ValueError, match="cold_start"):
        ModelConfig(name="m", cold_start=True, session_mode="recycle")
    p = tmp_path / "sched.toml"
    p.write_text(
        "[scheduler]\n"
        "enabled = true\n"
        "overload_clear_s = 0.25\n"
        "min_share = 0.1\n"
        "idle_demote_s = 3.0\n"
        "[[model]]\n"
        "name = \"toy\"\n"
        "family = \"toy\"\n"
        "priority = \"batch\"\n"
        "cold_start = true\n")
    cfg = load_config(str(p))
    assert cfg.scheduler.enabled and cfg.scheduler.min_share == 0.1
    assert cfg.scheduler.idle_demote_s == 3.0
    assert cfg.models[0].priority == "batch" and cfg.models[0].cold_start
    cfg2 = load_config(str(p), overrides=["scheduler.overload_clear_s=2.0"])
    assert cfg2.scheduler.overload_clear_s == 2.0


# ---------------------------------------------------------------------------
# Warm/cold state machine units
# ---------------------------------------------------------------------------

def test_warm_cold_state_machine(loop):
    async def go():
        sched = make_sched(warm_retry_after_s=0.2)
        calls = []

        async def warm_fn():
            calls.append(1)
            await asyncio.sleep(0.02)
            return {"version": 2}

        sched.register("m", StubBatcher(), toy_model_cfg("m", cold_start=True),
                       warm_fn=warm_fn, cold=True)
        assert sched.state_of("m") == "cold"
        assert not sched.is_warm("m")
        shed = sched.check_admission("m", "interactive")
        assert shed is not None and shed.status == 503
        assert shed.reason == "model_warming" and shed.retry_after >= 1
        info = await sched.warm("m")  # joins the kicked warm task
        assert info["state"] == "warm" and calls == [1]
        assert sched.is_warm("m")
        assert sched.check_admission("m", "interactive") is None
        again = await sched.warm("m")
        assert again.get("already_warm") and calls == [1]  # idempotent

    loop.run_until_complete(go())


def test_failed_warm_backs_off_then_explicit_retry(loop):
    async def go():
        sched = make_sched(warm_retry_after_s=5.0)
        attempts = []

        async def bad_warm():
            attempts.append(1)
            raise RuntimeError("corrupt checkpoint")

        sched.register("m", StubBatcher(), toy_model_cfg("m", cold_start=True),
                       warm_fn=bad_warm, cold=True)
        with pytest.raises(RuntimeError, match="corrupt"):
            await sched.warm("m")
        assert sched.state_of("m") == "cold" and len(attempts) == 1
        # Request-triggered warms back off; no new task spins.
        sched.check_admission("m", "interactive")
        await asyncio.sleep(0.01)
        assert len(attempts) == 1
        # An explicit :warm overrides the backoff and retries.
        with pytest.raises(RuntimeError):
            await sched.warm("m")
        assert len(attempts) == 2

    loop.run_until_complete(go())


def test_idle_sweep_demotes_via_runtime(loop):
    async def go():
        sched = make_sched(idle_demote_s=0.05)

        class StubRuntime:
            released = 0

            def release_params(self):
                StubRuntime.released += 1

        async def warm_fn():
            return {}

        b = StubBatcher(pending=0)
        sched.register("m", b, toy_model_cfg("m", cold_start=True),
                       runtime=StubRuntime(), warm_fn=warm_fn)
        assert sched.state_of("m") == "warm"
        sched._entries["m"].last_used = time.monotonic() - 1.0
        b.pending = 3
        assert sched.sweep_idle() == 0  # queued work blocks demotion
        b.pending = 0
        assert sched.sweep_idle() == 1
        assert sched.state_of("m") == "cold"
        assert StubRuntime.released == 1
        # Non-cold_start models never demote.
        sched.register("pinned", StubBatcher(), toy_model_cfg("pinned"),
                       runtime=StubRuntime(), warm_fn=warm_fn)
        sched._entries["pinned"].last_used = time.monotonic() - 1.0
        assert sched.sweep_idle() == 0

    loop.run_until_complete(go())


# ---------------------------------------------------------------------------
# HTTP end-to-end
# ---------------------------------------------------------------------------

def test_unmeetable_deadline_shed_504_before_enqueue(loop):
    """Clockwork admission over HTTP: with a 5 s service EWMA on the
    books, a 200 ms-deadline request sheds with a FAST 504
    (deadline_unmeetable + Retry-After) before decode or enqueue — the
    batcher never sees it."""
    cfg = sched_server_cfg([toy_model_cfg()])
    state = ServerState(cfg)
    state.build()

    async def go():
        server = TestServer(make_app(state))
        async with TestClient(server) as client:
            b = state.batchers["toy"]
            b._ewma_ms[(4,)] = 5000.0  # every bucket "takes" 5 s
            batches_before = b._c_batches.value
            t0 = time.perf_counter()
            r = await client.post("/v1/models/toy:predict",
                                  data=npy_image(), headers=NPY,
                                  params={"timeout_ms": "200"})
            elapsed = time.perf_counter() - t0
            body = await r.json()
            assert r.status == 504, body
            assert body["reason"] == "deadline_unmeetable"
            assert "Retry-After" in r.headers
            assert elapsed < 0.15, "shed must be fast, not at the deadline"
            assert b._c_batches.value == batches_before  # never enqueued
            m = state.metrics.counter(
                "sched_sheds_total{model=toy,reason=deadline_unmeetable}")
            assert m.value == 1
            # A roomy deadline admits and serves normally.
            r = await client.post("/v1/models/toy:predict",
                                  data=npy_image(), headers=NPY,
                                  params={"timeout_ms": "30000"})
            assert r.status == 200

    loop.run_until_complete(go())


def test_priority_shed_and_queue_wait_split_http(loop):
    """Saturated fleet over HTTP: batch-class sheds 503 priority_shed
    with Retry-After; interactive admits; the queue-wait histogram is
    split by priority; junk X-Priority 400s."""
    cfg = sched_server_cfg(
        [toy_model_cfg()],
        scheduler=SchedulerConfig(enabled=True, overload_clear_s=0.5))
    state = ServerState(cfg)
    state.build()

    async def go():
        server = TestServer(make_app(state))
        async with TestClient(server) as client:
            # Serve one real request per class so the split histograms see
            # traffic (the fleet is not saturated yet).
            for prio in ("interactive", "batch"):
                r = await client.post("/v1/models/toy:predict",
                                      data=npy_image(), headers={
                                          **NPY, "X-Priority": prio})
                assert r.status == 200
            for prio in ("interactive", "batch"):
                h = state.metrics.queue_wait_histogram("toy", prio)
                assert h.n >= 1, f"queue_wait_ms missing for {prio}"

            r = await client.post("/v1/models/toy:predict",
                                  data=npy_image(),
                                  headers={**NPY, "X-Priority": "urgent"})
            assert r.status == 400

            # Saturate: a 5 s backlog on the books.
            b = state.batchers["toy"]
            b._ewma_ms[(1,)] = 1000.0
            b._pending = 5
            assert state.scheduler.saturated()
            r = await client.post("/v1/models/toy:predict",
                                  data=npy_image(),
                                  headers={**NPY, "X-Priority": "batch"})
            body = await r.json()
            assert r.status == 503 and body["reason"] == "priority_shed"
            assert "Retry-After" in r.headers
            b._pending = 0  # restore before teardown accounting

            async with client.get("/stats") as r:
                stats = await r.json()
            srow = stats["scheduler"]
            assert srow["models"]["toy"]["sheds"]["priority_shed"] == 1
            assert srow["min_share"] == cfg.scheduler.min_share

    loop.run_until_complete(go())


def _poll_until_200(client, path, body, deadline_s=30.0):
    async def go():
        t0 = time.monotonic()
        statuses = []
        while time.monotonic() - t0 < deadline_s:
            r = await client.post(path, data=body, headers=NPY)
            statuses.append(r.status)
            if r.status == 200:
                return statuses, await r.json()
            assert r.status == 503, await r.text()  # warming sheds only
            await asyncio.sleep(0.05)
        raise AssertionError(f"never warmed: {statuses}")
    return go()


def test_cold_start_warm_demote_rewarm_zero_recompiles(loop):
    """The weight-paging acceptance path: a cold-declared model boots
    with zero device params and zero compiled variants; the first request
    sheds 503 model_warming and triggers staging through the lifecycle
    path (no request is ever answered by unstaged weights — everything is
    a shed or a real 200); idle demotion frees the params; the next
    request re-warms through the SAME compiled variants with a
    runtime_compiles_total delta of 0."""
    cfg = sched_server_cfg(
        [toy_model_cfg(cold_start=True)],
        scheduler=SchedulerConfig(enabled=True, idle_demote_s=0.3,
                                  sweep_interval_s=0.05))
    state = ServerState(cfg)
    state.build()
    rt = state.runtimes["toy"]
    assert not rt.params_resident, "cold boot must not load device params"
    assert rt.compiles_total == 0, "cold boot must not compile variants"

    async def go():
        server = TestServer(make_app(state))
        async with TestClient(server) as client:
            assert state.metrics.gauge("model_state{model=toy}").value == 0.0
            statuses, body = await _poll_until_200(
                client, "/v1/models/toy:predict", npy_image())
            assert statuses[0] == 503, "first request sheds while warming"
            assert "top_k" in body
            assert rt.params_resident
            compiles_after_warm = rt.compiles_total
            assert compiles_after_warm > 0
            version_after_warm = rt.version

            # Idle out; the sweep demotes and frees the params.
            t0 = time.monotonic()
            while rt.params_resident and time.monotonic() - t0 < 10.0:
                await asyncio.sleep(0.05)
            assert not rt.params_resident, "idle demotion must free params"
            assert state.scheduler.state_of("toy") == "cold"
            assert state.metrics.gauge("model_state{model=toy}").value == 0.0

            # Re-warm on demand: same variants, zero new compiles.
            statuses, body = await _poll_until_200(
                client, "/v1/models/toy:predict", npy_image())
            assert "top_k" in body
            assert rt.compiles_total == compiles_after_warm, \
                "warm->cold->warm churn must not recompile"
            assert rt.version > version_after_warm  # a fresh publish
            m = state.metrics.counter(
                "sched_sheds_total{model=toy,reason=model_warming}")
            assert m.value >= 2  # both warming windows shed

    loop.run_until_complete(go())


def test_warm_endpoint_http(loop):
    """POST :warm stages a cold model to serving synchronously; /stats
    reflects the state; :warm on a scheduler-less server 409s."""
    cfg = sched_server_cfg([toy_model_cfg(cold_start=True)])
    state = ServerState(cfg)
    state.build()

    async def go():
        server = TestServer(make_app(state))
        async with TestClient(server) as client:
            async with client.get("/stats") as r:
                stats = await r.json()
            assert stats["scheduler"]["models"]["toy"]["state"] == "cold"
            assert stats["scheduler"]["models"]["toy"]["cold_start"] is True

            r = await client.post("/admin/models/toy:warm")
            body = await r.json()
            assert r.status == 200, body
            assert body["state"] == "warm" and body["warm_ms"] > 0
            assert state.runtimes["toy"].params_resident

            # Immediately serves — no warming shed after an explicit warm.
            r = await client.post("/v1/models/toy:predict",
                                  data=npy_image(), headers=NPY)
            assert r.status == 200

            r = await client.post("/admin/models/toy:warm")
            body = await r.json()
            assert r.status == 200 and body.get("already_warm")

            r = await client.post("/admin/models/nope:warm")
            assert r.status == 404

    loop.run_until_complete(go())

    # Scheduler disabled: the endpoint refuses rather than pretending.
    cfg2 = ServerConfig(models=[toy_model_cfg()], decode_threads=2,
                        startup_canary=False)
    state2 = ServerState(cfg2)
    state2.build()

    async def go2():
        server = TestServer(make_app(state2))
        async with TestClient(server) as client:
            r = await client.post("/admin/models/toy:warm")
            assert r.status == 409

    loop.run_until_complete(go2())


def test_quiet_model_survives_hot_neighbor_saturation(loop):
    """The cross-model isolation property in-process: a hot model with
    slow compute and a deep backlog must not starve a quiet model's
    interactive traffic — every quiet request answers 200 while the hot
    model is saturated."""
    from tpuserve.config import FaultRuleConfig, FaultsConfig

    cfg = sched_server_cfg(
        [toy_model_cfg("hot"), toy_model_cfg("quiet")],
        scheduler=SchedulerConfig(enabled=True, overload_clear_s=0.2),
        faults=FaultsConfig(enabled=True, rules=[FaultRuleConfig(
            kind="slow_compute", model="hot", probability=1.0,
            delay_ms=60.0)]))
    state = ServerState(cfg)
    state.build()

    async def go():
        server = TestServer(make_app(state))
        async with TestClient(server) as client:
            async def flood_hot(n):
                async def one(i):
                    return await client.post("/v1/models/hot:predict",
                                             data=npy_image(i), headers=NPY)
                return await asyncio.gather(*(one(i) for i in range(n)))

            flood = asyncio.ensure_future(flood_hot(24))
            await asyncio.sleep(0.2)  # let the hot backlog form
            quiet_statuses = []
            for i in range(10):
                r = await client.post("/v1/models/quiet:predict",
                                      data=npy_image(100 + i), headers=NPY)
                quiet_statuses.append(r.status)
            await flood
            assert quiet_statuses == [200] * 10, quiet_statuses

    loop.run_until_complete(go())


# ---------------------------------------------------------------------------
# Fleet isolation drill logic
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_drill_victim_contained_survivors_hold(loop):
    """run_fleet_drill: 3 toy models, one poisoned with device_error at
    100% — the victim's breaker opens and every survivor holds
    availability >= 99% (the summary's gated `availability` is the worst
    survivor's)."""
    cfg = sched_server_cfg(
        [toy_model_cfg("victim", breaker_threshold=3),
         toy_model_cfg("ok_a"), toy_model_cfg("ok_b")])

    summary = loop.run_until_complete(run_fleet_drill(
        cfg, victim="victim", duration_s=4.0, warmup_s=0.5, concurrency=4))

    assert summary["victim"] == "victim"
    assert summary["victim_breaker_open"], summary["victim_breaker"]
    assert summary["availability"] >= 0.99, summary["availability"]
    for name in ("ok_a", "ok_b"):
        row = summary["models"][name]
        assert row["role"] == "survivor"
        assert row["availability"] >= 0.99, (name, row)
        assert row["n_ok"] > 0
    assert summary["models"]["victim"]["availability"] < 0.5
    assert summary["models"]["victim"]["role"] == "victim"
    assert any(f["kind"] == "device_error" and f["fired"] > 0
               for f in summary["faults"])


def test_fleet_drill_requires_three_models(loop):
    cfg = sched_server_cfg([toy_model_cfg("a"), toy_model_cfg("b")])
    with pytest.raises(ValueError, match=">= 3 models"):
        loop.run_until_complete(run_fleet_drill(cfg))
