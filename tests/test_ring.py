"""Ring attention (tpuserve.ops.ring_attention) on the 8-fake-device mesh.

Correctness bar: the sequence-parallel ring result must match dense
single-device attention to f32 tolerance, with and without key-padding masks,
and under combined dp+sp sharding (SURVEY.md §5 long-context).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuserve.ops import dense_attention, ring_attention
from tpuserve.parallel import make_mesh
from tpuserve.parallel.mesh import MeshPlan

pytestmark = pytest.mark.slow


def _qkv(rng, b=2, s=16, h=4, d=8):
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, h, d)).astype(np.float32)
    v = rng.normal(size=(b, s, h, d)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.fixture
def mesh():
    # 8 devices -> dp=2, tp=2, sp=2: exercises seq rotation with other axes live.
    return make_mesh(MeshPlan(tp=2, sp=2))


def test_matches_dense(mesh, rng):
    q, k, v = _qkv(rng)
    out = ring_attention(q, k, v, mesh)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_matches_dense_with_key_padding(mesh, rng):
    q, k, v = _qkv(rng)
    pad = np.zeros((2, 16), np.float32)
    pad[:, 12:] = -1e9  # mask the tail keys
    out = ring_attention(q, k, v, mesh, key_padding=jnp.asarray(pad))
    bias = jnp.asarray(pad)[:, None, None, :]
    ref = dense_attention(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_dp_plus_sp_spec(mesh, rng):
    q, k, v = _qkv(rng)
    spec = P("data", "seq", None, None)
    sh = NamedSharding(mesh, spec)
    q, k, v = jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh)
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh, spec=spec))(q, k, v)
    ref = dense_attention(*_qkv(np.random.default_rng(0)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_sequence_actually_sharded(mesh, rng):
    """The output really is seq-sharded (not silently gathered)."""
    q, k, v = _qkv(rng)
    spec = P("data", "seq", None, None)
    sh = NamedSharding(mesh, spec)
    q, k, v = jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh)
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh, spec=spec))(q, k, v)
    assert out.sharding.spec == spec
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(1, 8, 4, 8)}  # b/dp=1, s/sp=8


def test_bf16_inputs(mesh, rng):
    q, k, v = _qkv(rng)
    out = ring_attention(q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
                         v.astype(jnp.bfloat16), mesh)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=5e-2)


def test_train_step_with_ring_attention():
    from tpuserve.train import dryrun

    loss = dryrun(jax.devices(), steps=1)  # 8 devs -> sp=2 -> ring path
    assert np.isfinite(loss)


def test_long_context_serving_4096_auto_dense(monkeypatch):
    """Push the long-context serving proof to seq 4096 (8x the BERT-512
    regime): ring attention over sp=4, whole serving path, with the
    memory-derived auto local_impl choosing dense per-device math (1024-row
    local tiles are far below the flash threshold) — asserted via a spy,
    not assumed."""
    import importlib

    from tpuserve.config import ModelConfig
    from tpuserve.models import build
    from tpuserve.runtime import build_runtime

    # The package re-exports the FUNCTION under the same name; spy on the
    # module's attribute, which both ring and ulysses resolve at call time.
    ra = importlib.import_module("tpuserve.ops.ring_attention")

    picked = []
    orig = ra.auto_local_impl
    monkeypatch.setattr(
        ra, "auto_local_impl",
        lambda *a: picked.append(orig(*a)) or picked[-1])

    sp_mesh = make_mesh(MeshPlan(sp=4), devices=jax.devices()[:8])
    cfg = ModelConfig(
        name="bert-xl", family="bert", parallelism="sharded", sp=4,
        batch_buckets=[2], seq_buckets=[4096], dtype="float32", num_classes=4,
        options={"layers": 1, "d_model": 64, "heads": 2, "d_ff": 64,
                 "vocab_size": 512, "attention": "ring"},
    )
    model = build(cfg)
    rt = build_runtime(model, mesh=sp_mesh)
    (bucket,) = rt.executables
    assert bucket[1] == 4096
    item = model.host_decode(b"sixteen times the bert regime", "text/plain")
    out = rt.fetch(rt.run(bucket, model.assemble([item, item], bucket)))
    assert out["probs"].shape == (2, model.top_k)
    assert np.isfinite(out["probs"]).all()
    assert picked and all(p == "dense" for p in picked), picked


def test_long_context_serving_2048():
    """Long-context serving end-to-end: a (batch, 2048) bucket with ring
    attention over sp=4, the whole-path proof that sequence parallelism
    extends serving past the BERT-512 regime. At this size the ring's
    memory-derived auto local_impl picks DENSE per-device math (the 16 MB
    local score tile is far below the flash threshold, and dense measured
    faster on v5e — BASELINE.md "Flash vs dense"); the flash-under-ring
    composition is separately proven by the explicit local_impl='flash'
    parity tests in test_flash_attention.py."""
    from tpuserve.config import ModelConfig
    from tpuserve.models import build
    from tpuserve.runtime import build_runtime

    sp_mesh = make_mesh(MeshPlan(sp=4), devices=jax.devices()[:8])
    cfg = ModelConfig(
        name="bert-long", family="bert", parallelism="sharded", sp=4,
        batch_buckets=[2], seq_buckets=[2048], dtype="float32", num_classes=4,
        options={"layers": 1, "d_model": 256, "heads": 4, "d_ff": 64,
                 "vocab_size": 512, "attention": "ring"},
    )
    model = build(cfg)
    rt = build_runtime(model, mesh=sp_mesh)
    (bucket,) = rt.executables
    assert bucket[1] == 2048
    text = b'{"text": "' + b"a long context sentence " * 60 + b'"}'
    item = model.host_decode(text, "application/json")
    out = rt.fetch(rt.run(bucket, model.assemble([item, item], bucket)))
    assert out["probs"].shape == (2, model.top_k)
    assert np.isfinite(out["probs"]).all()
