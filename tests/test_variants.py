"""Compiled-variant registry, roofline attribution, and quantized serving
parity over the real HTTP path (ISSUE 6).

Covers the compute fast path's contracts:
- the registry enumerates every specialized variant (bucket x dtype x
  quantize x parallelism) and ``runtime_compiles_total`` counts exactly the
  compiles that happened — repeat buckets, prewarm, probes, and lifecycle
  churn all leave it flat (steady state recompiles NOTHING);
- ``device_preprocess`` is a real seam: forward == net(device_preprocess),
  and the wire signature stays raw uint8;
- the raw-executable probe yields per-bucket device-time ceilings and the
  /stats roofline block splits the serving compute phase against them;
- the int8 weight-only variant serves over the real HTTP path within
  tolerance of the fp path, with zero recompiles across the load;
- the bench-side variance windowing helpers (best consecutive window,
  spread, CV) pick settled windows, not lucky passes.
"""

import asyncio
import io

import jax
import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from tpuserve.bench import roofline as rl
from tpuserve.config import ModelConfig, PipelineConfig, ServerConfig
from tpuserve.models import build
from tpuserve.obs import Metrics
from tpuserve.runtime import VariantKey, build_runtime
from tpuserve.server import ServerState, make_app


def _toy_cfg(**kw) -> ModelConfig:
    base = dict(name="toy", family="toy", batch_buckets=[1, 2, 4],
                deadline_ms=5.0, dtype="float32", num_classes=10,
                parallelism="single", request_timeout_ms=10_000.0)
    base.update(kw)
    return ModelConfig(**base)


def npy_bytes(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


# -- registry ----------------------------------------------------------------

def test_registry_enumerates_variants_and_counts_compiles():
    metrics = Metrics()
    model = build(_toy_cfg())
    rt = build_runtime(model, metrics=metrics)
    # One variant per bucket, keyed by the full specialization.
    assert set(rt.variants) == {
        VariantKey(bucket=(b,), dtype="float32", quantize=None,
                   parallelism="single") for b in (1, 2, 4)}
    assert rt.compiles_total == 3  # 3 buckets x 1 replica
    assert metrics.counter(
        "runtime_compiles_total{model=toy}").value == 3
    assert metrics.gauge("runtime_variants{model=toy}").value == 3
    summaries = rt.variants_summary()
    assert [s["bucket"] for s in summaries] == [[1], [2], [4]]
    assert all(s["quantize"] is None and s["dtype"] == "float32"
               and s["replicas"] == 1 for s in summaries)
    assert all(s["compile_ms"] > 0 for s in summaries)
    # describe() exposes the enumeration (TF-Serving P2: variants are
    # cheaply-listable artifacts).
    d = rt.describe()
    assert len(d["variants"]) == 3 and d["compiles_total"] == 3


def test_repeat_buckets_and_reload_churn_recompile_nothing():
    metrics = Metrics()
    model = build(_toy_cfg())
    rt = build_runtime(model, metrics=metrics)
    rt.prewarm()
    before = rt.compiles_total
    img = np.random.default_rng(0).integers(0, 255, (8, 8, 3), np.uint8)
    for bucket in rt.executables:
        batch = model.assemble([img] * bucket[0], bucket)
        for _ in range(3):
            rt.fetch(rt.run(bucket, batch))
    # Version churn swaps trees under unchanged shapes: same variants.
    staged = rt.stage_params()
    rt.publish(staged)
    rt.rollback()
    assert rt.ensure_compiled() == 0
    assert rt.compiles_total == before
    # Per-variant serving counters are live (the smoke's "specialized
    # variant actually served" signal).
    assert metrics.counter(
        "runtime_variant_batches_total{model=toy,variant=1/float32/fp/single}"
    ).value > 0


def test_ensure_compiled_restores_missing_variant():
    model = build(_toy_cfg())
    rt = build_runtime(model)
    before = rt.compiles_total
    key = rt.variant_key((2,))
    del rt.variants[key]
    del rt.executables[(2,)]
    assert rt.ensure_compiled() == 1
    assert rt.compiles_total == before + 1
    img = np.zeros((8, 8, 3), np.uint8)
    out = rt.fetch(rt.run((2,), model.assemble([img, img], (2,))))
    assert np.isfinite(out["probs"]).all()


def test_lifecycle_stage_compiles_missing_variant_before_canary():
    """The reload pipeline's variant-completeness gate: a bucket whose
    executable went missing is recompiled at STAGE time, so the staged
    canary (and the first post-publish request) never pays first-compile."""
    from tpuserve.lifecycle import ModelLifecycle
    from tpuserve.config import LifecycleConfig

    metrics = Metrics()
    model = build(_toy_cfg())
    rt = build_runtime(model, metrics=metrics)
    lc = ModelLifecycle("toy", rt, model, LifecycleConfig(), metrics)
    del rt.variants[rt.variant_key((4,))]
    del rt.executables[(4,)]
    info = asyncio.run(lc.reload())
    assert info["version"] == 2
    assert (4,) in rt.executables  # back before the canary ran


# -- fused-preproc seam ------------------------------------------------------

def test_forward_routes_through_device_preprocess_seam():
    """forward(params, wire) == net(device_preprocess(wire)), and the wire
    signature stays raw uint8 — the fused-preproc contract."""
    model = build(_toy_cfg())
    params = model.init_params(jax.random.key(0))
    batch = np.random.default_rng(1).integers(
        0, 255, (2, 8, 8, 3), np.uint8)
    sig = model.input_signature((2,))
    assert sig.dtype == np.uint8  # raw bytes cross the wire
    x = np.asarray(model.device_preprocess(jax.numpy.asarray(batch)))
    assert x.dtype == np.float32 and x.max() <= 1.0  # cast happened on device
    out = model.forward(params, jax.numpy.asarray(batch))
    # Recompute the net over the seam's output by hand.
    h = np.tanh(x @ np.asarray(params["w1"]) + np.asarray(params["b1"]))
    logits = h @ np.asarray(params["w2"]) + np.asarray(params["b2"])
    e = np.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = e / e.sum(axis=-1, keepdims=True)
    top3 = np.sort(probs, axis=-1)[:, ::-1][:, :3]
    np.testing.assert_allclose(np.asarray(out["probs"]), top3, atol=1e-5)


def test_vision_prepare_batch_is_device_preprocess():
    from tpuserve.models.resnet import ResNet50Serving

    m = ResNet50Serving(ModelConfig(
        name="r", family="resnet50", dtype="float32", image_size=16,
        wire_size=16, num_classes=10))
    batch = jax.numpy.asarray(np.random.default_rng(2).integers(
        0, 255, (1, 16, 16, 3), np.uint8))
    np.testing.assert_array_equal(np.asarray(m.prepare_batch(batch)),
                                  np.asarray(m.device_preprocess(batch)))


# -- roofline probes + /stats ------------------------------------------------

def test_probe_raw_ms_and_h2d_sync():
    model = build(_toy_cfg())
    rt = build_runtime(model)
    before = rt.compiles_total
    ms = rt.probe_raw_ms((2,), iters=4)
    assert ms is not None and ms > 0
    assert rt.raw_ms_per_batch[(2,)] == pytest.approx(ms, abs=1e-3)
    all_ms = rt.probe_all_raw(iters=2)
    assert set(all_ms) == {(1,), (2,), (4,)}
    assert rt.compiles_total == before  # probing compiles nothing
    # h2d transfer-completion gate: same values either way; the flag only
    # moves where the wall time is attributed.
    img = np.zeros((8, 8, 3), np.uint8)
    batch = model.assemble([img, img], (2,))
    rt.h2d_sync = True
    dev_sync = rt.h2d((2,), batch)
    rt.h2d_sync = False
    dev_async = rt.h2d((2,), batch)
    np.testing.assert_array_equal(np.asarray(dev_sync), np.asarray(dev_async))


def test_batcher_start_propagates_h2d_sync(toy_cfg):
    import concurrent.futures as cf

    from tpuserve.batcher import ModelBatcher

    model = build(toy_cfg)
    rt = build_runtime(model)
    pool = cf.ThreadPoolExecutor(max_workers=2)

    async def go(sync: bool) -> bool:
        b = ModelBatcher(model, rt, Metrics(), pool,
                         pipeline_cfg=PipelineConfig(h2d_sync=sync))
        await b.start()
        try:
            return rt.h2d_sync
        finally:
            await b.stop()

    assert asyncio.run(go(True)) is True
    assert asyncio.run(go(False)) is False
    pool.shutdown()


def test_stats_roofline_block_over_http():
    cfg = ServerConfig(
        models=[_toy_cfg()], decode_threads=2, startup_canary=False,
        roofline_probe_iters=2,
    )
    state = ServerState(cfg)
    state.build()
    app = make_app(state)
    loop = asyncio.new_event_loop()
    try:
        async def go():
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                img = np.random.default_rng(3).integers(
                    0, 255, (8, 8, 3), np.uint8)
                r = await client.post(
                    "/v1/models/toy:classify", data=npy_bytes(img),
                    headers={"Content-Type": "application/x-npy"})
                assert r.status == 200
                r = await client.get("/stats")
                return await r.json()
            finally:
                await client.close()

        stats = loop.run_until_complete(go())
    finally:
        loop.close()
    roof = stats["roofline"]["toy"]
    assert len(roof["variants"]) == 3
    assert roof["compiles_total"] == 3
    # Startup probe armed: every bucket has a raw device-time ceiling.
    assert set(roof["raw_ms_per_batch"]) == {"[1]", "[2]", "[4]"}
    assert all(v and v > 0 for v in roof["raw_ms_per_batch"].values())
    split = roof["compute_split"]
    assert split["observed_p50_ms"] > 0 and split["device_ms"] > 0
    assert split["host_wait_ms"] >= 0
    assert 0 < split["pct_of_ceiling"] <= 100


# -- int8 over the real HTTP path -------------------------------------------

def test_int8_http_parity_with_fp_and_zero_recompiles():
    """The quantized variant on the measured serving path: identical
    requests through two real HTTP servers (fp vs int8 weight-only) agree
    within quantization tolerance, and the int8 server's compile counter
    stays flat across the whole load (repeat buckets, zero recompiles)."""

    def build_state(quantize):
        cfg = ServerConfig(
            models=[_toy_cfg(quantize=quantize, quantize_min_size=1024,
                             batch_buckets=[1, 2])],
            decode_threads=2, startup_canary=False,
        )
        state = ServerState(cfg)
        state.build()
        return state

    imgs = [np.random.default_rng(s).integers(0, 255, (8, 8, 3), np.uint8)
            for s in range(6)]
    loop = asyncio.new_event_loop()
    try:
        async def serve_and_query(state):
            client = TestClient(TestServer(make_app(state)))
            await client.start_server()
            try:
                out = []
                for img in imgs:
                    r = await client.post(
                        "/v1/models/toy:classify", data=npy_bytes(img),
                        headers={"Content-Type": "application/x-npy"})
                    assert r.status == 200
                    out.append(await r.json())
                # A client batch exercises the second bucket too.
                r = await client.post(
                    "/v1/models/toy:classify",
                    data=npy_bytes(np.stack(imgs[:2])),
                    headers={"Content-Type": "application/x-npy"})
                assert r.status == 200
                return out
            finally:
                await client.close()

        state_fp = build_state(None)
        out_fp = loop.run_until_complete(serve_and_query(state_fp))

        state_q = build_state("int8")
        rt_q = state_q.runtimes["toy"]
        # Something really is int8 on device.
        leaves = jax.tree_util.tree_leaves(rt_q.params_per_mesh[0])
        assert any(x.dtype == np.int8 for x in leaves)
        assert rt_q.variants_summary()[0]["quantize"] == "int8"
        compiles_after_startup = rt_q.compiles_total
        out_q = loop.run_until_complete(serve_and_query(state_q))
        assert rt_q.compiles_total == compiles_after_startup
    finally:
        loop.close()

    for a, b in zip(out_fp, out_q):
        assert a["top_k"][0]["class"] == b["top_k"][0]["class"]  # top-1
        pa = np.array([e["prob"] for e in a["top_k"]])
        pb = np.array([e["prob"] for e in b["top_k"]])
        np.testing.assert_allclose(pa, pb, atol=5e-3)


# -- bench variance + roofline helpers ---------------------------------------

def test_best_window_prefers_consecutive_settled_passes():
    vals = [480.0, 658.6, 606.0, 610.0, 600.0]
    start, win = rl.best_window(vals, k=3)
    assert start == 2 and win == [606.0, 610.0, 600.0]
    assert rl.spread_pct(win) < 2.0
    # Bimodal runs cannot fake convergence by cherry-picking.
    bimodal = [400.0, 800.0, 410.0, 790.0, 395.0]
    _, w = rl.best_window(bimodal, k=3)
    assert rl.spread_pct(w) > 15.0
    assert rl.best_window([], k=3) == (0, [])
    assert rl.best_window([100.0], k=3) == (0, [100.0])


def test_spread_and_cv():
    assert rl.spread_pct([100.0, 90.0, 95.0]) == pytest.approx(10.0)
    assert rl.spread_pct([]) == 0.0
    assert rl.cv_pct([5.0, 5.0, 5.0]) == 0.0
    assert rl.cv_pct([90.0, 110.0]) == pytest.approx(10.0)


def test_build_roofline_block_shape():
    latency = {
        "latency_ms{model=m,phase=compute}": {"n": 10, "p50_ms": 465.6},
        "latency_ms{model=m,phase=h2d}": {"n": 10, "p50_ms": 15.5},
        "latency_ms{model=m,phase=preproc}": {"n": 10, "p50_ms": 5.7},
    }
    block = rl.build_roofline(
        latency, "m", buckets=[64, 128],
        raw_ms_by_bucket={64: 12.0, 128: 24.1},
        link_mbps=14.3, img_bytes=38400, chip_img_s=10628.5,
        value_img_s=606.0)
    assert set(block["per_bucket"]) == {"64", "128"}
    b128 = block["per_bucket"]["128"]
    assert b128["raw_ms_per_batch"] == 24.1
    assert b128["raw_img_s"] == pytest.approx(128 / 24.1 * 1e3, rel=1e-3)
    assert b128["wire_ms_per_batch"] == pytest.approx(
        128 * 38400 / 14.3e6 * 1e3, rel=1e-3)
    comp = block["phases"]["compute"]
    assert comp["ceiling_ms"] == 24.1 and comp["ceiling_kind"] == "device"
    assert comp["pct_of_ceiling"] == pytest.approx(100 * 24.1 / 465.6, abs=0.1)
    split = block["compute_split"]
    assert split["device_ms"] == 24.1
    assert split["host_wait_ms"] == pytest.approx(441.5, abs=0.1)
    assert block["binding_phase"] == "compute"
    assert block["pct_of_chip_ceiling"] == pytest.approx(5.7, abs=0.1)
    # Postproc never observed: reported as null, no ceiling invented.
    assert block["phases"]["postproc"]["p50_ms"] is None
